"""The SWIM + Lifeguard membership engine (host plane).

Re-implements the layer the reference takes from ``memberlist-core``
(SURVEY.md §2.9): probe/ack/indirect-probe failure detection with Lifeguard
local-health awareness, suspicion with confirmation-shortened timeouts,
alive/suspect/dead dissemination over a transmit-limited gossip queue,
push/pull full-state anti-entropy over streams, and the delegate callback
surface serf hooks into.

Object API parity (grep-verified list in SURVEY.md §2.9): ``join``,
``join_many``, ``leave``, ``shutdown``, ``send``, ``update_node``,
``local_id``, ``local_node``, ``num_online_members``, ``health_score``,
``keyring``, ``encryption_enabled``, ``members``.
"""

from __future__ import annotations

import asyncio
import math
import random
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from serf_tpu import codec
from serf_tpu.host import messages as sm
from serf_tpu.host.admission import PeerPacer
from serf_tpu.host.broadcast import Broadcast, TransmitLimitedQueue
from serf_tpu.host.degrade import Backoff, CircuitBreaker
from serf_tpu.host.delegate import SwimDelegate
from serf_tpu.host.keyring import KeyringError, SecretKeyring
from serf_tpu.host.messages import SwimState
from serf_tpu.host.transport import Transport
from serf_tpu.host import wire
from serf_tpu.obs import flight, lifecycle
from serf_tpu.obs.trace import span
from serf_tpu.options import MemberlistOptions
from serf_tpu.types.member import Node
from serf_tpu.types.messages import encode_message_batch
from serf_tpu.utils import metrics

from serf_tpu.utils.logging import get_logger
from serf_tpu.utils.tasks import log_task_exception, spawn_logged

log = get_logger("memberlist")

# Version-range constants live beside the wire format (DEFAULT_VSN) in
# messages.py — a leaf module options.py can import without a cycle.
from serf_tpu.host.messages import (  # noqa: F401 - re-exported API
    DELEGATE_VERSION_MAX,
    DELEGATE_VERSION_MIN,
    PROTOCOL_VERSION_MAX,
    PROTOCOL_VERSION_MIN,
)


class VersionError(Exception):
    """A peer speaks an incompatible protocol/delegate version."""


def vsn_mismatch(vsn) -> Optional[str]:
    """Why ``vsn`` ([pmin, pmax, pcur, dmin, dmax, dcur]) cannot interop
    with us — or None if it can.  Compatibility = the ranges intersect
    AND the peer's CURRENT versions fall inside our supported ranges.

    The current-version containment is DELIBERATELY stricter than pure
    range intersection (ADVICE r4): peers encode their wire traffic at
    their *current* version and this implementation has no
    downgrade-negotiation step, so a peer whose cur is outside our
    supported range would send frames we cannot decode even though some
    lower version is mutually supported.  If a future version bump adds
    down-negotiation (advertise-and-agree before the alive gate), relax
    the pcur/dcur checks to range-intersection-only at the same time."""
    pmin, pmax, pcur, dmin, dmax, dcur = vsn
    if pmin > PROTOCOL_VERSION_MAX or pmax < PROTOCOL_VERSION_MIN:
        return (f"protocol range [{pmin}, {pmax}] does not intersect our "
                f"supported [{PROTOCOL_VERSION_MIN}, {PROTOCOL_VERSION_MAX}]")
    if not PROTOCOL_VERSION_MIN <= pcur <= PROTOCOL_VERSION_MAX:
        return (f"speaks protocol v{pcur}, outside our supported "
                f"[{PROTOCOL_VERSION_MIN}, {PROTOCOL_VERSION_MAX}]")
    if dmin > DELEGATE_VERSION_MAX or dmax < DELEGATE_VERSION_MIN:
        return (f"delegate range [{dmin}, {dmax}] does not intersect our "
                f"supported [{DELEGATE_VERSION_MIN}, {DELEGATE_VERSION_MAX}]")
    if not DELEGATE_VERSION_MIN <= dcur <= DELEGATE_VERSION_MAX:
        return (f"delegate v{dcur}, outside our supported "
                f"[{DELEGATE_VERSION_MIN}, {DELEGATE_VERSION_MAX}]")
    return None


@dataclass
class NodeState:
    node: Node
    incarnation: int = 0
    state: SwimState = SwimState.ALIVE
    meta: bytes = b""
    vsn: tuple = sm.DEFAULT_VSN
    state_change: float = field(default_factory=time.monotonic)

    @property
    def id(self) -> str:
        return self.node.id

    @property
    def addr(self):
        return self.node.addr


class _Awareness:
    """Lifeguard local-health multiplier (NSA): degrade our own probe
    timeouts when we are likely the slow one."""

    def __init__(self, max_mult: int):
        self.max = max(1, max_mult)
        self.score = 0

    def apply_delta(self, delta: int) -> None:
        self.score = min(self.max - 1, max(0, self.score + delta))

    def scale(self, timeout: float) -> float:
        return timeout * (self.score + 1)


class _Suspicion:
    """Suspicion timer whose deadline shrinks as independent confirmations
    arrive (Lifeguard)."""

    def __init__(self, k: int, min_t: float, max_t: float, from_node: str):
        self.k = max(1, k)
        self.min_t = min_t
        self.max_t = max_t
        # the original accuser is remembered for dedup but is NOT an
        # *independent* confirmation: the timer starts at max_t
        self.confirmations = {from_node}
        self.start = time.monotonic()

    def confirm(self, from_node: str) -> bool:
        if from_node in self.confirmations:
            return False
        self.confirmations.add(from_node)
        return True

    def deadline(self) -> float:
        c = len(self.confirmations) - 1  # independent confirmations only
        frac = math.log(c + 1) / math.log(self.k + 1)
        timeout = max(self.min_t, self.max_t - (self.max_t - self.min_t) * frac)
        return self.start + timeout


class Memberlist:
    def __init__(
        self,
        transport: Transport,
        opts: MemberlistOptions,
        node_id: str,
        delegate: Optional[SwimDelegate] = None,
        keyring: Optional[SecretKeyring] = None,
        rng: Optional[random.Random] = None,
    ):
        self.transport = transport
        self.opts = opts
        self.delegate = delegate or SwimDelegate()
        self._keyring = keyring
        self.rng = rng or random.Random()
        opts.validate()

        self.local = Node(node_id, transport.local_addr)
        self._vsn = (PROTOCOL_VERSION_MIN, PROTOCOL_VERSION_MAX,
                     opts.protocol_version,
                     DELEGATE_VERSION_MIN, DELEGATE_VERSION_MAX,
                     opts.delegate_version)
        self._incarnation = 1
        self._nodes: Dict[str, NodeState] = {}
        self._probe_order: List[str] = []
        self._probe_index = 0
        self._seq = 0
        self._ack_futures: Dict[int, asyncio.Future] = {}
        self._nack_counts: Dict[int, int] = {}
        self._suspicions: Dict[str, _Suspicion] = {}
        self._probing: set = set()  # node ids with an in-flight probe
        self._awareness = _Awareness(opts.awareness_max_multiplier)
        # graceful degradation (host/degrade.py): dead/unreachable peers
        # must not eat a full dial timeout on every stream operation
        self._breaker = CircuitBreaker(
            opts.breaker_threshold, opts.breaker_cooldown,
            labels=opts.metric_labels, node=node_id)
        # the SWIM queue carries MEMBERSHIP FACTS (alive/suspect/dead):
        # the top of the shedding priority order — never byte-shed, even
        # under an overload storm (losing a death story is a correctness
        # hazard; every other queue gives way first)
        self.broadcasts = TransmitLimitedQueue(
            opts.retransmit_mult, lambda: max(1, self.num_online_members()),
            sheddable=False,
        )
        # per-peer send pacing for the USER plane only (host/admission.py,
        # enforced in send()): loss-based — a paced-out packet is dropped
        # and counted rather than queued without bound.  The SWIM packet
        # plane is never paced (membership is never shed).
        self._pacer = (PeerPacer(opts.peer_send_rate, opts.peer_send_burst)
                       if opts.peer_send_rate > 0 else None)
        self._leaving = False
        self._shutdown = False
        #: receive timestamp of the packet currently being handled
        #: (lifecycle ledger `transport` stage anchor)
        self._pkt_t0 = time.monotonic()
        self._tasks: List[asyncio.Task] = []
        self._bg: set = set()  # dynamic tasks (suspicion timers, stream serves)
        self._started = False

    def _spawn(self, coro, name: str) -> asyncio.Task:
        """Dynamic background task: retained in ``_bg``, exception-logged
        on death (serflint async-fire-forget contract)."""
        return spawn_logged(coro, name, registry=self._bg)

    def _track(self, coro, name: str) -> asyncio.Task:
        """Protocol-loop task: retained in ``_tasks`` for shutdown,
        exception-logged the moment it dies — a dead probe loop is a
        loud log line, not a cluster that silently stops detecting."""
        t = asyncio.create_task(coro, name=name)
        t.add_done_callback(log_task_exception)
        self._tasks.append(t)
        return t

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    async def start(self) -> None:
        """Set the local node alive and spin up the protocol loops."""
        meta = self.delegate.node_meta(512)
        me = NodeState(self.local, self._incarnation, SwimState.ALIVE, meta,
                       vsn=self._vsn)
        self._nodes[self.local.id] = me
        self._probe_order.append(self.local.id)
        self.delegate.notify_join(me)
        self._tasks = []
        self._track(self._packet_loop(), f"ml-packet-{self.local.id}")
        self._track(self._stream_loop(), f"ml-stream-{self.local.id}")
        self._track(self._probe_loop(), f"ml-probe-{self.local.id}")
        self._track(self._gossip_loop(), f"ml-gossip-{self.local.id}")
        if self.opts.push_pull_interval > 0:
            self._track(self._push_pull_loop(), f"ml-pp-{self.local.id}")
        self._started = True

    async def shutdown(self) -> None:
        if self._shutdown:
            return
        self._shutdown = True
        for t in [*self._tasks, *self._bg]:
            t.cancel()
        for t in [*self._tasks, *list(self._bg)]:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        await self.transport.shutdown()

    async def leave(self, timeout: float) -> None:
        """Broadcast a voluntary leave (Dead with from==self) and wait for it
        to be gossiped out (or ``timeout``)."""
        self._leaving = True
        me = self._nodes.get(self.local.id)
        if me is None:
            return
        me.state = SwimState.LEFT
        me.state_change = time.monotonic()
        done = asyncio.Event()
        msg = sm.Dead(me.incarnation, self.local.id, self.local.id)
        self._queue_broadcast(sm.encode_swim(msg), name=self.local.id, notify=done)
        if self._any_alive_peer():
            try:
                await asyncio.wait_for(done.wait(), timeout)
            except asyncio.TimeoutError:
                log.warning("leave broadcast not fully disseminated before timeout")

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    def local_id(self) -> str:
        return self.local.id

    def local_node(self) -> Node:
        return self.local

    def local_state(self) -> Optional[NodeState]:
        return self._nodes.get(self.local.id)

    def node_state(self, node_id: str) -> Optional[NodeState]:
        """This node's SWIM-level record of ``node_id`` (None if unknown)."""
        return self._nodes.get(node_id)

    def members(self) -> List[NodeState]:
        return list(self._nodes.values())

    def online_members(self) -> List[NodeState]:
        return [n for n in self._nodes.values() if n.state == SwimState.ALIVE]

    def num_online_members(self) -> int:
        return sum(1 for n in self._nodes.values() if n.state == SwimState.ALIVE)

    def advertise_node(self) -> Node:
        """The (id, address) this node announces to peers (reference
        memberlist ``advertise_node``)."""
        return self.local

    def advertise_address(self):
        return self.transport.local_addr

    def health_score(self) -> int:
        return self._awareness.score

    def keyring(self) -> Optional[SecretKeyring]:
        return self._keyring

    def encryption_enabled(self) -> bool:
        return self._keyring is not None

    async def join(self, addr) -> None:
        """Push/pull state sync with a seed node (reference join path,
        SURVEY.md §3.2).  The target goes through the transport's resolver
        first, so joins accept unresolved names (reference
        MaybeResolvedAddress).

        Bounded retry with jittered backoff (``opts.join_retries``): a
        seed node mid-restart or a lossy path must not fail the whole
        join on one refused dial.  Version incompatibility never
        retries — the peer will not become compatible by waiting."""
        addr = await self.transport.resolve(addr)
        backoff = Backoff(self.opts.dial_backoff_base,
                          self.opts.dial_backoff_max, rng=self.rng)
        last: Optional[Exception] = None
        for attempt in range(1 + self.opts.join_retries):
            if attempt:
                metrics.incr("serf.degraded.join_retry", 1,
                             self.opts.metric_labels)
                flight.record("dial-retry", node=self.local.id,
                              target=str(addr), op="join", attempt=attempt)
                await asyncio.sleep(backoff.next_delay())
            try:
                await self._push_pull_with(addr, join=True)
                return
            except VersionError:
                raise
            except (ConnectionError, TimeoutError, OSError) as e:
                last = e
            if self._shutdown:
                break
        raise last if last is not None else ConnectionError(
            f"join {addr!r} failed")

    async def join_many(self, addrs: Sequence) -> Tuple[int, List[Exception]]:
        ok, errs = 0, []
        for a in addrs:
            try:
                await self.join(a)
                ok += 1
            except Exception as e:  # noqa: BLE001 - joins best-effort by design
                errs.append(e)
        return ok, errs

    async def send(self, addr, buf: bytes) -> None:
        """Unreliable user-plane send (serf query responses/acks/relays).

        Per-peer pacing applies HERE and only here: this is the user
        fan-out seam.  The SWIM packet plane (_send_packet: probes,
        acks, gossip) is membership traffic — top of the shedding
        priority order, never paced — or a gossip burst to one peer
        could starve the very probe ack that keeps it ALIVE."""
        if self._pacer is not None and not self._pacer.admit(addr):
            # over-rate user packets to one destination are shed at the
            # seam (UDP semantics — query relays and gossip redundancy
            # cover the loss)
            metrics.incr("serf.overload.paced_dropped", 1,
                         self.opts.metric_labels)
            flight.record("paced-drop", node=self.local.id, dest=str(addr))
            return
        await self._send_packet(addr, sm.encode_swim(sm.UserMsg(buf)))

    async def update_node(self, timeout: float) -> None:
        """Re-advertise local meta (after a tag change): broadcast a fresh
        alive with a bumped incarnation."""
        me = self._nodes[self.local.id]
        self._incarnation += 1
        me.incarnation = self._incarnation
        me.meta = self.delegate.node_meta(512)
        # the local delegate view must see the change too (memberlist's
        # setAlive->aliveNode path notifies for the local node as well) —
        # without this the tag-setter's OWN member table keeps stale tags
        self.delegate.notify_update(me)
        msg = sm.Alive(me.incarnation, self.local, me.meta, self._vsn)
        done = asyncio.Event()
        self._queue_broadcast(sm.encode_swim(msg), name=self.local.id, notify=done)
        if self._any_alive_peer():
            try:
                await asyncio.wait_for(done.wait(), timeout)
            except asyncio.TimeoutError:
                pass

    # ------------------------------------------------------------------
    # wire helpers
    # ------------------------------------------------------------------

    async def _send_packet(self, addr, buf: bytes) -> None:
        # NO pacing here: this is the SWIM membership plane (probes,
        # acks, gossip) — never shed (see send() for the paced seam)
        buf = self._encode_wire(buf)
        metrics.observe("memberlist.packet.sent", len(buf), self.opts.metric_labels)
        await self.transport.send_packet(addr, buf)

    def _encode_wire(self, buf: bytes) -> bytes:
        """Outbound packet pipeline: compress -> checksum -> encrypt
        (capability parity with the reference's compression/checksum/
        encryption transport features, SURVEY.md §2.9; algorithm
        registries in ``host/wire.py``)."""
        with span("wire.encode", node=self.local.id, bytes=len(buf)):
            buf = wire.encode_wire(buf, self.opts.compression,
                                   self.opts.checksum)
            if self._keyring is not None:
                buf = self._keyring.encrypt(buf)
            return buf

    def _decode_wire(self, buf: bytes) -> Optional[bytes]:
        """Inbound pipeline: decrypt -> verify checksum -> decompress.
        Any failure drops the packet (UDP semantics), with a metric and a
        flight-recorder entry naming the failed stage."""
        with span("wire.decode", node=self.local.id, bytes=len(buf)):
            if self._keyring is not None:
                try:
                    buf = self._keyring.decrypt(buf)
                except KeyringError:
                    metrics.incr("memberlist.packet.decrypt_failed", 1,
                                 self.opts.metric_labels)
                    flight.record("packet-dropped", node=self.local.id,
                                  stage="decrypt", bytes=len(buf))
                    return None
            try:
                return wire.decode_wire(buf, self.opts.compression,
                                        self.opts.checksum)
            except wire.WireError as e:
                metrics.incr(f"memberlist.packet.{e.stage}_failed", 1,
                             self.opts.metric_labels)
                flight.record("packet-dropped", node=self.local.id,
                              stage=e.stage, bytes=len(buf))
                return None

    def _wire_overhead(self) -> int:
        """Worst-case bytes _encode_wire adds (marker + checksum + expansion
        headroom + AES-GCM version/nonce/tag) — reserved out of the UDP
        packet budget so encoded packets stay UDP-safe."""
        overhead = wire.wire_overhead(self.opts.compression,
                                      self.opts.checksum)
        if self._keyring is not None:
            overhead += 1 + 12 + 16             # version + nonce + GCM tag
        return overhead

    def _queue_broadcast(self, buf: bytes, name: Optional[str] = None,
                         notify: Optional[asyncio.Event] = None) -> None:
        self.broadcasts.queue_broadcast(Broadcast(buf, name=name, notify=notify))

    def _any_alive_peer(self) -> bool:
        return any(
            n.state == SwimState.ALIVE and n.id != self.local.id
            for n in self._nodes.values()
        )

    def _next_seq(self) -> int:
        self._seq += 1
        return self._seq

    # ------------------------------------------------------------------
    # protocol loops
    # ------------------------------------------------------------------

    async def _packet_loop(self) -> None:
        while not self._shutdown:
            try:
                src, raw = await self.transport.recv_packet()
            except ConnectionError:
                return
            # lifecycle ledger: remember when THIS packet hit the host,
            # so a sampled serf message it carries can attribute wire
            # decode + SWIM decode to its `transport` stage.  Kept per
            # memberlist (not on the shared ledger) because co-located
            # loopback nodes interleave packet loops at await points.
            self._pkt_t0 = time.monotonic()
            buf = self._decode_wire(raw)
            if buf is None:
                continue
            metrics.observe("memberlist.packet.received", len(buf), self.opts.metric_labels)
            try:
                msg = sm.decode_swim(buf)
            except codec.DecodeError as e:
                log.debug("dropping undecodable packet from %r: %s", src, e)
                continue
            except Exception:  # noqa: BLE001 - a decode bug must not kill the loop
                log.exception("decode_swim failed on packet from %r", src)
                continue
            for m in msg if isinstance(msg, list) else [msg]:
                try:
                    await self._handle_message(src, m)
                except Exception:  # noqa: BLE001 - one bad message must not kill the loop
                    log.exception("error handling %s from %r", type(m).__name__, src)

    async def _handle_message(self, src, m) -> None:
        if isinstance(m, sm.Ping):
            await self._handle_ping(src, m)
        elif isinstance(m, sm.IndirectPing):
            # spawned: this handler waits for an ack that arrives through the
            # same packet loop — awaiting it inline would self-deadlock
            self._spawn(self._handle_indirect_ping(src, m),
                        name=f"ml-indirect-{self.local.id}")
        elif isinstance(m, sm.Ack):
            self._handle_ack(m)
        elif isinstance(m, sm.Nack):
            self._handle_nack(m)
        elif isinstance(m, sm.Suspect):
            self._handle_suspect(m)
        elif isinstance(m, sm.Alive):
            self._handle_alive(m)
        elif isinstance(m, sm.Dead):
            self._handle_dead(m)
        elif isinstance(m, sm.UserMsg):
            # note the packet timestamp right before the synchronous
            # serf dispatch chain consumes it (no awaits in between)
            lifecycle.global_ledger().note_packet(self._pkt_t0)
            self.delegate.notify_message(m.payload)
        else:
            log.debug("unhandled packet-plane message %s", type(m).__name__)

    async def _handle_ping(self, src, p: sm.Ping) -> None:
        if p.target and p.target != self.local.id:
            log.warning("misdirected ping for %r arrived at %r", p.target, self.local.id)
            return
        payload = self.delegate.ack_payload()
        await self._send_packet(src, sm.encode_swim(sm.Ack(p.seq, payload)))

    async def _handle_indirect_ping(self, src, ip: sm.IndirectPing) -> None:
        """Probe ``target`` on behalf of ``source``; relay ack or nack."""
        seq = self._next_seq()
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._ack_futures[seq] = fut
        await self._send_packet(
            ip.target.addr, sm.encode_swim(sm.Ping(seq, self.local, ip.target.id))
        )
        try:
            await asyncio.wait_for(fut, self.opts.probe_timeout)
            await self._send_packet(src, sm.encode_swim(sm.Ack(ip.seq)))
        except asyncio.TimeoutError:
            await self._send_packet(src, sm.encode_swim(sm.Nack(ip.seq)))
        finally:
            self._ack_futures.pop(seq, None)

    def _handle_ack(self, a: sm.Ack) -> None:
        fut = self._ack_futures.get(a.seq)
        if fut is not None and not fut.done():
            fut.set_result((time.monotonic(), a.payload))

    def _handle_nack(self, n: sm.Nack) -> None:
        # only track nacks for probes still in flight (no unbounded growth)
        if n.seq in self._ack_futures:
            self._nack_counts[n.seq] = self._nack_counts.get(n.seq, 0) + 1

    # --- state transitions -------------------------------------------------

    def _refute(self, incarnation: int) -> None:
        """Someone claims we are suspect/dead: bump past their incarnation and
        broadcast alive.  Lifeguard: being refuted degrades our own health."""
        me = self._nodes[self.local.id]
        self._incarnation = max(self._incarnation, incarnation) + 1
        me.incarnation = self._incarnation
        self._awareness.apply_delta(1)
        msg = sm.Alive(me.incarnation, self.local, me.meta, self._vsn)
        self._queue_broadcast(sm.encode_swim(msg), name=self.local.id)

    def _handle_alive(self, a: sm.Alive) -> None:
        if self._leaving and a.node.id == self.local.id:
            return
        err = self.delegate.notify_alive(a)
        if err is not None:
            log.debug("alive for %r vetoed: %s", a.node.id, err)
            return
        mismatch = vsn_mismatch(a.vsn)
        if mismatch is not None:
            # version gate (reference version.rs:9-43 / memberlist Vsn
            # handshake): never admit a peer we cannot interop with
            log.error("refusing node %r: %s", a.node.id, mismatch)
            metrics.incr("memberlist.node.version_rejected", 1,
                         self.opts.metric_labels)
            return
        ns = self._nodes.get(a.node.id)
        if ns is None:
            ns = NodeState(a.node, a.incarnation, SwimState.ALIVE, a.meta,
                           vsn=a.vsn)
            self._nodes[a.node.id] = ns
            # insert at a random probe position so new nodes get probed fairly
            idx = self.rng.randint(0, len(self._probe_order))
            self._probe_order.insert(idx, a.node.id)
            self.delegate.notify_join(ns)
            self._queue_broadcast(sm.encode_swim(a), name=a.node.id)
            metrics.incr("memberlist.node.join", 1, self.opts.metric_labels)
            return
        # address conflict: same id, different address
        if ns.addr != a.node.addr:
            self.delegate.notify_conflict(ns, a)
            if a.node.id == self.local.id and a.incarnation >= self._incarnation:
                # it is about us: refute with higher incarnation; the
                # delegate's conflict resolution decides who survives
                self._refute(a.incarnation)
            return
        if a.node.id == self.local.id:
            # a rebroadcast of our own alive: refute only if it beats us
            if a.incarnation > self._incarnation:
                self._refute(a.incarnation)
            return
        if a.incarnation <= ns.incarnation and ns.state == SwimState.ALIVE:
            if a.incarnation == ns.incarnation and a.meta != ns.meta:
                ns.meta = a.meta
                self.delegate.notify_update(ns)
            return
        if a.incarnation < ns.incarnation:
            return
        # a.incarnation > ns.incarnation, or equal while suspect/dead requires >
        if a.incarnation == ns.incarnation and ns.state != SwimState.ALIVE:
            return  # alive does not clear suspicion at equal incarnation
        meta_changed = a.meta != ns.meta
        was_gone = ns.state in (SwimState.DEAD, SwimState.LEFT)
        ns.incarnation = a.incarnation
        ns.meta = a.meta
        ns.vsn = a.vsn
        if ns.state != SwimState.ALIVE:
            ns.state = SwimState.ALIVE
            ns.state_change = time.monotonic()
            self._suspicions.pop(ns.id, None)
        if was_gone:
            flight.record("swim-state", node=self.local.id, member=ns.id,
                          state="ALIVE", incarnation=ns.incarnation)
            self.delegate.notify_join(ns)
            metrics.incr("memberlist.node.join", 1, self.opts.metric_labels)
        elif meta_changed:
            self.delegate.notify_update(ns)
        self._queue_broadcast(sm.encode_swim(a), name=a.node.id)

    def _handle_suspect(self, s: sm.Suspect) -> None:
        ns = self._nodes.get(s.node)
        if ns is None or s.incarnation < ns.incarnation:
            return
        if s.node == self.local.id:
            if not self._leaving:
                self._refute(s.incarnation)
            return
        if ns.state == SwimState.SUSPECT:
            susp = self._suspicions.get(s.node)
            if susp is not None and susp.confirm(s.from_node):
                self._queue_broadcast(sm.encode_swim(s), name=s.node)
            return
        if ns.state != SwimState.ALIVE:
            return
        ns.state = SwimState.SUSPECT
        ns.state_change = time.monotonic()
        self._start_suspicion(ns, s.incarnation, s.from_node)
        self._queue_broadcast(sm.encode_swim(s), name=s.node)
        metrics.incr("memberlist.node.suspect", 1, self.opts.metric_labels)
        flight.record("swim-state", node=self.local.id, member=s.node,
                      state="SUSPECT", accuser=s.from_node,
                      incarnation=s.incarnation)

    def _start_suspicion(self, ns: NodeState, incarnation: int, from_node: str) -> None:
        n = max(1, self.num_online_members())
        min_t = self.opts.suspicion_mult * max(1.0, math.log10(max(n, 1) + 1)) * self.opts.probe_interval
        max_t = self.opts.suspicion_max_timeout_mult * min_t
        susp = _Suspicion(self.opts.indirect_checks, min_t, max_t, from_node)
        self._suspicions[ns.id] = susp
        self._spawn(self._suspicion_timer(ns.id, incarnation),
                    name=f"ml-susp-{self.local.id}-{ns.id}")

    async def _suspicion_timer(self, node_id: str, incarnation: int) -> None:
        while not self._shutdown:
            susp = self._suspicions.get(node_id)
            ns = self._nodes.get(node_id)
            if susp is None or ns is None or ns.state != SwimState.SUSPECT:
                return
            now = time.monotonic()
            deadline = susp.deadline()
            if now >= deadline:
                self._suspicions.pop(node_id, None)
                self._mark_dead(ns, max(incarnation, ns.incarnation), self.local.id)
                return
            await asyncio.sleep(min(deadline - now, self.opts.probe_interval))

    def _mark_dead(self, ns: NodeState, incarnation: int, from_node: str) -> None:
        d = sm.Dead(incarnation, ns.id, from_node)
        self._handle_dead(d)

    def _handle_dead(self, d: sm.Dead) -> None:
        ns = self._nodes.get(d.node)
        if ns is None:
            return
        is_leave = d.from_node == d.node
        # Stale-incarnation dead/leave messages are ignored unconditionally
        # (matching reference memberlist): a leave exemption here would let an
        # old leave still circulating in gossip re-mark a rejoined/refuted
        # node LEFT despite its higher incarnation, causing repeated flapping.
        if d.incarnation < ns.incarnation:
            return
        if d.node == self.local.id:
            if not self._leaving:
                self._refute(d.incarnation)
            return
        if ns.state in (SwimState.DEAD, SwimState.LEFT):
            return
        ns.incarnation = max(ns.incarnation, d.incarnation)
        ns.state = SwimState.LEFT if is_leave else SwimState.DEAD
        ns.state_change = time.monotonic()
        self._suspicions.pop(d.node, None)
        self._queue_broadcast(sm.encode_swim(d), name=d.node)
        flight.record("swim-state", node=self.local.id, member=d.node,
                      state=ns.state.name, from_node=d.from_node,
                      incarnation=d.incarnation)
        self.delegate.notify_leave(ns)
        metrics.incr("memberlist.node.dead", 1, self.opts.metric_labels)

    # --- probe / gossip / push-pull loops ---------------------------------

    async def _probe_loop(self) -> None:
        while not self._shutdown:
            await asyncio.sleep(self.opts.probe_interval)
            try:
                target = self._next_probe_target()
                if target is not None and target.id not in self._probing:
                    # run the probe concurrently so an awareness-scaled slow
                    # probe never stalls detection of other members
                    self._probing.add(target.id)
                    t = self._spawn(self._probe_node(target),
                                    name=f"ml-probe1-{self.local.id}-{target.id}")
                    t.add_done_callback(
                        lambda _t, nid=target.id: self._probing.discard(nid))
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001
                log.exception("probe iteration failed")

    def _next_probe_target(self) -> Optional[NodeState]:
        """Round-robin over a shuffled order, reshuffling each full pass
        (SWIM's bounded-detection-time trick)."""
        n = len(self._probe_order)
        for _ in range(n):
            if self._probe_index >= len(self._probe_order):
                self.rng.shuffle(self._probe_order)
                self._probe_index = 0
            node_id = self._probe_order[self._probe_index]
            self._probe_index += 1
            ns = self._nodes.get(node_id)
            if ns is None:
                self._probe_order.remove(node_id)
                self._probe_index = max(0, self._probe_index - 1)
                continue
            if ns.id == self.local.id or ns.state in (SwimState.DEAD, SwimState.LEFT):
                continue
            return ns
        return None

    async def _probe_node(self, ns: NodeState) -> None:
        with span("swim.probe", node=self.local.id, target=ns.id) as sp:
            await self._probe_node_inner(ns, sp)

    async def _probe_node_inner(self, ns: NodeState, sp) -> None:
        seq = self._next_seq()
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._ack_futures[seq] = fut
        sent = time.monotonic()
        try:
            await self._send_packet(ns.addr, sm.encode_swim(sm.Ping(seq, self.local, ns.id)))
            timeout = self._awareness.scale(self.opts.probe_timeout)
            try:
                _, payload = await asyncio.wait_for(fut, timeout)
                rtt = time.monotonic() - sent
                self._awareness.apply_delta(-1)
                sp.attrs["outcome"] = "ack"
                sp.attrs["rtt_ms"] = round(rtt * 1e3, 3)
                self.delegate.notify_ping_complete(ns, rtt, payload)
                return
            except asyncio.TimeoutError:
                pass
            # indirect probes through k random alive peers
            peers = [
                p for p in self._nodes.values()
                if p.state == SwimState.ALIVE and p.id not in (self.local.id, ns.id)
            ]
            self.rng.shuffle(peers)
            relays = peers[: self.opts.indirect_checks]
            if relays:
                seq2 = self._next_seq()
                fut2: asyncio.Future = asyncio.get_running_loop().create_future()
                self._ack_futures[seq2] = fut2
                ip = sm.IndirectPing(seq2, self.local, ns.node)
                for r in relays:
                    await self._send_packet(r.addr, sm.encode_swim(ip))
                nacks = 0
                try:
                    await asyncio.wait_for(fut2, self._awareness.scale(self.opts.probe_timeout) * 2)
                    self._awareness.apply_delta(-1)
                    sp.attrs["outcome"] = "indirect-ack"
                    return
                except asyncio.TimeoutError:
                    pass
                finally:
                    self._ack_futures.pop(seq2, None)
                    nacks = self._nack_counts.pop(seq2, 0)
                # Lifeguard: missing nacks mean *we* may be degraded
                missed_nacks = len(relays) - nacks
                self._awareness.apply_delta(1 + max(0, missed_nacks))
            else:
                self._awareness.apply_delta(1)
            if ns.state == SwimState.ALIVE:
                metrics.incr("memberlist.probe.failed", 1, self.opts.metric_labels)
                sp.attrs["outcome"] = "failed"
                flight.record("probe-failed", node=self.local.id,
                              target=ns.id, relays=len(relays))
                s = sm.Suspect(ns.incarnation, ns.id, self.local.id)
                self._handle_suspect(s)
        finally:
            self._ack_futures.pop(seq, None)

    async def _gossip_loop(self) -> None:
        while not self._shutdown:
            await asyncio.sleep(self.opts.gossip_interval)
            try:
                await self._gossip_once()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001
                log.exception("gossip tick failed")

    async def _gossip_once(self) -> None:
        with span("swim.gossip", node=self.local.id):
            await self._gossip_once_inner()

    async def _gossip_once_inner(self) -> None:
        # gossip to alive + suspect nodes, and occasionally to dead ones
        # (gives partitioned/dead nodes a chance to refute and recover)
        candidates = [
            n for n in self._nodes.values()
            if n.id != self.local.id and (
                n.state in (SwimState.ALIVE, SwimState.SUSPECT)
                or (n.state == SwimState.DEAD
                    and time.monotonic() - n.state_change < 10 * self.opts.probe_interval)
            )
        ]
        if not candidates:
            return
        self.rng.shuffle(candidates)
        budget = self.transport.max_packet_size - self._wire_overhead()
        # Drain once per tick and send the same payload to all k targets —
        # one queue "transmit" fans out to gossip_nodes deliveries, matching
        # memberlist's dissemination rate.
        parts = self.broadcasts.get_broadcasts(4, budget)
        used = sum(len(p) + 4 for p in parts)
        extra = self.delegate.broadcast_messages(6, budget - used)
        if len(extra) > 1:
            # batched codec (host-plane throughput rebuild): ALL queued
            # serf broadcasts ride ONE UserMsg/BATCH envelope — one SWIM
            # frame + one wire encode + one sendto per target amortize
            # over the whole drain (the 6-byte-per-message budget charge
            # above stays conservative: batch framing costs 1-2 B/part)
            parts.append(sm.encode_swim(sm.UserMsg(
                encode_message_batch(extra))))
            metrics.incr("serf.codec.batch", 1, self.opts.metric_labels)
            metrics.incr("serf.codec.batch-messages", len(extra),
                         self.opts.metric_labels)
        elif extra:
            parts.append(sm.encode_swim(sm.UserMsg(extra[0])))
        if not parts:
            return
        packet = sm.encode_compound(parts) if len(parts) > 1 else parts[0]
        targets = candidates[: self.opts.gossip_nodes]
        if (self._keyring is not None and len(targets) > 1
                and self.opts.gossip_encrypt_amortize):
            # one-encrypt-per-fanout (ISSUE 20): the same payload goes to
            # every target, so run the wire pipeline (compress/checksum/
            # encrypt — ONE fresh-nonce AEAD seal) once and fan the
            # pre-sealed bytes out, saving k-1 AEAD calls per tick
            buf = self._encode_wire(packet)
            metrics.incr("serf.keyring.encrypt_amortized",
                         len(targets) - 1, self.opts.metric_labels)
            for target in targets:
                metrics.observe("memberlist.packet.sent", len(buf),
                                self.opts.metric_labels)
                await self.transport.send_packet(target.addr, buf)
        else:
            for target in targets:
                await self._send_packet(target.addr, packet)

    async def _push_pull_loop(self) -> None:
        while not self._shutdown:
            await asyncio.sleep(self.opts.push_pull_interval)
            peers = [n for n in self.online_members() if n.id != self.local.id]
            if not peers:
                continue
            peer = self.rng.choice(peers)
            if self._breaker.is_open(str(peer.addr)):
                # degraded peer: skip this tick instead of burning a dial
                # timeout (the breaker admits a half-open trial after its
                # cooldown, so recovery is still discovered)
                metrics.incr("serf.degraded.pushpull_skipped", 1,
                             self.opts.metric_labels)
                continue
            try:
                await self._push_pull_with(peer.addr, join=False)
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001
                log.debug("periodic push/pull with %r failed: %s", peer.id, e)

    def _local_push_states(self) -> List[sm.PushNodeState]:
        return [
            sm.PushNodeState(n.node, n.incarnation, n.state, n.meta, n.vsn)
            for n in self._nodes.values()
        ]

    async def _push_pull_with(self, addr, join: bool) -> None:
        with span("swim.push-pull", node=self.local.id, join=join,
                  target=str(addr)):
            await self._push_pull_with_inner(addr, join)

    async def _dial_stream(self, addr):
        """Stream dial with jittered exponential backoff and the per-peer
        circuit breaker: an OPEN circuit fast-fails (no timeout burned);
        transient refusals retry up to ``opts.dial_retries`` times.

        The dial alone never marks the circuit HEALTHY — a half-dead
        peer can accept connections and then fail every sync, and a
        dial-time reset would erase the mid-sync failure count forever.
        The caller reports the outcome of the WHOLE operation
        (``_push_pull_with_inner``); a failed dial still counts against
        the circuit here."""
        key = str(addr)
        if not self._breaker.allow(key):
            raise ConnectionError(f"circuit open for {addr!r}")
        backoff = Backoff(self.opts.dial_backoff_base,
                          self.opts.dial_backoff_max, rng=self.rng)
        last: Optional[Exception] = None
        for attempt in range(1 + self.opts.dial_retries):
            if attempt:
                if self._breaker.is_open(key):
                    # our own failures just opened (or re-opened) the
                    # circuit: stop burning timeouts mid-loop
                    break
                metrics.incr("serf.degraded.dial_retry", 1,
                             self.opts.metric_labels)
                flight.record("dial-retry", node=self.local.id,
                              target=key, op="dial", attempt=attempt)
                await asyncio.sleep(backoff.next_delay())
            try:
                return await self.transport.dial(
                    addr, timeout=self.opts.timeout)
            except (ConnectionError, TimeoutError, OSError) as e:
                last = e
                self._breaker.failure(key)
            except BaseException:
                # cancellation/unexpected errors judge neither the peer
                # nor the circuit — but an abandoned half-open trial
                # must be released or the peer is wedged out forever
                self._breaker.release(key)
                raise
            if self._shutdown:
                break
        raise last if last is not None else ConnectionError(
            f"dial {addr!r} failed")

    async def _push_pull_with_inner(self, addr, join: bool) -> None:
        key = str(addr)
        stream = await self._dial_stream(addr)
        try:
            out = sm.PushPull(join, tuple(self._local_push_states()),
                              self.delegate.local_state(join))
            await stream.send_frame(self._encode_wire(sm.encode_swim(out)))
            reply_raw = await stream.recv_frame(self.opts.timeout)
            reply = self._decode_stream_msg(reply_raw)
            if isinstance(reply, sm.ErrorResp):
                # the server refused before replying (today: version
                # incompatibility) — surface its reason directly; a
                # refusal is still a LIVE, responsive peer
                self._breaker.success(key)
                raise VersionError(f"refused by {addr}: {reply.error}")
            if not isinstance(reply, sm.PushPull):
                raise codec.DecodeError("expected push/pull reply")
            self._merge_remote(reply, join)
            # the WHOLE sync succeeded — only now is the peer healthy
            self._breaker.success(key)
        except (ConnectionError, TimeoutError):
            # a peer dying MID-sync counts against its circuit too — the
            # dial succeeded, but the sync did not
            self._breaker.failure(key)
            raise
        except VersionError:
            # incompatible but alive; a no-op after the ErrorResp path's
            # success(), and frees any half-open trial on the
            # _merge_remote verification path
            self._breaker.release(key)
            raise
        except (codec.DecodeError, KeyringError) as e:
            # garbled peer: quarantined, and an abandoned half-open
            # trial must not wedge the circuit in the half-open state
            self._breaker.release(key)
            self._quarantine_frame(addr, e)
            raise
        except BaseException:
            # cancellation or an unexpected error (delegate callbacks in
            # the merge path can raise anything): the trial is abandoned,
            # not judged — release so the circuit can re-trial later
            # instead of staying wedged half-open forever
            self._breaker.release(key)
            raise
        finally:
            await stream.close()

    def _quarantine_frame(self, src, err) -> None:
        """Corrupt-frame quarantine: an undecodable stream frame is logged,
        counted and flight-recorded — never a task death, never a retry
        loop on garbage."""
        metrics.incr("serf.degraded.corrupt_frame", 1,
                     self.opts.metric_labels)
        flight.record("corrupt-frame", node=self.local.id, peer=str(src),
                      error=str(err)[:200])
        log.warning("quarantined corrupt stream frame from %r: %s", src, err)

    async def _stream_loop(self) -> None:
        while not self._shutdown:
            try:
                src, stream = await self.transport.accept()
            except ConnectionError:
                return
            self._spawn(self._serve_stream(src, stream),
                        name=f"ml-serve-{self.local.id}")

    async def _serve_stream(self, src, stream) -> None:
        try:
            raw = await stream.recv_frame(self.opts.timeout)
            msg = self._decode_stream_msg(raw)
            if isinstance(msg, sm.PushPull):
                if msg.join:
                    # refuse BEFORE replying: the joiner must not learn
                    # our state if we cannot interop with its cluster.
                    # Tell it WHY (ErrorResp) before closing — otherwise
                    # the joiner only sees a generic recv timeout and
                    # repeated joins look like network failures (ADVICE r4)
                    try:
                        self._verify_versions(msg.states)
                    except VersionError as e:
                        try:
                            await stream.send_frame(self._encode_wire(
                                sm.encode_swim(sm.ErrorResp(str(e)))))
                        except (ConnectionError, TimeoutError):
                            pass
                        raise
                out = sm.PushPull(False, tuple(self._local_push_states()),
                                  self.delegate.local_state(msg.join))
                await stream.send_frame(self._encode_wire(sm.encode_swim(out)))
                self._merge_remote(msg, msg.join, verified=True)
            elif isinstance(msg, sm.UserMsg):
                # stream-delivered serf message: the frame was received
                # + decoded just above — note that as the transport
                # anchor (begin() consumes the note, so a stale packet
                # timestamp can never backdate this message's clock)
                lifecycle.global_ledger().note_packet(time.monotonic())
                self.delegate.notify_message(msg.payload)
        except VersionError as e:
            log.warning("refusing push/pull from %r: %s", src, e)
            metrics.incr("memberlist.node.version_rejected", 1,
                         self.opts.metric_labels)
        except (codec.DecodeError, KeyringError) as e:
            self._quarantine_frame(src, e)
        except (ConnectionError, TimeoutError) as e:
            log.debug("stream from %r failed: %s", src, e)
        except Exception:  # noqa: BLE001
            log.exception("stream handler error from %r", src)
        finally:
            await stream.close()

    def _decode_stream_msg(self, raw: bytes):
        buf = self._decode_wire(raw)
        if buf is None:
            raise KeyringError("undecodable stream frame")
        return sm.decode_swim(buf)

    def _verify_versions(self, states) -> None:
        """Joining is a handshake: an incompatible peer in the remote
        state set fails the WHOLE join with a clear reason (the periodic
        anti-entropy path instead just skips such nodes in _handle_alive).
        Reference slot: version.rs:9-43."""
        for st in states:
            mismatch = vsn_mismatch(st.vsn)
            if mismatch is not None:
                raise VersionError(
                    f"cannot join: remote node {st.node.id!r} {mismatch}")

    def _merge_remote(self, pp: sm.PushPull, join: bool,
                      verified: bool = False) -> None:
        if join and not verified:
            # client path: verify the seed's reply (the server path has
            # already verified before replying — it passes verified=True)
            self._verify_versions(pp.states)
        err = self.delegate.notify_merge(pp.states)
        if err is not None:
            log.warning("push/pull merge vetoed: %s", err)
            return
        for st in pp.states:
            if st.state == SwimState.ALIVE:
                self._handle_alive(
                    sm.Alive(st.incarnation, st.node, st.meta, st.vsn))
            elif st.state in (SwimState.SUSPECT, SwimState.DEAD):
                # Remote suspect AND dead both merge as *suspect* (memberlist
                # semantics): gives a live node the chance to refute instead
                # of resurrect-then-kill churn.  Unknown nodes are skipped —
                # we never first-learn a node from its death notice.
                if st.node.id in self._nodes:
                    self._handle_suspect(sm.Suspect(st.incarnation, st.node.id, self.local.id))
            elif st.state == SwimState.LEFT:
                if st.node.id in self._nodes:
                    self._handle_dead(sm.Dead(st.incarnation, st.node.id, st.node.id))
        if pp.user_data:
            self.delegate.merge_remote_state(pp.user_data, join)
