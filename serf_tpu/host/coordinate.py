"""Vivaldi network coordinates (host plane).

Reference: serf-core/src/types/coordinate.rs (1282 LoC; SURVEY.md §2.5) —
Vivaldi [Dabek et al. 2004] with the Network-Coordinates-in-the-Wild
refinements [Ledlie 2007]: height vectors, error-weighted spring relaxation,
median latency filtering, rolling adjustment term, and gravity re-centering.

The same math vectorizes on the device plane (``serf_tpu.models.vivaldi``)
as N×8 arrays; this scalar version is the parity oracle and serves the host
Serf's ping integration.
"""

from __future__ import annotations

import math
import random
import threading
from dataclasses import dataclass, replace
from typing import Dict, List, Optional, Sequence

from serf_tpu import codec

SECONDS_TO_NS = 1.0e9


@dataclass(frozen=True)
class CoordinateOptions:
    """Defaults match the reference (coordinate.rs:52-204)."""

    dimensionality: int = 8
    vivaldi_error_max: float = 1.5
    vivaldi_ce: float = 0.25
    vivaldi_cc: float = 0.25
    adjustment_window_size: int = 20
    height_min: float = 10.0e-6
    latency_filter_size: int = 3
    gravity_rho: float = 150.0


@dataclass(frozen=True)
class Coordinate:
    """A point in the latency space; distances estimate RTT in seconds."""

    portion: tuple = ()
    error: float = 1.5
    adjustment: float = 0.0
    height: float = 10.0e-6

    @classmethod
    def new(cls, opts: CoordinateOptions) -> "Coordinate":
        return cls(
            portion=(0.0,) * opts.dimensionality,
            error=opts.vivaldi_error_max,
            adjustment=0.0,
            height=opts.height_min,
        )

    def is_valid(self) -> bool:
        return all(math.isfinite(p) for p in self.portion) and \
            math.isfinite(self.error) and math.isfinite(self.adjustment) and \
            math.isfinite(self.height)

    def is_compatible_with(self, other: "Coordinate") -> bool:
        return len(self.portion) == len(other.portion)

    def distance_to(self, other: "Coordinate") -> float:
        """Estimated RTT in seconds: euclidean + heights + adjustments
        (floored at zero before adjustment re-add, per the reference)."""
        dist = _magnitude(_diff(self.portion, other.portion)) + self.height + other.height
        adjusted = dist + self.adjustment + other.adjustment
        return adjusted if adjusted > 0.0 else dist

    def raw_distance_to(self, other: "Coordinate") -> float:
        return _magnitude(_diff(self.portion, other.portion)) + self.height + other.height

    def apply_force(self, height_min: float, force: float,
                    other: "Coordinate", rng: random.Random) -> "Coordinate":
        """Move along the unit vector away-from/toward ``other`` by ``force``
        (reference coordinate.rs:212-430; random unit vector on coincident
        points so identical coordinates can separate)."""
        unit, mag = _unit_vector(self.portion, other.portion, rng)
        portion = tuple(p + u * force for p, u in zip(self.portion, unit))
        height = self.height
        if mag > 0.0:
            height = max(height_min, (self.height + other.height) * force / mag + self.height)
        return replace(self, portion=portion, height=height)

    # wire format (rides in SWIM ping acks)
    def encode(self) -> bytes:
        out = b"".join(codec.encode_double_field(1, p) for p in self.portion)
        out += codec.encode_double_field(2, self.error)
        out += codec.encode_double_field(3, self.adjustment)
        out += codec.encode_double_field(4, self.height)
        return out

    @classmethod
    def decode(cls, buf: bytes) -> "Coordinate":
        portion: List[float] = []
        error, adjustment, height = 1.5, 0.0, 10.0e-6
        for f, _w, v, _p in codec.iter_fields(buf):
            if f == 1:
                portion.append(codec.read_double(v))
            elif f == 2:
                error = codec.read_double(v)
            elif f == 3:
                adjustment = codec.read_double(v)
            elif f == 4:
                height = codec.read_double(v)
        return cls(tuple(portion), error, adjustment, height)


def _diff(a: Sequence[float], b: Sequence[float]) -> List[float]:
    return [x - y for x, y in zip(a, b)]


def _magnitude(v: Sequence[float]) -> float:
    return math.sqrt(sum(x * x for x in v))


def _unit_vector(a: Sequence[float], b: Sequence[float],
                 rng: random.Random) -> tuple:
    d = _diff(a, b)
    mag = _magnitude(d)
    if mag > 1.0e-9:  # ZERO_THRESHOLD
        return [x / mag for x in d], mag
    # coincident points: random unit vector, zero distance
    d = [rng.random() - 0.5 for _ in a]
    mag = _magnitude(d)
    if mag > 1.0e-9:
        return [x / mag for x in d], 0.0
    unit = [0.0] * len(list(a))
    if unit:
        unit[0] = 1.0
    return unit, 0.0


class CoordinateClient:
    """Per-node coordinate estimator (reference CoordinateClient<I>).

    ``update(peer_id, peer_coord, rtt_seconds)`` runs the median latency
    filter, Vivaldi spring relaxation, adjustment-term update, and gravity,
    returning the new local coordinate.  Invalid results (NaN/Inf) reset the
    client (reset counter tracked, reference coordinate.rs:909-914).
    """

    MAX_RTT = 10.0  # seconds; sanity cap (coordinate.rs:893-897)

    def __init__(self, opts: Optional[CoordinateOptions] = None,
                 rng: Optional[random.Random] = None):
        self.opts = opts or CoordinateOptions()
        self.rng = rng or random.Random()
        self._lock = threading.Lock()
        self.coord = Coordinate.new(self.opts)
        self.origin = Coordinate.new(self.opts)
        self.adjustment_samples: List[float] = [0.0] * self.opts.adjustment_window_size
        self.adjustment_index = 0
        self.latency_filters: Dict[str, List[float]] = {}
        self.resets = 0

    def get_coordinate(self) -> Coordinate:
        with self._lock:
            return self.coord

    def set_coordinate(self, coord: Coordinate) -> None:
        self._check(coord)
        with self._lock:
            self.coord = coord

    def forget_node(self, node_id: str) -> None:
        with self._lock:
            self.latency_filters.pop(node_id, None)

    def stats(self) -> dict:
        return {"resets": self.resets}

    def distance_to(self, other: Coordinate) -> float:
        return self.get_coordinate().distance_to(other)

    def update(self, node_id: str, other: Coordinate, rtt: float) -> Coordinate:
        """Returns the updated local coordinate; raises ValueError on
        incompatible dimensions or insane RTT."""
        self._check(other)
        if not (0.0 < rtt <= self.MAX_RTT):
            raise ValueError(f"round trip time not in valid range: {rtt}")
        with self._lock:
            rtt_f = self._latency_filter(node_id, rtt)
            self._update_vivaldi(other, rtt_f)
            self._update_adjustment(other, rtt_f)
            self._update_gravity()
            if not self.coord.is_valid():
                self.resets += 1
                self.coord = Coordinate.new(self.opts)
            return self.coord

    # internals (reference coordinate.rs:699-762) --------------------------

    def _latency_filter(self, node_id: str, rtt: float) -> float:
        samples = self.latency_filters.setdefault(node_id, [])
        samples.append(rtt)
        if len(samples) > self.opts.latency_filter_size:
            samples.pop(0)
        return sorted(samples)[len(samples) // 2]

    def _update_vivaldi(self, other: Coordinate, rtt: float) -> None:
        rtt = max(rtt, 1.0e-9)
        dist = self.coord.distance_to(other)  # adjustment-inclusive (reference)
        wrongness = abs(dist - rtt) / rtt
        total_error = max(self.coord.error + other.error, 1.0e-9)
        weight = self.coord.error / total_error
        error = self.coord.error * (1.0 - self.opts.vivaldi_ce * weight) \
            + wrongness * self.opts.vivaldi_ce * weight
        error = min(error, self.opts.vivaldi_error_max)
        force = self.opts.vivaldi_cc * weight * (rtt - dist)
        self.coord = replace(
            self.coord.apply_force(self.opts.height_min, force, other, self.rng),
            error=error,
        )

    def _update_adjustment(self, other: Coordinate, rtt: float) -> None:
        if self.opts.adjustment_window_size == 0:
            return
        dist = self.coord.raw_distance_to(other)
        self.adjustment_samples[self.adjustment_index] = rtt - dist
        self.adjustment_index = (self.adjustment_index + 1) % self.opts.adjustment_window_size
        self.coord = replace(
            self.coord,
            adjustment=sum(self.adjustment_samples) / (2.0 * self.opts.adjustment_window_size),
        )

    def _update_gravity(self) -> None:
        dist = self.origin.distance_to(self.coord)  # adjustment-inclusive
        force = -1.0 * (dist / self.opts.gravity_rho) ** 2
        self.coord = self.coord.apply_force(self.opts.height_min, force, self.origin, self.rng)

    def _check(self, coord: Coordinate) -> None:
        if not coord.is_compatible_with(self.coord):
            raise ValueError(
                f"dimensions aren't compatible: {len(coord.portion)} vs "
                f"{len(self.coord.portion)}"
            )
        if not coord.is_valid():
            raise ValueError("coordinate is invalid")
