"""Admission control: token-bucket ingress limits, health-aware shedding,
and per-peer send pacing.

The overload-protection plane's front door (ISSUE 5).  The reference's
production story assumes gossip stays convergent while user events and
queries stampede; the Lifeguard insight — self-awareness modulating
protocol behavior — extends naturally from probe timing to admission:
a node that KNOWS it is degraded (``obs.health`` score under pressure
from loop lag / queue fill) sheds user-plane ingress early and fast-fails
queries with an explicit overloaded response instead of timing out
silently, keeping the membership plane (which is never shed) healthy.

Three pieces, all opt-in through :class:`serf_tpu.options.Options` knobs
(rate 0 = disabled, so nothing changes for configs that don't ask):

- :class:`TokenBucket` — the standard refill-on-read limiter.
- :class:`AdmissionController` — per-op buckets (``user_event``,
  ``query``) plus the health gate, sampled through the engine's
  :class:`~serf_tpu.obs.health.HealthScorer` with a small cache so a
  storm of ingress calls cannot itself become the load.
- :class:`PeerPacer` — per-destination token buckets at the USER-plane
  send seam (``Memberlist.send``: query responses/acks/relays; the SWIM
  probe/ack/gossip plane is never paced — membership is never shed).
  Pacing is LOSS-based (a paced-out packet is dropped, counted in
  ``serf.overload.paced_dropped``): gossip is redundant by design, so
  dropping beats queueing unbounded sends behind a slow peer.

Every shed emits a ``serf.overload.*`` counter and a flight event —
ingress accounting must always close (admitted + shed == offered).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

from serf_tpu import obs
from serf_tpu.utils import metrics

from serf_tpu.utils.logging import get_logger

log = get_logger("admission")

#: how long a health sample stays fresh for admission decisions — keeps
#: the gate O(1) under an ingress storm (the health sources walk queue
#: depths and counters; doing that per user_event would be self-load)
HEALTH_CACHE_S = 0.05

#: fraction of the event-inbox bound at which the node reports itself
#: overloaded even before the health score degrades (queue pressure is
#: a leading indicator; the score's EWMA components lag)
INBOX_PRESSURE_FRACTION = 0.9

#: bound on distinct peers the pacer tracks; beyond it the stalest
#: bucket is evicted (bounded everything — the pacer must not become
#: the unbounded map it exists to prevent)
PACER_MAX_PEERS = 4096

#: bound on distinct (op, name-class) tenant buckets, stalest-evicted —
#: per-tenant fairness must not itself be an unbounded map under a
#: storm of invented tenant names.  Documented tradeoff: an adversary
#: minting fresh name classes gets each new bucket's burst before its
#: first shed, and an evicted-then-returning tenant comes back with a
#: full bucket — any bounded keyed limiter has this; the GLOBAL
#: per-op bucket stays the hard backstop (it is checked on every call
#: and cannot be churned away), and eviction picks the least-recently
#:-USED bucket, so an active tenant's drained budget is never reset.
TENANT_MAX_BUCKETS = 1024


class OverloadError(RuntimeError):
    """An ingress operation was shed by admission control.

    Carries the operation (``user_event``/``query``) and the reason
    (``rate`` = token bucket empty, ``health`` = node under its health
    floor).  The caller should back off and retry — an explicit fast
    failure instead of a silent timeout.
    """

    def __init__(self, op: str, reason: str):
        super().__init__(f"{op} shed by admission control ({reason})")
        self.op = op
        self.reason = reason


class TokenBucket:
    """Refill-on-read token bucket; ``rate <= 0`` admits everything."""

    __slots__ = ("rate", "burst", "tokens", "_last", "_clock")

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic):
        if burst <= 0:
            raise ValueError("token bucket burst must be positive")
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self._clock = clock
        self._last = clock()

    def try_take(self, n: float = 1.0) -> bool:
        if self.rate <= 0:
            return True
        now = self._clock()
        self.tokens = min(self.burst,
                          self.tokens + (now - self._last) * self.rate)
        self._last = now
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False


class AdmissionController:
    """Ingress gate for one Serf engine.

    ``admit(op)`` returns ``None`` when the operation may proceed, else
    the shed reason — the engine raises :class:`OverloadError` and
    counts.  ``overloaded()`` is the responder-side signal (query
    fast-fail): True when the health score is under the configured floor
    or the event inbox is near its bound.
    """

    def __init__(self, serf):
        self._serf = serf
        opts = serf.opts
        self._buckets: Dict[str, TokenBucket] = {}
        if opts.user_event_rate > 0:
            self._buckets["user_event"] = TokenBucket(
                opts.user_event_rate, opts.user_event_burst)
        if opts.query_rate > 0:
            self._buckets["query"] = TokenBucket(
                opts.query_rate, opts.query_burst)
        #: per-tenant fairness config + bounded bucket map (keyed by
        #: (op, name-class); rate 0 = the whole plane is off)
        self._tenant_cfg = {
            "user_event": (opts.tenant_event_rate, opts.tenant_event_burst),
            "query": (opts.tenant_query_rate, opts.tenant_query_burst),
        }
        self._tenants: Dict[tuple, TokenBucket] = {}
        self.min_health = opts.admission_min_health
        self._health_at = -1e9
        self._health_score = 100

    # -- health gate --------------------------------------------------------

    def _score(self) -> int:
        """Health score with a short cache (HEALTH_CACHE_S): admission
        must stay O(1) per call under the very storms it exists for."""
        now = time.monotonic()
        if now - self._health_at >= HEALTH_CACHE_S:
            try:
                # consume=False: observing must not shrink the periodic
                # monitor's counter-delta window (obs.health contract)
                self._health_score = self._serf._health.sample(
                    consume=False).score
            except Exception:  # noqa: BLE001 - a broken signal never gates
                self._health_score = 100
            self._health_at = now
        return self._health_score

    def overloaded(self) -> bool:
        """Responder-side self-awareness: should this node fast-fail
        user queries rather than serve them late (or never)?"""
        cap = self._serf.opts.event_inbox_max
        if cap > 0 and (self._serf.pipeline_depth()
                        >= INBOX_PRESSURE_FRACTION * cap):
            return True
        if self.min_health <= 0:
            return False
        return self._score() < self.min_health

    # -- ingress ------------------------------------------------------------

    def admit(self, op: str, name: Optional[str] = None) -> Optional[str]:
        """None = admitted; otherwise the shed reason.  ``name`` (the
        event/query name) engages the per-tenant fairness buckets when
        configured: the tenant identity is the NAME CLASS
        (``host.pipeline.name_class`` — ``storm-17`` → ``storm``), so
        one chatty tenant exhausts its own budget while the others keep
        their full rate.  Tenant sheds drain NO global token (the
        global bucket is checked last) and report reason ``tenant``."""
        if self.min_health > 0 and self._score() < self.min_health:
            return "health"
        tenant_bucket = None
        if name is not None:
            admitted, tenant_bucket = self._tenant_admit(op, name)
            if not admitted:
                return "tenant"
        bucket = self._buckets.get(op)
        if bucket is not None and not bucket.try_take():
            # fairness holds in BOTH directions: a global-rate shed must
            # not leave the tenant's budget drained (or a quiet tenant
            # would pay for a storm it never joined) — refund the token
            if tenant_bucket is not None:
                tenant_bucket.tokens = min(tenant_bucket.burst,
                                           tenant_bucket.tokens + 1.0)
            return "rate"
        return None

    def _tenant_admit(self, op: str, name: str):
        """(admitted, bucket-or-None) — the bucket is returned so a
        downstream global-rate shed can refund the tenant token."""
        rate, burst = self._tenant_cfg.get(op, (0.0, 1))
        if rate <= 0:
            return True, None
        from serf_tpu.host.pipeline import name_class
        key = (op, name_class(name))
        bucket = self._tenants.get(key)
        if bucket is None:
            if len(self._tenants) >= TENANT_MAX_BUCKETS:
                stalest = min(self._tenants,
                              key=lambda k: self._tenants[k]._last)
                del self._tenants[stalest]
            bucket = self._tenants[key] = TokenBucket(rate, burst)
        return bucket.try_take(), bucket


def record_ingress(labels: Dict[str, str], node: str, op: str,
                   reason: Optional[str]) -> None:
    """One accounting point for every ingress decision: admitted + shed
    counters always sum to offered, and every shed leaves a flight
    event."""
    if reason is None:
        metrics.incr("serf.overload.ingress_admitted", 1,
                     {**labels, "op": op})
        return
    metrics.incr("serf.overload.ingress_shed", 1,
                 {**labels, "op": op, "reason": reason})
    obs.record("ingress-shed", node=node, op=op, reason=reason)


class PeerPacer:
    """Per-destination pacing for the user-plane send seam.

    One token bucket per peer address; a send with no token is DROPPED
    (gossip tolerates loss; queueing would re-create the unbounded
    buffer this plane removes).  The peer map itself is bounded at
    ``PACER_MAX_PEERS`` with stalest-eviction.
    """

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self._peers: Dict[object, TokenBucket] = {}

    def admit(self, addr) -> bool:
        if self.rate <= 0:
            return True
        bucket = self._peers.get(addr)
        if bucket is None:
            if len(self._peers) >= PACER_MAX_PEERS:
                stalest = min(self._peers, key=lambda a: self._peers[a]._last)
                del self._peers[stalest]
            bucket = self._peers[addr] = TokenBucket(self.rate, self.burst)
        return bucket.try_take()
