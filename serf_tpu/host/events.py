"""Event model and delivery pipeline: member/user/query events, subscriber
channels, and coalescers.

Reference: serf-core/src/event.rs (Event enum, EventProducer/Subscriber,
QueryEvent respond machinery) and serf-core/src/coalesce* (member/user
coalescers driven by coalesce/quiescent timers) — SURVEY.md §2.2.
"""

from __future__ import annotations

import asyncio
import enum
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from serf_tpu.obs import flight
from serf_tpu.types.clock import LamportTime
from serf_tpu.types.member import Member
from serf_tpu.utils import metrics
from serf_tpu.types.messages import (
    QueryFlag,
    QueryResponseMessage,
    encode_message,
)
from serf_tpu.types.member import Node

from serf_tpu.utils.logging import get_logger

log = get_logger("events")


class MemberEventType(enum.IntEnum):
    JOIN = 0
    LEAVE = 1
    FAILED = 2
    UPDATE = 3
    REAP = 4


@dataclass(frozen=True)
class MemberEvent:
    ty: MemberEventType
    members: Tuple[Member, ...]


@dataclass(frozen=True)
class UserEvent:
    ltime: LamportTime
    name: str
    payload: bytes
    coalesce: bool = False


@dataclass
class QueryEvent:
    """A query delivered to the application; ``respond`` sends the answer
    back to the originator (direct send + relay through ``relay_factor``
    random members) with a deadline check (reference event.rs:19-99)."""

    ltime: LamportTime
    name: str
    payload: bytes
    id: int
    from_node: Node
    relay_factor: int
    deadline: float            # monotonic
    tctx: object = field(default=None, repr=False)  # TraceContext | None
    _serf: object = field(default=None, repr=False)
    _responded: bool = field(default=False, repr=False)

    def expired(self) -> bool:
        return time.monotonic() > self.deadline

    async def respond(self, payload: bytes) -> None:
        if self._responded:
            raise RuntimeError("query already responded")
        if self.expired():
            raise TimeoutError("query deadline already passed")
        serf = self._serf
        # echo the query's trace context so the originator's flight
        # recorder can correlate the response with the scattered query
        msg = QueryResponseMessage(
            ltime=self.ltime, id=self.id, from_node=serf.memberlist.local_node(),
            flags=QueryFlag.NONE, payload=payload, tctx=self.tctx,
        )
        raw = encode_message(msg)
        if (len(raw) > serf.opts.query_response_size_limit
                and self.tctx is not None):
            # the trace echo is best-effort metadata: shed it before
            # failing a payload that fit the documented budget on its own
            msg = QueryResponseMessage(
                ltime=self.ltime, id=self.id,
                from_node=serf.memberlist.local_node(),
                flags=QueryFlag.NONE, payload=payload,
            )
            raw = encode_message(msg)
        if len(raw) > serf.opts.query_response_size_limit:
            raise ValueError(
                f"query response is {len(raw)} bytes, limit "
                f"{serf.opts.query_response_size_limit}"
            )
        self._responded = True
        await serf.memberlist.send(self.from_node.addr, raw)
        await serf.relay_response(self.relay_factor, self.from_node, raw)


Event = object  # MemberEvent | UserEvent | QueryEvent


class EventSubscriber:
    """Async stream of events.

    Two bounded modes, matching the reference's channel split
    (event.rs:394-512 offers bounded *blocking* and unbounded channels):

    - default (``lossless=False``): drop-oldest on overflow — a slow
      consumer can never wedge the protocol; losses are counted in
      ``dropped`` and the ``serf.subscriber.dropped`` metric.
    - ``lossless=True``: bounded BLOCKING — the event pipeline awaits
      until the consumer makes room, so no event is ever dropped.  This
      backpressures the delivery pipeline task only (gossip itself keeps
      running; the inbox between the protocol and the pipeline is still
      bounded by process memory), which is exactly the reference's
      bounded-producer semantics.  Opt in only when every event matters
      more than delivery latency.
    """

    def __init__(self, maxsize: int = 4096, lossless: bool = False):
        self._q: asyncio.Queue = asyncio.Queue(maxsize=maxsize)
        self.lossless = lossless
        #: events discarded by drop-oldest overflow (stays 0 in lossless
        #: mode unless a sync producer violates the contract — see _push)
        self.dropped = 0
        #: drop-oldest firings on a lossless subscriber (contract breaks)
        self.lossless_violations = 0

    def _push(self, ev) -> None:
        """Synchronous push: drop-oldest semantics regardless of mode —
        prefer ``push`` from async producers (it honors lossless).  A
        drop on a ``lossless=True`` subscriber is a CONTRACT VIOLATION
        (some sync producer bypassed the awaiting ``push``): it is
        logged loudly and flight-recorded rather than silently eaten."""
        while True:
            try:
                self._q.put_nowait(ev)
                return
            except asyncio.QueueFull:
                try:
                    dropped_ev = self._q.get_nowait()  # drop oldest
                    self.dropped += 1
                    metrics.incr("serf.subscriber.dropped", 1)
                    if self.lossless:
                        self.lossless_violations += 1
                        metrics.incr("serf.subscriber.lossless_violation", 1)
                        flight.record("subscriber-drop",
                                      event=type(dropped_ev).__name__,
                                      total_dropped=self.dropped,
                                      contract="lossless")
                        log.warning(
                            "LOSSLESS subscriber overflowed: a synchronous "
                            "producer forced drop-oldest, violating the "
                            "no-loss contract (%d violations so far)",
                            self.lossless_violations)
                    else:
                        flight.record("subscriber-drop",
                                      event=type(dropped_ev).__name__,
                                      total_dropped=self.dropped)
                        log.warning(
                            "event subscriber overflow: dropping oldest event")
                except asyncio.QueueEmpty:
                    pass

    async def push(self, ev) -> None:
        """Async push honoring the mode: awaits for room when lossless,
        drop-oldest otherwise."""
        if self.lossless:
            await self._q.put(ev)
        else:
            self._push(ev)

    async def next(self, timeout: Optional[float] = None):
        if timeout is None:
            return await self._q.get()
        return await asyncio.wait_for(self._q.get(), timeout)

    def try_next(self):
        try:
            return self._q.get_nowait()
        except asyncio.QueueEmpty:
            return None

    def __aiter__(self):
        return self

    async def __anext__(self):
        return await self._q.get()

    def qsize(self) -> int:
        return self._q.qsize()


# ---------------------------------------------------------------------------
# Coalescing
# ---------------------------------------------------------------------------


class MemberEventCoalescer:
    """Keep only the latest member event per node within the window; flush one
    merged MemberEvent per type (reference coalesce/member.rs:24-113).
    Update events always pass (tags changes must not be suppressed)."""

    def __init__(self):
        self.latest: Dict[str, MemberEventType] = {}
        self.members: Dict[str, Member] = {}

    def pending(self) -> int:
        """Buffered entries awaiting a flush (bounded by the pipeline's
        coalesce stage — see host.pipeline.CoalesceStage)."""
        return len(self.latest)

    def handle(self, ev) -> bool:
        if not isinstance(ev, MemberEvent):
            return False
        for m in ev.members:
            self.latest[m.node.id] = ev.ty
            self.members[m.node.id] = m
        return True

    def flush(self) -> List[MemberEvent]:
        by_type: Dict[MemberEventType, List[Member]] = {}
        for node_id, ty in self.latest.items():
            by_type.setdefault(ty, []).append(self.members[node_id])
        self.latest.clear()
        self.members.clear()
        return [
            MemberEvent(ty, tuple(sorted(ms, key=lambda m: m.node.id)))
            for ty, ms in sorted(by_type.items())
        ]


class UserEventCoalescer:
    """Dedup user events by (ltime, name) within the window
    (reference coalesce/user.rs)."""

    def __init__(self):
        self.seen: Dict[Tuple[int, str], UserEvent] = {}

    def pending(self) -> int:
        """Buffered entries awaiting a flush (bounded by the pipeline's
        coalesce stage — see host.pipeline.CoalesceStage)."""
        return len(self.seen)

    def handle(self, ev) -> bool:
        if not (isinstance(ev, UserEvent) and ev.coalesce):
            return False
        self.seen[(ev.ltime, ev.name)] = ev
        return True

    def flush(self) -> List[UserEvent]:
        out = [self.seen[k] for k in sorted(self.seen)]
        self.seen.clear()
        return out


async def coalesce_loop(
    inbox: asyncio.Queue,
    out: EventSubscriber,
    coalescer,
    coalesce_period: float,
    quiescent_period: float,
) -> None:
    """Buffer coalescable events; flush on the coalesce quantum or after a
    quiescent gap (reference coalesce.rs:22-155).  Non-coalescable events pass
    straight through."""
    pending = False
    flush_deadline = None
    loop = asyncio.get_running_loop()
    while True:
        if pending:
            now = loop.time()
            timeout = max(0.0, min(flush_deadline - now, quiescent_period))
        else:
            timeout = None
        try:
            if timeout is None:
                ev = await inbox.get()
            else:
                ev = await asyncio.wait_for(inbox.get(), timeout)
        except asyncio.TimeoutError:
            for flushed in coalescer.flush():
                await out.push(flushed)
            pending = False
            flush_deadline = None
            continue
        if ev is None:  # shutdown: flush what we have
            for flushed in coalescer.flush():
                await out.push(flushed)
            return
        if coalescer.handle(ev):
            if not pending:
                pending = True
                flush_deadline = loop.time() + coalesce_period
        else:
            await out.push(ev)
