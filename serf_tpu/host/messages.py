"""SWIM-layer wire messages (the memberlist protocol plane).

The reference consumes these from the external ``memberlist-core`` crate
(SURVEY.md §2.9); serf-tpu implements the layer from scratch.  Separate
envelope registry from the serf-layer messages (``serf_tpu.types.messages``):
these frame the *gossip transport* plane — probe/ack, suspicion, alive/dead
dissemination, push/pull state sync, compound packing, and user-message
encapsulation (which is how serf-layer bytes ride in gossip packets).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import List, Tuple

from serf_tpu import codec
from serf_tpu.types.member import Node


class SwimState(enum.IntEnum):
    ALIVE = 0
    SUSPECT = 1
    DEAD = 2
    LEFT = 3


class SwimMessageType(enum.IntEnum):
    PING = 1
    INDIRECT_PING = 2
    ACK = 3
    NACK = 4
    SUSPECT = 5
    ALIVE = 6
    DEAD = 7
    PUSH_PULL = 8
    COMPOUND = 9
    USER = 10          # serf-layer payload (delegate notify_message)
    ERROR = 11         # stream-level refusal (memberlist's errResp analog)


@dataclass(frozen=True)
class Ping:
    seq: int
    source: Node
    target: str  # target node id (sanity check against misdelivery)

    TYPE = SwimMessageType.PING

    def encode_body(self) -> bytes:
        return (codec.encode_varint_field(1, self.seq)
                + codec.encode_bytes_field(2, self.source.encode())
                + codec.encode_str_field(3, self.target))

    @classmethod
    def decode_body(cls, buf: bytes) -> "Ping":
        seq, src, tgt = 0, Node(""), ""
        for f, _w, v, _p in codec.iter_fields(buf):
            if f == 1:
                seq = codec.as_uint(v)
            elif f == 2:
                src = Node.decode(codec.as_bytes(v))
            elif f == 3:
                tgt = codec.as_str(v)
        return cls(seq, src, tgt)


@dataclass(frozen=True)
class IndirectPing:
    """Ask a third node to probe ``target`` on our behalf."""

    seq: int
    source: Node
    target: Node

    TYPE = SwimMessageType.INDIRECT_PING

    def encode_body(self) -> bytes:
        return (codec.encode_varint_field(1, self.seq)
                + codec.encode_bytes_field(2, self.source.encode())
                + codec.encode_bytes_field(3, self.target.encode()))

    @classmethod
    def decode_body(cls, buf: bytes) -> "IndirectPing":
        seq, src, tgt = 0, Node(""), Node("")
        for f, _w, v, _p in codec.iter_fields(buf):
            if f == 1:
                seq = codec.as_uint(v)
            elif f == 2:
                src = Node.decode(codec.as_bytes(v))
            elif f == 3:
                tgt = Node.decode(codec.as_bytes(v))
        return cls(seq, src, tgt)


@dataclass(frozen=True)
class Ack:
    """Ack for Ping ``seq``; ``payload`` carries the PingDelegate blob
    (Vivaldi coordinates — reference delegate.rs:656-795)."""

    seq: int
    payload: bytes = b""

    TYPE = SwimMessageType.ACK

    def encode_body(self) -> bytes:
        out = codec.encode_varint_field(1, self.seq)
        if self.payload:
            out += codec.encode_bytes_field(2, self.payload)
        return out

    @classmethod
    def decode_body(cls, buf: bytes) -> "Ack":
        seq, payload = 0, b""
        for f, _w, v, _p in codec.iter_fields(buf):
            if f == 1:
                seq = codec.as_uint(v)
            elif f == 2:
                payload = codec.as_bytes(v)
        return cls(seq, payload)


@dataclass(frozen=True)
class Nack:
    """Negative ack for an indirect probe (Lifeguard: lets the prober
    distinguish a dead relay from a dead target)."""

    seq: int

    TYPE = SwimMessageType.NACK

    def encode_body(self) -> bytes:
        return codec.encode_varint_field(1, self.seq)

    @classmethod
    def decode_body(cls, buf: bytes) -> "Nack":
        seq = 0
        for f, _w, v, _p in codec.iter_fields(buf):
            if f == 1:
                seq = codec.as_uint(v)
        return cls(seq)


@dataclass(frozen=True)
class Suspect:
    incarnation: int
    node: str
    from_node: str

    TYPE = SwimMessageType.SUSPECT

    def encode_body(self) -> bytes:
        return (codec.encode_varint_field(1, self.incarnation)
                + codec.encode_str_field(2, self.node)
                + codec.encode_str_field(3, self.from_node))

    @classmethod
    def decode_body(cls, buf: bytes) -> "Suspect":
        inc, node, frm = 0, "", ""
        for f, _w, v, _p in codec.iter_fields(buf):
            if f == 1:
                inc = codec.as_uint(v)
            elif f == 2:
                node = codec.as_str(v)
            elif f == 3:
                frm = codec.as_str(v)
        return cls(inc, node, frm)


# Supported version ranges (reference serf-core/src/types/version.rs:9-43
# carries these as ProtocolVersion/DelegateVersion; the refusal semantics
# mirror memberlist's Vsn handshake: a peer whose advertised [min, max]
# range does not intersect ours is rejected, loudly).
PROTOCOL_VERSION_MIN = 1
PROTOCOL_VERSION_MAX = 1
DELEGATE_VERSION_MIN = 1
DELEGATE_VERSION_MAX = 1

DEFAULT_VSN = (1, 1, 1, 1, 1, 1)


def _decode_vsn(raw: bytes):
    """6-byte version vector [pmin, pmax, pcur, dmin, dmax, dcur] (the
    memberlist ``Vsn`` layout); anything malformed falls back to v1."""
    if len(raw) == 6:
        return tuple(raw)
    return DEFAULT_VSN


@dataclass(frozen=True)
class Alive:
    incarnation: int
    node: Node
    meta: bytes = b""
    vsn: tuple = DEFAULT_VSN

    TYPE = SwimMessageType.ALIVE

    def encode_body(self) -> bytes:
        out = (codec.encode_varint_field(1, self.incarnation)
               + codec.encode_bytes_field(2, self.node.encode()))
        if self.meta:
            out += codec.encode_bytes_field(3, self.meta)
        # always on the wire (8 bytes) so version carriage is real, not
        # a default that decode would fabricate anyway
        out += codec.encode_bytes_field(4, bytes(self.vsn))
        return out

    @classmethod
    def decode_body(cls, buf: bytes) -> "Alive":
        inc, node, meta, vsn = 0, Node(""), b"", DEFAULT_VSN
        for f, _w, v, _p in codec.iter_fields(buf):
            if f == 1:
                inc = codec.as_uint(v)
            elif f == 2:
                node = Node.decode(codec.as_bytes(v))
            elif f == 3:
                meta = codec.as_bytes(v)
            elif f == 4:
                vsn = _decode_vsn(codec.as_bytes(v))
        return cls(inc, node, meta, vsn)


@dataclass(frozen=True)
class Dead:
    """``from_node == node`` signals a voluntary leave (LEFT, not DEAD) —
    the same convention memberlist uses."""

    incarnation: int
    node: str
    from_node: str

    TYPE = SwimMessageType.DEAD

    def encode_body(self) -> bytes:
        return (codec.encode_varint_field(1, self.incarnation)
                + codec.encode_str_field(2, self.node)
                + codec.encode_str_field(3, self.from_node))

    @classmethod
    def decode_body(cls, buf: bytes) -> "Dead":
        inc, node, frm = 0, "", ""
        for f, _w, v, _p in codec.iter_fields(buf):
            if f == 1:
                inc = codec.as_uint(v)
            elif f == 2:
                node = codec.as_str(v)
            elif f == 3:
                frm = codec.as_str(v)
        return cls(inc, node, frm)


@dataclass(frozen=True)
class PushNodeState:
    """One node's state in a push/pull exchange."""

    node: Node
    incarnation: int
    state: SwimState
    meta: bytes = b""
    vsn: tuple = DEFAULT_VSN

    def encode(self) -> bytes:
        out = (codec.encode_bytes_field(1, self.node.encode())
               + codec.encode_varint_field(2, self.incarnation)
               + codec.encode_varint_field(3, int(self.state)))
        if self.meta:
            out += codec.encode_bytes_field(4, self.meta)
        out += codec.encode_bytes_field(5, bytes(self.vsn))
        return out

    @classmethod
    def decode(cls, buf: bytes) -> "PushNodeState":
        node, inc, st, meta, vsn = Node(""), 0, SwimState.ALIVE, b"", DEFAULT_VSN
        for f, _w, v, _p in codec.iter_fields(buf):
            if f == 1:
                node = Node.decode(codec.as_bytes(v))
            elif f == 2:
                inc = codec.as_uint(v)
            elif f == 3:
                st = SwimState(codec.as_uint(v))
            elif f == 4:
                meta = codec.as_bytes(v)
            elif f == 5:
                vsn = _decode_vsn(codec.as_bytes(v))
        return cls(node, inc, st, meta, vsn)


@dataclass(frozen=True)
class PushPull:
    """Full-state anti-entropy exchange over a stream; ``user_data`` is the
    serf delegate's local_state blob (reference delegate.rs:386-425)."""

    join: bool
    states: Tuple[PushNodeState, ...] = ()
    user_data: bytes = b""

    TYPE = SwimMessageType.PUSH_PULL

    def encode_body(self) -> bytes:
        out = codec.encode_varint_field(1, 1 if self.join else 0)
        for st in self.states:
            out += codec.encode_bytes_field(2, st.encode())
        if self.user_data:
            out += codec.encode_bytes_field(3, self.user_data)
        return out

    @classmethod
    def decode_body(cls, buf: bytes) -> "PushPull":
        join, states, user = False, [], b""
        for f, _w, v, _p in codec.iter_fields(buf):
            if f == 1:
                join = bool(codec.as_uint(v))
            elif f == 2:
                states.append(PushNodeState.decode(codec.as_bytes(v)))
            elif f == 3:
                user = codec.as_bytes(v)
        return cls(join, tuple(states), user)


@dataclass(frozen=True)
class UserMsg:
    """Encapsulates serf-layer bytes; dispatched to delegate.notify_message."""

    payload: bytes

    TYPE = SwimMessageType.USER

    def encode_body(self) -> bytes:
        return codec.encode_bytes_field(1, self.payload)

    @classmethod
    def decode_body(cls, buf: bytes) -> "UserMsg":
        payload = b""
        for f, _w, v, _p in codec.iter_fields(buf):
            if f == 1:
                payload = codec.as_bytes(v)
        return cls(payload)


@dataclass(frozen=True)
class ErrorResp:
    """Stream-level refusal sent before closing, so the dialing side fails
    fast with the reason spelled out instead of timing out (the analog of
    memberlist's errResp; today sent for version-incompatible joins)."""

    error: str

    TYPE = SwimMessageType.ERROR

    def encode_body(self) -> bytes:
        return codec.encode_str_field(1, self.error)

    @classmethod
    def decode_body(cls, buf: bytes) -> "ErrorResp":
        error = ""
        for f, _w, v, _p in codec.iter_fields(buf):
            if f == 1:
                error = codec.as_str(v)
        return cls(error)


_DECODERS = {
    SwimMessageType.PING: Ping.decode_body,
    SwimMessageType.INDIRECT_PING: IndirectPing.decode_body,
    SwimMessageType.ACK: Ack.decode_body,
    SwimMessageType.NACK: Nack.decode_body,
    SwimMessageType.SUSPECT: Suspect.decode_body,
    SwimMessageType.ALIVE: Alive.decode_body,
    SwimMessageType.DEAD: Dead.decode_body,
    SwimMessageType.PUSH_PULL: PushPull.decode_body,
    SwimMessageType.USER: UserMsg.decode_body,
    SwimMessageType.ERROR: ErrorResp.decode_body,
}


# Upper bound on total decode units (messages + nested compounds) unwound
# from a single packet; a datagram is ≤64 KiB so a legitimate packet can
# never approach this.
_MAX_COMPOUND_UNITS = 4096


def encode_swim(msg) -> bytes:
    return bytes([int(msg.TYPE)]) + msg.encode_body()


def encode_compound(parts: List[bytes]) -> bytes:
    """Pack multiple encoded swim messages into one packet."""
    body = b"".join(codec.encode_bytes_field(1, p) for p in parts)
    return bytes([int(SwimMessageType.COMPOUND)]) + body


def decode_swim(buf: bytes):
    """Decode one packet; COMPOUND yields a list of messages (flattened).

    COMPOUND nesting is unwound iteratively with an explicit work list — a
    crafted deeply-nested datagram must not be able to exhaust the Python
    recursion limit (that would escape the DecodeError contract and kill the
    receive loop).  Fails closed with DecodeError on any malformation.
    """
    if not buf:
        raise codec.DecodeError("empty swim packet")

    def _type_of(b: bytes) -> SwimMessageType:
        if not b:
            raise codec.DecodeError("empty swim packet")
        try:
            return SwimMessageType(b[0])
        except ValueError as e:
            raise codec.DecodeError(f"unknown swim message type {b[0]}") from e

    top = _type_of(buf)
    is_compound = top == SwimMessageType.COMPOUND
    out = []
    work: List[bytes] = [buf]
    units = 0
    while work:
        cur = work.pop()
        units += 1
        if units > _MAX_COMPOUND_UNITS:
            raise codec.DecodeError(
                f"compound packet exceeds {_MAX_COMPOUND_UNITS} units")
        ty = _type_of(cur)
        body = cur[1:]
        try:
            if ty == SwimMessageType.COMPOUND:
                # push in reverse so nested parts decode in wire order
                parts = [codec.as_bytes(v)
                         for f, _w, v, _p in codec.iter_fields(body) if f == 1]
                work.extend(reversed(parts))
            else:
                out.append(_DECODERS[ty](body))
        except codec.DecodeError:
            raise
        except (AttributeError, TypeError, UnicodeDecodeError, ValueError) as e:
            raise codec.DecodeError(f"malformed {ty.name} body: {e}") from e
    return out if is_compound else out[0]
