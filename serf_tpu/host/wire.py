"""Packet wire pipeline: compress → checksum → encrypt, with pluggable
algorithm registries.

Capability parity with the reference's transport features
(serf-core/src/types.rs:10-48; SURVEY.md §2.9): the reference feature-gates
checksums {crc32, xxhash, murmur3} and compressions {snappy, zstd, lz4,
brotli}.  Here the checksum registry carries the reference's exact variants
(xxhash32 and murmur3 are hand-rolled below — small, well-specified, and
dependency-free) plus adler32; the compression registry carries zlib, the
hand-rolled native LZ4 and snappy block codecs (native/codec.cpp), zstd
via the baked-in ``zstandard`` module, and brotli via ctypes bindings to
the system libbrotlienc/libbrotlidec (round 4 — the full reference
variant set {snappy, zstd, lz4, brotli} is now covered with zero new
dependencies).  Registering another algorithm is one dict entry.

Wire layout (outermost first):  [AES-GCM]([checksum4](marker1 + payload))
"""

from __future__ import annotations

import struct
import zlib
from typing import Callable, Dict, Optional, Tuple

from serf_tpu.codec import decode_varint, encode_varint


# ---------------------------------------------------------------------------
# checksums (reference: crc32 / xxhash / murmur3; plus adler32)
# ---------------------------------------------------------------------------

_M = 0xFFFFFFFF


def xxhash32(data: bytes, seed: int = 0) -> int:
    """XXH32 (the reference's xxhash feature), from the public spec."""
    p1, p2, p3, p4, p5 = (2654435761, 2246822519, 3266489917,
                          668265263, 374761393)

    def rotl(x: int, r: int) -> int:
        return ((x << r) | (x >> (32 - r))) & _M

    n = len(data)
    idx = 0
    if n >= 16:
        v1 = (seed + p1 + p2) & _M
        v2 = (seed + p2) & _M
        v3 = seed & _M
        v4 = (seed - p1) & _M
        while idx <= n - 16:
            for ref in range(4):
                (lane,) = struct.unpack_from("<I", data, idx)
                if ref == 0:
                    v1 = (rotl((v1 + lane * p2) & _M, 13) * p1) & _M
                elif ref == 1:
                    v2 = (rotl((v2 + lane * p2) & _M, 13) * p1) & _M
                elif ref == 2:
                    v3 = (rotl((v3 + lane * p2) & _M, 13) * p1) & _M
                else:
                    v4 = (rotl((v4 + lane * p2) & _M, 13) * p1) & _M
                idx += 4
        h = (rotl(v1, 1) + rotl(v2, 7) + rotl(v3, 12) + rotl(v4, 18)) & _M
    else:
        h = (seed + p5) & _M
    h = (h + n) & _M
    while idx <= n - 4:
        (lane,) = struct.unpack_from("<I", data, idx)
        h = (rotl((h + lane * p3) & _M, 17) * p4) & _M
        idx += 4
    while idx < n:
        h = (rotl((h + data[idx] * p5) & _M, 11) * p1) & _M
        idx += 1
    h ^= h >> 15
    h = (h * p2) & _M
    h ^= h >> 13
    h = (h * p3) & _M
    h ^= h >> 16
    return h


def murmur3_32(data: bytes, seed: int = 0) -> int:
    """MurmurHash3 x86_32 (the reference's murmur3 feature)."""
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = seed & _M
    n = len(data)
    rounds = n // 4
    for i in range(rounds):
        (k,) = struct.unpack_from("<I", data, i * 4)
        k = (k * c1) & _M
        k = ((k << 15) | (k >> 17)) & _M
        k = (k * c2) & _M
        h ^= k
        h = ((h << 13) | (h >> 19)) & _M
        h = (h * 5 + 0xE6546B64) & _M
    k = 0
    tail = data[rounds * 4:]
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * c1) & _M
        k = ((k << 15) | (k >> 17)) & _M
        k = (k * c2) & _M
        h ^= k
    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _M
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _M
    h ^= h >> 16
    return h


def _native_or(name: str, py_fn: Callable[[bytes], int]):
    """Prefer the native C++ implementation (native/codec.cpp) of a
    checksum; the Python spec implementation above stays the oracle
    (differential-pinned in tests/test_wire.py).

    Resolution is deferred to the first call: ``_native.load()`` may build
    the shared library with g++, and that must not happen at import time
    of the host stack."""
    impl: list = []

    def dispatch(data: bytes) -> int:
        if not impl:
            fn = None
            try:
                from serf_tpu.codec import _native
                fn = _native.checksum_fn(name)
            except Exception:  # noqa: BLE001 - native strictly optional
                fn = None
            impl.append(fn or py_fn)
        return impl[0](data)

    return dispatch


CHECKSUMS: Dict[str, Callable[[bytes], int]] = {
    "crc32": lambda b: zlib.crc32(b) & _M,
    "adler32": lambda b: zlib.adler32(b) & _M,
    "xxhash32": _native_or("xxhash32", xxhash32),
    "murmur3": _native_or("murmur3", murmur3_32),
}

# lz4 payloads carry varint(raw_len) + block: the LZ4 block format does
# not encode its own output size.  Output size is sanity-capped well above
# the largest stream frame.
_LZ4_MAX_RAW = 64 * 1024 * 1024


_native_fns_cache: Dict[str, tuple] = {}


def _native_fns(name: str):
    """Lazy (compress, decompress) from the native codec library; raises
    RuntimeError if native/codec.cpp could not be built/loaded.  Deferred
    to first use — loading may run g++, which must not happen at import
    time of the host stack."""
    fns = _native_fns_cache.get(name)
    if fns is None:
        from serf_tpu.codec import _native
        fns = getattr(_native, f"{name}_fns")()
        if fns is None:
            raise RuntimeError(
                f"{name} compression requires the native codec library "
                "(native/codec.cpp could not be built/loaded)")
        _native_fns_cache[name] = fns
    return fns


def _lz4_compress(data: bytes) -> bytes:
    comp, _ = _native_fns("lz4")
    return encode_varint(len(data)) + comp(data)


def _lz4_decompress(payload: bytes) -> bytes:
    _, decomp = _native_fns("lz4")
    raw_len, pos = decode_varint(payload)
    # bound the declared size by the format's maximum expansion (~255x)
    # BEFORE allocating — a tiny crafted packet must not force a huge
    # alloc+memset (memory amplification)
    if raw_len > _LZ4_MAX_RAW or raw_len > len(payload) * 255 + 64:
        raise ValueError(f"lz4 declared size {raw_len} implausible "
                         f"for a {len(payload)}-byte payload")
    return decomp(payload[pos:], raw_len)


def _snappy_compress(data: bytes) -> bytes:
    comp, _ = _native_fns("snappy")
    return comp(data)


def _snappy_decompress(payload: bytes) -> bytes:
    _, decomp = _native_fns("snappy")
    # the snappy preamble declares the raw size; apply the same
    # amplification guard as lz4 before the native decoder allocates
    raw_len, _pos = decode_varint(payload)
    if raw_len > _LZ4_MAX_RAW or raw_len > len(payload) * 255 + 64:
        raise ValueError(f"snappy declared size {raw_len} implausible "
                         f"for a {len(payload)}-byte payload")
    return decomp(payload, raw_len)


# zstd rides the baked-in ``zstandard`` module (no new dependency); absent
# from the registry when unavailable so Options validation reports it.
# Contexts are reused across packets (context setup dominates small
# payloads; the asyncio host plane is single-threaded, so this is safe).
try:
    import zstandard as _zstandard
    _zstd_c = _zstandard.ZstdCompressor(level=1)
    _zstd_d = _zstandard.ZstdDecompressor()
except ImportError:  # pragma: no cover - present in this image
    _zstandard = None


def _zstd_compress(data: bytes) -> bytes:
    return _zstd_c.compress(data)


#: amplification cap for compressors WITHOUT a format-level expansion
#: bound (zstd, brotli).  lz4/snappy literal runs cannot exceed ~255x by
#: construction, so their guards stay strictly payload-proportional; a
#: zstd/brotli stream can LEGITIMATELY exceed 255x on uniform data (found
#: live: 5000 x 'x' -> a 19-byte zstd frame, declared 5000 > 19*255+64),
#: so those guards get a 1 MiB allocation floor — still a hard bound on
#: what a malicious tiny packet can force us to allocate.
_ENTROPY_CAP_FLOOR = 1 << 20


def _entropy_cap(payload_len: int) -> int:
    return min(_LZ4_MAX_RAW, max(_ENTROPY_CAP_FLOOR, payload_len * 255 + 64))


def _zstd_decompress(payload: bytes) -> bytes:
    # the frame header declares the content size (ZstdCompressor writes
    # it); bound it BEFORE the decompressor allocates — a ~2 KB RLE frame
    # can otherwise declare (and force allocation of) tens of MB
    params = _zstandard.get_frame_parameters(payload)
    cap = _entropy_cap(len(payload))
    if params.content_size > cap:
        raise ValueError(f"zstd declared size {params.content_size} "
                         f"implausible for a {len(payload)}-byte payload")
    return _zstd_d.decompress(payload, max_output_size=cap)


# brotli rides the system shared libraries (libbrotlienc/libbrotlidec —
# present in this image) through ctypes: no new Python dependency, no
# vendored code.  This closes the reference's 4th feature-gated variant
# (serf-core/Cargo.toml:30-37).  Absent from the registry when the
# libraries are missing, exactly like zstd.
def _load_brotli():
    import ctypes

    try:
        enc = ctypes.CDLL("libbrotlienc.so.1")
        dec = ctypes.CDLL("libbrotlidec.so.1")
        _bind_brotli_symbols(enc, dec)
    except (OSError, AttributeError):
        # missing libraries OR a stripped/old build lacking a symbol:
        # degrade to an absent registry entry, never an import crash
        return None
    return enc, dec


def _bind_brotli_symbols(enc, dec):
    import ctypes

    enc.BrotliEncoderMaxCompressedSize.restype = ctypes.c_size_t
    enc.BrotliEncoderMaxCompressedSize.argtypes = [ctypes.c_size_t]
    enc.BrotliEncoderCompress.restype = ctypes.c_int
    enc.BrotliEncoderCompress.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_size_t,
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_size_t), ctypes.c_void_p]
    dec.BrotliDecoderCreateInstance.restype = ctypes.c_void_p
    dec.BrotliDecoderCreateInstance.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
    dec.BrotliDecoderDestroyInstance.restype = None
    dec.BrotliDecoderDestroyInstance.argtypes = [ctypes.c_void_p]
    dec.BrotliDecoderDecompressStream.restype = ctypes.c_int
    dec.BrotliDecoderDecompressStream.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_size_t),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)),
        ctypes.POINTER(ctypes.c_size_t),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)),
        ctypes.POINTER(ctypes.c_size_t)]


_brotli = _load_brotli()


def _brotli_compress(data: bytes) -> bytes:
    import ctypes

    enc, _ = _brotli
    cap = enc.BrotliEncoderMaxCompressedSize(len(data)) or (len(data) + 1024)
    out = ctypes.create_string_buffer(cap)
    out_len = ctypes.c_size_t(cap)
    # quality 1 / lgwin 22 / mode 0 (GENERIC): the latency-first setting,
    # matching the level-1 stance of the zlib/zstd variants
    ok = enc.BrotliEncoderCompress(1, 22, 0, len(data), data,
                                   ctypes.byref(out_len), out)
    if not ok:
        raise ValueError("brotli compression failed")
    return ctypes.string_at(out, out_len.value)


_BROTLI_CHUNK = 65536


def _brotli_decompress(payload: bytes) -> bytes:
    """Streaming decode with the same payload-proportional amplification
    guard as lz4/snappy/zstd.  Brotli streams carry no declared output
    size, so the bound is enforced incrementally: output grows in
    ``_BROTLI_CHUNK`` pieces and the decode aborts the moment the total
    would exceed the cap — no full-cap allocation ever happens (a 1400-
    byte packet must not cost a 357 KB zeroed buffer per decode)."""
    import ctypes

    _, dec = _brotli
    cap = _entropy_cap(len(payload))
    state = dec.BrotliDecoderCreateInstance(None, None, None)
    if not state:
        raise ValueError("brotli decoder allocation failed")
    try:
        # zero-copy input: the decoder only READS the buffer, and the
        # `payload` local keeps the bytes object alive for the call
        next_in = ctypes.cast(ctypes.c_char_p(payload),
                              ctypes.POINTER(ctypes.c_ubyte))
        avail_in = ctypes.c_size_t(len(payload))
        total = ctypes.c_size_t(0)
        out_chunk = (ctypes.c_ubyte * _BROTLI_CHUNK)()
        chunks = []
        produced_total = 0
        while True:
            next_out = ctypes.cast(out_chunk,
                                   ctypes.POINTER(ctypes.c_ubyte))
            avail_out = ctypes.c_size_t(_BROTLI_CHUNK)
            res = dec.BrotliDecoderDecompressStream(
                state, ctypes.byref(avail_in), ctypes.byref(next_in),
                ctypes.byref(avail_out), ctypes.byref(next_out),
                ctypes.byref(total))
            produced = _BROTLI_CHUNK - avail_out.value
            if produced:
                produced_total += produced
                if produced_total > cap:
                    raise ValueError(
                        f"brotli output exceeds {cap} bytes for a "
                        f"{len(payload)}-byte payload (amplification)")
                chunks.append(ctypes.string_at(out_chunk, produced))
            if res == 1:                      # SUCCESS
                return b"".join(chunks)
            if res == 3:                      # NEEDS_MORE_OUTPUT
                continue
            # 0 = ERROR (corrupt), 2 = NEEDS_MORE_INPUT (truncated)
            raise ValueError(f"brotli decode failed (result {res})")
    finally:
        dec.BrotliDecoderDestroyInstance(state)


# marker byte → (compress, decompress); marker 0 = uncompressed
COMPRESSIONS: Dict[str, Tuple[int, Callable[[bytes], bytes],
                              Callable[[bytes], bytes]]] = {
    "zlib": (1, lambda b: zlib.compress(b, level=1), zlib.decompress),
    "lz4": (2, _lz4_compress, _lz4_decompress),
    "snappy": (3, _snappy_compress, _snappy_decompress),
}
if _zstandard is not None:
    COMPRESSIONS["zstd"] = (4, _zstd_compress, _zstd_decompress)
if _brotli is not None:
    COMPRESSIONS["brotli"] = (5, _brotli_compress, _brotli_decompress)
_DECOMPRESS_BY_MARKER = {m: d for (m, _c, d) in COMPRESSIONS.values()}


def compression_available(name: str) -> bool:
    """Whether a registered variant can actually run here.  The native
    variants (lz4/snappy) need the C++ library; probing may build it once.
    Options validation uses this so an unusable variant fails at
    construction, not on the first packet send."""
    if name not in COMPRESSIONS:
        return False
    if name in ("lz4", "snappy"):
        try:
            _native_fns(name)
        except RuntimeError:
            return False
    return True


class WireError(Exception):
    """Inbound pipeline failure (drop the packet, UDP semantics).

    ``stage`` names the layer that failed — "checksum" (bad or truncated
    checksum frame) or "decompress" (bad marker/payload) — so callers
    can emit the right metric."""

    def __init__(self, stage: str):
        super().__init__(stage)
        self.stage = stage  # "checksum" | "decompress"


def encode_wire(buf: bytes, compression: Optional[str],
                checksum: Optional[str]) -> bytes:
    """compress → checksum (encryption is the keyring's layer, above)."""
    if compression is not None:
        marker, comp, _ = COMPRESSIONS[compression]
        buf = bytes([marker]) + comp(buf)
    elif checksum is not None:
        buf = b"\x00" + buf
    if checksum is not None:
        buf = CHECKSUMS[checksum](buf).to_bytes(4, "big") + buf
    return buf


def decode_wire(buf: bytes, compression: Optional[str],
                checksum: Optional[str]) -> bytes:
    """verify checksum → decompress; raises WireError on any failure."""
    if checksum is not None:
        if len(buf) < 5:
            raise WireError("checksum")
        want = int.from_bytes(buf[:4], "big")
        buf = buf[4:]
        if CHECKSUMS[checksum](buf) != want:
            raise WireError("checksum")
    if compression is not None or checksum is not None:
        if not buf:
            raise WireError("decompress")
        marker, buf = buf[0], buf[1:]
        if marker != 0:
            dec = _DECOMPRESS_BY_MARKER.get(marker)
            if dec is None:
                raise WireError("decompress")
            try:
                buf = dec(buf)
            except Exception as e:  # noqa: BLE001 - any codec failure = drop
                raise WireError("decompress") from e
    return buf


# Batch amortization note (host-plane throughput rebuild): ONE
# encode_wire pass — compress + checksum + encrypt — already covers a
# whole gossip packet (the SWIM compound), and the serf codec's BATCH
# envelope (types/messages.encode_message_batch, framing primitive in
# serf_tpu.codec.encode_frames/decode_frames) packs N queued broadcasts
# into one message inside it — the per-message wire cost is amortized
# at both layers, so no separate wire-level framing API lives here.

# worst-case expansion headroom per compressor on packet-sized payloads
# (zlib: header+adler; lz4: varint size prefix + token overhead n/255+16,
# ~27B at the 1400B UDP budget; snappy: preamble + literal tags n/60;
# zstd: frame header + block headers; brotli: stream header + uncompressed
# meta-block headers).  Keep this table covering the whole COMPRESSIONS
# registry — the .get default below is only a safety net for
# externally-registered algorithms.
_COMPRESSION_OVERHEAD = {"zlib": 16, "lz4": 32, "snappy": 48, "zstd": 64,
                         "brotli": 64}


def wire_overhead(compression: Optional[str], checksum: Optional[str]) -> int:
    """Worst-case bytes encode_wire adds (marker + checksum + compressor
    expansion headroom)."""
    overhead = 0
    if compression is not None or checksum is not None:
        overhead += 1
    if checksum is not None:
        overhead += 4
    if compression is not None:
        overhead += _COMPRESSION_OVERHEAD.get(compression, 64)
    return overhead
