"""Bounded MPMC event pipeline with dependency-aware parallel application.

This module is THE hand-off seam between the protocol plane (message
handlers emitting events) and the delivery plane (snapshotter tee,
coalescers, the application subscriber).  It replaces the serial
``_event_inbox`` → tee task → mid-queue → subscriber chain the PR-12
lifecycle ledger measured as the host hot path's dominant latency owner
(queue-wait owned p50 AND p99 under the query-storm plan): multiple
producers (transport dispatch, stream delivery, local-origin
``user_event``/``query``) feed a bounded keyed intake drained by N
applier workers — Virtual-Link's multi-producer/multi-consumer queue
architecture, made safe by the dependency analysis of "Rethinking
State-Machine Replication for Parallelism" (PAPERS.md).

**Dependency keys** (:func:`dependency_key`) decide what must stay
serial and what may reorder:

- membership events key on the MEMBER IDENTITY — JOIN/FAILED/LEAVE for
  one node apply in arrival order (the snapshotter's alive-set and the
  subscriber's view of a member's life are order-sensitive), while
  events about *different* members commute and apply in parallel;
- user events and queries key on their NAME CLASS
  (:func:`name_class` — the tenant identity: ``storm-17`` → ``storm``),
  so one tenant's events stay FIFO while tenants proceed independently;
- anything unrecognized falls to one serial catch-all key (safe by
  default).

Per-key FIFO is structural: a key's entries live in one deque owned by
exactly one place at a time (the ready ring or a worker), and a worker
finishes an entry — snapshotter observe + delivery push included —
before taking the key's next one.  Cross-key entries are applied by
whichever worker frees first: commutative operations reorder freely.

**Overload semantics are unchanged from PR 5**: the intake is bounded
(``Options.event_inbox_max``); the engine sheds non-membership events at
the bound with counters/flight events closing the accounting, and
MemberEvents are NEVER shed.  Entries carry their own enqueue timestamp
(the old parallel ``_inbox_enq`` side-deque is gone — an entry shed on
one path can no longer leave its timestamp behind on another), feeding
the ``serf.queue.age.inbox``/``.tee`` gauges and the lifecycle ledger's
``queue-wait``/``tee`` stages, which re-anchor onto this pipeline
unchanged.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from serf_tpu.obs import lifecycle
from serf_tpu.utils import metrics
from serf_tpu.utils.logging import get_logger

log = get_logger("pipeline")

#: default applier-worker count (``Options.pipeline_workers``)
DEFAULT_WORKERS = 4

#: longest run one worker serves from a single key before rotating the
#: key to the back of the ready ring — per-key FIFO is preserved, but a
#: hot tenant cannot starve the others
BATCH_MAX = 32

#: gauge-emission sampling: depth/keys gauges are refreshed every N
#: offers (and by the periodic health monitor), never per event — the
#: measurement must not become the load (PR-5 discipline)
GAUGE_EVERY = 64


def name_class(name: str) -> str:
    """Tenant identity of an event/query name: the name with one
    trailing ``-``/``:``/``.``-separated numeric sequence segment
    stripped (``storm-17`` → ``storm``, ``deploy`` → ``deploy``,
    ``svc.web.42`` → ``svc.web``).  Used for dependency keys, per-tenant
    admission buckets, and bounded-cardinality per-name metrics."""
    if not name:
        return name
    for sep in ("-", ":", "."):
        head, _s, tail = name.rpartition(sep)
        if head and tail.isdigit():
            return head
    return name


def dependency_key(ev: Any) -> Tuple[str, str]:
    """The serialization key of one event: same key ⇒ per-key FIFO,
    different keys ⇒ free parallel/reordered application."""
    # imported lazily to keep this module import-light (events imports
    # messages imports codec; the analysis plane never imports us)
    from serf_tpu.host.events import MemberEvent, QueryEvent, UserEvent

    if isinstance(ev, MemberEvent):
        # engine-emitted member events carry exactly one member; a
        # coalesced multi-member event (foreign producer) serializes on
        # the first member — conservative, never unsafe
        mid = ev.members[0].node.id if ev.members else ""
        return ("member", mid)
    if isinstance(ev, UserEvent):
        return ("user", name_class(ev.name))
    if isinstance(ev, QueryEvent):
        return ("query", name_class(ev.name))
    return ("misc", "")


class _Entry:
    """One queued event + its own enqueue timestamp (satellite: the age
    gauge can no longer skew — shed/deliver paths share the entry)."""

    __slots__ = ("ev", "enq")

    def __init__(self, ev: Any, enq: float):
        self.ev = ev
        self.enq = enq


class CoalesceStage:
    """One coalescer + its flush timing, fed synchronously from applier
    workers (``feed``) with the reference's timing contract: flush at
    ``coalesce_period`` after the first buffered event, or sooner after
    a ``quiescent_period`` gap with no new coalescable events
    (reference coalesce.rs:22-155 — the old ``coalesce_loop`` semantics,
    re-hosted off the serial chain)."""

    #: bound on entries a stage may buffer between flushes: past it,
    #: ``feed`` declines and the event takes the direct (possibly
    #: awaiting) push path instead — a flusher wedged on a stalled
    #: LOSSLESS consumer therefore re-engages the pipeline's normal
    #: backpressure (intake fills → shed accounting) instead of growing
    #: the coalescer's buffer without bound or health signal
    MAX_BUFFERED = 4096

    def __init__(self, coalescer, out: Callable, coalesce_period: float,
                 quiescent_period: float, spawn: Callable, name: str,
                 max_buffered: int = MAX_BUFFERED):
        self.coalescer = coalescer
        self._out = out                       # async fn(ev)
        self.coalesce_period = coalesce_period
        self.quiescent_period = quiescent_period
        self.max_buffered = max_buffered
        self._first_at: Optional[float] = None
        self._last_at = 0.0
        self._wake = asyncio.Event()
        self._task = spawn(self._flusher(), name)

    def feed(self, ev: Any) -> bool:
        """True when the coalescer buffered ``ev`` (it will reach the
        subscriber merged, on the flush tick).  False past the buffer
        bound: the caller delivers directly, uncoalesced — losing a
        merge beats losing the memory bound."""
        if self.coalescer.pending() >= self.max_buffered:
            return False
        if not self.coalescer.handle(ev):
            return False
        now = asyncio.get_running_loop().time()
        if self._first_at is None:
            self._first_at = now
            self._wake.set()
        self._last_at = now
        return True

    async def flush_now(self) -> None:
        self._first_at = None
        for ev in self.coalescer.flush():
            await self._out(ev)

    async def _flusher(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            if self._first_at is None:
                self._wake.clear()
                await self._wake.wait()
                continue
            now = loop.time()
            deadline = min(self._first_at + self.coalesce_period,
                           self._last_at + self.quiescent_period)
            if now >= deadline:
                await self.flush_now()
            else:
                await asyncio.sleep(deadline - now)


class EventPipeline:
    """The bounded MPMC hand-off (module docstring has the contract).

    ``offer(ev)`` is the ONE producer API — everything between the
    protocol handlers and delivery goes through it (the serflint
    ``pipeline-bypass`` rule guards the seam).  ``observe`` (sync; the
    snapshotter tee) and ``deliver`` (async; coalescers + subscriber
    push) run per event inside the applier workers, per-key serial.

    All state is mutated on the event-loop thread only; ``offer`` is
    synchronous and workers only interleave at their ``deliver`` awaits,
    so the chain/ready structures need no locks (the same discipline the
    lifecycle ledger documents).
    """

    def __init__(self, *, spawn: Callable,
                 observe: Optional[Callable[[Any], None]] = None,
                 deliver: Optional[Callable] = None,
                 deliver_sync: Optional[Callable[[Any], None]] = None,
                 workers: int = DEFAULT_WORKERS,
                 batch_max: int = BATCH_MAX,
                 labels: Optional[Dict[str, str]] = None,
                 node: str = ""):
        if deliver is not None and deliver_sync is not None:
            raise ValueError("pass deliver (async) OR deliver_sync, not both")
        self._observe = observe
        self._deliver = deliver
        #: fully-synchronous delivery (drop-oldest subscriber +
        #: coalescer feeds never await): enables the run-to-completion
        #: fast path — an event whose dependency chain is idle is
        #: applied INLINE at offer() (zero queue-wait, no task wake),
        #: degrading to the queued MPMC hand-off exactly when there is
        #: contention to serialize.  A LOSSLESS subscriber's awaiting
        #: push keeps the async path (and its backpressure contract).
        self._deliver_sync = deliver_sync
        self.batch_max = max(1, batch_max)
        self._labels = {**(labels or {}), "node": node}
        self._chains: Dict[Tuple[str, str], Deque[_Entry]] = {}
        self._ready: Deque[Tuple[str, str]] = deque()
        self._pending = 0
        self._offers = 0
        #: events fully applied (observe + deliver complete)
        self.applied = 0
        #: applied INLINE at offer() (the run-to-completion fast path:
        #: zero queue-wait, no worker wake) — ``applied - inline_applied``
        #: is the queued MPMC remainder; the split is the
        #: ``serf.pipeline.inline-share`` gauge on the monitor tick
        self.inline_applied = 0
        #: per-worker enqueue timestamp of the entry being serviced
        self._inflight: Dict[int, float] = {}
        self._wake = asyncio.Event()
        self._closing = False
        self._drained = asyncio.Event()
        # applier workers spawn LAZILY on the first queued entry: the
        # run-to-completion fast path needs no tasks at all, and an
        # engine constructed outside a running loop (test oracles drive
        # handlers synchronously) stays constructible
        self._spawn = spawn
        self._node = node
        self._nworkers = max(1, workers)
        self._workers: List[asyncio.Task] = []

    def _ensure_workers(self) -> None:
        if not self._workers:
            self._workers = [
                self._spawn(self._worker(i), f"pipeline-w{i}-{self._node}")
                for i in range(self._nworkers)]

    # -- producer side ------------------------------------------------------

    def offer(self, ev: Any) -> None:
        """Enqueue one event for dependency-keyed application.  ``None``
        is the graceful-stop sentinel: the pipeline drains everything
        already offered, flushes nothing further, and the workers
        exit.  Bounding/shedding policy lives with the CALLER
        (``Serf._emit`` — it owns the member-exemption and the
        accounting); ``depth()`` is the signal it checks."""
        if ev is None:
            self._closing = True
            self._wake.set()
            return
        key = dependency_key(ev)
        chain = self._chains.get(key)
        self._offers += 1
        if chain is None and self._deliver_sync is not None \
                and not self._closing:
            # run-to-completion fast path: the chain is idle (nothing
            # older with this key is pending OR in service — keys stay
            # in _chains until their last entry finishes) and delivery
            # never awaits, so applying here preserves per-key FIFO and
            # skips the queue hop entirely
            self._apply_sync(ev)
        else:
            self._ensure_workers()
            entry = _Entry(ev, time.monotonic())
            if chain is None:
                # ownership: a key living in _chains is either in the
                # ready ring or held by a worker — never both
                self._chains[key] = deque((entry,))
                self._ready.append(key)
                self._wake.set()
            else:
                chain.append(entry)
            self._pending += 1
        if self._offers % GAUGE_EVERY == 0:
            self._gauge()

    def _apply_sync(self, ev: Any) -> None:
        ledger = lifecycle.global_ledger()
        ledger.event_stamp(ev, "queue-wait")     # ≈0: no queue was waited
        try:
            if self._observe is not None:
                self._observe(ev)
            self._deliver_sync(ev)
        except Exception:  # noqa: BLE001 - one event must not break the
            # producer's handler frame (same discipline as the workers)
            log.exception("inline event application failed for %r",
                          type(ev).__name__)
        ledger.event_finish(ev, "tee")
        self.applied += 1
        self.inline_applied += 1

    # -- consumer side ------------------------------------------------------

    async def _worker(self, idx: int) -> None:
        led = lifecycle.global_ledger
        while True:
            while not self._ready:
                # drained = nothing pending AND nothing mid-delivery in
                # another worker — aclose() must not cancel a sibling
                # inside its push on the strength of an empty intake
                if self._closing and self._pending == 0 \
                        and not self._inflight:
                    self._drained.set()
                    return
                self._wake.clear()
                await self._wake.wait()
            key = self._ready.popleft()
            chain = self._chains.get(key)
            served = 0
            while chain and served < self.batch_max:
                entry = chain.popleft()
                self._pending -= 1
                served += 1
                ev = entry.ev
                self._inflight[idx] = entry.enq
                ledger = led()
                ledger.event_stamp(ev, "queue-wait")
                try:
                    if self._observe is not None:
                        self._observe(ev)
                    if self._deliver is not None:
                        await self._deliver(ev)
                    elif self._deliver_sync is not None:
                        self._deliver_sync(ev)
                except asyncio.CancelledError:
                    self._inflight.pop(idx, None)
                    raise
                except Exception:  # noqa: BLE001 - one event must not
                    # kill the applier (UDP-plane discipline: log + go on)
                    log.exception("event application failed for %r",
                                  type(ev).__name__)
                self._inflight.pop(idx, None)
                ledger.event_finish(ev, "tee")
                self.applied += 1
            if served:
                metrics.observe("serf.pipeline.batch", served, self._labels)
            if chain:
                # key still hot: rotate to the back of the ready ring
                # (per-key FIFO intact, no tenant starves the rest)
                self._ready.append(key)
            else:
                # no awaits between the emptiness check and the delete:
                # a producer appending during our last deliver await saw
                # the chain in _chains and we saw its entry just above
                self._chains.pop(key, None)

    # -- signals / reads ----------------------------------------------------

    def depth(self) -> int:
        """Entries offered but not yet picked up by a worker (the
        backpressure bound ``Serf._emit`` sheds against)."""
        return self._pending

    def inflight(self) -> int:
        return len(self._inflight)

    def keys(self) -> int:
        """Active dependency chains (parallelism breadth signal)."""
        return len(self._chains)

    def oldest_age(self, now: Optional[float] = None) -> float:
        """Age of the oldest entry still waiting in the intake (the
        ``serf.queue.age.inbox`` signal); 0.0 when idle."""
        heads = [c[0].enq for c in self._chains.values() if c]
        if not heads:
            return 0.0
        if now is None:
            now = time.monotonic()
        return max(0.0, now - min(heads))

    def oldest_service_age(self, now: Optional[float] = None) -> float:
        """Age (since ENQUEUE) of the oldest entry currently being
        applied (the ``serf.queue.age.tee`` signal — a growing value
        with flat depth means a wedged delivery, not a burst)."""
        if not self._inflight:
            return 0.0
        if now is None:
            now = time.monotonic()
        return max(0.0, now - min(self._inflight.values()))

    def _gauge(self) -> None:
        metrics.gauge("serf.pipeline.depth", self._pending, self._labels)
        metrics.gauge("serf.pipeline.keys", len(self._chains), self._labels)
        metrics.gauge("serf.events.tee_depth",
                      self._pending + len(self._inflight), self._labels)

    def gauge(self) -> None:
        """Refresh the pipeline gauges (periodic monitor hook): depth/
        keys plus the PR-15 observability-gap set — per-worker occupancy
        (what fraction of appliers are mid-delivery), the inline-vs-
        queued delivery split (how often the run-to-completion fast path
        wins), the ready-ring depth (keys waiting for a worker), and the
        per-dependency-key chain length p50/max (is one tenant's chain
        the backlog, or is it broad?).  O(keys) work, monitor-tick
        cadence only — never per event."""
        from serf_tpu.utils.metrics import percentile_of

        self._gauge()
        metrics.gauge("serf.pipeline.occupancy",
                      len(self._inflight) / self._nworkers, self._labels)
        if self.applied:
            metrics.gauge("serf.pipeline.inline-share",
                          self.inline_applied / self.applied,
                          self._labels)
        metrics.gauge("serf.pipeline.ready-depth", len(self._ready),
                      self._labels)
        lens = sorted(len(c) for c in self._chains.values())
        metrics.gauge("serf.pipeline.chain-p50",
                      percentile_of(lens, 50) if lens else 0.0,
                      self._labels)
        metrics.gauge("serf.pipeline.chain-max",
                      float(lens[-1]) if lens else 0.0, self._labels)

    async def aclose(self, timeout: float = 2.0) -> None:
        """Graceful stop: drain everything already offered, then stop
        the workers.  Bounded — a wedged delivery degrades to a loud
        warning + cancel, never a hung shutdown."""
        self._closing = True
        self._wake.set()
        if self._pending or self._inflight:
            try:
                await asyncio.wait_for(self._drained.wait(), timeout)
            except asyncio.TimeoutError:
                log.warning("pipeline close timed out with %d pending",
                            self._pending)
        for t in self._workers:
            t.cancel()
