"""Transmit-limited broadcast queue.

The retransmit-limited gossip queue the reference takes from memberlist-core
(SURVEY.md §2.3/§2.9): each queued broadcast is re-gossiped until it has been
transmitted ``retransmit_mult * ceil(log10(n+1))`` times, drained
highest-remaining-retransmits-first under a per-packet byte budget.

Serf's three queues (intent/event/query) use *no invalidation* — Lamport-time
dedup supersedes it (reference broadcast.rs:15-45); the SWIM layer's own
queue invalidates older broadcasts about the same node.

Overload protection (ISSUE 5): a queue can carry a BYTE budget on top of
the reference's count-only QueueChecker prune.  A queue over its budget
sheds the most-transmitted (oldest among equals) broadcasts first — they
have had the most dissemination — emitting ``serf.overload.queue_shed``
counters and flight events so every shed is accounted.  Queues carrying
membership state (the SWIM alive/suspect/dead queue) are constructed
``sheddable=False`` and never byte-shed: the shedding priority order is
membership facts > leave/join intents > user events > query fan-out,
realized through each queue's budget (intent gets the largest, query the
smallest).
"""

from __future__ import annotations

import asyncio
import math
import time
from typing import Callable, Dict, List, Optional

from serf_tpu.obs import flight
from serf_tpu.utils import metrics


class Broadcast:
    """One queued message."""

    __slots__ = ("msg", "name", "transmits", "notify", "_seq", "decoded",
                 "enqueued_at")

    def __init__(self, msg: bytes, name: Optional[str] = None,
                 notify: Optional[asyncio.Event] = None):
        self.msg = msg
        self.name = name      # invalidation key (None = never invalidates)
        self.transmits = 0
        self.notify = notify
        self._seq = 0
        #: monotonic enqueue time, stamped by queue_broadcast — feeds
        #: the oldest-item age gauges (serf.queue.age.*)
        self.enqueued_at = 0.0
        #: consumer-owned memo of the decoded message (``msg`` is
        #: immutable, so decoding once is enough — the reaper's pending-
        #: leave index uses this to stop re-decoding every queued intent
        #: broadcast on every tick)
        self.decoded = None

    def finished(self) -> None:
        if self.notify is not None:
            self.notify.set()


def retransmit_limit(retransmit_mult: int, n: int) -> int:
    return retransmit_mult * max(1, math.ceil(math.log10(n + 1)))


class TransmitLimitedQueue:
    """Priority queue keyed by (fewest transmits first, newest first).

    ``node_count_fn`` is the live NodeCalculator the reference wires in
    (serf-core/src/serf.rs:123-131) — the retransmit limit tracks cluster
    size as it changes.
    """

    def __init__(self, retransmit_mult: int, node_count_fn: Callable[[], int],
                 name: Optional[str] = None,
                 labels: Optional[Dict[str, str]] = None,
                 max_bytes: int = 0, sheddable: bool = True):
        self.retransmit_mult = retransmit_mult
        self.node_count_fn = node_count_fn
        #: observability identity: named queues emit ``serf.queue.<name>``
        #: depth gauges at every mutation (queue/drain/prune) and flight
        #: events on overflow/retirement; unnamed queues stay silent
        self.name = name
        self.labels = labels
        #: byte budget: over this, queue_broadcast sheds most-transmitted
        #: items until back under.  0 = unbounded.  Ignored (with a
        #: construction-time error) when the queue is not sheddable.
        self.max_bytes = max_bytes
        #: queues carrying membership state are constructed
        #: sheddable=False: they may be depth-pruned by the legacy
        #: QueueChecker but NEVER byte-shed — losing a death/alive fact
        #: is a correctness hazard, losing a user event is load shedding
        self.sheddable = sheddable
        if max_bytes > 0 and not sheddable:
            raise ValueError("a non-sheddable queue cannot take a byte "
                             "budget (it would have no way to honor it)")
        self._items: List[Broadcast] = []
        self._bytes = 0
        self._seq = 0
        #: bumped whenever queue MEMBERSHIP changes (queue/invalidate/
        #: retire/prune) — cheap change detection for derived indexes
        #: (transmit-count bumps alone don't count: they change no
        #: membership-derived answer)
        self.mutations = 0
        #: broadcasts/bytes shed by the byte budget over this queue's life
        self.shed = 0
        self.shed_bytes = 0

    def __len__(self) -> int:
        return len(self._items)

    def num_queued(self) -> int:
        return len(self._items)

    def bytes(self) -> int:
        """Total payload bytes currently queued."""
        return self._bytes

    def oldest_age(self, now: Optional[float] = None) -> float:
        """Age (seconds) of the oldest still-queued broadcast; 0.0 when
        empty.  O(depth) scan, called on the periodic monitor tick only
        (depth is bounded by the QueueChecker prune / byte budget)."""
        if not self._items:
            return 0.0
        if now is None:
            now = time.monotonic()
        return max(0.0, now - min(b.enqueued_at for b in self._items))

    def _gauge_depth(self) -> None:
        if self.name is not None:
            metrics.gauge(f"serf.queue.{self.name}", len(self._items),
                          self.labels)
            metrics.gauge(f"serf.queue.bytes.{self.name}", self._bytes,
                          self.labels)

    def _remove(self, b: Broadcast) -> None:
        self._items.remove(b)
        self._bytes -= len(b.msg)

    def queue_broadcast(self, b: Broadcast) -> None:
        if b.name is not None:
            # invalidate older broadcasts about the same subject
            for old in [x for x in self._items if x.name == b.name]:
                self._remove(old)
                old.finished()
        self._seq += 1
        b._seq = self._seq
        b.enqueued_at = time.monotonic()
        self._items.append(b)
        self._bytes += len(b.msg)
        self.mutations += 1
        if self.max_bytes > 0 and self._bytes > self.max_bytes:
            self._shed_over_bytes()
        self._gauge_depth()

    def _shed_over_bytes(self) -> None:
        """Byte-budget enforcement: drop most-transmitted (oldest among
        equals) broadcasts until back under ``max_bytes``.  The freshly
        queued item is the LAST candidate — but a single over-budget
        item still sheds (the bound is hard, not advisory)."""
        self._items.sort(key=lambda x: (x.transmits, -x._seq))
        dropped = 0
        dropped_bytes = 0
        while self._bytes > self.max_bytes and self._items:
            victim = self._items.pop()        # most transmits, then oldest
            self._bytes -= len(victim.msg)
            dropped += 1
            dropped_bytes += len(victim.msg)
            victim.finished()
        if not dropped:
            return
        self.shed += dropped
        self.shed_bytes += dropped_bytes
        self.mutations += 1
        qname = self.name or "unnamed"
        labels = {**(self.labels or {}), "queue": qname}
        metrics.incr("serf.overload.queue_shed", dropped, labels)
        metrics.incr("serf.overload.queue_shed_bytes", dropped_bytes, labels)
        flight.record("queue-shed", queue=qname, dropped=dropped,
                      bytes=dropped_bytes, budget=self.max_bytes)

    def get_broadcasts(self, overhead: int, limit: int) -> List[bytes]:
        """Drain up to ``limit`` bytes of broadcasts, ``overhead`` bytes
        charged per message (envelope/frame cost).  Mutates transmit counts
        and retires exhausted broadcasts."""
        if not self._items:
            return []
        transmit_max = retransmit_limit(self.retransmit_mult, self.node_count_fn())
        # fewest transmits first; among equal, newest (highest seq) first
        self._items.sort(key=lambda b: (b.transmits, -b._seq))
        out: List[bytes] = []
        used = 0
        retired: List[Broadcast] = []
        for b in self._items:
            cost = overhead + len(b.msg)
            if used + cost > limit:
                continue
            used += cost
            out.append(b.msg)
            b.transmits += 1
            if b.transmits >= transmit_max:
                retired.append(b)
        if retired:
            self.mutations += 1
        for b in retired:
            self._remove(b)
            b.finished()
            if self.name is not None:
                flight.record("broadcast-retired", queue=self.name,
                              transmits=b.transmits, bytes=len(b.msg),
                              subject=b.name)
        if out:
            self._gauge_depth()
        return out

    def prune(self, max_retained: int) -> None:
        """Drop the most-transmitted items beyond ``max_retained``
        (reference QueueChecker, base.rs:683-740)."""
        if len(self._items) <= max_retained:
            return
        self._items.sort(key=lambda b: (b.transmits, -b._seq))
        dropped = len(self._items) - max_retained
        for b in self._items[max_retained:]:
            self._bytes -= len(b.msg)
            b.finished()
        del self._items[max_retained:]
        self.mutations += 1
        if self.name is not None:
            flight.record("queue-overflow", queue=self.name,
                          dropped=dropped, retained=max_retained)
        self._gauge_depth()
