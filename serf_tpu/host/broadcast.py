"""Transmit-limited broadcast queue.

The retransmit-limited gossip queue the reference takes from memberlist-core
(SURVEY.md §2.3/§2.9): each queued broadcast is re-gossiped until it has been
transmitted ``retransmit_mult * ceil(log10(n+1))`` times, drained
highest-remaining-retransmits-first under a per-packet byte budget.

Serf's three queues (intent/event/query) use *no invalidation* — Lamport-time
dedup supersedes it (reference broadcast.rs:15-45); the SWIM layer's own
queue invalidates older broadcasts about the same node.
"""

from __future__ import annotations

import asyncio
import math
from typing import Callable, Dict, List, Optional

from serf_tpu.obs import flight
from serf_tpu.utils import metrics


class Broadcast:
    """One queued message."""

    __slots__ = ("msg", "name", "transmits", "notify", "_seq", "decoded")

    def __init__(self, msg: bytes, name: Optional[str] = None,
                 notify: Optional[asyncio.Event] = None):
        self.msg = msg
        self.name = name      # invalidation key (None = never invalidates)
        self.transmits = 0
        self.notify = notify
        self._seq = 0
        #: consumer-owned memo of the decoded message (``msg`` is
        #: immutable, so decoding once is enough — the reaper's pending-
        #: leave index uses this to stop re-decoding every queued intent
        #: broadcast on every tick)
        self.decoded = None

    def finished(self) -> None:
        if self.notify is not None:
            self.notify.set()


def retransmit_limit(retransmit_mult: int, n: int) -> int:
    return retransmit_mult * max(1, math.ceil(math.log10(n + 1)))


class TransmitLimitedQueue:
    """Priority queue keyed by (fewest transmits first, newest first).

    ``node_count_fn`` is the live NodeCalculator the reference wires in
    (serf-core/src/serf.rs:123-131) — the retransmit limit tracks cluster
    size as it changes.
    """

    def __init__(self, retransmit_mult: int, node_count_fn: Callable[[], int],
                 name: Optional[str] = None,
                 labels: Optional[Dict[str, str]] = None):
        self.retransmit_mult = retransmit_mult
        self.node_count_fn = node_count_fn
        #: observability identity: named queues emit ``serf.queue.<name>``
        #: depth gauges at every mutation (queue/drain/prune) and flight
        #: events on overflow/retirement; unnamed queues stay silent
        self.name = name
        self.labels = labels
        self._items: List[Broadcast] = []
        self._seq = 0
        #: bumped whenever queue MEMBERSHIP changes (queue/invalidate/
        #: retire/prune) — cheap change detection for derived indexes
        #: (transmit-count bumps alone don't count: they change no
        #: membership-derived answer)
        self.mutations = 0

    def __len__(self) -> int:
        return len(self._items)

    def num_queued(self) -> int:
        return len(self._items)

    def _gauge_depth(self) -> None:
        if self.name is not None:
            metrics.gauge(f"serf.queue.{self.name}", len(self._items),
                          self.labels)

    def queue_broadcast(self, b: Broadcast) -> None:
        if b.name is not None:
            # invalidate older broadcasts about the same subject
            for old in [x for x in self._items if x.name == b.name]:
                self._items.remove(old)
                old.finished()
        self._seq += 1
        b._seq = self._seq
        self._items.append(b)
        self.mutations += 1
        self._gauge_depth()

    def get_broadcasts(self, overhead: int, limit: int) -> List[bytes]:
        """Drain up to ``limit`` bytes of broadcasts, ``overhead`` bytes
        charged per message (envelope/frame cost).  Mutates transmit counts
        and retires exhausted broadcasts."""
        if not self._items:
            return []
        transmit_max = retransmit_limit(self.retransmit_mult, self.node_count_fn())
        # fewest transmits first; among equal, newest (highest seq) first
        self._items.sort(key=lambda b: (b.transmits, -b._seq))
        out: List[bytes] = []
        used = 0
        retired: List[Broadcast] = []
        for b in self._items:
            cost = overhead + len(b.msg)
            if used + cost > limit:
                continue
            used += cost
            out.append(b.msg)
            b.transmits += 1
            if b.transmits >= transmit_max:
                retired.append(b)
        if retired:
            self.mutations += 1
        for b in retired:
            self._items.remove(b)
            b.finished()
            if self.name is not None:
                flight.record("broadcast-retired", queue=self.name,
                              transmits=b.transmits, bytes=len(b.msg),
                              subject=b.name)
        if out:
            self._gauge_depth()
        return out

    def prune(self, max_retained: int) -> None:
        """Drop the most-transmitted items beyond ``max_retained``
        (reference QueueChecker, base.rs:683-740)."""
        if len(self._items) <= max_retained:
            return
        self._items.sort(key=lambda b: (b.transmits, -b._seq))
        dropped = len(self._items) - max_retained
        for b in self._items[max_retained:]:
            b.finished()
        del self._items[max_retained:]
        self.mutations += 1
        if self.name is not None:
            flight.record("queue-overflow", queue=self.name,
                          dropped=dropped, retained=max_retained)
        self._gauge_depth()
