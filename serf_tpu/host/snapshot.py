"""Snapshot / checkpoint-resume: append-only log of membership + clocks.

Reference: serf-core/src/snapshot.rs (885 LoC; SURVEY.md §2.6/§5).  Records:
Alive(node), NotAlive(node), Clock/EventClock/QueryClock(t), Leave, Comment.
The writer consumes the event stream (tee'd in the serf event pipeline),
flushes every FLUSH_INTERVAL, re-stamps clocks every CLOCK_INTERVAL, fsyncs
on leave/shutdown, and compacts (rewrite alive-set + clocks, atomic rename)
when the file exceeds ``max(min_compact_size, 2 * 128 * N_alive)``.

Resume: replay on startup seeds the clocks (witness), sets event/query
min-times to old+1 so replayed events are suppressed, and hands back the
known-alive nodes for shuffled auto-rejoin (reference base.rs:129-165).
"""

from __future__ import annotations

import asyncio
import os
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from serf_tpu import codec
from serf_tpu.host.events import MemberEvent, MemberEventType, QueryEvent, UserEvent
from serf_tpu.obs.trace import span
from serf_tpu.types.member import Node
from serf_tpu.utils import metrics

from serf_tpu.utils.logging import get_logger

log = get_logger("snapshot")

# record types
R_ALIVE = 1
R_NOT_ALIVE = 2
R_CLOCK = 3
R_EVENT_CLOCK = 4
R_QUERY_CLOCK = 5
R_LEAVE = 6
R_COMMENT = 7

FLUSH_INTERVAL = 0.5
CLOCK_INTERVAL = 0.5
MEMBER_RECORD_SIZE_HINT = 128  # bytes/member estimate for compaction threshold


class SnapshotLockError(RuntimeError):
    """A second process already owns this snapshot file (ISSUE 19
    satellite: two agents pointed at one snapshot dir must fail closed —
    interleaved appends from two writers would corrupt the log for
    both)."""


def _record(ty: int, payload: bytes = b"") -> bytes:
    return bytes([ty]) + codec.encode_varint(len(payload)) + payload


def _iter_records(buf: bytes):
    """Yield ``(ty, payload, end_pos)`` for each complete record.

    A crash during append leaves a TORN TAIL — a record whose header or
    payload is cut mid-write.  Replay tolerates it: the torn bytes are
    skipped with a warning + ``serf.snapshot.torn_tail`` counter (never
    an exception — boot must succeed on the complete prefix), and the
    yielded ``end_pos`` lets the writer truncate the file back to the
    last complete record so post-restart appends never interleave with
    garbage.
    """
    pos = 0
    n = len(buf)
    while pos < n:
        ty = buf[pos]
        try:
            ln, p = codec.decode_varint(buf, pos + 1)
        except codec.DecodeError:
            _report_torn_tail(pos, n, "record header")
            return
        if p + ln > n:
            _report_torn_tail(pos, n, "record payload")
            return
        yield ty, buf[p : p + ln], p + ln
        pos = p + ln


def _report_torn_tail(pos: int, total: int, what: str) -> None:
    log.warning("snapshot torn tail: %s cut at byte %d/%d; skipping "
                "%d trailing bytes (crash during append)",
                what, pos, total, total - pos)
    metrics.incr("serf.snapshot.torn_tail", 1)
    from serf_tpu.obs import flight
    flight.record("snapshot-torn-tail", offset=pos,
                  dropped_bytes=total - pos, what=what)


def _safe_varint(payload: bytes, fallback: int) -> int:
    """A corrupt clock record must not prevent boot (replay is best-effort)."""
    try:
        value, _ = codec.decode_varint(payload)
        return value
    except codec.DecodeError:
        log.warning("corrupt clock record in snapshot; keeping previous value")
        return fallback


@dataclass
class ReplayResult:
    alive_nodes: List[Node] = field(default_factory=list)
    last_clock: int = 0
    last_event_clock: int = 0
    last_query_clock: int = 0
    left_before: bool = False
    #: bytes of the file covered by COMPLETE records; anything past this
    #: is a torn tail (crash mid-append) the writer truncates on reopen
    valid_length: int = 0
    #: unknown/legacy record types skipped during replay (counted in
    #: ``serf.snapshot.unknown_record``) — replay continues past them
    unknown_records: int = 0


def open_and_replay_snapshot(path: str, rejoin_after_leave: bool = False) -> ReplayResult:
    """(reference snapshot.rs:228-347)"""
    res = ReplayResult()
    if not os.path.exists(path):
        return res
    with open(path, "rb") as f:
        buf = f.read()
    alive: Dict[str, Node] = {}
    for ty, payload, end in _iter_records(buf):
        res.valid_length = end
        if ty == R_ALIVE:
            try:
                node = Node.decode(payload)
            except codec.DecodeError:
                continue
            alive[node.id] = node
        elif ty == R_NOT_ALIVE:
            try:
                node = Node.decode(payload)
            except codec.DecodeError:
                continue
            alive.pop(node.id, None)
        elif ty == R_CLOCK:
            res.last_clock = _safe_varint(payload, res.last_clock)
        elif ty == R_EVENT_CLOCK:
            res.last_event_clock = _safe_varint(payload, res.last_event_clock)
        elif ty == R_QUERY_CLOCK:
            res.last_query_clock = _safe_varint(payload, res.last_query_clock)
        elif ty == R_LEAVE:
            res.left_before = True
            if not rejoin_after_leave:
                alive.clear()
        elif ty == R_COMMENT:
            pass
        else:
            # unknown/legacy record type: SKIP it and keep replaying
            # (reference snapshot.rs:115-215 skips legacy Coordinate
            # records the same way).  The length prefix makes the skip
            # safe without understanding the payload; aborting here
            # would throw away every record after the first one a newer
            # (or older) build wrote.
            res.unknown_records += 1
            metrics.incr("serf.snapshot.unknown_record", 1)
            log.warning("skipping unknown snapshot record type %d "
                        "(%d bytes payload)", ty, len(payload))
    res.alive_nodes = list(alive.values())
    return res


class Snapshotter:
    """Event-stream consumer writing the append-only log."""

    def __init__(self, path: str, replay: ReplayResult, labels=None,
                 clock_fn: Optional[Callable[[], Tuple[int, int, int]]] = None,
                 min_compact_size: int = 128 * 1024,
                 rejoin_after_leave: bool = False):
        self.path = path
        self.labels = labels
        self.clock_fn = clock_fn
        self.min_compact_size = min_compact_size
        self.rejoin_after_leave = rejoin_after_leave
        self.left_before = replay.left_before
        self._alive: Dict[str, Node] = {n.id: n for n in replay.alive_nodes}
        self._last_clocks = (replay.last_clock, replay.last_event_clock,
                             replay.last_query_clock)
        # EXCLUSIVITY GUARD (before any repair or append): one writer per
        # snapshot file, enforced with a non-blocking flock on a sidecar
        # lock file.  The lock dies with the process (SIGKILL included),
        # so a crash-restart re-acquires it immediately — while a second
        # LIVE process fails closed instead of interleaving appends.
        self._lock_path = path + ".lock"
        self._lock_fd = os.open(self._lock_path,
                                os.O_WRONLY | os.O_CREAT, 0o644)
        try:
            import fcntl
            fcntl.flock(self._lock_fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError as e:
            holder = ""
            try:
                with open(self._lock_path) as lf:
                    holder = lf.read().strip()
            except OSError:
                pass
            os.close(self._lock_fd)
            self._lock_fd = -1
            metrics.incr("serf.snapshot.lock_conflict", 1)
            raise SnapshotLockError(
                f"snapshot {path} is owned by another process"
                + (f" (pid {holder})" if holder else "") + f": {e}") from e
        # pid is diagnostic only (flock is the guard): truncate-then-write
        # keeps stale pids from a previous holder out of the message
        os.ftruncate(self._lock_fd, 0)
        os.write(self._lock_fd, str(os.getpid()).encode())
        # torn-tail repair: a crash mid-append left bytes past the last
        # complete record — truncate them BEFORE appending, so the new
        # records never interleave with garbage (a later replay would
        # otherwise stop at the tear and silently drop everything after)
        try:
            size = os.path.getsize(path) if os.path.exists(path) else 0
        except OSError:
            size = 0
        if replay.valid_length < size:
            log.warning("truncating snapshot %s torn tail: %d -> %d bytes",
                        path, size, replay.valid_length)
            with open(path, "r+b") as f:
                f.truncate(replay.valid_length)
        self._f = open(path, "ab")
        self._dirty = False
        self._stopped = False
        self._leaving = False

    # -- event tee (called synchronously from the serf event pipeline) -----

    def observe(self, ev) -> None:
        if self._stopped or self._leaving:
            return
        if isinstance(ev, MemberEvent):
            if ev.ty in (MemberEventType.JOIN, MemberEventType.UPDATE):
                for m in ev.members:
                    self._alive[m.node.id] = m.node
                    self._append(R_ALIVE, m.node.encode())
            elif ev.ty in (MemberEventType.LEAVE, MemberEventType.FAILED,
                           MemberEventType.REAP):
                for m in ev.members:
                    self._alive.pop(m.node.id, None)
                    self._append(R_NOT_ALIVE, m.node.encode())
        elif isinstance(ev, UserEvent):
            self._append(R_EVENT_CLOCK, codec.encode_varint(ev.ltime))
        elif isinstance(ev, QueryEvent):
            self._append(R_QUERY_CLOCK, codec.encode_varint(ev.ltime))

    def _append(self, ty: int, payload: bytes = b"") -> None:
        if self._stopped:
            return
        start = time.monotonic()
        self._f.write(_record(ty, payload))
        self._dirty = True
        metrics.observe("serf.snapshot.append_line",
                        (time.monotonic() - start) * 1e3, self.labels)

    # -- background loop ----------------------------------------------------

    async def run(self) -> None:
        last_clock_stamp = 0.0
        try:
            while not self._stopped:
                await asyncio.sleep(FLUSH_INTERVAL)
                now = time.monotonic()
                if self.clock_fn is not None and now - last_clock_stamp >= CLOCK_INTERVAL:
                    c, e, q = self.clock_fn()
                    lc, le, lq = self._last_clocks
                    if c != lc:
                        self._append(R_CLOCK, codec.encode_varint(c))
                    if e != le:
                        self._append(R_EVENT_CLOCK, codec.encode_varint(e))
                    if q != lq:
                        self._append(R_QUERY_CLOCK, codec.encode_varint(q))
                    self._last_clocks = (c, e, q)
                    last_clock_stamp = now
                if self._dirty:
                    self._f.flush()
                    self._dirty = False
                self._maybe_compact()
        except asyncio.CancelledError:
            raise

    def _maybe_compact(self) -> None:
        """(reference snapshot.rs:766-884)"""
        try:
            size = self._f.tell()
        except ValueError:
            return
        threshold = max(self.min_compact_size,
                        2 * MEMBER_RECORD_SIZE_HINT * max(1, len(self._alive)))
        if size <= threshold or self._leaving:
            # after leave(), a compaction would rewrite the log without the
            # leave record and with the full alive set — a restart would then
            # auto-rejoin a cluster the operator deliberately left
            return
        start = time.monotonic()
        tmp = self.path + ".compact"
        with span("snapshot.compact", bytes_before=size) as sp:
            with open(tmp, "wb") as out:
                c, e, q = self._last_clocks
                if self.clock_fn is not None:
                    c, e, q = self.clock_fn()
                out.write(_record(R_CLOCK, codec.encode_varint(c)))
                out.write(_record(R_EVENT_CLOCK, codec.encode_varint(e)))
                out.write(_record(R_QUERY_CLOCK, codec.encode_varint(q)))
                for node in self._alive.values():
                    out.write(_record(R_ALIVE, node.encode()))
                out.flush()
                os.fsync(out.fileno())
            self._f.close()
            os.replace(tmp, self.path)
            self._f = open(self.path, "ab")
            sp.attrs["bytes_after"] = self._f.tell()
        metrics.observe("serf.snapshot.compact",
                        (time.monotonic() - start) * 1e3, self.labels)
        log.info("snapshot compacted to %d bytes", self._f.tell())

    # -- lifecycle ----------------------------------------------------------

    async def leave(self) -> None:
        """Mark a deliberate leave so restart does not auto-rejoin
        (reference snapshot.rs:562-579): append the leave record, then stop
        recording and compacting, and drop the alive set unless the operator
        asked to rejoin after leave."""
        self._append(R_LEAVE)
        self._leaving = True
        if not self.rejoin_after_leave:
            self._alive.clear()
        self._fsync()

    async def shutdown(self) -> None:
        if self._stopped:
            return
        self._stopped = True
        self._fsync()
        self._f.close()
        # release the exclusivity lock LAST: the file is closed, a
        # successor (e.g. a restart in the same process tree) may open
        if self._lock_fd >= 0:
            try:
                os.close(self._lock_fd)
            except OSError:
                pass
            self._lock_fd = -1

    def _fsync(self) -> None:
        try:
            self._f.flush()
            os.fsync(self._f.fileno())
        except (OSError, ValueError):
            pass
