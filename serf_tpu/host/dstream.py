"""Datagram-stream transport: reliable framed streams over UDP.

The reference wires THREE stream transports — TCP, TLS-over-TCP, and QUIC
(quinn) (serf/Cargo.toml:24-56, README.md:114-131).  QUIC's role there is
"encrypted reliable streams without TCP": the push/pull anti-entropy and
large sends ride UDP.  No QUIC implementation exists in this image and a
from-scratch RFC 9000 stack is out of scope, so this module fills the same
architectural slot with an honest, minimal protocol:

- one UDP socket carries BOTH planes, demultiplexed by a 1-byte type
  prefix: gossip packets (type 0) and stream segments (type 1);
- streams are connection-oriented (8-byte random connection id, SYN /
  SYN-ACK handshake), segment-sequenced ARQ with a fixed in-flight
  window, out-of-order receive buffer, cumulative ACKs, and exponential
  retransmit backoff;
- optional AES-GCM encryption of every segment (header included) through
  the cluster ``SecretKeyring`` — the keyring that already encrypts
  gossip packets also covers the stream plane, mirroring QUIC's
  always-encrypted stance without a TLS handshake;
- frames (the `Stream` contract) are 4-byte length-prefixed byte strings
  chunked into ≤``MSS``-byte segments.

Congestion control (round 4): the in-flight window is AIMD-adapted per
connection — additive increase of one segment per acked round-trip
(``cwnd += acked / cwnd``), multiplicative halving on every retransmit
timeout, bounded to [CWND_MIN, CWND_MAX].  That is the TCP-Reno-shaped
response QUIC's NewReno default gives the reference's quinn transport
(serf/Cargo.toml:40-56), so a WAN bottleneck or loss burst backs the
sender off instead of flooding retransmits.

Loss recovery (round 5): SACK + fast retransmit.  Every ACK carries a
selective-ack bitmap of the receiver's out-of-order buffer; the sender
marks SACKed segments (never re-sent) and, on ``FAST_RETX_DUPS``
duplicate cumulative ACKs, enters a NewReno-style fast-recovery episode:
halve cwnd ONCE per episode, immediately resend the unSACKed holes, and
resend the next hole on each partial ACK — so single-segment loss
recovers in ~1 RTT instead of waiting out the RTO (the reference's quinn
gives the same property via QUIC's SACK ranges + NewReno recovery).

What this is NOT (documented deviation, PARITY.md): QUIC's stream
multiplexing, path migration, 0-RTT, or wire format.  It is an ARQ sized
for serf's push/pull exchanges, conformance-tested alongside tcp/tls
through the same cluster scenarios.

Both endpoints of a cluster must run the same transport (exactly as a
quinn-only reference cluster cannot interoperate with plain TCP nodes).
"""

from __future__ import annotations

import asyncio
import os
import struct
from collections import deque
from typing import Dict, Optional, Tuple


from serf_tpu.host.net import _resolve_address
from serf_tpu.host.transport import Stream, Transport
from serf_tpu.utils import metrics

from serf_tpu.utils.logging import get_logger

log = get_logger("dstream")

MSS = 1200              # max segment payload (UDP-safe with header room)
CWND_INIT = 16          # initial congestion window (segments)
CWND_MIN = 2            # floor after repeated losses
CWND_MAX = 256          # in-flight ceiling per connection
WINDOW = CWND_MAX       # compat alias: the hard in-flight bound
RTO_MIN = 0.15          # initial retransmit timeout (s)
RTO_MAX = 2.0           # backoff cap (s)
MAX_RETRIES = 30        # per-oldest-segment retransmit budget
FAST_RETX_DUPS = 3      # duplicate cumulative ACKs before fast retransmit
# Out-of-order buffer bound: a compliant sender never has more than
# CWND_MAX segments in flight, and one of those is the in-order hole the
# receiver is waiting on, so CWND_MAX bounds what can legitimately arrive
# out of order.  Sized explicitly (not a WINDOW multiple — ADVICE r4: the
# old 4*WINDOW rode the CWND_MAX alias up to 1024 segments, letting one
# remote address pin ~75 MB across MAX_PEER_CONNS connections).
MAX_OOO = CWND_MAX      # out-of-order buffer bound (segments, ~300 KB/conn)
HANDSHAKE_TIMEOUT = 5.0
MAX_FRAME = 32 * 1024 * 1024
CLOSE_FLUSH_TIMEOUT = 5.0   # close() waits this long for inflight to drain
FIN_LINGER = 2 * RTO_MAX    # FIN receiver keeps the conn this long for
                            # re-acks (and frees it even if the app never
                            # calls close after EOF)
MAX_ACCEPT_BACKLOG = 128    # un-accepted streams queued transport-wide
MAX_PEER_CONNS = 64         # connections (incl. pending) per remote addr

T_PACKET = 0            # wire type: app gossip packet
T_SEGMENT = 1           # wire type: stream segment

K_SYN = 1
K_SYN_ACK = 2
K_DATA = 3
K_ACK = 4
K_FIN = 5
K_RST = 6

_HDR = struct.Struct(">8sBI")   # cid, kind, seq
_AAD = b"serf-tpu-dstream-v1"


def _norm(addr) -> Tuple[str, int]:
    # (host, port): IPv6 sockets report 4-tuple sources; connection keys
    # and reply targets use the 2-tuple form everywhere
    return (addr[0], addr[1])


class _Conn:
    """One reliable segment-sequenced connection (both directions)."""

    def __init__(self, transport: "DatagramStreamTransport", peer, cid: bytes):
        self.t = transport
        self.peer = _norm(peer)
        self.cid = cid
        # sender state
        self.snd_next = 0                      # next seq to assign
        self.snd_una = 0                       # oldest unacked seq
        self.inflight: Dict[int, bytes] = {}   # seq -> encoded wire segment
        self.retries = 0
        self.rto = RTO_MIN
        self.cwnd = float(CWND_INIT)           # AIMD congestion window
        self.cwnd_min_seen = float(CWND_INIT)  # diagnostics/tests
        # SACK / fast-recovery state (NewReno-shaped, see module docstring)
        self.sacked: set = set()               # seqs the peer holds OOO
        self.dup_acks = 0                      # consecutive dup cumulative acks
        self.recovery_until = -1               # episode ends when snd_una passes
        self.fast_retx_done: set = set()       # holes resent this episode
        self.fast_retx_count = 0               # diagnostics/tests
        self.retx_handle: Optional[asyncio.TimerHandle] = None
        self.window_free = asyncio.Event()
        self.window_free.set()
        self.drained = asyncio.Event()         # set while nothing is inflight
        self.drained.set()
        # receiver state
        self.rcv_next = 0
        self.ooo: Dict[int, Tuple[int, bytes]] = {}   # seq -> (kind, payload)
        self.rbuf = bytearray()
        self.frames: asyncio.Queue = asyncio.Queue()
        # lifecycle
        self.established = asyncio.Event()
        self.closed = False
        self.error: Optional[str] = None

    # -- sending ------------------------------------------------------------

    def _send_segment(self, kind: int, seq: int, payload: bytes = b"",
                      track: bool = True) -> None:
        wire = self.t._encode_segment(self.cid, kind, seq, payload)
        if track:
            self.inflight[seq] = wire
            self.drained.clear()
            self._arm_retx()
        self.t._sendto(wire, self.peer)

    def _arm_retx(self) -> None:
        if self.retx_handle is None and self.inflight and not self.closed:
            loop = asyncio.get_running_loop()
            self.retx_handle = loop.call_later(self.rto, self._on_retx)

    def _on_retx(self) -> None:
        self.retx_handle = None
        if self.closed or not self.inflight:
            return
        self.retries += 1
        if self.retries > MAX_RETRIES:
            self._fail(f"retransmit budget exhausted to {self.peer}")
            return
        self.rto = min(self.rto * 2.0, RTO_MAX)
        metrics.incr("serf.dstream.retransmits", 1)
        # multiplicative decrease: a lost round means we overran the path
        self.cwnd = max(float(CWND_MIN), self.cwnd / 2.0)
        self.cwnd_min_seen = min(self.cwnd_min_seen, self.cwnd)
        # retransmit at most the HALVED window, oldest-first, skipping
        # SACKed segments (the peer already holds them): re-blasting the
        # whole inflight set would re-flood the very bottleneck the cwnd
        # cut is backing off from (the rest re-sends as the cumulative
        # ACK advances or on later timeouts)
        pending = sorted(s for s in self.inflight if s not in self.sacked)
        if not pending:
            # every tracked segment is SACKed but the cumulative ack is
            # lost/stale: nudge ONLY the oldest — one delivered duplicate
            # elicits a fresh cumulative ACK without re-blasting
            # already-delivered data into the congested path
            pending = sorted(self.inflight)[:1]
        for seq in pending[:max(1, int(self.cwnd))]:
            self.t._sendto(self.inflight[seq], self.peer)
        self._arm_retx()

    def _retransmit_holes(self, limit: Optional[int] = None) -> None:
        """Fast-recovery resend: unSACKed inflight segments the receiver
        has demonstrably missed (below the highest SACKed seq), plus the
        cumulative hole itself, oldest first; each hole is resent at most
        once per recovery episode (the RTO path still backstops a lost
        resend)."""
        if self.closed or not self.inflight:
            return
        high = max(self.sacked) if self.sacked else self.snd_una
        holes = sorted(
            s for s in self.inflight
            if s >= 0 and s not in self.sacked
            and s not in self.fast_retx_done
            and (s <= high))
        holes = holes[:max(1, int(self.cwnd)) if limit is None else limit]
        for s in holes:
            self.fast_retx_done.add(s)
            self.fast_retx_count += 1
            metrics.incr("serf.dstream.retransmits", 1)
            self.t._sendto(self.inflight[s], self.peer)
        if holes:
            self._arm_retx()

    async def send_bytes(self, data: bytes) -> None:
        """Chunk into sequenced DATA segments, respecting the window."""
        view = memoryview(data)
        off = 0
        while off < len(view) or (len(view) == 0 and off == 0):
            await self._wait_window()
            if self.error:
                raise ConnectionError(self.error)
            if self.closed:
                raise ConnectionError("stream closed")
            chunk = bytes(view[off:off + MSS])
            seq = self.snd_next
            self.snd_next += 1
            self._send_segment(K_DATA, seq, chunk)
            off += MSS
            if len(view) == 0:
                break
        self._update_window()

    async def _wait_window(self) -> None:
        while self.snd_next - self.snd_una >= self.cwnd and not self.error \
                and not self.closed:
            self.window_free.clear()
            await self.window_free.wait()

    def _update_window(self) -> None:
        if self.snd_next - self.snd_una < self.cwnd:
            self.window_free.set()

    # -- receiving (sync, called from the datagram callback) ----------------

    def on_segment(self, kind: int, seq: int, payload: bytes) -> None:
        if self.closed:
            if kind != K_ACK:
                self._send_segment(K_RST, 0, track=False)
            return
        if kind == K_SYN_ACK:
            self.established.set()
            # our SYN occupied no sequence number; just stop resending it
            self.inflight.pop(-1, None)
            if not self.inflight:
                self.drained.set()
                if self.retx_handle is not None:
                    self.retx_handle.cancel()
                    self.retx_handle = None
            self.retries = 0
            return
        if kind == K_ACK:
            # SACK bitmap payload: bit i set => seq + 1 + i is buffered
            # out of order at the receiver (never retransmit those)
            if payload:
                base = seq + 1
                for bi, byte in enumerate(payload):
                    off = bi * 8
                    while byte:
                        low = byte & -byte
                        s = base + off + low.bit_length() - 1
                        byte ^= low
                        if s >= self.snd_una and s in self.inflight:
                            self.sacked.add(s)
            if seq > self.snd_una:
                acked = seq - self.snd_una
                self.snd_una = seq
                for s in [s for s in self.inflight if s < seq]:
                    del self.inflight[s]
                self.sacked = {s for s in self.sacked if s >= seq}
                self.fast_retx_done = {s for s in self.fast_retx_done
                                       if s >= seq}
                if not self.inflight:
                    self.drained.set()
                self.retries = 0
                self.rto = RTO_MIN
                self.dup_acks = 0
                # additive increase: +1 segment per acked round-trip
                self.cwnd = min(float(CWND_MAX),
                                self.cwnd + acked / self.cwnd)
                if self.retx_handle is not None:
                    self.retx_handle.cancel()
                    self.retx_handle = None
                self._arm_retx()
                if self.snd_una < self.recovery_until:
                    # NewReno partial ack: the next hole is already lost
                    # too — resend it now rather than waiting for dup-acks
                    self._retransmit_holes(limit=1)
                else:
                    self.recovery_until = -1
                self._update_window()
            elif self.inflight and seq == self.snd_una:
                # duplicate cumulative ack: the hole at snd_una is still
                # missing while later segments keep landing
                self.dup_acks += 1
                if (self.dup_acks >= FAST_RETX_DUPS
                        and self.snd_una >= self.recovery_until):
                    # enter fast recovery ONCE per loss episode: halve,
                    # mark where the episode ends, resend the holes
                    self.recovery_until = self.snd_next
                    self.cwnd = max(float(CWND_MIN), self.cwnd / 2.0)
                    self.cwnd_min_seen = min(self.cwnd_min_seen, self.cwnd)
                    self.dup_acks = 0
                    self.fast_retx_done.clear()
                    self._retransmit_holes()
                elif self.sacked and self.snd_una < self.recovery_until:
                    # new SACK info inside an episode exposes more holes
                    self._retransmit_holes(limit=1)
            return
        if kind == K_RST:
            self._fail(f"connection reset by {self.peer}")
            return
        if kind in (K_DATA, K_FIN):
            if seq < self.rcv_next:
                pass                           # duplicate; re-ack below
            elif seq == self.rcv_next:
                self._deliver(kind, payload)
                self.rcv_next += 1
                while self.rcv_next in self.ooo:
                    k2, p2 = self.ooo.pop(self.rcv_next)
                    self._deliver(k2, p2)
                    self.rcv_next += 1
            elif len(self.ooo) < MAX_OOO:
                self.ooo[seq] = (kind, payload)
            else:
                # OOO buffer full: the segment is silently re-sent by the
                # peer later, but a sustained rate here means a
                # mixed-version or badly mistuned sender is overrunning
                # us — keep it visible (advisor finding: this degradation
                # was invisible before the counter)
                metrics.incr("serf.dstream.ooo_dropped", 1)
            self._send_segment(K_ACK, self.rcv_next, self._sack_bitmap(),
                               track=False)

    def _sack_bitmap(self) -> bytes:
        """Selective-ack bitmap over the out-of-order buffer: bit i set =>
        seq ``rcv_next + 1 + i`` is held (``rcv_next`` itself is the hole).
        ≤ MAX_OOO/8 = 32 bytes, trailing zero bytes trimmed — well inside
        a segment's MSS budget."""
        if not self.ooo:
            return b""
        bm = bytearray((MAX_OOO + 7) // 8)
        base = self.rcv_next + 1
        for s in self.ooo:
            off = s - base
            if 0 <= off < MAX_OOO:
                bm[off >> 3] |= 1 << (off & 7)
        while bm and bm[-1] == 0:
            bm.pop()
        return bytes(bm)

    def _deliver(self, kind: int, payload: bytes) -> None:
        if kind == K_FIN:
            self.frames.put_nowait(None)
            # the peer is done sending; keep the conn only long enough to
            # re-ack FIN retransmits, then free it even if the application
            # abandons the stream after EOF instead of calling close().
            # Must not cut short OUR outgoing direction: while local
            # segments are still unacked (a response being flushed), defer
            # and re-check rather than tearing down.
            asyncio.get_running_loop().call_later(FIN_LINGER,
                                                  self._linger_teardown)
            return
        self.rbuf += payload
        while len(self.rbuf) >= 4:
            (ln,) = struct.unpack(">I", self.rbuf[:4])
            if ln > MAX_FRAME:
                self._fail(f"frame of {ln} bytes exceeds limit")
                return
            if len(self.rbuf) < 4 + ln:
                break
            frame = bytes(self.rbuf[4:4 + ln])
            del self.rbuf[:4 + ln]
            self.frames.put_nowait(frame)

    def _linger_teardown(self) -> None:
        """FIN-linger expiry: free the conn unless our own send direction
        still has unacked segments (retransmission must keep running until
        close()'s flush completes or the retransmit budget fails it)."""
        if self.closed:
            return
        if self.inflight:
            asyncio.get_running_loop().call_later(FIN_LINGER,
                                                  self._linger_teardown)
            return
        self._teardown()

    def _fail(self, msg: str) -> None:
        if self.error is None:
            self.error = msg
        self.frames.put_nowait(None)
        self.window_free.set()
        self.established.set()
        self._teardown()

    def _teardown(self) -> None:
        self.closed = True
        self.inflight.clear()
        self.drained.set()
        # wake anyone parked on the window or a blocking recv: after
        # teardown the _wait_window/_deliver conditions are never
        # re-evaluated otherwise (transport.shutdown() reaches here
        # directly, without _fail), and the AIMD floor parks senders in
        # _wait_window far more often than the old fixed window did
        self.window_free.set()
        self.frames.put_nowait(None)
        if self.retx_handle is not None:
            self.retx_handle.cancel()
            self.retx_handle = None
        self.t._conns.pop((self.peer, self.cid), None)


class DgramStream(Stream):
    """`Stream` adapter over a `_Conn`."""

    def __init__(self, conn: _Conn):
        self._c = conn

    async def send_frame(self, buf: bytes) -> None:
        if self._c.error:
            raise ConnectionError(self._c.error)
        await self._c.send_bytes(struct.pack(">I", len(buf)) + buf)

    async def recv_frame(self, timeout: Optional[float] = None) -> bytes:
        try:
            if timeout is None:
                item = await self._c.frames.get()
            else:
                item = await asyncio.wait_for(self._c.frames.get(), timeout)
        except asyncio.TimeoutError:
            raise TimeoutError("stream recv timeout") from None
        if item is None:
            # re-enqueue the EOF/error sentinel so EVERY post-EOF call
            # raises (the TcpStream contract) instead of blocking forever
            self._c.frames.put_nowait(None)
            if self._c.error:
                raise ConnectionError(self._c.error)
            raise ConnectionError("stream closed by peer")
        return item

    async def close(self) -> None:
        c = self._c
        if c.closed or c.error:
            c._teardown()
            return
        try:
            await c._wait_window()
            seq = c.snd_next
            c.snd_next += 1
            c._send_segment(K_FIN, seq)
        except ConnectionError:
            pass
        # flush: wait until every inflight segment (data + the FIN) is
        # acked, so the final frames of a stream are never silently dropped
        # under loss (the TcpStream close() contract).  Retransmission keeps
        # running through the wait; only on timeout (peer unresponsive) fall
        # back to the fixed linger before tearing down regardless.
        try:
            await asyncio.wait_for(c.drained.wait(), CLOSE_FLUSH_TIMEOUT)
        except asyncio.TimeoutError:
            asyncio.get_running_loop().call_later(RTO_MAX, c._teardown)
            return
        c._teardown()


class _DgramProtocol(asyncio.DatagramProtocol):
    def __init__(self, transport: "DatagramStreamTransport"):
        self._t = transport

    def datagram_received(self, data: bytes, addr) -> None:
        self._t._on_datagram(data, addr)


class DatagramStreamTransport(Transport):
    """UDP-only transport: gossip packets and reliable streams on one
    socket.  ``keyring``: optional ``SecretKeyring`` — when set, stream
    segments are AES-GCM encrypted and authenticated end-to-end."""

    def __init__(self, keyring=None):
        self._addr = None
        self._packets: asyncio.Queue = asyncio.Queue()
        self._accepts: asyncio.Queue = asyncio.Queue()
        self._conns: Dict[Tuple[tuple, bytes], _Conn] = {}
        self._udp = None
        self._shut = False
        self._keyring = keyring

    @classmethod
    async def bind(cls, addr: Tuple[str, int], *, keyring=None
                   ) -> "DatagramStreamTransport":
        t = cls(keyring=keyring)
        loop = asyncio.get_running_loop()
        t._udp, _ = await loop.create_datagram_endpoint(
            lambda: _DgramProtocol(t), local_addr=addr)
        sock = t._udp.get_extra_info("socket")
        t._addr = sock.getsockname()[:2]
        return t

    # -- wire ---------------------------------------------------------------

    @property
    def max_packet_size(self) -> int:
        return MSS  # 1-byte demux prefix eats into the UDP budget

    def _encode_segment(self, cid: bytes, kind: int, seq: int,
                        payload: bytes = b"") -> bytes:
        body = _HDR.pack(cid, kind, seq) + payload
        if self._keyring is not None:
            body = self._keyring.encrypt(body, aad=_AAD)
        return bytes([T_SEGMENT]) + body

    def _sendto(self, wire: bytes, addr) -> None:
        if not self._shut and self._udp is not None:
            self._udp.sendto(wire, addr)

    def _on_datagram(self, data: bytes, addr) -> None:
        if not data:
            return
        t, body = data[0], data[1:]
        addr = _norm(addr)
        if t == T_PACKET:
            self._packets.put_nowait((addr, body))
            return
        if t != T_SEGMENT:
            return
        if self._keyring is not None:
            try:
                body = self._keyring.decrypt(body, aad=_AAD)
            except Exception:
                log.debug("dropping undecryptable segment from %r", addr)
                return
        if len(body) < _HDR.size:
            return
        cid, kind, seq = _HDR.unpack_from(body)
        payload = body[_HDR.size:]
        key = (addr, cid)
        conn = self._conns.get(key)
        if conn is None:
            if kind == K_SYN and not self._shut:
                # bound resource growth from unsolicited (or replayed) SYNs:
                # cap the un-accepted backlog transport-wide and the live
                # connection count per remote address.  A recorded encrypted
                # SYN still decrypts (constant AAD), so replay cannot be
                # rejected cryptographically without a handshake nonce echo —
                # these caps bound what a replay storm can allocate.
                if self._accepts.qsize() >= MAX_ACCEPT_BACKLOG:
                    log.debug("dropping SYN from %r: accept backlog full", addr)
                    return
                if sum(1 for (a, _c) in self._conns if a == addr) \
                        >= MAX_PEER_CONNS:
                    log.debug("dropping SYN from %r: per-peer conn cap", addr)
                    return
                conn = _Conn(self, addr, cid)
                conn.established.set()
                self._conns[key] = conn
                self._accepts.put_nowait((addr, DgramStream(conn)))
            elif kind in (K_DATA, K_FIN):
                # stale connection: tell the peer to give up
                self._sendto(self._encode_segment(cid, K_RST, 0), addr)
                return
            else:
                return
        if kind == K_SYN:
            # duplicate SYN (our SYN_ACK was lost): re-ack, don't re-accept
            self._sendto(self._encode_segment(cid, K_SYN_ACK, 0), addr)
            return
        conn.on_segment(kind, seq, payload)

    # -- Transport contract -------------------------------------------------

    @property
    def local_addr(self):
        return self._addr

    async def resolve(self, addr):
        return await _resolve_address(addr, self._addr)

    async def send_packet(self, addr, buf: bytes) -> None:
        if self._shut:
            raise ConnectionError("transport shut down")
        self._udp.sendto(bytes([T_PACKET]) + buf, _norm(addr))

    async def recv_packet(self):
        item = await self._packets.get()
        if item is None:
            raise ConnectionError("transport shut down")
        return item

    async def dial(self, addr, timeout: Optional[float] = None) -> Stream:
        if self._shut:
            raise ConnectionError("transport shut down")
        addr = _norm(addr)
        cid = os.urandom(8)
        conn = _Conn(self, addr, cid)
        self._conns[(addr, cid)] = conn
        # SYN rides the retransmit machinery under pseudo-seq -1 (it
        # occupies no data sequence number)
        conn.inflight[-1] = self._encode_segment(cid, K_SYN, 0)
        self._sendto(conn.inflight[-1], addr)
        conn._arm_retx()
        try:
            await asyncio.wait_for(conn.established.wait(),
                                   timeout or HANDSHAKE_TIMEOUT)
        except asyncio.TimeoutError:
            conn._teardown()
            raise TimeoutError(f"dial {addr!r} timed out") from None
        if conn.error:
            raise ConnectionError(conn.error)
        return DgramStream(conn)

    async def accept(self):
        item = await self._accepts.get()
        if item is None:
            raise ConnectionError("transport shut down")
        return item

    async def shutdown(self) -> None:
        if self._shut:
            return
        self._shut = True
        for conn in list(self._conns.values()):
            conn._teardown()
        if self._udp is not None:
            self._udp.close()
        self._packets.put_nowait(None)
        self._accepts.put_nowait(None)
