"""Host plane: the asyncio Serf engine with reference-parity API surface.

Quick start::

    from serf_tpu.host import Serf, LoopbackNetwork, EventSubscriber
    from serf_tpu.options import Options

    net = LoopbackNetwork()
    a = await Serf.create(net.bind("a"), Options.local(), "node-a")
    b = await Serf.create(net.bind("b"), Options.local(), "node-b")
    await b.join("a")
    await a.user_event("deploy", b"v2")
"""

from serf_tpu.host.admission import OverloadError, TokenBucket
from serf_tpu.host.serf import Serf, SerfState, Stats
from serf_tpu.obs.cluster import ClusterSnapshot  # Serf.cluster_stats() result
from serf_tpu.obs.health import HealthReport      # Serf.health_report() result
from serf_tpu.host.events import (
    EventSubscriber,
    MemberEvent,
    MemberEventType,
    QueryEvent,
    UserEvent,
)
from serf_tpu.host.query import NodeResponse, QueryParam, QueryResponse
from serf_tpu.host.transport import LoopbackNetwork, LoopbackTransport, Transport
from serf_tpu.host.memberlist import Memberlist
from serf_tpu.host.keyring import SecretKeyring
from serf_tpu.host.delegate import CompositeDelegate, MergeDelegate, ReconnectDelegate
from serf_tpu.host.coordinate import Coordinate, CoordinateClient, CoordinateOptions
from serf_tpu.host.key_manager import KeyManager, KeyResponse

__all__ = [
    "Serf",
    "SerfState",
    "Stats",
    "ClusterSnapshot",
    "HealthReport",
    "EventSubscriber",
    "MemberEvent",
    "MemberEventType",
    "QueryEvent",
    "UserEvent",
    "NodeResponse",
    "QueryParam",
    "QueryResponse",
    "LoopbackNetwork",
    "LoopbackTransport",
    "Transport",
    "Memberlist",
    "SecretKeyring",
    "CompositeDelegate",
    "MergeDelegate",
    "ReconnectDelegate",
    "Coordinate",
    "CoordinateClient",
    "CoordinateOptions",
    "KeyManager",
    "KeyResponse",
]
