"""(package)"""
