"""Secret keyring: AES-GCM packet/stream encryption with rotatable keys.

Reference capability: memberlist's ``SecretKey``/keyring with AES encryption,
orchestrated cluster-wide by serf's key manager (SURVEY.md §2.7/§2.9).
Encrypt with the primary key; decrypt by trying every installed key, so the
cluster stays connected mid-rotation.

Wire format: ``[0x01 version][12-byte nonce][ciphertext+tag]``.
"""

from __future__ import annotations

import json
import os
import threading
from base64 import b64decode, b64encode
from typing import List, Optional

try:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
except ImportError:  # pragma: no cover - environment-dependent
    # Encryption is an optional capability: images without the
    # ``cryptography`` wheel must still import the host plane (plaintext
    # clusters, tests, tooling).  Constructing a SecretKeyring without it
    # raises KeyringError with the reason.
    AESGCM = None

ENCRYPTION_VERSION = 1
KEY_SIZES = (16, 24, 32)
NONCE_SIZE = 12


class KeyringError(Exception):
    pass


class SecretKeyring:
    def __init__(self, primary: bytes, keys: Optional[List[bytes]] = None):
        if AESGCM is None:
            raise KeyringError(
                "encryption unavailable: the 'cryptography' package is not "
                "installed in this environment")
        _check_key(primary)
        self._lock = threading.Lock()
        self._primary = primary
        self._keys: List[bytes] = [primary]
        for k in keys or []:
            if k != primary:
                _check_key(k)
                self._keys.append(k)

    # key management --------------------------------------------------------

    def primary_key(self) -> bytes:
        return self._primary

    def keys(self) -> List[bytes]:
        with self._lock:
            return list(self._keys)

    def install(self, key: bytes) -> None:
        _check_key(key)
        with self._lock:
            if key not in self._keys:
                self._keys.append(key)

    def use_key(self, key: bytes) -> None:
        with self._lock:
            if key not in self._keys:
                raise KeyringError("cannot use a key that is not installed")
            self._primary = key

    def remove(self, key: bytes) -> None:
        with self._lock:
            if key == self._primary:
                raise KeyringError("cannot remove the primary key")
            if key in self._keys:
                self._keys.remove(key)

    # crypto ----------------------------------------------------------------

    def encrypt(self, plaintext: bytes, aad: bytes = b"") -> bytes:
        nonce = os.urandom(NONCE_SIZE)
        ct = AESGCM(self._primary).encrypt(nonce, plaintext, aad or None)
        return bytes([ENCRYPTION_VERSION]) + nonce + ct

    def decrypt(self, buf: bytes, aad: bytes = b"") -> bytes:
        if len(buf) < 1 + NONCE_SIZE + 16 or buf[0] != ENCRYPTION_VERSION:
            raise KeyringError("malformed encrypted payload")
        nonce, ct = buf[1 : 1 + NONCE_SIZE], buf[1 + NONCE_SIZE :]
        for key in self.keys():
            try:
                return AESGCM(key).decrypt(nonce, ct, aad or None)
            except Exception:
                continue
        raise KeyringError("no installed key decrypts this payload")

    # persistence (reference writes keyring file mode 0600, base.rs:399-434)

    def save(self, path: str) -> None:
        # primary first, so load() restores the rotation state
        keys = [self._primary] + [k for k in self.keys() if k != self._primary]
        data = json.dumps([b64encode(k).decode() for k in keys])
        # atomic write-tmp-fsync-rename (ISSUE 19 satellite): a process
        # killed mid-save must leave the OLD keyring intact, never a
        # torn file a restart then fails to decrypt the cluster with
        from serf_tpu.utils.files import atomic_write_text
        atomic_write_text(path, data, mode=0o600)

    @classmethod
    def load(cls, path: str) -> "SecretKeyring":
        with open(path) as f:
            keys = [b64decode(s) for s in json.load(f)]
        if not keys:
            raise KeyringError(f"keyring file {path} is empty")
        return cls(keys[0], keys[1:])


def _check_key(key: bytes) -> None:
    if len(key) not in KEY_SIZES:
        raise KeyringError(f"key must be 16/24/32 bytes, got {len(key)}")
