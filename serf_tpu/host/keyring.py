"""Secret keyring: AEAD packet/stream encryption with rotatable keys.

Reference capability: memberlist's ``SecretKey``/keyring with AES encryption,
orchestrated cluster-wide by serf's key manager (SURVEY.md §2.7/§2.9).
Encrypt with the primary key; decrypt by trying the primary FIRST and then
every secondary key in install order, so the cluster stays connected
mid-rotation — a fallback hit (a peer still encrypting with an older/newer
primary) is counted on ``serf.keyring.decrypt_fallback`` and a miss across
the whole ring on ``serf.keyring.decrypt_fail``.

Wire format: ``[0x01 version][12-byte nonce][ciphertext+tag16]`` (the
``ENCRYPTION_FRAME_SCHEMA`` literal below is the serflint-pinned shape).

Backends: AES-GCM via the ``cryptography`` wheel when available, else a
pure-stdlib AEAD (SHA-256 keystream in CTR construction + encrypt-then-MAC
HMAC-SHA256 tag truncated to 16 bytes over ``nonce||ct||aad``) with the
identical frame layout.  The fallback exists so images without the wheel
still run encrypted clusters end-to-end (chaos plans, proc agents, tests);
it is NOT wire-compatible with the AES-GCM backend — a cluster must run one
backend, which ``CRYPTO_BACKEND`` names.
"""

from __future__ import annotations

import binascii
import hashlib
import hmac as _hmac
import json
import os
import threading
from base64 import b64decode, b64encode
from typing import List, Optional

from serf_tpu.utils import metrics

try:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    CRYPTO_BACKEND = "aes-gcm"
except ImportError:  # pragma: no cover - environment-dependent
    # Encryption stays a live capability without the wheel: the stdlib
    # AEAD below takes over with the same frame layout (same-backend
    # clusters only; CRYPTO_BACKEND tells operators which one runs).
    AESGCM = None
    CRYPTO_BACKEND = "hmac-sha256-ctr"

ENCRYPTION_VERSION = 1
KEY_SIZES = (16, 24, 32)
NONCE_SIZE = 12
TAG_SIZE = 16

#: serflint-pinned crypto framing (analysis/schema.py folds this literal
#: into the wire fingerprint): a silent change to the encrypted frame
#: layout or to where encryption sits in the packet pipeline fails lint
#: until `python tools/serflint.py --bump-schema` (MIGRATION.md).
ENCRYPTION_FRAME_SCHEMA = {
    "encrypted-frame": ("version=0x01", "nonce[12]", "ciphertext||tag[16]"),
    "encrypt-pipeline": ("encode", "compress", "checksum", "encrypt"),
    "batch-encryption": ("one-encrypt-per-BATCH-frame",
                         "gossip-fanout-amortized"),
}


class KeyringError(Exception):
    pass


# --------------------------------------------------------------------------
# AEAD backends: AES-GCM when the wheel exists, stdlib HMAC-CTR otherwise
# --------------------------------------------------------------------------

def _ctr_xor(key: bytes, nonce: bytes, data: bytes) -> bytes:
    """SHA-256 keystream in counter mode: block i = H(key||nonce||i)."""
    out = bytearray(len(data))
    for block in range((len(data) + 31) // 32):
        ks = hashlib.sha256(
            key + nonce + block.to_bytes(4, "big")).digest()
        lo = block * 32
        chunk = data[lo:lo + 32]
        for j, b in enumerate(chunk):
            out[lo + j] = b ^ ks[j]
    return bytes(out)


def _seal(key: bytes, nonce: bytes, plaintext: bytes, aad: bytes) -> bytes:
    if AESGCM is not None:
        return AESGCM(key).encrypt(nonce, plaintext, aad or None)
    ct = _ctr_xor(key, nonce, plaintext)
    tag = _hmac.new(key, nonce + ct + aad, hashlib.sha256).digest()[:TAG_SIZE]
    return ct + tag


def _open(key: bytes, nonce: bytes, buf: bytes, aad: bytes) -> bytes:
    if AESGCM is not None:
        return AESGCM(key).decrypt(nonce, buf, aad or None)
    if len(buf) < TAG_SIZE:
        raise KeyringError("ciphertext shorter than the tag")
    ct, tag = buf[:-TAG_SIZE], buf[-TAG_SIZE:]
    want = _hmac.new(key, nonce + ct + aad,
                     hashlib.sha256).digest()[:TAG_SIZE]
    if not _hmac.compare_digest(tag, want):
        raise KeyringError("authentication tag mismatch")
    return _ctr_xor(key, nonce, ct)


def key_digest(key: bytes) -> str:
    """Loggable, non-secret identity of a key (forensics/invariants)."""
    return hashlib.sha256(key).hexdigest()[:12]


class SecretKeyring:
    def __init__(self, primary: bytes, keys: Optional[List[bytes]] = None):
        _check_key(primary)
        self._lock = threading.Lock()
        self._primary = primary
        self._keys: List[bytes] = [primary]
        for k in keys or []:
            if k != primary:
                _check_key(k)
                self._keys.append(k)

    # key management --------------------------------------------------------

    def primary_key(self) -> bytes:
        return self._primary

    def keys(self) -> List[bytes]:
        with self._lock:
            return list(self._keys)

    def install(self, key: bytes) -> None:
        _check_key(key)
        with self._lock:
            if key not in self._keys:
                self._keys.append(key)

    def use_key(self, key: bytes) -> None:
        with self._lock:
            if key not in self._keys:
                raise KeyringError("cannot use a key that is not installed")
            self._primary = key

    def remove(self, key: bytes) -> None:
        with self._lock:
            if key == self._primary:
                raise KeyringError("cannot remove the primary key")
            if key in self._keys:
                self._keys.remove(key)

    def digest(self) -> dict:
        """Non-secret keyring identity: primary digest + sorted key
        digests.  The keyring-divergence invariant compares these across
        live nodes, and red-run black boxes carry them for forensics."""
        with self._lock:
            return {"primary": key_digest(self._primary),
                    "keys": sorted(key_digest(k) for k in self._keys)}

    # crypto ----------------------------------------------------------------

    def encrypt(self, plaintext: bytes, aad: bytes = b"") -> bytes:
        nonce = os.urandom(NONCE_SIZE)
        with self._lock:
            primary = self._primary
        ct = _seal(primary, nonce, plaintext, aad)
        metrics.incr("serf.keyring.encrypt")
        return bytes([ENCRYPTION_VERSION]) + nonce + ct

    def decrypt(self, buf: bytes, aad: bytes = b"") -> bytes:
        if len(buf) < 1 + NONCE_SIZE + TAG_SIZE \
                or buf[0] != ENCRYPTION_VERSION:
            raise KeyringError("malformed encrypted payload")
        nonce, ct = buf[1 : 1 + NONCE_SIZE], buf[1 + NONCE_SIZE :]
        # primary first (the overwhelmingly common case), then the
        # secondaries in install order — mid-rotation, a peer may still
        # encrypt with a key we have merely installed
        with self._lock:
            order = [self._primary] + [k for k in self._keys
                                       if k != self._primary]
        for i, key in enumerate(order):
            try:
                pt = _open(key, nonce, ct, aad)
            except Exception:
                continue
            if i:
                metrics.incr("serf.keyring.decrypt_fallback")
            return pt
        metrics.incr("serf.keyring.decrypt_fail")
        raise KeyringError("no installed key decrypts this payload")

    # persistence (reference writes keyring file mode 0600, base.rs:399-434)

    def save(self, path: str) -> None:
        # primary first, so load() restores the rotation state
        keys = [self._primary] + [k for k in self.keys() if k != self._primary]
        data = json.dumps([b64encode(k).decode() for k in keys])
        # atomic write-tmp-fsync-rename (ISSUE 19 satellite): a process
        # killed mid-save must leave the OLD keyring intact, never a
        # torn file a restart then fails to decrypt the cluster with
        from serf_tpu.utils.files import atomic_write_text
        atomic_write_text(path, data, mode=0o600)

    @classmethod
    def load(cls, path: str) -> "SecretKeyring":
        try:
            with open(path) as f:
                keys = [b64decode(s) for s in json.load(f)]
        except (ValueError, binascii.Error) as e:
            # a torn/corrupt file fails closed with a keyring error, not
            # a JSON traceback (the atomic save makes this unreachable
            # for our own writes; it guards hand-edited/foreign files)
            raise KeyringError(f"keyring file {path} is unreadable: {e}")
        if not keys:
            raise KeyringError(f"keyring file {path} is empty")
        return cls(keys[0], keys[1:])


def _check_key(key: bytes) -> None:
    if len(key) not in KEY_SIZES:
        raise KeyringError(f"key must be 16/24/32 bytes, got {len(key)}")
