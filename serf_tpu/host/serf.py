"""The Serf engine: Lamport-clocked cluster state machine over SWIM gossip.

Re-implements the reference's serf-core layer (SURVEY.md §2.1/§3): three
Lamport clocks, the member table with buffered intents, the message handlers
with dedup ring buffers and rebroadcast decisions, three transmit-limited
broadcast queues piggy-backed onto gossip, the query engine, push/pull
anti-entropy of serf state, background Reaper/Reconnector/QueueCheckers, and
the public API (new/join/leave/shutdown/user_event/query/set_tags/members/
stats/remove_failed_node/coordinate/key_manager).

Reference call stacks mirrored here: bootstrap base.rs:62-344, join
api.rs:318-342, user_event api.rs:241-297, query base.rs:875-944, failure
path base.rs:1375-1440 + 612-681 + 483-610.
"""

from __future__ import annotations

import asyncio
import enum
import random
import time
from dataclasses import dataclass, field as dataclass_field, replace
from typing import Dict, List, Optional, Sequence, Set, Tuple

from serf_tpu import codec
from serf_tpu.host.admission import (
    AdmissionController,
    OverloadError,
    record_ingress,
)
from serf_tpu.host.broadcast import Broadcast, TransmitLimitedQueue
from serf_tpu.host.coordinate import Coordinate, CoordinateClient, CoordinateOptions
from serf_tpu.host.delegate import CompositeDelegate, SwimDelegate
from serf_tpu.host.events import (
    EventSubscriber,
    MemberEvent,
    MemberEventType,
    MemberEventCoalescer,
    QueryEvent,
    UserEvent,
    UserEventCoalescer,
)
from serf_tpu.host.pipeline import CoalesceStage, EventPipeline, name_class
from serf_tpu.host.keyring import SecretKeyring
from serf_tpu.host.memberlist import Memberlist, NodeState
from serf_tpu.host.messages import SwimState
from serf_tpu.host.query import (
    QueryParam,
    QueryResponse,
    default_query_timeout,
    random_members,
    should_process_query,
)
from serf_tpu.host.transport import Transport
from serf_tpu.options import Options, USER_EVENT_SIZE_LIMIT
from serf_tpu.types.clock import LamportClock, LamportTime
from serf_tpu.types.member import (
    IntentType,
    Member,
    MemberState,
    MemberStatus,
    Node,
    NodeIntent,
    recent_intent,
    reap_intents,
    upsert_intent,
)
from serf_tpu.types.messages import (
    ConflictResponseMessage,
    JoinMessage,
    LeaveMessage,
    MessageType,
    PushPullMessage,
    QueryFlag,
    QueryMessage,
    QueryResponseMessage,
    RelayMessage,
    UserEventMessage,
    UserEvents,
    decode_message,
    decode_message_batch,
    decode_message_cached,
    encode_message,
    encode_relay_message,
)
from serf_tpu.types.tags import Tags
from serf_tpu import obs
from serf_tpu.obs import lifecycle
from serf_tpu.obs.health import HealthReport, HealthScorer, serf_sources
from serf_tpu.obs.propagation import PropagationLedger
from serf_tpu.obs.trace import new_trace, span, trace_scope
from serf_tpu.utils import metrics
from serf_tpu.utils.tasks import log_task_exception, spawn_logged

from serf_tpu.utils.logging import get_logger

log = get_logger("serf")

# Internal query name-space (reference event/crate_event.rs:60-69)
INTERNAL_PING = "_serf_ping"
INTERNAL_CONFLICT = "_serf_conflict"
INTERNAL_INSTALL_KEY = "_serf_install_key"
INTERNAL_USE_KEY = "_serf_use_key"
INTERNAL_REMOVE_KEY = "_serf_remove_key"
INTERNAL_LIST_KEYS = "_serf_list_keys"
INTERNAL_STATS = "_serf_stats"       # cluster stats aggregation (obs.cluster)
PING_VERSION = 1

#: bound on user events deferred while a join(ignore_old=True) is still
#: computing its event-time cutoff (joins are sub-second; this is ample)
DEFERRED_EVENTS_MAX = 4096


class SerfState(enum.IntEnum):
    ALIVE = 0
    LEAVING = 1
    LEFT = 2
    SHUTDOWN = 3


@dataclass
class Stats:
    """Operator snapshot (reference api.rs:586-602), extended with the
    full observability picture: the metrics sink, the retained trace
    spans, and the flight-recorder events (serf_tpu.obs) — one call
    yields everything needed to reconstruct a protocol round."""

    members: int
    failed: int
    left: int
    health_score: int
    member_time: LamportTime
    event_time: LamportTime
    query_time: LamportTime
    intent_queue: int
    event_queue: int
    query_queue: int
    encrypted: bool
    coordinate_resets: int
    #: JSON-ready metrics snapshot (counters/gauges/histogram summaries)
    metrics: dict = dataclass_field(default_factory=dict)
    #: finished trace spans, oldest first (obs.trace ring)
    trace: list = dataclass_field(default_factory=list)
    #: flight-recorder events, oldest first (obs.flight ring)
    flight: list = dataclass_field(default_factory=list)
    #: Lifeguard-style node health report (obs.health): score + components
    health: dict = dataclass_field(default_factory=dict)


class _SerfSwimDelegate(SwimDelegate):
    """Bridge: SWIM layer callbacks into the serf engine
    (reference SerfDelegate, serf-core/src/serf/delegate.rs)."""

    def __init__(self):
        self.serf: Optional["Serf"] = None  # back-linked after construction

    # -- node meta / messages ----------------------------------------------

    def node_meta(self, limit: int) -> bytes:
        s = self.serf
        raw = s._tags.encode()
        if len(raw) > limit:
            log.error("encoded tags exceed meta limit; advertising none")
            return b""
        return raw

    def notify_message(self, raw: bytes) -> None:
        s = self.serf
        if s is None or s.state == SerfState.SHUTDOWN:
            return
        if raw and raw[0] == int(MessageType.BATCH):
            # batched codec: one SWIM frame carried N serf messages —
            # unwrap once, then run each part through the normal
            # per-message path (every part gets its own lifecycle
            # clock; the packet-timestamp note anchors the first)
            try:
                parts = decode_message_batch(raw)
            except codec.DecodeError as e:
                log.debug("undecodable serf batch: %s", e)
                return
            for part in parts:
                self._notify_one(part)
            return
        self._notify_one(raw)

    def _notify_one(self, raw: bytes) -> None:
        s = self.serf
        metrics.observe("serf.messages.received", len(raw), s._labels)
        # lifecycle ledger (obs.lifecycle): begin the per-message stage
        # clock at the transport seam — the memberlist packet loop noted
        # the packet's receive timestamp, so wire+SWIM decode land in
        # the `transport` stage and the codec pass in `decode`
        led = lifecycle.global_ledger()
        clk = led.begin("remote")
        try:
            msg = decode_message_cached(raw)
        except codec.DecodeError as e:
            led.discard_current()
            log.debug("undecodable serf message: %s", e)
            return
        if clk is not None:
            clk.kind = type(msg).__name__
            clk.stamp("decode")
        try:
            s._dispatch(msg, raw)
        finally:
            led.finish_current()

    def broadcast_messages(self, overhead: int, limit: int) -> List[bytes]:
        s = self.serf
        if s is None:
            return []
        out: List[bytes] = []
        used = 0
        with span("serf.broadcast.drain", node=s.local_id) as sp:
            for q in (s.intent_broadcasts, s.event_broadcasts,
                      s.query_broadcasts):
                msgs = q.get_broadcasts(overhead, limit - used)
                for m in msgs:
                    used += overhead + len(m)
                    metrics.observe("serf.messages.sent", len(m), s._labels)
                out.extend(msgs)
            sp.attrs["messages"] = len(out)
            sp.attrs["bytes"] = used
        return out

    # -- anti-entropy -------------------------------------------------------

    def local_state(self, join: bool) -> bytes:
        s = self.serf
        status_ltimes: Dict[str, LamportTime] = {}
        left: List[str] = []
        for ms in s._members.values():
            status_ltimes[ms.id] = ms.status_time
            if ms.member.status == MemberStatus.LEFT:
                left.append(ms.id)
        events = tuple(ue for ue in s._event_buffer if ue is not None)
        pp = PushPullMessage(
            ltime=s.clock.time(),
            status_ltimes=status_ltimes,
            left_members=tuple(left),
            event_ltime=s.event_clock.time(),
            events=events,
            query_ltime=s.query_clock.time(),
        )
        return encode_message(pp)

    def merge_remote_state(self, buf: bytes, is_join: bool) -> None:
        s = self.serf
        with span("serf.push-pull.merge", node=s.local_id, join=is_join):
            self._merge_remote_state(buf, is_join)

    def _merge_remote_state(self, buf: bytes, is_join: bool) -> None:
        s = self.serf
        try:
            pp = decode_message(buf)
        except codec.DecodeError as e:
            log.warning("bad remote serf state: %s", e)
            return
        if not isinstance(pp, PushPullMessage):
            log.warning("remote serf state was %s", type(pp).__name__)
            return
        if pp.ltime > 0:
            s.clock.witness(pp.ltime - 1)
        if pp.event_ltime > 0:
            s.event_clock.witness(pp.event_ltime - 1)
        if pp.query_ltime > 0:
            s.query_clock.witness(pp.query_ltime - 1)
        # left members FIRST so their status_ltimes entries apply as leaves
        # (reference delegate.rs:490-523 ordering requirement)
        left_set = set(pp.left_members)
        for node_id in pp.left_members:
            lt = pp.status_ltimes.get(node_id, 0)
            s._handle_node_leave_intent(LeaveMessage(lt, node_id), rebroadcast=False)
        for node_id, lt in pp.status_ltimes.items():
            if node_id in left_set:
                continue
            s._handle_node_join_intent(JoinMessage(lt, node_id), rebroadcast=False)
        # user events: replay through the normal handler (dedup + min_time)
        if is_join and s._event_join_ignore:
            s._event_min_time = pp.event_ltime + 1
        for cell in pp.events:
            if cell is None:
                continue
            for ev in cell.events:
                s._handle_user_event(
                    UserEventMessage(cell.ltime, ev.name, ev.payload, ev.cc),
                    rebroadcast=False,
                )

    # -- membership notifications ------------------------------------------

    def notify_join(self, ns: NodeState) -> None:
        self.serf._handle_node_join(ns)

    def notify_leave(self, ns: NodeState) -> None:
        self.serf._handle_node_leave(ns)

    def notify_update(self, ns: NodeState) -> None:
        self.serf._handle_node_update(ns)

    def notify_alive(self, alive) -> Optional[str]:
        return None

    def notify_merge(self, peers) -> Optional[str]:
        s = self.serf
        if s.user_delegate is not None:
            members = []
            for st in peers:
                tags = _decode_tags(st.meta)
                members.append(Member(st.node, tags, _swim_to_status(st.state)))
            return s.user_delegate.notify_merge(members)
        return None

    def notify_conflict(self, existing: NodeState, other) -> None:
        s = self.serf
        if existing.id != s.local_id:
            # observers only log (reference: resolution is driven by the
            # conflicted node itself, base.rs:1658-1670)
            log.warning("node id %r claimed by both %r and %r",
                        existing.id, existing.addr, other.node.addr)
            return
        if s.opts.enable_id_conflict_resolution and not s._conflict_resolving:
            s._conflict_resolving = True
            s._spawn(s._resolve_node_conflict(existing, other), "serf-conflict")

    # -- ping plane (Vivaldi) ----------------------------------------------

    def ack_payload(self) -> bytes:
        s = self.serf
        if s is None or s.coord_client is None:
            return b""
        return bytes([PING_VERSION]) + s.coord_client.get_coordinate().encode()

    def notify_ping_complete(self, ns: NodeState, rtt: float, payload: bytes) -> None:
        s = self.serf
        if s is None or s.coord_client is None or not payload:
            return
        if payload[0] != PING_VERSION:
            log.warning("unsupported ping version %d from %s", payload[0], ns.id)
            metrics.incr("serf.coordinate.rejected", 1, s._labels)
            obs.record("coordinate-rejected", node=s.local_id, peer=ns.id,
                       reason=f"ping version {payload[0]}")
            return
        try:
            other = Coordinate.decode(payload[1:])
        except codec.DecodeError as e:
            log.warning("bad coordinate from %s: %s", ns.id, e)
            metrics.incr("serf.coordinate.rejected", 1, s._labels)
            obs.record("coordinate-rejected", node=s.local_id, peer=ns.id,
                       reason=f"undecodable: {e}")
            return
        if rtt <= 0.0:
            metrics.incr("serf.coordinate.zero-rtt", 1, s._labels)
            return
        start = time.monotonic()
        try:
            s.coord_client.update(ns.id, other, rtt)
        except ValueError as e:
            log.debug("coordinate update rejected for %s: %s", ns.id, e)
            metrics.incr("serf.coordinate.rejected", 1, s._labels)
            obs.record("coordinate-rejected", node=s.local_id, peer=ns.id,
                       reason=str(e))
            return
        metrics.observe("serf.coordinate.adjustment-ms",
                        (time.monotonic() - start) * 1e3, s._labels)
        s._coord_cache[ns.id] = other
        s._coord_cache[s.local_id] = s.coord_client.get_coordinate()


def _decode_tags(meta: bytes) -> Tags:
    if not meta:
        return Tags()
    try:
        return Tags.decode(meta)
    except codec.DecodeError:
        return Tags()


def _swim_to_status(state: SwimState) -> MemberStatus:
    return {
        SwimState.ALIVE: MemberStatus.ALIVE,
        SwimState.SUSPECT: MemberStatus.ALIVE,
        SwimState.DEAD: MemberStatus.FAILED,
        SwimState.LEFT: MemberStatus.LEFT,
    }[state]


class Serf:
    """Public handle (reference ``Serf<T, D>``, serf-core/src/serf.rs:177)."""

    # ------------------------------------------------------------------
    # construction (reference new_in, base.rs:62-344)
    # ------------------------------------------------------------------

    def __init__(self, transport: Transport, opts: Options,
                 node_id: str,
                 user_delegate: Optional[CompositeDelegate] = None,
                 keyring: Optional[SecretKeyring] = None,
                 rng: Optional[random.Random] = None):
        opts.validate()
        self.opts = opts
        self.user_delegate = user_delegate
        self.rng = rng or random.Random()
        self._labels = dict(opts.memberlist.metric_labels)
        self._tags = opts.tags
        self._tags.check_meta_size()

        self.clock = LamportClock()
        self.event_clock = LamportClock()
        self.query_clock = LamportClock()
        # seed clocks so no message is ever sent at ltime 0 (base.rs:196-205)
        self.clock.increment()
        self.event_clock.increment()
        self.query_clock.increment()

        self._members: Dict[str, MemberState] = {}
        self._failed: List[MemberState] = []
        self._left: List[MemberState] = []
        self._recent_intents: Dict[str, NodeIntent] = {}

        self._event_buffer: List[Optional[UserEvents]] = [None] * opts.event_buffer_size
        self._event_min_time: LamportTime = 0
        self._event_join_ignore = False
        self._deferred_events: List[UserEventMessage] = []
        self._query_buffer: List[Optional[Tuple[LamportTime, Set[int]]]] = \
            [None] * opts.query_buffer_size
        self._query_min_time: LamportTime = 0
        self._query_responses: Dict[Tuple[LamportTime, int], QueryResponse] = {}

        self.state = SerfState.ALIVE
        self._state_lock = asyncio.Lock()
        self._join_lock = asyncio.Lock()

        self._delegate = _SerfSwimDelegate()
        self.memberlist = Memberlist(
            transport, opts.memberlist, node_id,
            delegate=self._delegate, keyring=keyring, rng=self.rng,
        )
        self._delegate.serf = self  # back-link (reference SerfWeakRef)

        def _num_nodes() -> int:
            return max(1, len(self._members))

        rm = opts.memberlist.retransmit_mult
        # named queues emit serf.queue.<name> depth + byte gauges at every
        # mutation (the QueueChecker still re-gauges periodically).  Byte
        # budgets realize the shedding priority order (ISSUE 5): the SWIM
        # membership queue (memberlist.broadcasts) is never shed at all;
        # intents carry the largest budget, user events less, query
        # fan-out least — under a storm, queries give way first.
        self.intent_broadcasts = TransmitLimitedQueue(
            rm, _num_nodes, name="intent", labels=self._labels,
            max_bytes=opts.intent_queue_bytes)
        self.event_broadcasts = TransmitLimitedQueue(
            rm, _num_nodes, name="event", labels=self._labels,
            max_bytes=opts.event_queue_bytes)
        self.query_broadcasts = TransmitLimitedQueue(
            rm, _num_nodes, name="query", labels=self._labels,
            max_bytes=opts.query_queue_bytes)

        self.coord_client: Optional[CoordinateClient] = None
        self._coord_cache: Dict[str, Coordinate] = {}
        if not opts.disable_coordinates:
            self.coord_client = CoordinateClient(CoordinateOptions(), rng=self.rng)

        #: the MPMC event pipeline (host/pipeline.py): bounded keyed
        #: intake + N applier workers, wired in ``create()`` once the
        #: subscriber/coalescer topology is known.  Queue-age tracking
        #: rides the pipeline's own entries (each carries its enqueue
        #: timestamp), so a shed entry can never leave a stale
        #: timestamp behind on a side-deque.
        self._pipeline: Optional[EventPipeline] = None
        self._subscriber: Optional[EventSubscriber] = None
        self.snapshotter = None  # wired by serf_tpu.host.snapshot
        self._key_manager = None

        # health plane (obs.health): sources read engine state lazily
        self._loop_lag_ewma_ms = 0.0
        self._health = HealthScorer(serf_sources(self))
        # propagation provenance (obs.propagation): how the gossip
        # fabric treats user-event broadcasts at this node — folded
        # cluster-wide through the _serf_stats partials
        self.prop_ledger = PropagationLedger()
        # admission control (host/admission.py): ingress token buckets +
        # health-aware shedding; all knobs default off
        self._admission = AdmissionController(self)
        #: non-membership events shed at the inbox bound (accounting)
        self._events_shed = 0
        # record/replay ingress tap (serf_tpu.replay): when set, every
        # OFFERED user_event/query is reported before admission — the
        # recording captures what was asked for, sheds replay as sheds
        self._ingress_tap = None
        # forensics attachments (obs.watchdog / obs.blackbox): the chaos
        # executor (or any embedder) attaches a per-node BlackBox and a
        # shared Watchdog here; `_serf_blackbox` answers from them
        self.blackbox = None
        self.watchdog = None

        self._tasks: List[asyncio.Task] = []
        self._bg: set = set()
        self._shutdown_event = asyncio.Event()
        self._conflict_resolving = False
        # reaper-tick cache of the pending-leave index (see
        # _pending_leave_ltimes): recomputed only when the intent queue's
        # membership actually changed
        self._leave_index: Dict[str, LamportTime] = {}
        self._leave_index_version = -1

    def _spawn(self, coro, name: str) -> asyncio.Task:
        """Dynamic background task: retained in ``_bg``, exception-logged
        on death (serflint async-fire-forget contract)."""
        return spawn_logged(coro, f"{name}-{self.local_id}",
                            registry=self._bg)

    def _track(self, coro, name: str) -> asyncio.Task:
        """Long-lived engine task: retained in ``_tasks`` for shutdown,
        exception-logged on death — a reaper that dies mid-run is a loud
        log line now, not a silent stall until shutdown."""
        t = asyncio.create_task(coro, name=name)
        t.add_done_callback(log_task_exception)
        self._tasks.append(t)
        return t

    @classmethod
    async def create(cls, transport: Transport, opts: Options, node_id: str,
                     user_delegate: Optional[CompositeDelegate] = None,
                     keyring: Optional[SecretKeyring] = None,
                     subscriber: Optional[EventSubscriber] = None,
                     rng: Optional[random.Random] = None) -> "Serf":
        """Async constructor: snapshot replay, memberlist start, background
        tasks, auto-rejoin (reference Serf::new + new_in)."""
        s = cls(transport, opts, node_id, user_delegate, keyring, rng)
        s._subscriber = subscriber
        s._pipeline = s._build_pipeline()

        # snapshot replay (reference base.rs:130-155)
        replay_nodes: List[Node] = []
        if opts.snapshot_path:
            from serf_tpu.host.snapshot import open_and_replay_snapshot, Snapshotter
            replay = open_and_replay_snapshot(opts.snapshot_path,
                                              opts.rejoin_after_leave)
            s.clock.witness(replay.last_clock)
            s.event_clock.witness(replay.last_event_clock)
            s.query_clock.witness(replay.last_query_clock)
            s._event_min_time = replay.last_event_clock + 1
            s._query_min_time = replay.last_query_clock + 1
            replay_nodes = replay.alive_nodes
            s.snapshotter = Snapshotter(
                opts.snapshot_path, replay, s._labels,
                clock_fn=lambda: (s.clock.time(), s.event_clock.time(),
                                  s.query_clock.time()),
                min_compact_size=opts.snapshot_min_compact_size,
                rejoin_after_leave=opts.rejoin_after_leave)
            s._track(s.snapshotter.run(), f"serf-snapshot-{node_id}")

        await s.memberlist.start()

        # key manager (encryption feature)
        if keyring is not None:
            from serf_tpu.host.key_manager import KeyManager
            s._key_manager = KeyManager(s)

        # background tasks (reference base.rs:284-335)
        s._track(s._reaper(), f"serf-reaper-{node_id}")
        s._track(s._reconnector(), f"serf-reconnect-{node_id}")
        s._track(s._health_monitor(), f"serf-health-{node_id}")
        s._track(s._query_sweeper(), f"serf-query-sweep-{node_id}")
        for qname, q in (("intent", s.intent_broadcasts),
                         ("event", s.event_broadcasts),
                         ("query", s.query_broadcasts)):
            s._track(s._queue_checker(qname, q),
                     f"serf-qc-{qname}-{node_id}")

        # auto-rejoin snapshot nodes (reference handle_rejoin, base.rs:1782)
        if replay_nodes and (opts.rejoin_after_leave or not getattr(
                s.snapshotter, "left_before", False)):
            s._spawn(s._handle_rejoin(replay_nodes), "serf-rejoin")
        return s

    # ------------------------------------------------------------------
    # event pipeline (host/pipeline.py: bounded MPMC + dependency keys)
    # ------------------------------------------------------------------

    def _build_pipeline(self) -> EventPipeline:
        """Assemble the delivery topology onto the MPMC pipeline.

        The snapshotter is a non-blocking tee (reference snapshot.rs
        tee_stream) run as the workers' ``observe`` hook: it sees every
        event BEFORE the (possibly blocking, if lossless) subscriber
        push, so a stalled consumer can never freeze snapshot
        persistence for events already picked up.  Events still waiting
        in the bounded intake are not yet persisted — the ``tee`` health
        component (``event_tee_fill``) therefore counts intake + in-
        service, so the signal saturates while a wedge is FORMING, not
        after memory is gone.  Coalescers (when configured) are fan-out
        stages fed synchronously by the workers; non-coalescable events
        push straight through, exactly the reference's channel-wrapper
        chain (base.rs:88-115) minus the serial hop-per-stage."""
        out = self._subscriber
        member_stage = user_stage = None
        if out is not None:
            if self.opts.coalesce_period > 0:
                member_stage = CoalesceStage(
                    MemberEventCoalescer(), out.push,
                    self.opts.coalesce_period, self.opts.quiescent_period,
                    self._track, f"serf-coalesce-m-{self.local_id}")
            if self.opts.user_coalesce_period > 0:
                user_stage = CoalesceStage(
                    UserEventCoalescer(), out.push,
                    self.opts.user_coalesce_period,
                    self.opts.user_quiescent_period,
                    self._track, f"serf-coalesce-u-{self.local_id}")

        deliver = deliver_sync = None
        if out is None:
            # drain mode: no subscriber — observe-only, fully sync
            def deliver_sync(ev):
                return None
        elif out.lossless:
            # lossless push AWAITS for room (the backpressure contract):
            # delivery must stay async, contention queues at the intake
            async def deliver(ev):
                if member_stage is not None and member_stage.feed(ev):
                    return
                if user_stage is not None and user_stage.feed(ev):
                    return
                await out.push(ev)
        else:
            # drop-oldest push and coalescer feeds never await: the
            # pipeline's run-to-completion fast path applies idle-chain
            # events inline (zero queue-wait — the collapse the PR-12
            # ledger demanded), queuing only under per-key contention
            def deliver_sync(ev):
                if member_stage is not None and member_stage.feed(ev):
                    return
                if user_stage is not None and user_stage.feed(ev):
                    return
                out._push(ev)

        def observe(ev) -> None:
            if self.snapshotter is not None:
                self.snapshotter.observe(ev)

        return EventPipeline(
            spawn=self._track, observe=observe, deliver=deliver,
            deliver_sync=deliver_sync,
            workers=self.opts.pipeline_workers,
            labels=self._labels, node=self.local_id)

    def _emit(self, ev) -> None:
        """Enqueue an event for the delivery pipeline, shedding under
        overload: once the inbox holds ``event_inbox_max`` entries,
        non-membership events are dropped with a counter + flight
        event.  In practice that is user events plus a node's OWN
        query deliveries (remote queries fast-fail earlier, at
        ``overloaded()``'s 0.9-of-cap pressure threshold, so they
        rarely reach a full inbox).  MemberEvents are membership state
        and are ALWAYS enqueued — the shedding priority order never
        sacrifices them, and the snapshotter (fed from this pipeline)
        must not miss an alive-set change."""
        if self._pipeline is None:
            # direct-constructed engine (Serf() without create(), e.g.
            # handler-level test oracles): build the delivery topology
            # on first emit — drain mode is fully synchronous, so no
            # running loop is required until something queues
            self._pipeline = self._build_pipeline()
        cap = self.opts.event_inbox_max
        led = lifecycle.global_ledger()
        if (cap > 0 and ev is not None and not isinstance(ev, MemberEvent)
                and self._pipeline.depth() >= cap):
            kind = type(ev).__name__
            self._events_shed += 1
            led.attach_current(ev, shed=True)
            metrics.incr("serf.overload.event_shed", 1,
                         {**self._labels, "event": kind})
            obs.record("event-shed", node=self.local_id, event=kind,
                       inbox=self._pipeline.depth())
            return
        if ev is not None:
            led.attach_current(ev)
        self._pipeline.offer(ev)

    # ------------------------------------------------------------------
    # public API (reference api.rs)
    # ------------------------------------------------------------------

    @property
    def local_id(self) -> str:
        return self.memberlist.local_id()

    def local_member(self) -> Member:
        ms = self._members.get(self.local_id)
        if ms is not None:
            return ms.member
        return Member(self.memberlist.local_node(), self._tags, MemberStatus.ALIVE)

    def members(self) -> List[Member]:
        return [ms.member for ms in self._members.values()]

    def num_members(self) -> int:
        return len(self._members)

    def encryption_enabled(self) -> bool:
        return self.memberlist.encryption_enabled()

    def key_manager(self):
        return self._key_manager

    def tags(self) -> Tags:
        return self._tags

    async def set_tags(self, tags: Tags) -> None:
        """Hot-swap tags and re-advertise meta (reference api.rs:219-235)."""
        tags.check_meta_size()
        self._tags = tags
        await self.memberlist.update_node(self.opts.broadcast_timeout)

    def stats(self) -> Stats:
        return Stats(
            metrics=obs.metrics_snapshot(),
            trace=obs.trace_dump(),
            flight=obs.flight_dump(),
            health=self.health_report().to_dict(),
            members=len(self._members),
            failed=len(self._failed),
            left=len(self._left),
            health_score=self.memberlist.health_score(),
            member_time=self.clock.time(),
            event_time=self.event_clock.time(),
            query_time=self.query_clock.time(),
            intent_queue=len(self.intent_broadcasts),
            event_queue=len(self.event_broadcasts),
            query_queue=len(self.query_broadcasts),
            encrypted=self.encryption_enabled(),
            coordinate_resets=(self.coord_client.stats()["resets"]
                               if self.coord_client else 0),
        )

    # -- health / cluster observability -------------------------------------

    def event_tee_fill(self) -> float:
        """Fill fraction of the event delivery path: pipeline intake
        (events not yet snapshotter-persisted) plus in-service entries,
        over the intake bound — the health signal climbs while a wedged
        consumer backs the pipeline up, not after memory is gone.  0.0
        when the intake is unbounded or the pipeline is not running."""
        p = self._pipeline
        cap = self.opts.event_inbox_max
        if p is None or cap <= 0:
            return 0.0
        return (p.depth() + p.inflight()) / cap

    def pipeline_depth(self) -> int:
        """Events offered to the MPMC pipeline and not yet picked up by
        an applier worker (the bounded-intake backpressure signal)."""
        p = self._pipeline
        return 0 if p is None else p.depth()

    def loop_lag_ms(self) -> float:
        """EWMA of event-loop scheduling lag (ms), fed by the health
        monitor — how late our timers fire under load."""
        return self._loop_lag_ewma_ms

    def health_report(self, consume: bool = False) -> HealthReport:
        """Sample the Lifeguard-style node health score (obs.health) and
        export ``serf.health.score`` + per-component load gauges, labeled
        with the node id so co-located nodes stay distinguishable.
        Only the periodic monitor passes ``consume=True`` (advancing the
        counter-delta baselines); on-demand calls observe without
        shrinking the measurement window."""
        report = self._health.sample(consume=consume)
        labels = {**self._labels, "node": self.local_id}
        metrics.gauge("serf.health.score", report.score, labels)
        for name, comp in report.components.items():
            metrics.gauge(f"serf.health.component.{name}", comp.load, labels)
        return report

    async def cluster_stats(self, params: Optional[QueryParam] = None):
        """Scatter the ``_serf_stats`` internal query over the cluster and
        fold every node's health + key metrics into one
        ``obs.cluster.ClusterSnapshot`` (min/p50/max aggregates,
        unhealthy-node list, membership-view divergence).  ``params``
        tunes the underlying query (e.g. a longer timeout for large
        clusters)."""
        from serf_tpu.obs.cluster import collect_cluster_stats
        return await collect_cluster_stats(self, params)

    async def cluster_blackbox(self, params: Optional[QueryParam] = None):
        """Scatter the ``_serf_blackbox`` internal query and fold every
        node's black-box bundle inventory (``obs.blackbox``) into one
        ``ClusterBlackbox`` — which nodes hold forensic bundles, their
        latest dump reason, and where to read them."""
        from serf_tpu.obs.blackbox import collect_cluster_blackbox
        return await collect_cluster_blackbox(self, params)

    async def _health_monitor(self) -> None:
        """Periodic health plane tick: measure event-loop lag (sleep
        overshoot), refresh the EWMA + gauges, re-sample the health
        score."""
        interval = max(0.05, self.opts.health_interval)
        loop = asyncio.get_running_loop()
        while not self._shutdown_event.is_set():
            t0 = loop.time()
            await asyncio.sleep(interval)
            lag_ms = max(0.0, loop.time() - t0 - interval) * 1e3
            self._loop_lag_ewma_ms = (0.8 * self._loop_lag_ewma_ms
                                      + 0.2 * lag_ms)
            metrics.gauge("serf.loop.lag-ms", self._loop_lag_ewma_ms,
                          {**self._labels, "node": self.local_id})
            self._gauge_queue_ages()
            try:
                self.health_report(consume=True)
            except Exception:  # noqa: BLE001
                log.exception("health monitor tick failed")

    def _gauge_queue_ages(self) -> None:
        """Oldest-item age gauges for every bounded queue (sampled on
        the monitor tick): the three broadcast queues plus the event
        inbox and the tee queue.  A growing age with flat depth means a
        stuck consumer, not a burst — the signal the lifecycle ledger's
        queue-wait stage should corroborate."""
        now = time.monotonic()
        labels = {**self._labels, "node": self.local_id}
        p = self._pipeline
        ages = {
            "intent": self.intent_broadcasts.oldest_age(now),
            "event": self.event_broadcasts.oldest_age(now),
            "query": self.query_broadcasts.oldest_age(now),
            # pipeline entries carry their own enqueue timestamp: the
            # intake's oldest waiting entry and the oldest entry still
            # in service (shed entries never skew either — there is no
            # parallel timestamp deque to fall out of sync)
            "inbox": p.oldest_age(now) if p is not None else 0.0,
            "tee": p.oldest_service_age(now) if p is not None else 0.0,
        }
        for qname, age in ages.items():
            metrics.gauge(f"serf.queue.age.{qname}", age, labels)
        if p is not None:
            p.gauge()

    def coordinate(self) -> Optional[Coordinate]:
        return self.coord_client.get_coordinate() if self.coord_client else None

    def cached_coordinate(self, node_id: str) -> Optional[Coordinate]:
        return self._coord_cache.get(node_id)

    # -- join / leave -------------------------------------------------------

    async def join(self, addr, ignore_old: bool = False) -> None:
        """(reference api.rs:318-417)"""
        if self.state != SerfState.ALIVE:
            raise RuntimeError(f"cannot join while {self.state.name}")
        async with self._join_lock:
            self._event_join_ignore = ignore_old
            try:
                await self.memberlist.join(addr)
                await self._broadcast_join(self.clock.increment())
            finally:
                self._event_join_ignore = False
                self._flush_deferred_events()

    async def join_many(self, addrs: Sequence, ignore_old: bool = False
                        ) -> Tuple[int, List[Exception]]:
        if self.state != SerfState.ALIVE:
            raise RuntimeError(f"cannot join while {self.state.name}")
        async with self._join_lock:
            self._event_join_ignore = ignore_old
            try:
                ok, errs = await self.memberlist.join_many(addrs)
                if ok > 0:
                    await self._broadcast_join(self.clock.increment())
                return ok, errs
            finally:
                self._event_join_ignore = False
                self._flush_deferred_events()

    def _flush_deferred_events(self) -> None:
        """Re-run user events deferred during a join(ignore_old=True):
        ``_event_min_time`` is settled now, so the normal handler drops
        the pre-join ones and delivers the rest in arrival order.  No
        rebroadcast — we were not their origin, and the cluster gossiped
        them while we were joining."""
        if not self._deferred_events:
            return
        pending, self._deferred_events = self._deferred_events, []
        for msg in pending:
            self._handle_user_event(msg, rebroadcast=False)

    async def _broadcast_join(self, ltime: LamportTime) -> None:
        """(reference base.rs:364-397)"""
        msg = JoinMessage(ltime, self.local_id)
        self._handle_node_join_intent(msg, rebroadcast=False)
        self._queue(self.intent_broadcasts, encode_message(msg))

    async def leave(self) -> None:
        """Graceful leave: broadcast intent, drain, memberlist leave
        (reference api.rs:422-499)."""
        if self.state in (SerfState.LEFT, SerfState.SHUTDOWN):
            return
        async with self._state_lock:
            # re-check after acquiring: a concurrent leave() may have finished
            if self.state in (SerfState.LEFT, SerfState.SHUTDOWN):
                return
            self.state = SerfState.LEAVING
            if self.snapshotter is not None:
                await self.snapshotter.leave()
            ltime = self.clock.increment()
            msg = LeaveMessage(ltime, self.local_id)
            self._handle_node_leave_intent(msg, rebroadcast=False)
            if self._has_alive_peers():
                done = asyncio.Event()
                self._queue(self.intent_broadcasts, encode_message(msg), notify=done)
                try:
                    await asyncio.wait_for(done.wait(), self.opts.broadcast_timeout)
                except asyncio.TimeoutError:
                    log.warning("timeout while waiting for leave broadcast")
            await self.memberlist.leave(self.opts.broadcast_timeout)
            if self._has_alive_peers():
                # serflint: ignore[async-lock-await] -- deliberate: leave()
                # must serialize end-to-end; a concurrent leave() parking
                # here is exactly the intended behavior (reference
                # api.rs:477 sleeps the propagate delay inside the leave
                # critical section too)
                await asyncio.sleep(self.opts.leave_propagate_delay)
            self.state = SerfState.LEFT

    def _has_alive_peers(self) -> bool:
        return any(ms.member.status == MemberStatus.ALIVE
                   and ms.id != self.local_id for ms in self._members.values())

    async def shutdown(self) -> None:
        """(reference api.rs:525-558)"""
        if self.state == SerfState.SHUTDOWN:
            return
        self.state = SerfState.SHUTDOWN
        self._shutdown_event.set()
        await self.memberlist.shutdown()
        for t in [*self._tasks, *self._bg]:
            t.cancel()
        for t in [*self._tasks, *list(self._bg)]:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        for key, resp in list(self._query_responses.items()):
            resp.close()
        self._query_responses.clear()
        if self.snapshotter is not None:
            await self.snapshotter.shutdown()

    async def remove_failed_node(self, node_id: str, prune: bool = False) -> None:
        """Force-leave: broadcast a leave intent on behalf of a failed node
        (reference api.rs:505-515, base.rs force_leave)."""
        ltime = self.clock.increment()
        msg = LeaveMessage(ltime, node_id, prune)
        if not self._handle_node_leave_intent(msg, rebroadcast=False) \
                and node_id not in self._members and node_id not in self._recent_intents:
            return  # nothing known about this node
        if not self._has_alive_peers():
            return
        done = asyncio.Event()
        self._queue(self.intent_broadcasts, encode_message(msg), notify=done)
        try:
            await asyncio.wait_for(done.wait(), self.opts.broadcast_timeout)
        except asyncio.TimeoutError:
            log.warning("timeout broadcasting force-leave for %s", node_id)

    # -- user events --------------------------------------------------------

    def set_ingress_tap(self, fn) -> None:
        """Install (or clear, with ``None``) the record/replay ingress
        tap: ``fn(op, node_id, name=..., payload=..., ...)`` is called
        for every OFFERED ``user_event``/``query`` before validation or
        admission, in call order — the seam ``serf_tpu.replay`` records
        a run's ingress through (``RunRecorder.ingress_tap()``).
        Internal ``_serf_*`` control queries are NOT tapped: they are
        regenerated by the replay cluster itself."""
        self._ingress_tap = fn

    async def user_event(self, name: str, payload: bytes, coalesce: bool = True) -> None:
        """(reference api.rs:241-299); raises :class:`OverloadError` when
        admission control (token bucket / health floor) sheds the event —
        an explicit fast failure the caller can back off on."""
        if self._ingress_tap is not None:
            self._ingress_tap("user-event", self.local_id, name=name,
                              payload=payload, coalesce=coalesce)
        # size validation FIRST: a rejected oversized event must not
        # drain a rate-limit token nor count as admitted ingress
        size = len(name) + len(payload)
        if size > self.opts.max_user_event_size:
            raise ValueError(
                f"user event exceeds configured limit of "
                f"{self.opts.max_user_event_size} bytes before encoding")
        if size > USER_EVENT_SIZE_LIMIT:
            raise ValueError(f"user event exceeds sane limit of {USER_EVENT_SIZE_LIMIT} bytes")
        reason = self._admission.admit("user_event", name)
        record_ingress(self._labels, self.local_id, "user_event", reason)
        if reason is not None:
            raise OverloadError("user_event", reason)
        ltime = self.event_clock.increment()
        tctx = new_trace(self.local_id)
        msg = UserEventMessage(ltime, name, payload, coalesce, tctx)
        raw = encode_message(msg)
        if len(raw) > USER_EVENT_SIZE_LIMIT:
            raise ValueError(
                f"encoded user event exceeds sane limit of {USER_EVENT_SIZE_LIMIT} bytes")
        # metrics are counted once, inside the handler (reference base.rs:818)
        led = lifecycle.global_ledger()
        led.begin("local", kind="UserEventMessage")
        try:
            with trace_scope(tctx), span("serf.user-event",
                                         node=self.local_id,
                                         event=name, bytes=len(raw)):
                self._handle_user_event(msg, rebroadcast=False)
                self._queue(self.event_broadcasts, raw)
        finally:
            led.finish_current()

    # -- queries ------------------------------------------------------------

    async def query(self, name: str, payload: bytes,
                    params: Optional[QueryParam] = None) -> QueryResponse:
        """(reference api.rs:304-313, base.rs:875-944); raises
        :class:`OverloadError` when admission control sheds the query
        (internal ``_serf_*`` control queries are exempt — the operator
        needs the stats plane most while the node is overloaded)."""
        params = params or QueryParam()
        # internal _serf_* control queries (conflict resolution, stats
        # sweeps, key ops) are protocol machinery, not user ingress —
        # recording them would make replay re-issue them ON TOP of the
        # replay cluster's own internally-generated copies
        if self._ingress_tap is not None and not name.startswith("_serf_"):
            self._ingress_tap("query", self.local_id, name=name,
                              payload=payload, timeout=params.timeout)
        # cheap size pre-check FIRST (raw <= encoded, so raw over the
        # limit can never encode under it): an obviously oversized query
        # must not drain a token nor count as admitted ingress.  The
        # exact encoded-size check below still governs.
        if len(name) + len(payload) > self.opts.query_size_limit:
            raise ValueError(
                f"query exceeds limit of {self.opts.query_size_limit} bytes")
        if not name.startswith("_serf_"):
            reason = self._admission.admit("query", name)
            record_ingress(self._labels, self.local_id, "query", reason)
            if reason is not None:
                raise OverloadError("query", reason)
        timeout = params.timeout or default_query_timeout(
            max(1, len(self._members)),
            self.opts.memberlist.gossip_interval,
            self.opts.query_timeout_mult,
        )
        ltime = self.query_clock.increment()
        qid = self.rng.getrandbits(32)
        flags = QueryFlag.NONE
        if params.request_ack:
            flags |= QueryFlag.ACK
        tctx = new_trace(self.local_id)
        msg = QueryMessage(
            ltime=ltime, id=qid, from_node=self.memberlist.local_node(),
            filters=tuple(params.filters), flags=flags,
            relay_factor=params.relay_factor,
            timeout_ns=int(timeout * 1e9), name=name, payload=payload,
            tctx=tctx,
        )
        raw = encode_message(msg)
        if len(raw) > self.opts.query_size_limit:
            raise ValueError(f"query exceeds limit of {self.opts.query_size_limit} bytes")
        resp = QueryResponse(ltime, qid, timeout, params.request_ack,
                             len(self._members))
        self._admit_query_response((ltime, qid), resp)
        led = lifecycle.global_ledger()
        led.begin("local", kind="QueryMessage")
        try:
            with trace_scope(tctx), span("serf.query", node=self.local_id,
                                         query=name, bytes=len(raw)):
                self._handle_query(msg, rebroadcast=False)
                self._queue(self.query_broadcasts, raw)
        finally:
            led.finish_current()
        return resp

    def _admit_query_response(self, key, resp: QueryResponse) -> None:
        """Bounded insert into the originator-side handler map: at
        ``max_query_responses`` the expired entries are reclaimed inline;
        if the map is still full, the entry closest to its deadline is
        evicted (closed, counted, flight-recorded) — a query storm can
        no longer grow the map without limit.  The periodic
        ``_query_sweeper`` does the routine TTL reclamation."""
        cap = self.opts.max_query_responses
        if len(self._query_responses) >= cap:
            self._sweep_query_responses(time.monotonic())
        if len(self._query_responses) >= cap:
            victim_key = min(self._query_responses,
                             key=lambda k: self._query_responses[k].deadline)
            victim = self._query_responses.pop(victim_key)
            victim.close()
            metrics.incr("serf.overload.query_responses_shed", 1,
                         self._labels)
            obs.record("query-responses-shed", node=self.local_id,
                       ltime=victim_key[0], qid=victim_key[1], cap=cap)
        self._query_responses[key] = resp

    def _sweep_query_responses(self, now: float) -> int:
        """Close + drop every expired handler; returns how many."""
        expired = [k for k, r in self._query_responses.items()
                   if now > r.deadline]
        for k in expired:
            resp = self._query_responses.pop(k, None)
            if resp is not None:
                resp.close()
        return len(expired)

    async def _query_sweeper(self) -> None:
        """ONE periodic task reclaims every expired query handler —
        replacing the per-query expiry task the engine used to spawn
        (a query storm meant a task storm).  Consumers never notice the
        latency: ``QueryResponse`` iterators end at the deadline on
        their own; the sweep only reclaims the map entry."""
        interval = self.opts.query_sweep_interval
        while not self._shutdown_event.is_set():
            await asyncio.sleep(interval)
            try:
                self._sweep_query_responses(time.monotonic())
                metrics.gauge("serf.overload.query_responses",
                              len(self._query_responses),
                              {**self._labels, "node": self.local_id})
            except Exception:  # noqa: BLE001
                log.exception("query sweeper tick failed")

    async def relay_response(self, relay_factor: int, target: Node, raw: bytes) -> None:
        """Redundantly relay a query response through k random members
        (reference query.rs:523-601)."""
        if relay_factor == 0 or len(self._members) < relay_factor + 1:
            return
        relay = encode_relay_message(target, raw)
        picks = random_members(
            relay_factor, self.members(),
            {self.local_id, target.id}, self.rng)
        for m in picks:
            await self.memberlist.send(m.node.addr, relay)

    # ------------------------------------------------------------------
    # inbound dispatch (reference delegate.rs notify_message, 157-315)
    # ------------------------------------------------------------------

    def _dispatch(self, msg, raw: bytes) -> None:
        # stage clock: decode -> here is the `dispatch` hop; the handler
        # body through to the inbox enqueue is `apply` (stamped by
        # _emit / finish_current)
        lifecycle.global_ledger().stamp_current("dispatch")
        if isinstance(msg, LeaveMessage):
            if self._handle_node_leave_intent(msg):
                self._queue(self.intent_broadcasts, raw)
        elif isinstance(msg, JoinMessage):
            if self._handle_node_join_intent(msg):
                self._queue(self.intent_broadcasts, raw)
        elif isinstance(msg, UserEventMessage):
            if self._handle_user_event(msg):
                self.prop_ledger.rebroadcast(msg.tctx)
                metrics.incr("serf.propagation.rebroadcasts", 1,
                             self._labels)
                self._queue(self.event_broadcasts, self._hop_raw(msg, raw))
        elif isinstance(msg, QueryMessage):
            if self._handle_query(msg):
                self._queue(self.query_broadcasts, self._hop_raw(msg, raw))
        elif isinstance(msg, QueryResponseMessage):
            self._handle_query_response(msg)
        elif isinstance(msg, RelayMessage):
            self._handle_relay(msg)
        else:
            log.debug("unhandled serf message %s", type(msg).__name__)

    def _handle_relay(self, msg: RelayMessage) -> None:
        if msg.node.id == self.local_id or msg.node.addr == self.memberlist.local_node().addr:
            try:
                inner = decode_message_cached(msg.payload)
            except codec.DecodeError as e:
                log.debug("bad relayed message: %s", e)
                return
            self._dispatch(inner, msg.payload)
        else:
            self._spawn(self.memberlist.send(msg.node.addr, msg.payload), "serf-relay-fwd")

    def _queue(self, q: TransmitLimitedQueue, raw: bytes,
               notify: Optional[asyncio.Event] = None) -> None:
        q.queue_broadcast(Broadcast(raw, name=None, notify=notify))

    @staticmethod
    def _hop_raw(msg, raw: bytes) -> bytes:
        """Bytes to rebroadcast: when the message carries a trace context,
        re-encode with the hop count bumped so downstream flight events
        record their dissemination depth; untraced messages forward the
        original bytes untouched (zero re-encode cost)."""
        tctx = getattr(msg, "tctx", None)
        if tctx is None:
            return raw
        return encode_message(replace(msg, tctx=tctx.hop()))

    # ------------------------------------------------------------------
    # member-event handlers (reference base.rs:1206-1866)
    # ------------------------------------------------------------------

    def _handle_node_join(self, ns: NodeState) -> None:
        """memberlist says a node is alive (reference base.rs:1206-1334)."""
        tags = _decode_tags(ns.meta)
        old = self._members.get(ns.id)
        status_time = 0
        status = MemberStatus.ALIVE
        jt = recent_intent(self._recent_intents, ns.id, IntentType.JOIN)
        if jt is not None:
            status_time = jt
        lt = recent_intent(self._recent_intents, ns.id, IntentType.LEAVE)
        if lt is not None and lt > status_time:
            status = MemberStatus.LEAVING
            status_time = lt
        self._recent_intents.pop(ns.id, None)
        pv, dv = ns.vsn[2], ns.vsn[5]   # current protocol/delegate versions
        if old is None:
            ms = MemberState(
                Member(ns.node, tags, status, pv, dv), status_time, 0.0)
            self._members[ns.id] = ms
        else:
            # rejoin: flap detection (reference base.rs:1236-1249)
            if old.member.status in (MemberStatus.FAILED, MemberStatus.LEFT):
                if time.monotonic() - old.leave_time < self.opts.flap_timeout:
                    metrics.incr("serf.member.flap", 1, self._labels)
                self._failed = [m for m in self._failed if m.id != ns.id]
                self._left = [m for m in self._left if m.id != ns.id]
            ms = old
            ms.member = Member(ns.node, tags, status, pv, dv)
            if status_time:
                ms.status_time = status_time
        metrics.incr("serf.member.join", 1, self._labels)
        obs.record("member-state", node=self.local_id, member=ns.id,
                   status=ms.member.status.name, via="notify_join")
        self._emit(MemberEvent(MemberEventType.JOIN, (ms.member,)))

    def _handle_node_leave(self, ns: NodeState) -> None:
        """memberlist says a node failed or left (reference base.rs:1375-1440)."""
        ms = self._members.get(ns.id)
        if ms is None:
            return
        cur = ms.member.status
        if cur == MemberStatus.LEAVING or ns.state == SwimState.LEFT:
            ms.member = ms.member.with_status(MemberStatus.LEFT)
            ms.leave_time = time.monotonic()
            self._left.append(ms)
            ty = MemberEventType.LEAVE
            metrics.incr("serf.member.leave", 1, self._labels)
        elif cur == MemberStatus.ALIVE:
            ms.member = ms.member.with_status(MemberStatus.FAILED)
            ms.leave_time = time.monotonic()
            self._failed.append(ms)
            ty = MemberEventType.FAILED
            metrics.incr("serf.member.failed", 1, self._labels)
        else:
            return
        obs.record("member-state", node=self.local_id, member=ns.id,
                   status=ms.member.status.name, via="notify_leave")
        self._emit(MemberEvent(ty, (ms.member,)))

    def _handle_node_update(self, ns: NodeState) -> None:
        """tags/meta changed (reference base.rs:1576-1624)."""
        ms = self._members.get(ns.id)
        if ms is None:
            return
        tags = _decode_tags(ns.meta)
        if tags == ms.member.tags:
            return
        ms.member = Member(ns.node, tags, ms.member.status,
                           ms.member.protocol_version, ms.member.delegate_version)
        metrics.incr("serf.member.update", 1, self._labels)
        self._emit(MemberEvent(MemberEventType.UPDATE, (ms.member,)))

    def _handle_node_join_intent(self, msg: JoinMessage,
                                 rebroadcast: bool = True) -> bool:
        """(reference base.rs:1338-1373); returns whether to rebroadcast."""
        self.clock.witness(msg.ltime)
        ms = self._members.get(msg.id)
        if ms is None:
            return upsert_intent(self._recent_intents, msg.id, IntentType.JOIN,
                                 msg.ltime)
        if msg.ltime <= ms.status_time:
            return False
        # A newer join intent about ourselves needs no special handling:
        # it is a story that we are ALIVE, which we are — adopt the ltime
        # and move on.  (Push/pull ``status_ltimes`` carries no status, so
        # a higher ltime about self is usually just an echo of our own
        # state as witnessed elsewhere; broadcasting a "re-assert" here —
        # as rounds 2-3 did — turns every such echo into clock churn and
        # fights the dangling-LEAVING sweep over equal-ltime races.)  The
        # genuine threats are covered elsewhere, matching the reference
        # which only self-refutes leave intents (base.rs:1468-1480):
        #   * a peer holding us LEFT exports us in push/pull left_members,
        #     which arrives as a leave intent -> self-refutation above;
        #   * a peer stuck holding us LEAVING while SWIM probes us alive
        #     repairs ITS OWN view via _sweep_dangling_leaving.
        ms.status_time = msg.ltime
        if ms.member.status == MemberStatus.LEAVING:
            # join intent refutes an in-flight leave
            ms.member = ms.member.with_status(MemberStatus.ALIVE)
        elif ms.member.status == MemberStatus.LEFT:
            # A join intent strictly newer than the leave can only mean the
            # node rejoined: join intents originate from the subject, whose
            # own clock guarantees its leave ltime exceeded all its earlier
            # joins.  Reviving here (deviation: the reference keeps LEFT
            # and relies on the memberlist notify_join) keeps serf status
            # Lamport-monotone and — critically — stops this node from
            # exporting the member in push/pull ``left_members`` stamped
            # with the NEW ltime, which would poison freshly-joined peers
            # with an unbeatable LEAVING state (found by soak seed 7).
            # FAILED members are NOT revived: for crashes, the failure
            # detector's judgment wins (as in the reference).
            ms.member = ms.member.with_status(MemberStatus.ALIVE)
            self._left = [m for m in self._left if m.id != msg.id]
            # no JOIN event here: the memberlist notify_join that follows a
            # real rejoin emits the single canonical JOIN; if the rejoiner
            # died before its aliveness reached us, the reaper's zombie
            # sweep (below) demotes this entry back to FAILED
        return True

    def _handle_node_leave_intent(self, msg: LeaveMessage,
                                  rebroadcast: bool = True) -> bool:
        """(reference base.rs:1442-1572, incl. consul#8179 fix and
        self-refutation); returns whether to rebroadcast."""
        self.clock.witness(msg.ltime)
        ms = self._members.get(msg.id)
        if ms is None:
            return upsert_intent(self._recent_intents, msg.id, IntentType.LEAVE,
                                 msg.ltime)
        if msg.ltime <= ms.status_time:
            return False
        # stale leave about ourselves while alive: refute (base.rs:1468-1480)
        if msg.id == self.local_id and self.state == SerfState.ALIVE:
            log.warning("refuting a stale leave intent about ourselves")
            self._spawn(self._broadcast_join(self.clock.increment()),
                        "serf-refute-leave")
            return False
        status = ms.member.status
        if status == MemberStatus.ALIVE:
            ms.member = ms.member.with_status(MemberStatus.LEAVING)
            ms.status_time = msg.ltime
            obs.record("member-state", node=self.local_id, member=msg.id,
                       status="LEAVING", via="leave_intent")
            if msg.prune:
                self._handle_prune(ms)
            return True
        if status == MemberStatus.FAILED:
            # failed node declared left: move to graceful-left so reapers use
            # tombstone timing; emit a Leave event (consul semantics)
            ms.member = ms.member.with_status(MemberStatus.LEFT)
            ms.status_time = msg.ltime
            ms.leave_time = time.monotonic()
            self._failed = [m for m in self._failed if m.id != msg.id]
            self._left.append(ms)
            obs.record("member-state", node=self.local_id, member=msg.id,
                       status="LEFT", via="leave_intent_on_failed")
            self._emit(MemberEvent(MemberEventType.LEAVE, (ms.member,)))
            if msg.prune:
                self._handle_prune(ms)
            return True
        if status in (MemberStatus.LEAVING, MemberStatus.LEFT):
            # already leaving/left: update time, do NOT rebroadcast
            # (anti-infinite-rebroadcast, reference base.rs:1482-1496)
            ms.status_time = msg.ltime
            if msg.prune:
                self._handle_prune(ms)
        return False

    def _handle_prune(self, ms: MemberState) -> None:
        """Erase a member entirely (reference base.rs:1628-1653)."""
        node_id = ms.id
        log.info("pruning member %s", node_id)
        self._erase_member(ms)

    def _erase_member(self, ms: MemberState) -> None:
        node_id = ms.id
        self._members.pop(node_id, None)
        self._failed = [m for m in self._failed if m.id != node_id]
        self._left = [m for m in self._left if m.id != node_id]
        if self.coord_client is not None:
            self.coord_client.forget_node(node_id)
            self._coord_cache.pop(node_id, None)

    # ------------------------------------------------------------------
    # user event / query handlers (reference base.rs:750-1202)
    # ------------------------------------------------------------------

    def _handle_user_event(self, msg: UserEventMessage,
                           rebroadcast: bool = True) -> bool:
        """(reference base.rs:750-837); returns whether to rebroadcast."""
        self.event_clock.witness(msg.ltime)
        if self._event_join_ignore:
            # A join(ignore_old=True) is in flight: until its push/pull
            # merge computes ``_event_min_time`` we cannot tell a
            # pre-join event (to be ignored) from a concurrent fresh one
            # — and gossip can beat the merge, leaking "old" events to
            # the subscriber.  Defer everything (the join-merge replay
            # included) and re-run against the settled cutoff when the
            # join finishes (_flush_deferred_events).
            if len(self._deferred_events) < DEFERRED_EVENTS_MAX:
                self._deferred_events.append(msg)
            return False
        if msg.ltime < self._event_min_time:
            return False
        buf_len = len(self._event_buffer)
        cur = self.event_clock.time()
        if msg.ltime + buf_len < cur:
            log.warning("received old event %s from time %d (current: %d)",
                        msg.name, msg.ltime, cur)
            return False
        idx = msg.ltime % buf_len
        cell = self._event_buffer[idx]
        if cell is not None and cell.ltime == msg.ltime:
            for prev in cell.events:
                if prev.name == msg.name and prev.payload == msg.payload:
                    # dedup-ring hit: the host analog of a redundant
                    # wire slot — the propagation observatory's
                    # redundancy evidence on this plane
                    self.prop_ledger.duplicate(msg.tctx)
                    metrics.incr("serf.propagation.duplicates", 1,
                                 self._labels)
                    return False
            self._event_buffer[idx] = UserEvents(
                cell.ltime, cell.events + (msg,))
        else:
            self._event_buffer[idx] = UserEvents(msg.ltime, (msg,))
        metrics.incr("serf.events", 1, self._labels)
        # keyed by NAME CLASS, not raw name: a storm of sequence-named
        # events ("storm-1", "storm-2", ...) must not grow the metrics
        # sink without bound (every sampler tick walks the whole sink)
        metrics.incr(f"serf.events.{name_class(msg.name)}", 1, self._labels)
        # first sight of this event at this node: provenance for the
        # cluster-wide coverage fold (trace id + first-seen clock)
        self.prop_ledger.accept(msg.tctx)
        metrics.incr("serf.propagation.events-seen", 1, self._labels)
        with trace_scope(msg.tctx):
            # trace-stamped while the event's context is active: the same
            # trace id lands in the flight ring of every node that accepts
            # this event (origin included — user_event() reuses this path)
            obs.record("user-event", node=self.local_id, event=msg.name,
                       ltime=msg.ltime,
                       **({"origin": msg.tctx.origin, "hops": msg.tctx.hops}
                          if msg.tctx is not None else {}))
        self._emit(UserEvent(msg.ltime, msg.name, msg.payload, msg.cc))
        return True

    def _handle_query(self, msg: QueryMessage, rebroadcast: bool = True) -> bool:
        """(reference base.rs:972-1154); returns whether to rebroadcast."""
        self.query_clock.witness(msg.ltime)
        if msg.ltime < self._query_min_time:
            return False
        buf_len = len(self._query_buffer)
        cur = self.query_clock.time()
        if msg.ltime + buf_len < cur:
            log.warning("received old query %s from time %d (current: %d)",
                        msg.name, msg.ltime, cur)
            return False
        idx = msg.ltime % buf_len
        cell = self._query_buffer[idx]
        if cell is not None and cell[0] == msg.ltime:
            if msg.id in cell[1]:
                return False
            cell[1].add(msg.id)
        else:
            self._query_buffer[idx] = (msg.ltime, {msg.id})
        rebroadcast_out = not msg.no_broadcast()
        metrics.incr("serf.queries", 1, self._labels)
        # name-class key: bounded cardinality (see _handle_user_event)
        metrics.incr(f"serf.queries.{name_class(msg.name)}", 1, self._labels)
        if not should_process_query(msg.filters, self.local_id, self._tags):
            return rebroadcast_out
        # the trace scope covers flight recording, the ack send, and —
        # because create_task snapshots contextvars — the spawned internal
        # query handler, so responder-side spans carry the query's trace id
        with trace_scope(msg.tctx):
            obs.record("query-received", node=self.local_id, query=msg.name,
                       ltime=msg.ltime, qid=msg.id,
                       **({"origin": msg.tctx.origin, "hops": msg.tctx.hops}
                          if msg.tctx is not None else {}))
            if (not msg.name.startswith("_serf_")
                    and msg.from_node.id != self.local_id
                    and self._admission.overloaded()):
                # Lifeguard-style self-awareness at the query plane: a
                # node under loop-lag/queue pressure fast-fails with an
                # explicit OVERLOADED response instead of serving late
                # (or timing out silently).  Internal control queries
                # are exempt, and so is OUR OWN query's local handling
                # (sending ourselves an OVERLOADED packet would burn a
                # send exactly when overloaded — local delivery shedding
                # at the bounded inbox covers that case).  The query
                # still rebroadcasts so healthy nodes serve it.
                metrics.incr("serf.overload.query_fastfail", 1,
                             self._labels)
                obs.record("query-fastfail", node=self.local_id,
                           query=msg.name, qid=msg.id)
                over = QueryResponseMessage(
                    ltime=msg.ltime, id=msg.id,
                    from_node=self.memberlist.local_node(),
                    flags=QueryFlag.OVERLOADED, tctx=msg.tctx)
                self._spawn(self._send_and_relay(msg, encode_message(over)),
                            "serf-query-overloaded")
                return rebroadcast_out
            if msg.ack():
                ack = QueryResponseMessage(
                    ltime=msg.ltime, id=msg.id,
                    from_node=self.memberlist.local_node(),
                    flags=QueryFlag.ACK, tctx=msg.tctx)
                raw = encode_message(ack)
                self._spawn(self._send_and_relay(msg, raw), "serf-query-ack")
            ev = QueryEvent(
                ltime=msg.ltime, name=msg.name, payload=msg.payload, id=msg.id,
                from_node=msg.from_node, relay_factor=msg.relay_factor,
                deadline=time.monotonic() + msg.timeout_ns / 1e9,
                tctx=msg.tctx, _serf=self,
            )
            if msg.name.startswith("_serf_"):
                from serf_tpu.host.internal_query import handle_internal_query
                self._spawn(handle_internal_query(self, ev),
                            "serf-internal-query")
            else:
                self._emit(ev)
        return rebroadcast_out

    async def _send_and_relay(self, msg: QueryMessage, raw: bytes) -> None:
        await self.memberlist.send(msg.from_node.addr, raw)
        await self.relay_response(msg.relay_factor, msg.from_node, raw)

    def _handle_query_response(self, msg: QueryResponseMessage) -> None:
        """(reference base.rs:1158-1202)"""
        resp = self._query_responses.get((msg.ltime, msg.id))
        if resp is None:
            return
        if msg.tctx is not None:
            # close the cross-node loop: the responder echoed our trace id
            obs.record("query-response", node=self.local_id,
                       responder=msg.from_node.id, ack=msg.ack(),
                       trace=msg.tctx.hex_id, hops=msg.tctx.hops)
        if msg.overloaded():
            obs.record("query-overloaded-response", node=self.local_id,
                       responder=msg.from_node.id)
            resp.handle_overloaded(msg.from_node.id, self._labels)
        elif msg.ack():
            resp.handle_ack(msg.from_node.id, self._labels)
        else:
            resp.handle_response(msg.from_node.id, msg.payload, self._labels)

    # ------------------------------------------------------------------
    # conflict resolution (reference base.rs:1658-1780)
    # ------------------------------------------------------------------

    async def _resolve_node_conflict(self, existing: NodeState, other) -> None:
        """Majority vote via an internal query about OUR OWN id: every node
        answers with the address it has for the conflicted id; if the
        majority disagrees with our address, we are the usurper and shut
        down (reference base.rs:1685-1780)."""
        try:
            local = self.memberlist.local_node()
            payload = local.id.encode("utf-8")
            resp = await self.query(INTERNAL_CONFLICT, payload, QueryParam())
            responses = 0
            matching = 0
            async for r in resp.responses():
                try:
                    inner = decode_message(r.payload)
                except codec.DecodeError:
                    continue
                if not isinstance(inner, ConflictResponseMessage):
                    continue
                if inner.member.node.id != local.id:
                    continue
                responses += 1
                if inner.member.node.addr == local.addr:
                    matching += 1
            majority = responses // 2 + 1
            if responses > 0 and matching < majority:
                log.error(
                    "minority in node-id conflict (%d/%d agree with us); shutting down",
                    matching, responses)
                await self.shutdown()
        finally:
            self._conflict_resolving = False

    # ------------------------------------------------------------------
    # background tasks (reference base.rs:483-740)
    # ------------------------------------------------------------------

    async def _reaper(self) -> None:
        zombie_since: Dict[str, float] = {}
        leaving_since: Dict[str, list] = {}   # id -> [first_seen, grace_start]
        while not self._shutdown_event.is_set():
            await asyncio.sleep(self.opts.reap_interval)
            try:
                now = time.monotonic()
                self._reap(self._failed, now, self.opts.reconnect_timeout,
                           use_reconnect_override=True)
                self._reap(self._left, now, self.opts.tombstone_timeout)
                reap_intents(self._recent_intents, now, self.opts.recent_intent_timeout)
                self._sweep_zombies(zombie_since, now)
                self._sweep_dangling_leaving(leaving_since, now)
            except Exception:  # noqa: BLE001
                log.exception("reaper tick failed")

    def _zombie_grace(self) -> float:
        """How long a serf-ALIVE member may lack memberlist backing before
        demotion.  Generous: a slow SWIM refutation after a rejoin can
        legitimately leave the gap open for several anti-entropy cycles; a
        true zombie stays unbacked forever, so patience costs nothing."""
        return max(2 * self.opts.reap_interval,
                   10 * self.opts.memberlist.push_pull_interval)

    def _sweep_zombies(self, zombie_since: Dict[str, float],
                       now: float) -> None:
        """Demote serf-ALIVE/LEAVING members with no live memberlist backing.

        The intent-path LEFT revival (see _handle_node_join_intent) can
        leave a member serf-ALIVE when the rejoiner died before its SWIM
        aliveness reached us: the memberlist never probes it, so no
        notify_leave will ever fire and the entry would otherwise dodge the
        reaper forever.  LEAVING is covered too — an unbacked revived
        member that then absorbs a newer leave intent has no notify_leave
        to complete its LEAVING→LEFT transition either.  A member
        continuously unbacked past the grace window goes to FAILED,
        restoring the normal reap/reconnect path."""
        grace = self._zombie_grace()
        current: set = set()
        for node_id, ms in self._members.items():
            if node_id == self.local_id:
                continue
            if ms.member.status not in (MemberStatus.ALIVE,
                                        MemberStatus.LEAVING):
                continue
            ns = self.memberlist.node_state(node_id)
            if ns is not None and ns.state in (SwimState.ALIVE,
                                               SwimState.SUSPECT):
                continue
            current.add(node_id)
            first = zombie_since.setdefault(node_id, now)
            if now - first >= grace:
                log.warning("demoting zombie member %s (serf %s, no "
                            "memberlist backing for %.1fs)", node_id,
                            ms.member.status.name, now - first)
                ms.member = ms.member.with_status(MemberStatus.FAILED)
                ms.leave_time = time.monotonic()
                self._failed.append(ms)
                obs.record("member-state", node=self.local_id,
                           member=node_id, status="FAILED",
                           via="zombie_sweep")
                self._emit(MemberEvent(MemberEventType.FAILED, (ms.member,)))
                metrics.incr("serf.member.failed", 1, self._labels)
        # forget healed or departed entries so the timer restarts fresh
        for node_id in list(zombie_since):
            if node_id not in current:
                zombie_since.pop(node_id, None)

    def _pending_leave_ltimes(self) -> Dict[str, LamportTime]:
        """node id -> highest leave-intent ltime still sitting in the
        local intent queue.

        Two-level cache so the reaper tick stops re-decoding every
        queued intent broadcast: the queue's ``mutations`` counter
        short-circuits the whole scan while membership is unchanged, and
        each broadcast memoizes its own decode (``Broadcast.decoded`` —
        the bytes are immutable) so even a membership change only
        decodes the broadcasts it added."""
        q = self.intent_broadcasts
        if q.mutations == self._leave_index_version:
            return self._leave_index
        pending: Dict[str, LamportTime] = {}
        for b in q._items:
            dec = b.decoded
            if dec is None:
                try:
                    msg = decode_message(b.msg)
                except codec.DecodeError:
                    msg = None
                dec = b.decoded = ((msg.id, msg.ltime)
                                   if isinstance(msg, LeaveMessage) else ())
            if dec:
                node_id, lt = dec
                pending[node_id] = max(pending.get(node_id, 0), lt)
        self._leave_index = pending
        self._leave_index_version = q.mutations
        return pending

    def _sweep_dangling_leaving(self, leaving_since: Dict[str, list],
                                now: float) -> None:
        """Restore LEAVING members the SWIM layer still probes ALIVE long
        past the time a genuine leave needs to complete.

        Root cause this repairs (found by soak seed 2 under load): an
        equal-Lamport-time join/leave race.  A rejoiner's fresh clock can
        collide with its own old leave's ltime — the push/pull merge
        witnesses ``pp.ltime - 1`` (reference-faithful, Go serf does the
        same), so ``clock.increment()`` for the rejoin broadcast can
        reproduce exactly the leave's ltime.  Both intent handlers ignore
        ``ltime <= status_time``, so at EQUAL ltimes whichever intent a
        node happened to apply first wins *at that node*, permanently:
        nodes that saw join-then-leave hold ALIVE(t), nodes that saw
        leave-then-join hold LEAVING(t), and no later message can flip
        either (the reference has the same non-confluence and leans on
        snapshot clock continuity to avoid the collision).

        A genuinely leaving node completes ``memberlist.leave`` within
        ``broadcast_timeout + leave_propagate_delay``, after which
        notify_leave moves LEAVING→LEFT.  A member still SWIM-probed
        ALIVE well past that window is the race, not a leave — the
        failure detector's judgment wins (the same principle as the
        zombie sweep, inverted).  Lamport state is left untouched, so a
        genuinely newer leave intent still applies normally.
        """
        grace = 2 * (self.opts.broadcast_timeout
                     + self.opts.leave_propagate_delay)
        pending_leaves = self._pending_leave_ltimes()
        current: set = set()
        for node_id, ms in self._members.items():
            if node_id == self.local_id:
                continue
            if ms.member.status != MemberStatus.LEAVING:
                continue
            ns = self.memberlist.node_state(node_id)
            if ns is None or ns.state != SwimState.ALIVE:
                continue
            current.add(node_id)
            entry = leaving_since.get(node_id)
            if entry is None:
                entry = leaving_since[node_id] = [now, now]
            first_seen, grace_start = entry
            if (pending_leaves.get(node_id, -1) >= ms.status_time
                    and now - first_seen < 5 * grace):
                # the CURRENT leave story (ltime >= status_time — a stale
                # superseded leave does not count) has not even finished
                # disseminating locally (congested queue / large cluster):
                # the grace window has not meaningfully started.  Hold the
                # repair (grace restarts when dissemination completes) so
                # a slow genuine leaver is not resurrected mid-leave — but
                # only up to 5x grace total: a transmit-starved broadcast
                # in a churning queue must not defer the repair forever
                # (the sweep's whole point is ending a permanent wedge;
                # the failure detector's judgment wins eventually).
                entry[1] = now
                continue
            if now - grace_start >= grace:
                log.warning("restoring dangling LEAVING member %s to ALIVE "
                            "(memberlist-alive %.1fs past the leave window)",
                            node_id, now - grace_start)
                ms.member = ms.member.with_status(MemberStatus.ALIVE)
                metrics.incr("serf.member.unleave", 1, self._labels)
                obs.record("member-state", node=self.local_id,
                           member=node_id, status="ALIVE",
                           via="dangling_leaving_sweep")
                current.discard(node_id)   # timer restarts if it re-enters
        for node_id in list(leaving_since):
            if node_id not in current:
                leaving_since.pop(node_id, None)

    def _reap(self, lst: List[MemberState], now: float, timeout: float,
              use_reconnect_override: bool = False) -> None:
        for ms in list(lst):
            t = timeout
            if use_reconnect_override and self.user_delegate is not None:
                t = self.user_delegate.reconnect_timeout(ms.member, timeout)
            if now - ms.leave_time > t:
                log.info("reaping member %s", ms.id)
                self._erase_member(ms)
                self._emit(MemberEvent(MemberEventType.REAP, (ms.member,)))

    async def _reconnector(self) -> None:
        """(reference base.rs:612-681)"""
        while not self._shutdown_event.is_set():
            await asyncio.sleep(self.opts.reconnect_interval)
            try:
                if not self._failed:
                    continue
                n = max(1, len(self._members))
                prob = len(self._failed) / n
                if self.rng.random() > prob:
                    continue
                ms = self.rng.choice(self._failed)
                addr = ms.member.node.addr
                log.debug("attempting reconnect to %s", ms.id)
                try:
                    await self.memberlist.join(addr)
                except (ConnectionError, TimeoutError, OSError):
                    pass
            except Exception:  # noqa: BLE001
                log.exception("reconnector tick failed")

    async def _queue_checker(self, name: str, q: TransmitLimitedQueue) -> None:
        """(reference base.rs:683-740)"""
        while not self._shutdown_event.is_set():
            await asyncio.sleep(self.opts.queue_check_interval)
            depth = len(q)
            metrics.gauge(f"serf.queue.{name}", depth, self._labels)
            if depth > self.opts.queue_depth_warning:
                log.warning("queue %s depth: %d", name, depth)
            max_depth = self.opts.max_queue_depth
            if self.opts.min_queue_depth > 0:
                max_depth = max(self.opts.min_queue_depth, 2 * len(self._members))
            if depth > max_depth:
                log.warning("queue %s depth (%d) exceeds limit (%d); pruning",
                            name, depth, max_depth)
                q.prune(max_depth)

    async def _handle_rejoin(self, nodes: List[Node]) -> None:
        """(reference base.rs:1782-1808): shuffle snapshot nodes and rejoin
        the first that answers."""
        nodes = list(nodes)
        self.rng.shuffle(nodes)
        for node in nodes:
            if node.id == self.local_id:
                continue
            try:
                await self.memberlist.join(node.addr)
                log.info("rejoined cluster via %s", node.id)
                await self._broadcast_join(self.clock.increment())
                return
            except (ConnectionError, TimeoutError, OSError):
                continue
        log.warning("failed to rejoin any previously known node")
