"""Real-socket transport: UDP datagrams + length-framed TCP/TLS streams.

Capability parity with the reference's ``NetTransport`` (TCP/UDP and
TLS-over-TCP wiring, serf/Cargo.toml:24-56): the packet plane is UDP, the
stream plane (push/pull anti-entropy, large sends) is TCP with 4-byte
big-endian length frames — optionally TLS-wrapped (``TlsNetTransport``).
Packet-plane confidentiality is the keyring's AES-GCM layer (as in the
reference, where TLS covers the stream transport and the keyring encrypts
gossip packets).  Joins resolve DNS names through the transport's
``resolve`` seam.  Loopback (`transport.py`) remains the default for
in-process clusters; this backend is the cross-process conformance path.
"""

from __future__ import annotations

import asyncio
import ipaddress
import socket
import ssl as ssl_mod
import struct
from typing import Optional, Tuple

from serf_tpu.host.transport import Stream, Transport

MAX_FRAME = 32 * 1024 * 1024  # sanity bound on a single stream frame


async def _resolve_address(addr, bound_addr):
    """Shared resolver (the reference's ``Transport::Resolver`` seam):
    ``"host:port"`` strings and hostname tuples resolve through the event
    loop; numeric literals pass through; resolution is constrained to the
    bound socket's address family."""
    if isinstance(addr, str) and ":" in addr:
        try:
            # a bare IPv6 literal is an address, not host:port
            ipaddress.ip_address(addr)
        except ValueError:
            host, _, port = addr.rpartition(":")
            try:
                addr = (host.strip("[]"), int(port))
            except ValueError as e:
                raise ConnectionError(
                    f"malformed host:port target {addr!r}") from e
    if not (isinstance(addr, tuple) and len(addr) == 2):
        return addr
    host, port = addr
    try:
        # numeric literals skip the resolver entirely
        ipaddress.ip_address(host)
        return (host, port)
    except ValueError:
        pass
    # constrain to the bound socket's family: a dual-stack hostname must
    # not resolve to an address our AF_INET/AF_INET6 socket cannot reach
    family = 0
    if bound_addr is not None:
        try:
            bound_ip = ipaddress.ip_address(bound_addr[0])
            family = (socket.AF_INET6 if bound_ip.version == 6
                      else socket.AF_INET)
        except ValueError:
            pass
    loop = asyncio.get_running_loop()
    try:
        infos = await loop.getaddrinfo(host, port, family=family,
                                       type=socket.SOCK_DGRAM)
    except socket.gaierror as e:
        raise ConnectionError(f"cannot resolve {host!r}: {e}") from e
    if not infos:
        raise ConnectionError(f"cannot resolve {host!r}")
    return infos[0][4][:2]


class TcpStream(Stream):
    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self._r = reader
        self._w = writer

    async def send_frame(self, buf: bytes) -> None:
        self._w.write(struct.pack(">I", len(buf)) + buf)
        await self._w.drain()

    async def recv_frame(self, timeout: Optional[float] = None) -> bytes:
        async def _read() -> bytes:
            hdr = await self._r.readexactly(4)
            (ln,) = struct.unpack(">I", hdr)
            if ln > MAX_FRAME:
                raise ConnectionError(f"frame of {ln} bytes exceeds limit")
            return await self._r.readexactly(ln)

        try:
            if timeout is None:
                return await _read()
            return await asyncio.wait_for(_read(), timeout)
        except asyncio.TimeoutError:
            raise TimeoutError("stream recv timeout") from None
        except asyncio.IncompleteReadError as e:
            raise ConnectionError("stream closed by peer") from e

    async def close(self) -> None:
        try:
            self._w.close()
            await self._w.wait_closed()
        except (ConnectionError, OSError):
            pass


class _UdpProtocol(asyncio.DatagramProtocol):
    def __init__(self, queue: asyncio.Queue):
        self._q = queue

    def datagram_received(self, data: bytes, addr) -> None:
        self._q.put_nowait((addr, data))


class NetTransport(Transport):
    """Bind with ``await NetTransport.bind(("127.0.0.1", 7946))`` — one port
    serves both UDP packets and TCP streams."""

    def __init__(self):
        self._addr: Optional[Tuple[str, int]] = None
        self._packets: asyncio.Queue = asyncio.Queue()
        self._accepts: asyncio.Queue = asyncio.Queue()
        self._udp_transport = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._shut = False

    @classmethod
    async def bind(cls, addr: Tuple[str, int], **kw) -> "NetTransport":
        t = cls(**kw)
        loop = asyncio.get_running_loop()
        t._udp_transport, _ = await loop.create_datagram_endpoint(
            lambda: _UdpProtocol(t._packets), local_addr=addr)
        sock = t._udp_transport.get_extra_info("socket")
        bound = sock.getsockname()[:2]

        async def on_conn(reader, writer):
            peer = writer.get_extra_info("peername")
            t._accepts.put_nowait((peer, TcpStream(reader, writer)))

        t._server = await asyncio.start_server(
            on_conn, host=bound[0], port=bound[1], ssl=t._server_ssl())
        t._addr = (bound[0], bound[1])
        return t

    def _server_ssl(self) -> Optional[ssl_mod.SSLContext]:
        return None

    def _client_ssl(self) -> Optional[ssl_mod.SSLContext]:
        return None

    async def resolve(self, addr):
        """DNS seam: a ``"host:port"`` string (or a tuple with a hostname)
        resolves via the event loop's resolver; numeric addresses pass
        through untouched.  IPv6 literals with ports use brackets
        (``[::1]:7946``); an unbracketed all-colons string is treated as a
        bare IPv6 address, not host:port."""
        return await _resolve_address(addr, self._addr)

    @property
    def local_addr(self):
        return self._addr

    async def send_packet(self, addr, buf: bytes) -> None:
        if self._shut:
            raise ConnectionError("transport shut down")
        self._udp_transport.sendto(buf, tuple(addr))

    async def recv_packet(self):
        item = await self._packets.get()
        if item is None:
            raise ConnectionError("transport shut down")
        return item

    async def dial(self, addr, timeout: Optional[float] = None) -> Stream:
        ctx = self._client_ssl()
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(
                    addr[0], addr[1], ssl=ctx,
                    server_hostname=self._tls_server_hostname()
                    if ctx is not None else None),
                timeout)
        except asyncio.TimeoutError:
            raise TimeoutError(f"dial {addr!r} timed out") from None
        except OSError as e:
            raise ConnectionError(f"connection refused: {addr!r}: {e}") from e
        return TcpStream(reader, writer)

    def _tls_server_hostname(self) -> Optional[str]:
        return None

    async def accept(self):
        item = await self._accepts.get()
        if item is None:
            raise ConnectionError("transport shut down")
        return item

    async def shutdown(self) -> None:
        if self._shut:
            return
        self._shut = True
        if self._udp_transport is not None:
            self._udp_transport.close()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        self._packets.put_nowait(None)
        self._accepts.put_nowait(None)


class TlsNetTransport(NetTransport):
    """``NetTransport`` with a TLS-wrapped stream plane (the reference's
    ``TokioTlsSerf`` wiring, serf/Cargo.toml:24-56, README.md:114-131).

    The push/pull anti-entropy and large-send channel runs over TLS; the
    UDP packet plane stays cleartext framing whose confidentiality comes
    from the AES-GCM keyring (matching the reference's layering).  Pass
    ``ssl.SSLContext`` objects built by the operator — e.g. via
    ``make_tls_contexts`` for tests/self-signed deployments.
    """

    def __init__(self, server_ctx: ssl_mod.SSLContext,
                 client_ctx: ssl_mod.SSLContext,
                 server_hostname: Optional[str] = None):
        super().__init__()
        self._server_ctx = server_ctx
        self._client_ctx = client_ctx
        self._server_hostname = server_hostname

    @classmethod
    async def bind(cls, addr: Tuple[str, int], *, server_ctx, client_ctx,
                   server_hostname: Optional[str] = None) -> "TlsNetTransport":
        return await super().bind(addr, server_ctx=server_ctx,
                                  client_ctx=client_ctx,
                                  server_hostname=server_hostname)

    def _server_ssl(self) -> Optional[ssl_mod.SSLContext]:
        return self._server_ctx

    def _client_ssl(self) -> Optional[ssl_mod.SSLContext]:
        return self._client_ctx

    def _tls_server_hostname(self) -> Optional[str]:
        return self._server_hostname


def make_tls_contexts(cert_pem: str, key_pem: str, ca_pem: Optional[str] = None,
                      server_hostname: Optional[str] = None):
    """Build (server_ctx, client_ctx) from PEM files.  The client verifies
    against ``ca_pem`` (defaults to the server cert itself — the self-signed
    single-cert cluster deployment)."""
    server_ctx = ssl_mod.SSLContext(ssl_mod.PROTOCOL_TLS_SERVER)
    server_ctx.load_cert_chain(cert_pem, key_pem)
    client_ctx = ssl_mod.SSLContext(ssl_mod.PROTOCOL_TLS_CLIENT)
    client_ctx.load_verify_locations(ca_pem or cert_pem)
    if server_hostname is None:
        client_ctx.check_hostname = False
    return server_ctx, client_ctx
