"""Real-socket transport: UDP datagrams + length-framed TCP streams.

Capability parity with the reference's ``NetTransport`` (TCP/UDP wiring,
serf/Cargo.toml:24-56): the packet plane is UDP, the stream plane (push/pull
anti-entropy, large sends) is TCP with 4-byte big-endian length frames.
Loopback (`transport.py`) remains the default for in-process clusters; this
backend is the cross-process conformance path.
"""

from __future__ import annotations

import asyncio
import struct
from typing import Optional, Tuple

from serf_tpu.host.transport import Stream, Transport

MAX_FRAME = 32 * 1024 * 1024  # sanity bound on a single stream frame


class TcpStream(Stream):
    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self._r = reader
        self._w = writer

    async def send_frame(self, buf: bytes) -> None:
        self._w.write(struct.pack(">I", len(buf)) + buf)
        await self._w.drain()

    async def recv_frame(self, timeout: Optional[float] = None) -> bytes:
        async def _read() -> bytes:
            hdr = await self._r.readexactly(4)
            (ln,) = struct.unpack(">I", hdr)
            if ln > MAX_FRAME:
                raise ConnectionError(f"frame of {ln} bytes exceeds limit")
            return await self._r.readexactly(ln)

        try:
            if timeout is None:
                return await _read()
            return await asyncio.wait_for(_read(), timeout)
        except asyncio.TimeoutError:
            raise TimeoutError("stream recv timeout") from None
        except asyncio.IncompleteReadError as e:
            raise ConnectionError("stream closed by peer") from e

    async def close(self) -> None:
        try:
            self._w.close()
            await self._w.wait_closed()
        except (ConnectionError, OSError):
            pass


class _UdpProtocol(asyncio.DatagramProtocol):
    def __init__(self, queue: asyncio.Queue):
        self._q = queue

    def datagram_received(self, data: bytes, addr) -> None:
        self._q.put_nowait((addr, data))


class NetTransport(Transport):
    """Bind with ``await NetTransport.bind(("127.0.0.1", 7946))`` — one port
    serves both UDP packets and TCP streams."""

    def __init__(self):
        self._addr: Optional[Tuple[str, int]] = None
        self._packets: asyncio.Queue = asyncio.Queue()
        self._accepts: asyncio.Queue = asyncio.Queue()
        self._udp_transport = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._shut = False

    @classmethod
    async def bind(cls, addr: Tuple[str, int]) -> "NetTransport":
        t = cls()
        loop = asyncio.get_running_loop()
        t._udp_transport, _ = await loop.create_datagram_endpoint(
            lambda: _UdpProtocol(t._packets), local_addr=addr)
        sock = t._udp_transport.get_extra_info("socket")
        bound = sock.getsockname()[:2]

        async def on_conn(reader, writer):
            peer = writer.get_extra_info("peername")
            t._accepts.put_nowait((peer, TcpStream(reader, writer)))

        t._server = await asyncio.start_server(on_conn, host=bound[0], port=bound[1])
        t._addr = (bound[0], bound[1])
        return t

    @property
    def local_addr(self):
        return self._addr

    async def send_packet(self, addr, buf: bytes) -> None:
        if self._shut:
            raise ConnectionError("transport shut down")
        self._udp_transport.sendto(buf, tuple(addr))

    async def recv_packet(self):
        item = await self._packets.get()
        if item is None:
            raise ConnectionError("transport shut down")
        return item

    async def dial(self, addr, timeout: Optional[float] = None) -> Stream:
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(addr[0], addr[1]), timeout)
        except asyncio.TimeoutError:
            raise TimeoutError(f"dial {addr!r} timed out") from None
        except OSError as e:
            raise ConnectionError(f"connection refused: {addr!r}: {e}") from e
        return TcpStream(reader, writer)

    async def accept(self):
        item = await self._accepts.get()
        if item is None:
            raise ConnectionError("transport shut down")
        return item

    async def shutdown(self) -> None:
        if self._shut:
            return
        self._shut = True
        if self._udp_transport is not None:
            self._udp_transport.close()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        self._packets.put_nowait(None)
        self._accepts.put_nowait(None)
