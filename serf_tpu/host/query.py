"""Query engine: scatter a question, gather acks and responses.

Reference: serf-core/src/serf/query.rs (QueryParam, QueryResponse with dedup
and deadline, default log-N timeout, modified Fisher-Yates member sampling,
relay redundancy) — SURVEY.md §2.1.
"""

from __future__ import annotations

import asyncio
import math
import random
import time
from dataclasses import dataclass
from typing import List, Sequence, Set, Tuple

from serf_tpu.types.filters import Filter
from serf_tpu.types.member import Member, MemberStatus
from serf_tpu.utils import metrics


@dataclass
class QueryParam:
    """reference query.rs:37-93."""

    filters: Tuple[Filter, ...] = ()
    request_ack: bool = False
    relay_factor: int = 0
    timeout: float = 0.0  # 0 = use default_query_timeout


def default_query_timeout(n: int, gossip_interval: float, query_timeout_mult: int) -> float:
    """gossip_interval * mult * ceil(log10(N+1)) (reference query.rs:421-427)."""
    return gossip_interval * query_timeout_mult * max(1.0, math.ceil(math.log10(n + 1)))


@dataclass(frozen=True)
class NodeResponse:
    from_id: str
    payload: bytes


class QueryResponse:
    """Originator-side handle: streams of acks and responses until the
    deadline (reference query.rs:95-370)."""

    def __init__(self, ltime: int, id: int, timeout: float, with_acks: bool,
                 num_nodes: int):
        self.ltime = ltime
        self.id = id
        self.started = time.monotonic()
        self.deadline = self.started + timeout
        self.with_acks = with_acks
        self.num_nodes = num_nodes
        self._acks: asyncio.Queue = asyncio.Queue()
        self._responses: asyncio.Queue = asyncio.Queue()
        self._ack_seen: Set[str] = set()
        self._resp_seen: Set[str] = set()
        #: responders that explicitly fast-failed OVERLOADED instead of
        #: answering (admission control, ISSUE 5) — the originator can
        #: tell shed load from silence
        self._overloaded: Set[str] = set()
        self._closed = False

    def finished(self) -> bool:
        return self._closed or time.monotonic() > self.deadline

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._acks.put_nowait(None)
            self._responses.put_nowait(None)

    # feeding (called by the serf engine on inbound QueryResponseMessage)

    def handle_ack(self, from_id: str, labels=None) -> None:
        if self.finished():
            return
        if from_id in self._ack_seen:
            metrics.incr("serf.query.duplicate_acks", 1, labels)
            return
        self._ack_seen.add(from_id)
        metrics.incr("serf.query.acks", 1, labels)
        self._acks.put_nowait(from_id)

    def handle_overloaded(self, from_id: str, labels=None) -> None:
        """A responder shed this query under overload: record the explicit
        fast-fail (no payload will come from it)."""
        if self.finished() or from_id in self._overloaded:
            return
        self._overloaded.add(from_id)
        metrics.incr("serf.overload.remote_overloaded", 1, labels)

    @property
    def overloaded_responders(self) -> Set[str]:
        return set(self._overloaded)

    def handle_response(self, from_id: str, payload: bytes, labels=None) -> None:
        if self.finished():
            return
        if from_id in self._resp_seen:
            metrics.incr("serf.query.duplicate_responses", 1, labels)
            return
        self._resp_seen.add(from_id)
        metrics.incr("serf.query.responses", 1, labels)
        # round-trip latency: query broadcast -> this node's answer
        metrics.observe("serf.query.rtt-ms",
                        (time.monotonic() - self.started) * 1e3, labels)
        self._responses.put_nowait(NodeResponse(from_id, payload))

    # consuming

    async def acks(self):
        """Async iterator of acking node ids until deadline/close."""
        if not self.with_acks:
            return
        while True:
            remaining = self.deadline - time.monotonic()
            if remaining <= 0 and self._acks.empty():
                return
            try:
                item = await asyncio.wait_for(self._acks.get(), max(remaining, 0.001))
            except asyncio.TimeoutError:
                return
            if item is None:
                return
            yield item

    async def responses(self):
        """Async iterator of NodeResponse until deadline/close."""
        while True:
            remaining = self.deadline - time.monotonic()
            if remaining <= 0 and self._responses.empty():
                return
            try:
                item = await asyncio.wait_for(self._responses.get(), max(remaining, 0.001))
            except asyncio.TimeoutError:
                return
            if item is None:
                return
            yield item

    async def collect(self) -> List[NodeResponse]:
        return [r async for r in self.responses()]


def random_members(k: int, members: Sequence[Member], exclude_ids: Set[str],
                   rng: random.Random) -> List[Member]:
    """Sample up to k alive members excluding ``exclude_ids`` — the modified
    Fisher-Yates partial shuffle of the reference (query.rs:388-409)."""
    pool = [m for m in members
            if m.status == MemberStatus.ALIVE and m.node.id not in exclude_ids]
    if k >= len(pool):
        rng.shuffle(pool)
        return pool
    for i in range(k):
        j = rng.randrange(i, len(pool))
        pool[i], pool[j] = pool[j], pool[i]
    return pool[:k]


def should_process_query(filters: Sequence[Filter], node_id: str, tags) -> bool:
    """All filters must pass (reference query.rs:439-521)."""
    return all(f.matches(node_id, tags) for f in filters)
