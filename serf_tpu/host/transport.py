"""Transports: how gossip packets and push/pull streams move.

The reference's transport seam is the ``Transport`` trait with packet
(unreliable datagram) and stream (reliable, framed) planes
(SURVEY.md §2.9; reference serf/Cargo.toml:24-56 wires TCP/UDP, TLS, QUIC).
serf-tpu ships:

- ``LoopbackTransport`` — in-memory network for in-process multi-node
  clusters and tests, with first-class fault injection (per-edge drop
  functions, partitions, latency), standing in for the reference's
  CI loopback-subnet strategy (ci/setup_subnet_ubuntu.sh).
- ``NetTransport`` (``serf_tpu.host.net``) — real UDP datagrams + TCP
  streams for cross-process conformance.

Fault injection is part of the transport contract because the device plane
treats drop masks as input tensors; the host plane mirrors that.
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass, field
from typing import Callable, Dict, FrozenSet, List, Optional, Tuple

from serf_tpu.utils import metrics

PACKET_BUDGET = 1400  # UDP-safe payload budget per gossip packet (bytes)


# ---------------------------------------------------------------------------
# Chaos rules (the unified fault surface — built by serf_tpu.faults)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class EdgeRates:
    """Per-directed-edge fault rates, overriding/adding to the rule's
    base rates on that edge."""

    drop: float = 0.0
    delay: float = 0.0
    duplicate: float = 0.0
    reorder: float = 0.0
    corrupt: float = 0.0


@dataclass
class ChaosRule:
    """One compiled fault state for the loopback fabric.

    THE fault-injection surface of the host plane: the legacy
    ``partition``/``set_drop_rate`` knobs delegate onto the network's
    internal legacy rule, and ``serf_tpu.faults.host`` compiles
    ``FaultPlan`` phases into rules installed via
    :meth:`LoopbackNetwork.apply_faults`.  All rates are probabilities
    per packet; delays are seconds.

    ``groups``: only nodes sharing a group communicate (None = no
    partition).  ``paused``: nodes delivering/receiving nothing (process
    alive, network gone).  ``edges``: per-directed-edge overrides ADDED
    to the base rates.  ``drop >= 1.0`` on an edge also refuses stream
    dials (a blackholed edge carries nothing).
    """

    groups: Optional[List[set]] = None
    paused: FrozenSet = frozenset()
    drop: float = 0.0
    delay: float = 0.0
    jitter: float = 0.0
    duplicate: float = 0.0
    reorder: float = 0.0
    reorder_window: float = 0.01
    corrupt: float = 0.0
    edges: Dict[Tuple[object, object], EdgeRates] = field(default_factory=dict)

    def group_blocked(self, src, dst) -> bool:
        if src in self.paused or dst in self.paused:
            return True
        if self.groups is None:
            return False
        for g in self.groups:
            if src in g and dst in g:
                return False
        return True

    def edge_rates(self, src, dst) -> EdgeRates:
        e = self.edges.get((src, dst))
        if e is None:
            return EdgeRates(self.drop, self.delay, self.duplicate,
                             self.reorder, self.corrupt)
        return EdgeRates(min(1.0, self.drop + e.drop),
                         self.delay + e.delay,
                         min(1.0, self.duplicate + e.duplicate),
                         min(1.0, self.reorder + e.reorder),
                         min(1.0, self.corrupt + e.corrupt))

    def blackholed(self, src, dst) -> bool:
        if not self.edges and self.drop < 1.0:
            return False
        return self.edge_rates(src, dst).drop >= 1.0

    def any_effects(self) -> bool:
        return bool(self.edges) or any(
            r > 0 for r in (self.drop, self.delay, self.duplicate,
                            self.reorder, self.corrupt, self.jitter))


def apply_edge_faults(rule: ChaosRule, rng: random.Random, src, dst,
                      buf: bytes) -> Optional[bytes]:
    """THE per-packet drop/corrupt decision for one directed edge —
    shared by every real-transport chaos seam (``serf_tpu.faults.host.
    attach_transport_chaos`` wraps both ``send_packet`` and dstream's
    ``_sendto`` with it) so the FaultPlan's 'same scenario on every
    transport' promise cannot drift between copies.  Returns None when
    the packet is dropped/blocked, else the (possibly bit-flipped)
    payload.  The loopback fabric's own ``_plan_delivery`` additionally
    models duplicate/reorder/delay, which have no sender-side analog."""
    if rule.group_blocked(src, dst):
        return None
    er = rule.edge_rates(src, dst)
    if er.drop > 0 and rng.random() < er.drop:
        metrics.incr("serf.faults.dropped", 1)
        return None
    if er.corrupt > 0 and rng.random() < er.corrupt:
        b = bytearray(buf)
        if b:
            i = rng.randrange(len(b))
            b[i] ^= 1 << rng.randrange(8)
            metrics.incr("serf.faults.corrupted", 1)
            return bytes(b)
    return buf


class Stream:
    """Reliable bidirectional framed byte stream."""

    async def send_frame(self, buf: bytes) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    async def recv_frame(self, timeout: Optional[float] = None) -> bytes:  # pragma: no cover
        raise NotImplementedError

    async def close(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError


class Transport:
    """Packet + stream planes bound to one local address."""

    @property
    def local_addr(self):  # pragma: no cover - abstract
        raise NotImplementedError

    @property
    def max_packet_size(self) -> int:
        return PACKET_BUDGET

    async def send_packet(self, addr, buf: bytes) -> None:  # pragma: no cover
        raise NotImplementedError

    async def recv_packet(self) -> Tuple[object, bytes]:  # pragma: no cover
        """Returns (source_addr, payload)."""
        raise NotImplementedError

    async def dial(self, addr, timeout: Optional[float] = None) -> Stream:  # pragma: no cover
        raise NotImplementedError

    async def accept(self) -> Stream:  # pragma: no cover - abstract
        raise NotImplementedError

    async def resolve(self, addr):
        """Resolve a join target to a transport address — the reference's
        ``Transport::Resolver`` seam (serf-core/src/serf.rs:133-137).
        Default: identity (pre-resolved addresses pass through)."""
        return addr

    async def shutdown(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError


# ---------------------------------------------------------------------------
# Loopback
# ---------------------------------------------------------------------------


class _LoopbackStream(Stream):
    def __init__(self, peer_q: asyncio.Queue, my_q: asyncio.Queue):
        self._peer_q = peer_q
        self._my_q = my_q
        self._closed = False

    async def send_frame(self, buf: bytes) -> None:
        if self._closed:
            raise ConnectionError("stream closed")
        await self._peer_q.put(buf)

    async def recv_frame(self, timeout: Optional[float] = None) -> bytes:
        try:
            if timeout is None:
                item = await self._my_q.get()
            else:
                item = await asyncio.wait_for(self._my_q.get(), timeout)
        except asyncio.TimeoutError:
            raise TimeoutError("stream recv timeout") from None
        if item is None:
            raise ConnectionError("stream closed by peer")
        return item

    async def close(self) -> None:
        if not self._closed:
            self._closed = True
            await self._peer_q.put(None)


@dataclass
class LoopbackNetwork:
    """Shared in-memory fabric.  Addresses are plain strings/ints.

    Fault injection goes through ONE surface — :class:`ChaosRule`
    (``apply_faults``; built from a declarative ``FaultPlan`` by
    ``serf_tpu.faults.host``).  The legacy knobs remain as sugar:
    ``partition``/``heal``/``set_drop_rate`` delegate onto an internal
    legacy rule composed with the executor-applied one, and
    ``drop_message_types`` still compiles to ``drop_fn`` (a manual
    ``drop_fn(src, dst, buf) -> bool`` / ``latency_fn(src, dst) ->
    float`` keep working and compose with both rules).
    """

    transports: Dict[object, "LoopbackTransport"] = field(default_factory=dict)
    drop_fn: Optional[Callable[[object, object, bytes], bool]] = None
    latency_fn: Optional[Callable[[object, object], float]] = None
    #: executor-installed rule (serf_tpu.faults.host.HostFaultExecutor)
    chaos: Optional[ChaosRule] = None
    rng: random.Random = field(default_factory=lambda: random.Random(0))
    #: knob-driven rule (partition/set_drop_rate delegate here)
    _legacy: ChaosRule = field(default_factory=ChaosRule)

    def bind(self, addr) -> "LoopbackTransport":
        if addr in self.transports:
            raise OSError(f"address {addr!r} already bound")
        t = LoopbackTransport(self, addr)
        self.transports[addr] = t
        return t

    def _release(self, addr) -> None:
        self.transports.pop(addr, None)

    # fault injection -------------------------------------------------------

    def apply_faults(self, rule: Optional[ChaosRule]) -> None:
        """Install (or clear, with None) the active chaos rule — the one
        API every fault source compiles to."""
        self.chaos = rule

    def partition(self, *groups: set) -> None:
        """Only nodes within the same group can communicate
        (delegates onto the unified chaos rule)."""
        self._legacy.groups = [set(g) for g in groups]

    def heal(self) -> None:
        self._legacy.groups = None

    def set_drop_rate(self, p: float, seed: int = 0) -> None:
        self._legacy.drop = max(0.0, p)
        if p > 0:
            self.rng = random.Random(seed)

    def drop_message_types(self, serf_types=(), swim_types=(),
                           keyring=None, opts=None) -> None:
        """Drop packets containing the given message types — the transport
        analog of the reference's test-only ``MessageDropper``
        (serf-core/src/serf/delegate.rs:42-45, SURVEY.md §4).

        Classification decodes the real wire format (``decode_swim``), so
        compound packets are dropped if ANY part matches, swim USER frames
        match both ``SwimMessageType.USER`` in ``swim_types`` and the inner
        serf envelope (including messages nested inside RELAY) in
        ``serf_types``.  For an encrypted cluster pass the cluster
        ``keyring``; for a cluster using compression/checksum wire options
        pass its ``MemberlistOptions`` as ``opts`` — without them such
        packets cannot be classified and are passed through untouched.
        """
        serf_set = {int(t) for t in serf_types}
        swim_set = {int(t) for t in swim_types}
        if not serf_set and not swim_set:
            self.drop_fn = None
            return

        from serf_tpu import codec
        from serf_tpu.host import messages as sm
        from serf_tpu.host.keyring import ENCRYPTION_VERSION, KeyringError

        def _serf_matches(payload: bytes) -> bool:
            while payload:
                if payload[0] in serf_set:
                    return True
                if payload[0] != 8:  # MessageType.RELAY: unwrap the nested msg
                    return False
                try:
                    inner = b""
                    for f, _w, v, _p in codec.iter_fields(payload[1:]):
                        if f == 2:
                            inner = codec.as_bytes(v)
                    payload = inner
                except codec.DecodeError:
                    return False
            return False

        def _drop(src, dst, buf: bytes) -> bool:
            if keyring is not None and buf and buf[0] == ENCRYPTION_VERSION:
                try:
                    buf = keyring.decrypt(buf)
                except KeyringError:
                    return False  # unclassifiable: pass through
            if opts is not None and (opts.checksum is not None
                                     or opts.compression is not None):
                # mirror the peer decode pipeline: strip checksum, marker,
                # decompress (classification only — no verification)
                if opts.checksum is not None:
                    if len(buf) < 5:
                        return False
                    buf = buf[4:]
                if not buf:
                    return False
                marker, buf = buf[0], buf[1:]
                if marker == 1:
                    import zlib
                    try:
                        buf = zlib.decompress(buf)
                    except zlib.error:
                        return False
            try:
                decoded = sm.decode_swim(buf)
            except codec.DecodeError:
                return False  # unclassifiable (e.g. encrypted, no keyring)
            parts = decoded if isinstance(decoded, list) else [decoded]
            for m in parts:
                if int(m.TYPE) in swim_set:
                    return True
                if isinstance(m, sm.UserMsg) and _serf_matches(m.payload):
                    return True
            return False

        self.drop_fn = _drop

    def _rules(self):
        if self.chaos is not None:
            yield self._legacy
            yield self.chaos
        else:
            yield self._legacy

    def _blocked(self, src, dst) -> bool:
        """Deterministically unreachable (partition / pause / blackholed
        edge) — blocks packets AND stream dials."""
        for rule in self._rules():
            if rule.group_blocked(src, dst) or rule.blackholed(src, dst):
                return True
        return False

    def _should_drop(self, src, dst, buf: bytes) -> bool:
        if self._blocked(src, dst):
            return True
        for rule in self._rules():
            if rule.drop == 0.0 and not rule.edges:
                continue
            p = rule.edge_rates(src, dst).drop
            if p > 0 and self.rng.random() < p:
                metrics.incr("serf.faults.dropped", 1)
                return True
        if self.drop_fn is not None and self.drop_fn(src, dst, buf):
            return True
        return False

    def _plan_delivery(self, src, dst, buf: bytes) -> List[Tuple[float, bytes]]:
        """Apply non-drop chaos effects: [(delay_s, payload), ...] —
        normally one entry; duplication adds a second, corruption flips
        a bit, reorder/delay/jitter stretch the delay."""
        delay = 0.0
        if self.latency_fn is not None:
            delay += self.latency_fn(src, dst)
        copies = 1
        for rule in self._rules():
            if not rule.any_effects():
                continue
            er = rule.edge_rates(src, dst)
            if er.delay > 0 or rule.jitter > 0:
                delay += er.delay + rule.jitter * self.rng.random()
                metrics.incr("serf.faults.delayed", 1)
            if er.reorder > 0 and self.rng.random() < er.reorder:
                # a reordered packet arrives later than its successors
                delay += self.rng.uniform(0.0, rule.reorder_window)
                metrics.incr("serf.faults.reordered", 1)
            if er.corrupt > 0 and self.rng.random() < er.corrupt:
                b = bytearray(buf)
                if b:
                    i = self.rng.randrange(len(b))
                    b[i] ^= 1 << self.rng.randrange(8)
                    buf = bytes(b)
                    metrics.incr("serf.faults.corrupted", 1)
            if er.duplicate > 0 and self.rng.random() < er.duplicate:
                copies += 1
                metrics.incr("serf.faults.duplicated", 1)
        out = [(delay, buf)]
        for _ in range(copies - 1):
            out.append((delay + self.rng.uniform(0.0, 0.002), buf))
        return out


class LoopbackTransport(Transport):
    def __init__(self, net: LoopbackNetwork, addr):
        self._net = net
        self._addr = addr
        self._packets: asyncio.Queue = asyncio.Queue()
        self._accepts: asyncio.Queue = asyncio.Queue()
        self._shut = False

    @property
    def local_addr(self):
        return self._addr

    async def send_packet(self, addr, buf: bytes) -> None:
        if self._shut:
            raise ConnectionError("transport shut down")
        net = self._net
        if net._should_drop(self._addr, addr, buf):
            return  # silently dropped, like UDP
        target = net.transports.get(addr)
        if target is None or target._shut:
            return  # unreachable, like UDP
        for delay, payload in net._plan_delivery(self._addr, addr, buf):
            if delay > 0:
                asyncio.get_running_loop().call_later(
                    delay, target._deliver_packet, (self._addr, payload))
            else:
                target._packets.put_nowait((self._addr, payload))

    def _deliver_packet(self, item) -> None:
        """Delayed-delivery sink: a transport shut down while the packet
        was in flight swallows it (UDP semantics) instead of waking a
        dead queue."""
        if not self._shut:
            self._packets.put_nowait(item)

    async def recv_packet(self) -> Tuple[object, bytes]:
        item = await self._packets.get()
        if item is None:
            raise ConnectionError("transport shut down")
        return item

    async def dial(self, addr, timeout: Optional[float] = None) -> Stream:
        if self._net._blocked(self._addr, addr):
            raise ConnectionError(f"no route to {addr!r} (partition)")
        target = self._net.transports.get(addr)
        if target is None or target._shut:
            raise ConnectionError(f"connection refused: {addr!r}")
        a2b: asyncio.Queue = asyncio.Queue()
        b2a: asyncio.Queue = asyncio.Queue()
        ours = _LoopbackStream(peer_q=a2b, my_q=b2a)
        theirs = _LoopbackStream(peer_q=b2a, my_q=a2b)
        target._accepts.put_nowait((self._addr, theirs))
        return ours

    async def accept(self) -> Tuple[object, Stream]:
        item = await self._accepts.get()
        if item is None:
            raise ConnectionError("transport shut down")
        return item

    async def shutdown(self) -> None:
        if not self._shut:
            self._shut = True
            self._net._release(self._addr)
            self._packets.put_nowait(None)
            self._accepts.put_nowait(None)
