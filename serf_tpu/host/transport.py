"""Transports: how gossip packets and push/pull streams move.

The reference's transport seam is the ``Transport`` trait with packet
(unreliable datagram) and stream (reliable, framed) planes
(SURVEY.md §2.9; reference serf/Cargo.toml:24-56 wires TCP/UDP, TLS, QUIC).
serf-tpu ships:

- ``LoopbackTransport`` — in-memory network for in-process multi-node
  clusters and tests, with first-class fault injection (per-edge drop
  functions, partitions, latency), standing in for the reference's
  CI loopback-subnet strategy (ci/setup_subnet_ubuntu.sh).
- ``NetTransport`` (``serf_tpu.host.net``) — real UDP datagrams + TCP
  streams for cross-process conformance.

Fault injection is part of the transport contract because the device plane
treats drop masks as input tensors; the host plane mirrors that.
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

PACKET_BUDGET = 1400  # UDP-safe payload budget per gossip packet (bytes)


class Stream:
    """Reliable bidirectional framed byte stream."""

    async def send_frame(self, buf: bytes) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    async def recv_frame(self, timeout: Optional[float] = None) -> bytes:  # pragma: no cover
        raise NotImplementedError

    async def close(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError


class Transport:
    """Packet + stream planes bound to one local address."""

    @property
    def local_addr(self):  # pragma: no cover - abstract
        raise NotImplementedError

    @property
    def max_packet_size(self) -> int:
        return PACKET_BUDGET

    async def send_packet(self, addr, buf: bytes) -> None:  # pragma: no cover
        raise NotImplementedError

    async def recv_packet(self) -> Tuple[object, bytes]:  # pragma: no cover
        """Returns (source_addr, payload)."""
        raise NotImplementedError

    async def dial(self, addr, timeout: Optional[float] = None) -> Stream:  # pragma: no cover
        raise NotImplementedError

    async def accept(self) -> Stream:  # pragma: no cover - abstract
        raise NotImplementedError

    async def resolve(self, addr):
        """Resolve a join target to a transport address — the reference's
        ``Transport::Resolver`` seam (serf-core/src/serf.rs:133-137).
        Default: identity (pre-resolved addresses pass through)."""
        return addr

    async def shutdown(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError


# ---------------------------------------------------------------------------
# Loopback
# ---------------------------------------------------------------------------


class _LoopbackStream(Stream):
    def __init__(self, peer_q: asyncio.Queue, my_q: asyncio.Queue):
        self._peer_q = peer_q
        self._my_q = my_q
        self._closed = False

    async def send_frame(self, buf: bytes) -> None:
        if self._closed:
            raise ConnectionError("stream closed")
        await self._peer_q.put(buf)

    async def recv_frame(self, timeout: Optional[float] = None) -> bytes:
        try:
            if timeout is None:
                item = await self._my_q.get()
            else:
                item = await asyncio.wait_for(self._my_q.get(), timeout)
        except asyncio.TimeoutError:
            raise TimeoutError("stream recv timeout") from None
        if item is None:
            raise ConnectionError("stream closed by peer")
        return item

    async def close(self) -> None:
        if not self._closed:
            self._closed = True
            await self._peer_q.put(None)


@dataclass
class LoopbackNetwork:
    """Shared in-memory fabric.  Addresses are plain strings/ints.

    ``drop_fn(src, dst, buf) -> bool`` returning True drops the packet;
    ``latency_fn(src, dst) -> float`` delays delivery.  Partitions are a
    convenience wrapper over ``drop_fn`` affecting packets AND streams.
    """

    transports: Dict[object, "LoopbackTransport"] = field(default_factory=dict)
    drop_fn: Optional[Callable[[object, object, bytes], bool]] = None
    latency_fn: Optional[Callable[[object, object], float]] = None
    _partitions: Optional[List[set]] = None
    rng: random.Random = field(default_factory=lambda: random.Random(0))

    def bind(self, addr) -> "LoopbackTransport":
        if addr in self.transports:
            raise OSError(f"address {addr!r} already bound")
        t = LoopbackTransport(self, addr)
        self.transports[addr] = t
        return t

    def _release(self, addr) -> None:
        self.transports.pop(addr, None)

    # fault injection -------------------------------------------------------

    def partition(self, *groups: set) -> None:
        """Only nodes within the same group can communicate."""
        self._partitions = [set(g) for g in groups]

    def heal(self) -> None:
        self._partitions = None

    def set_drop_rate(self, p: float, seed: int = 0) -> None:
        rng = random.Random(seed)
        self.drop_fn = (lambda s, d, b: rng.random() < p) if p > 0 else None

    def drop_message_types(self, serf_types=(), swim_types=(),
                           keyring=None, opts=None) -> None:
        """Drop packets containing the given message types — the transport
        analog of the reference's test-only ``MessageDropper``
        (serf-core/src/serf/delegate.rs:42-45, SURVEY.md §4).

        Classification decodes the real wire format (``decode_swim``), so
        compound packets are dropped if ANY part matches, swim USER frames
        match both ``SwimMessageType.USER`` in ``swim_types`` and the inner
        serf envelope (including messages nested inside RELAY) in
        ``serf_types``.  For an encrypted cluster pass the cluster
        ``keyring``; for a cluster using compression/checksum wire options
        pass its ``MemberlistOptions`` as ``opts`` — without them such
        packets cannot be classified and are passed through untouched.
        """
        serf_set = {int(t) for t in serf_types}
        swim_set = {int(t) for t in swim_types}
        if not serf_set and not swim_set:
            self.drop_fn = None
            return

        from serf_tpu import codec
        from serf_tpu.host import messages as sm
        from serf_tpu.host.keyring import ENCRYPTION_VERSION, KeyringError

        def _serf_matches(payload: bytes) -> bool:
            while payload:
                if payload[0] in serf_set:
                    return True
                if payload[0] != 8:  # MessageType.RELAY: unwrap the nested msg
                    return False
                try:
                    inner = b""
                    for f, _w, v, _p in codec.iter_fields(payload[1:]):
                        if f == 2:
                            inner = codec.as_bytes(v)
                    payload = inner
                except codec.DecodeError:
                    return False
            return False

        def _drop(src, dst, buf: bytes) -> bool:
            if keyring is not None and buf and buf[0] == ENCRYPTION_VERSION:
                try:
                    buf = keyring.decrypt(buf)
                except KeyringError:
                    return False  # unclassifiable: pass through
            if opts is not None and (opts.checksum is not None
                                     or opts.compression is not None):
                # mirror the peer decode pipeline: strip checksum, marker,
                # decompress (classification only — no verification)
                if opts.checksum is not None:
                    if len(buf) < 5:
                        return False
                    buf = buf[4:]
                if not buf:
                    return False
                marker, buf = buf[0], buf[1:]
                if marker == 1:
                    import zlib
                    try:
                        buf = zlib.decompress(buf)
                    except zlib.error:
                        return False
            try:
                decoded = sm.decode_swim(buf)
            except codec.DecodeError:
                return False  # unclassifiable (e.g. encrypted, no keyring)
            parts = decoded if isinstance(decoded, list) else [decoded]
            for m in parts:
                if int(m.TYPE) in swim_set:
                    return True
                if isinstance(m, sm.UserMsg) and _serf_matches(m.payload):
                    return True
            return False

        self.drop_fn = _drop

    def _blocked(self, src, dst) -> bool:
        if self._partitions is not None:
            for g in self._partitions:
                if src in g and dst in g:
                    return False
            return True
        return False

    def _should_drop(self, src, dst, buf: bytes) -> bool:
        if self._blocked(src, dst):
            return True
        if self.drop_fn is not None and self.drop_fn(src, dst, buf):
            return True
        return False


class LoopbackTransport(Transport):
    def __init__(self, net: LoopbackNetwork, addr):
        self._net = net
        self._addr = addr
        self._packets: asyncio.Queue = asyncio.Queue()
        self._accepts: asyncio.Queue = asyncio.Queue()
        self._shut = False

    @property
    def local_addr(self):
        return self._addr

    async def send_packet(self, addr, buf: bytes) -> None:
        if self._shut:
            raise ConnectionError("transport shut down")
        net = self._net
        if net._should_drop(self._addr, addr, buf):
            return  # silently dropped, like UDP
        target = net.transports.get(addr)
        if target is None or target._shut:
            return  # unreachable, like UDP
        if net.latency_fn is not None:
            delay = net.latency_fn(self._addr, addr)
            if delay > 0:
                asyncio.get_running_loop().call_later(
                    delay, target._packets.put_nowait, (self._addr, buf)
                )
                return
        target._packets.put_nowait((self._addr, buf))

    async def recv_packet(self) -> Tuple[object, bytes]:
        item = await self._packets.get()
        if item is None:
            raise ConnectionError("transport shut down")
        return item

    async def dial(self, addr, timeout: Optional[float] = None) -> Stream:
        if self._net._blocked(self._addr, addr):
            raise ConnectionError(f"no route to {addr!r} (partition)")
        target = self._net.transports.get(addr)
        if target is None or target._shut:
            raise ConnectionError(f"connection refused: {addr!r}")
        a2b: asyncio.Queue = asyncio.Queue()
        b2a: asyncio.Queue = asyncio.Queue()
        ours = _LoopbackStream(peer_q=a2b, my_q=b2a)
        theirs = _LoopbackStream(peer_q=b2a, my_q=a2b)
        target._accepts.put_nowait((self._addr, theirs))
        return ours

    async def accept(self) -> Tuple[object, Stream]:
        item = await self._accepts.get()
        if item is None:
            raise ConnectionError("transport shut down")
        return item

    async def shutdown(self) -> None:
        if not self._shut:
            self._shut = True
            self._net._release(self._addr)
            self._packets.put_nowait(None)
            self._accepts.put_nowait(None)
