"""Internal query service: handle ``_serf_*`` queries before they reach the
application.

Reference: serf-core/src/serf/internal_query.rs:32-486 — `_serf_ping`,
`_serf_conflict` (answer with our view of the conflicted id's address), and
the four keyring ops, with size-aware truncation of key-list responses.

Beyond the reference set, `_serf_stats` (PR 2) answers with this node's
compact health/stats self-report (``serf_tpu.obs.cluster``) — the
responder half of ``Serf.cluster_stats()``'s gossip-native aggregation —
and `_serf_blackbox` (PR 17) with the node's black-box bundle inventory
(``serf_tpu.obs.blackbox``), the responder half of
``Serf.cluster_blackbox()``.
"""

from __future__ import annotations


from serf_tpu.host.events import QueryEvent
from serf_tpu.host.keyring import KeyringError
from serf_tpu.types.messages import (
    ConflictResponseMessage,
    KeyRequestMessage,
    KeyResponseMessage,
    decode_message,
    encode_message,
)
from serf_tpu import codec

from serf_tpu.utils.logging import get_logger

log = get_logger("internal_query")

# minimum bytes to encode one key in a list response; used for truncation
# (reference MIN_ENCODED_KEY_LENGTH = 25, internal_query.rs)
MIN_ENCODED_KEY_LENGTH = 25


async def handle_internal_query(serf, ev: QueryEvent) -> None:
    try:
        if ev.name == "_serf_ping":
            pass  # intentionally no response (reference: ack-only)
        elif ev.name == "_serf_conflict":
            await _handle_conflict(serf, ev)
        elif ev.name == "_serf_install_key":
            await _handle_key_op(serf, ev, "install")
        elif ev.name == "_serf_use_key":
            await _handle_key_op(serf, ev, "use")
        elif ev.name == "_serf_remove_key":
            await _handle_key_op(serf, ev, "remove")
        elif ev.name == "_serf_list_keys":
            await _handle_list_keys(serf, ev)
        elif ev.name == "_serf_stats":
            await _handle_stats(serf, ev)
        elif ev.name == "_serf_blackbox":
            await _handle_blackbox(serf, ev)
        else:
            log.warning("unhandled internal query %r", ev.name)
    except Exception:  # noqa: BLE001
        log.exception("internal query %r failed", ev.name)


async def _handle_conflict(serf, ev: QueryEvent) -> None:
    """Respond with the member we have for the conflicted id
    (reference internal_query.rs handle_conflict)."""
    node_id = ev.payload.decode("utf-8", errors="replace")
    if node_id == serf.local_id:
        # never vote about ourselves — the conflicted nodes are the parties,
        # observers are the electorate (reference internal_query.rs:131-134)
        return
    ms = serf._members.get(node_id)
    if ms is None:
        return
    await ev.respond(encode_message(ConflictResponseMessage(ms.member)))


async def _handle_stats(serf, ev: QueryEvent) -> None:
    """Answer with this node's health/stats self-report (the scatter half
    lives in ``serf_tpu.obs.cluster.collect_cluster_stats``)."""
    from serf_tpu.obs.cluster import node_stats_payload
    try:
        await ev.respond(node_stats_payload(serf))
    except (TimeoutError, ValueError) as e:
        log.warning("could not respond to %r: %s", ev.name, e)


async def _handle_blackbox(serf, ev: QueryEvent) -> None:
    """Answer with this node's black-box bundle inventory (the scatter
    half lives in ``serf_tpu.obs.blackbox.collect_cluster_blackbox``).
    Nodes with no attached box still answer — an explicit empty
    inventory, so the collector can tell "no bundles" from "no reply"."""
    from serf_tpu.obs.blackbox import node_blackbox_payload
    try:
        await ev.respond(node_blackbox_payload(serf))
    except (TimeoutError, ValueError) as e:
        log.warning("could not respond to %r: %s", ev.name, e)


def _keyring_or_error(serf):
    ring = serf.memberlist.keyring()
    if ring is None:
        return None, "encryption is not enabled"
    return ring, None


async def _handle_key_op(serf, ev: QueryEvent, op: str) -> None:
    ring, err = _keyring_or_error(serf)
    if err is not None:
        await _respond_key(serf, ev, KeyResponseMessage(False, err))
        return
    try:
        req = decode_message(ev.payload)
    except codec.DecodeError as e:
        await _respond_key(serf, ev, KeyResponseMessage(False, f"bad request: {e}"))
        return
    if not isinstance(req, KeyRequestMessage):
        await _respond_key(serf, ev, KeyResponseMessage(False, "bad request type"))
        return
    try:
        if op == "install":
            ring.install(req.key)
        elif op == "use":
            ring.use_key(req.key)
        elif op == "remove":
            ring.remove(req.key)
        if serf.opts.keyring_file:
            ring.save(serf.opts.keyring_file)
        await _respond_key(serf, ev, KeyResponseMessage(True))
    except (KeyringError, OSError) as e:
        await _respond_key(serf, ev, KeyResponseMessage(False, str(e)))


async def _handle_list_keys(serf, ev: QueryEvent) -> None:
    ring, err = _keyring_or_error(serf)
    if err is not None:
        await _respond_key(serf, ev, KeyResponseMessage(False, err))
        return
    keys = ring.keys()
    primary = ring.primary_key()
    # size-aware truncation (reference key_list_response_with_correct_size)
    limit = serf.opts.query_response_size_limit
    max_keys = max(1, (limit - MIN_ENCODED_KEY_LENGTH) // MIN_ENCODED_KEY_LENGTH)
    msg = ""
    if len(keys) > max_keys:
        msg = f"truncated key list to {max_keys} of {len(keys)} keys"
        keys = keys[:max_keys]
        if primary not in keys:
            keys[0] = primary
    await _respond_key(
        serf, ev, KeyResponseMessage(True, msg, tuple(keys), primary))


async def _respond_key(serf, ev: QueryEvent, msg: KeyResponseMessage) -> None:
    try:
        await ev.respond(encode_message(msg))
    except (TimeoutError, ValueError) as e:
        log.warning("could not respond to %r: %s", ev.name, e)
