"""Agent control channel: length-framed JSON over a local socket.

The serf agent (``serf_tpu.host.agent``) exposes a control channel on
127.0.0.1 (TCP) or a unix socket: the proc-plane fault executor
(``serf_tpu.faults.proc``), ``tools/chaos.py --plane proc`` and the
bench harness drive a LIVE process through it — joins, user events,
queries, stats/health/lifecycle snapshots, chaos-rule installs onto the
``attach_transport_chaos`` real-transport seam, and black-box
dump-on-demand.

Wire format (mirrors the cluster stream plane, ``host/net.py``): every
message is a 4-byte big-endian length prefix followed by a UTF-8 JSON
object.  Requests carry ``{"op": <name>, "id": <seq>, ...args}``;
responses echo the ``id`` with ``{"ok": true, ...result}`` or
``{"ok": false, "error": <message>}``.  Binary payloads (user-event and
query bodies) ride base64 in ``*_b64`` fields — the channel stays
line-printable for debugging with ``nc``/``socat``.
"""

from __future__ import annotations

import asyncio
import base64
import json
import struct
from typing import Dict, Optional, Tuple

from serf_tpu.host.transport import ChaosRule, EdgeRates

#: control frames are small (stats snapshots dominate); anything bigger
#: is a protocol error, not a legitimate message
MAX_CTL_FRAME = 8 * 1024 * 1024


def encode_frame(obj: dict) -> bytes:
    body = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    if len(body) > MAX_CTL_FRAME:
        raise ValueError(f"control frame of {len(body)} bytes exceeds "
                         f"{MAX_CTL_FRAME}")
    return struct.pack(">I", len(body)) + body


def decode_frame(buf: bytes) -> dict:
    obj = json.loads(buf.decode("utf-8"))
    if not isinstance(obj, dict):
        raise ValueError("control frame is not a JSON object")
    return obj


async def read_frame(reader: asyncio.StreamReader,
                     timeout: Optional[float] = None) -> dict:
    async def _read() -> dict:
        hdr = await reader.readexactly(4)
        (ln,) = struct.unpack(">I", hdr)
        if ln > MAX_CTL_FRAME:
            raise ConnectionError(f"control frame of {ln} bytes exceeds "
                                  f"{MAX_CTL_FRAME}")
        return decode_frame(await reader.readexactly(ln))

    try:
        if timeout is None:
            return await _read()
        return await asyncio.wait_for(_read(), timeout)
    except asyncio.TimeoutError:
        raise TimeoutError("control channel recv timeout") from None
    except asyncio.IncompleteReadError as e:
        raise ConnectionError("control channel closed by peer") from e


def b64(data: bytes) -> str:
    return base64.b64encode(data).decode("ascii")


def unb64(text: Optional[str]) -> bytes:
    return base64.b64decode(text) if text else b""


# ---------------------------------------------------------------------------
# chaos-rule serde: ChaosRule <-> JSON (addresses are "host:port" strings)
# ---------------------------------------------------------------------------


def chaos_rule_to_dict(rule: Optional[ChaosRule]) -> Optional[dict]:
    """JSON-able form of a compiled chaos rule.  Edge keys flatten to
    ``"src|dst"`` (addresses never contain ``|``)."""
    if rule is None:
        return None
    return {
        "groups": (None if rule.groups is None
                   else [sorted(str(a) for a in g) for g in rule.groups]),
        "paused": sorted(str(a) for a in rule.paused),
        "drop": rule.drop,
        "delay": rule.delay,
        "jitter": rule.jitter,
        "duplicate": rule.duplicate,
        "reorder": rule.reorder,
        "reorder_window": rule.reorder_window,
        "corrupt": rule.corrupt,
        "edges": {f"{src}|{dst}": {
            "drop": e.drop, "delay": e.delay, "duplicate": e.duplicate,
            "reorder": e.reorder, "corrupt": e.corrupt,
        } for (src, dst), e in rule.edges.items()},
    }


def chaos_rule_from_dict(data: Optional[dict]) -> Optional[ChaosRule]:
    if data is None:
        return None
    edges: Dict[Tuple[object, object], EdgeRates] = {}
    for key, rates in (data.get("edges") or {}).items():
        src, _, dst = key.partition("|")
        edges[(src, dst)] = EdgeRates(**rates)
    groups = data.get("groups")
    return ChaosRule(
        groups=None if groups is None else [set(g) for g in groups],
        paused=frozenset(data.get("paused") or ()),
        drop=data.get("drop", 0.0),
        delay=data.get("delay", 0.0),
        jitter=data.get("jitter", 0.0),
        duplicate=data.get("duplicate", 0.0),
        reorder=data.get("reorder", 0.0),
        reorder_window=data.get("reorder_window", 0.01),
        corrupt=data.get("corrupt", 0.0),
        edges=edges,
    )


def addr_key(addr) -> str:
    """Normalize a transport destination to the plan's ``"host:port"``
    address space: tuples/lists flatten, strings pass through.  This is
    the ``addr_key`` the agent hands ``attach_transport_chaos`` so rules
    compiled by the executor match real send targets."""
    if isinstance(addr, (tuple, list)) and len(addr) == 2:
        return f"{addr[0]}:{addr[1]}"
    return str(addr)


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class ControlClient:
    """One TCP (or unix-socket) connection to an agent's control channel.
    Calls are serialized per client — the executor opens one client per
    agent, so cluster-wide fan-out still runs concurrently."""

    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter):
        self._r = reader
        self._w = writer
        self._seq = 0
        self._lock = asyncio.Lock()

    @classmethod
    async def connect(cls, addr, timeout: float = 5.0) -> "ControlClient":
        """``addr``: ``(host, port)`` / ``"host:port"`` for TCP, or a
        filesystem path (no colon) for a unix socket."""
        if isinstance(addr, str) and ":" in addr:
            host, _, port = addr.rpartition(":")
            addr = (host, int(port))
        try:
            if isinstance(addr, str):
                conn = asyncio.open_unix_connection(addr)
            else:
                conn = asyncio.open_connection(addr[0], addr[1])
            reader, writer = await asyncio.wait_for(conn, timeout)
        except asyncio.TimeoutError:
            raise TimeoutError(f"control dial {addr!r} timed out") from None
        except OSError as e:
            raise ConnectionError(f"control dial {addr!r}: {e}") from e
        return cls(reader, writer)

    async def call(self, op: str, timeout: float = 15.0, **kw) -> dict:
        async with self._lock:
            self._seq += 1
            req = {"op": op, "id": self._seq, **kw}
            self._w.write(encode_frame(req))
            await self._w.drain()
            resp = await read_frame(self._r, timeout=timeout)
        if resp.get("id") != req["id"]:
            raise ConnectionError(
                f"control response id {resp.get('id')} != {req['id']}")
        if not resp.get("ok"):
            raise RuntimeError(f"agent {op} failed: "
                               f"{resp.get('error', 'unknown error')}")
        return resp

    async def close(self) -> None:
        try:
            self._w.close()
            await self._w.wait_closed()
        except (ConnectionError, OSError):
            pass

    def close_nowait(self) -> None:
        """Synchronous close for teardown paths that must not await
        (e.g. reaping a killed process group mid-cancellation)."""
        try:
            self._w.close()
        except (ConnectionError, OSError):
            pass
