"""Graceful-degradation primitives: jittered backoff + per-peer circuit
breakers.

Serf's value proposition is behaving well when the network does not
(SWIM + Lifeguard bound false positives under loss and load — PAPERS.md);
this module gives the HOST plane the same discipline on its *reliable*
paths, which previously failed hard and retried hot:

- :class:`Backoff` — jittered exponential delay schedule for stream
  dials, push/pull sync and join retries.  Full jitter (delay drawn
  uniformly from ``[base/2, cap]``-style windows) so co-located nodes
  recovering from the same partition do not dial in lockstep.
- :class:`CircuitBreaker` — per-peer failure accounting: after
  ``threshold`` consecutive failures the circuit *opens* and further
  attempts fast-fail for ``cooldown`` seconds, after which ONE half-open
  trial is admitted; success closes the circuit, failure re-opens it.
  This is what keeps a dead peer from eating a full dial timeout on
  every push/pull tick while the cluster is already degraded.

Every decision is observable: ``serf.degraded.*`` counters plus
``circuit-breaker`` flight events (see README "Chaos & degradation").
"""

from __future__ import annotations

import random
import time
from typing import Dict, Optional

from serf_tpu.obs import flight
from serf_tpu.utils import metrics


class Backoff:
    """Jittered exponential backoff schedule.

    ``next_delay()`` returns the delay to sleep before the next retry:
    uniformly jittered around an exponentially growing base, capped at
    ``max_delay``.  ``reset()`` re-arms after a success.
    """

    def __init__(self, base: float, max_delay: float,
                 rng: Optional[random.Random] = None):
        self.base = max(1e-4, base)
        self.max_delay = max(self.base, max_delay)
        self.rng = rng or random.Random()
        self._cur = self.base

    def next_delay(self) -> float:
        # full jitter: uniform in [cur/2, cur] — desynchronizes peers
        # retrying after a shared fault without halving expected wait
        d = self._cur * (0.5 + 0.5 * self.rng.random())
        self._cur = min(self._cur * 2.0, self.max_delay)
        return d

    def reset(self) -> None:
        self._cur = self.base


class _Circuit:
    __slots__ = ("failures", "opened_at", "half_open")

    def __init__(self):
        self.failures = 0
        self.opened_at: Optional[float] = None
        self.half_open = False


class CircuitBreaker:
    """Per-peer circuit breaker for stream-plane operations.

    Keyed by an opaque peer key (stringified address).  State machine per
    peer: CLOSED --threshold consecutive failures--> OPEN --cooldown
    elapses--> HALF-OPEN (one trial) --success--> CLOSED / --failure-->
    OPEN again.  Peers that close are evicted, so the table only holds
    currently-degraded peers (bounded by cluster size).
    """

    def __init__(self, threshold: int, cooldown: float,
                 labels: Optional[dict] = None, node: Optional[str] = None):
        self.threshold = max(1, threshold)
        self.cooldown = max(0.0, cooldown)
        self.labels = labels
        self.node = node
        self._peers: Dict[str, _Circuit] = {}

    def allow(self, key: str) -> bool:
        """May we attempt an operation against ``key`` right now?  An
        OPEN circuit past its cooldown admits exactly one half-open
        trial (this call consumes it)."""
        c = self._peers.get(key)
        if c is None or c.opened_at is None:
            return True
        if c.half_open:
            return False          # a half-open trial is already in flight
        if time.monotonic() - c.opened_at >= self.cooldown:
            c.half_open = True
            return True
        metrics.incr("serf.degraded.breaker_fastfail", 1, self.labels)
        return False

    def is_open(self, key: str) -> bool:
        c = self._peers.get(key)
        return c is not None and c.opened_at is not None and not (
            not c.half_open
            and time.monotonic() - c.opened_at >= self.cooldown)

    def success(self, key: str) -> None:
        c = self._peers.pop(key, None)
        if c is not None and c.opened_at is not None:
            flight.record("circuit-breaker", node=self.node, peer=key,
                          state="closed")

    def failure(self, key: str) -> None:
        c = self._peers.setdefault(key, _Circuit())
        c.failures += 1
        if c.half_open:
            # the half-open trial failed: re-open, restart the cooldown
            c.half_open = False
            c.opened_at = time.monotonic()
            metrics.incr("serf.degraded.breaker_opened", 1, self.labels)
            flight.record("circuit-breaker", node=self.node, peer=key,
                          state="reopened", failures=c.failures)
            return
        if c.opened_at is None and c.failures >= self.threshold:
            c.opened_at = time.monotonic()
            metrics.incr("serf.degraded.breaker_opened", 1, self.labels)
            flight.record("circuit-breaker", node=self.node, peer=key,
                          state="open", failures=c.failures)

    def release(self, key: str) -> None:
        """Abandon an in-flight half-open trial without judging the peer
        (e.g. the trial was cancelled): the circuit returns to plain OPEN
        so the next cooldown expiry can admit a fresh trial."""
        c = self._peers.get(key)
        if c is not None and c.half_open:
            c.half_open = False
            c.opened_at = time.monotonic()

    def open_count(self) -> int:
        return sum(1 for c in self._peers.values() if c.opened_at is not None)

    def forget(self, key: str) -> None:
        self._peers.pop(key, None)
