"""The serf agent: one config-driven serf process on real sockets.

``python -m serf_tpu.host.agent --config agent.json`` (or the
``tools/serfd.py`` wrapper) runs ONE cluster member as an OS process —
the deployment shape the reference ships as ``serf agent`` and the unit
the proc-plane chaos executor (``serf_tpu.faults.proc``) SIGKILLs,
SIGSTOPs and re-execs.  The agent:

- binds a :class:`~serf_tpu.host.net.NetTransport` (UDP packets + TCP
  streams on one port), with a bounded bind-retry loop so a restart
  re-claiming its old port survives the previous process's lingering
  socket;
- wraps the transport with ``attach_transport_chaos`` so the executor
  can install compiled :class:`~serf_tpu.host.transport.ChaosRule`
  objects over the control channel — REAL packet loss/partitions at the
  real sender seam;
- serves the control channel (``serf_tpu.host.ctl``): join/user_event/
  query/load, stats/members/health/lifecycle snapshots, chaos installs,
  black-box dump-on-demand, and lifecycle ops (leave/shutdown);
- handles SIGTERM as a GRACEFUL exit: serf leave (peers see Left, the
  snapshot records the leave and flushes) then shutdown — versus
  SIGKILL, which peers must detect as Failed and the snapshot must
  survive via its torn-tail repair;
- counts background-task deaths through the ``utils.tasks`` failure-hook
  seam (``serf.proc.task_failures``) — the no-task-death invariant is
  judged from this counter across process boundaries.

Config is a JSON file (see :class:`AgentConfig`); the ``options`` block
reuses the ``Options.from_dict`` serde (humantime durations and all).
Once live, the agent atomically publishes a READY FILE — bound cluster
address, control address, pid, generation — which is how the spawning
harness learns the ephemeral ports.  This module must stay importable
without jax: agent processes are host-plane only and must start fast.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import sys
from dataclasses import dataclass, field
from typing import List, Optional

from serf_tpu.host import ctl
from serf_tpu.host.net import NetTransport
from serf_tpu.options import Options
from serf_tpu.utils import metrics
from serf_tpu.utils.files import atomic_write_text
from serf_tpu.utils.logging import get_logger
from serf_tpu.utils import tasks as task_hooks

log = get_logger("agent")

#: bounded bind retries: a restart re-claims its OLD concrete port while
#: the kernel may still hold the dead process's socket for a beat
BIND_RETRIES = 20
BIND_RETRY_DELAY_S = 0.1


@dataclass
class AgentConfig:
    """One agent's startup config (JSON file, written atomically by any
    harness — a crash mid-write must never leave a torn config a
    restart then trusts)."""

    node_id: str
    bind: str = "127.0.0.1:0"          # cluster UDP+TCP ("host:port")
    ctl: str = "127.0.0.1:0"           # control channel; a path = unix socket
    join: List[str] = field(default_factory=list)   # seed "host:port" peers
    snapshot_path: Optional[str] = None
    keyring_file: Optional[str] = None
    ready_file: Optional[str] = None
    blackbox_dir: Optional[str] = None
    profile: str = "proc"              # proc | local | lan
    generation: int = 0                # restart generation (harness-stamped)
    options: Optional[dict] = None     # deep overrides onto the profile
    #: lifecycle-ledger clock rate (1-in-N messages; None = library
    #: default, 0 = counters only) — the bench harness runs agents hot
    #: (4) so the per-stage decomposition is well-populated
    lifecycle_sample_n: Optional[int] = None

    def to_dict(self) -> dict:
        return dict(self.__dict__)

    @classmethod
    def from_dict(cls, data: dict) -> "AgentConfig":
        known = set(cls.__dataclass_fields__)
        unknown = set(data) - known
        if unknown:
            raise ValueError(f"unknown AgentConfig keys: {sorted(unknown)}")
        return cls(**data)

    @classmethod
    def load(cls, path: str) -> "AgentConfig":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def build_options(self) -> Options:
        profiles = {"proc": Options.proc, "local": Options.local,
                    "lan": Options}
        try:
            base = profiles[self.profile]()
        except KeyError:
            raise ValueError(f"unknown profile {self.profile!r}; "
                             f"have {sorted(profiles)}") from None
        if self.options:
            merged = base.to_dict()
            for key, value in self.options.items():
                if key == "memberlist" and isinstance(value, dict):
                    merged["memberlist"] = {**merged["memberlist"], **value}
                else:
                    merged[key] = value
            base = Options.from_dict(merged)
        return base.replace(snapshot_path=self.snapshot_path,
                            keyring_file=self.keyring_file)


def _parse_hostport(text: str):
    host, _, port = text.rpartition(":")
    return (host, int(port))


class Agent:
    """One running serf process: transport + Serf + control channel."""

    def __init__(self, cfg: AgentConfig):
        self.cfg = cfg
        self.serf = None
        self.transport = None
        self.box = None
        self._ctl_server = None
        self._ctl_addr: Optional[str] = None
        self._stop = asyncio.Event()
        self._exit_code = 0
        self._labels = {"node": cfg.node_id}

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> None:
        from serf_tpu.faults.host import attach_transport_chaos
        from serf_tpu.host.serf import Serf

        opts = self.cfg.build_options()
        self.transport = await self._bind_transport(
            _parse_hostport(self.cfg.bind))
        local = self.transport.local_addr
        # the chaos seam is armed (but idle) from the start: the executor
        # installs/clears rules over the control channel at phase edges
        attach_transport_chaos(self.transport, ctl.addr_key(local),
                               addr_key=ctl.addr_key)

        keyring = None
        if self.cfg.keyring_file and os.path.exists(self.cfg.keyring_file):
            from serf_tpu.host.keyring import SecretKeyring
            keyring = SecretKeyring.load(self.cfg.keyring_file)

        if self.cfg.lifecycle_sample_n is not None:
            from serf_tpu.obs import lifecycle as lc
            lc.set_global_ledger(
                lc.LifecycleLedger(sample_n=self.cfg.lifecycle_sample_n))

        task_hooks.add_failure_hook(self._on_task_death)
        self.serf = await Serf.create(self.transport, opts,
                                      self.cfg.node_id, keyring=keyring)
        if self.cfg.blackbox_dir:
            from serf_tpu.obs import lifecycle as lc
            from serf_tpu.obs.blackbox import BlackBox
            self.box = BlackBox(
                self.cfg.blackbox_dir, node=self.cfg.node_id,
                lifecycle=lambda: lc.global_ledger().snapshot(),
                health=lambda: self.serf.health_report().to_dict())
            self.serf.blackbox = self.box

        await self._start_ctl()
        self._publish_ready()
        metrics.gauge("serf.proc.generation", self.cfg.generation,
                      self._labels)
        for seed in self.cfg.join:
            try:
                await self.serf.join(seed)
            except Exception as e:  # noqa: BLE001 — seeds are best-effort;
                # the SWIM fabric heals the rest once any join lands
                log.warning("seed join %s failed: %r", seed, e)

    async def _bind_transport(self, addr) -> NetTransport:
        last: Optional[Exception] = None
        for attempt in range(BIND_RETRIES):
            try:
                return await NetTransport.bind(addr)
            except OSError as e:
                last = e
                metrics.incr("serf.proc.bind_retry", 1, self._labels)
                await asyncio.sleep(BIND_RETRY_DELAY_S)
        raise ConnectionError(
            f"cannot bind {addr!r} after {BIND_RETRIES} attempts: {last}")

    def _publish_ready(self) -> None:
        local = self.transport.local_addr
        info = {
            "pid": os.getpid(),
            "node_id": self.cfg.node_id,
            "addr": ctl.addr_key(local),
            "ctl": self._ctl_addr,
            "generation": self.cfg.generation,
        }
        if self.cfg.ready_file:
            atomic_write_text(self.cfg.ready_file, json.dumps(info))
        else:
            print(json.dumps(info), flush=True)

    def _on_task_death(self, name: str, exc: BaseException) -> None:
        metrics.incr("serf.proc.task_failures", 1, self._labels)

    def install_signal_handlers(self) -> None:
        loop = asyncio.get_running_loop()
        # SIGTERM = graceful leave (peers see Left, snapshot flushes the
        # leave record); SIGINT behaves the same for interactive runs.
        # SIGKILL is, by design, unhandleable — that is the crash path.
        for sig in (signal.SIGTERM, signal.SIGINT):
            loop.add_signal_handler(
                sig, lambda: asyncio.ensure_future(self._graceful_exit()))

    async def _graceful_exit(self) -> None:
        if self._stop.is_set():
            return
        try:
            if self.serf is not None:
                await self.serf.leave()
        except Exception:  # noqa: BLE001 — leaving is best-effort; dying
            log.exception("graceful leave failed")  # gracelessly is worse
        self._stop.set()

    async def run_until_stopped(self) -> int:
        await self._stop.wait()
        await self._teardown()
        return self._exit_code

    async def _teardown(self) -> None:
        task_hooks.remove_failure_hook(self._on_task_death)
        if self._ctl_server is not None:
            self._ctl_server.close()
            await self._ctl_server.wait_closed()
        if self.serf is not None:
            from serf_tpu.host.serf import SerfState
            if self.serf.state != SerfState.SHUTDOWN:
                await self.serf.shutdown()

    # -- control channel -----------------------------------------------------

    async def _start_ctl(self) -> None:
        spec = self.cfg.ctl
        if ":" in spec:
            host, port = _parse_hostport(spec)
            self._ctl_server = await asyncio.start_server(
                self._serve_ctl, host=host, port=port)
            bound = self._ctl_server.sockets[0].getsockname()[:2]
            self._ctl_addr = f"{bound[0]}:{bound[1]}"
        else:
            self._ctl_server = await asyncio.start_unix_server(
                self._serve_ctl, path=spec)
            self._ctl_addr = spec

    async def _serve_ctl(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                try:
                    req = await ctl.read_frame(reader)
                except (ConnectionError, asyncio.IncompleteReadError):
                    break
                resp = {"id": req.get("id")}
                try:
                    metrics.incr("serf.proc.ctl.requests", 1, self._labels)
                    result = await self._dispatch(req)
                    resp.update(ok=True, **(result or {}))
                except Exception as e:  # noqa: BLE001 — one bad op must
                    # not kill the channel; the error rides the response
                    resp.update(ok=False, error=f"{type(e).__name__}: {e}")
                writer.write(ctl.encode_frame(resp))
                await writer.drain()
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def _dispatch(self, req: dict) -> Optional[dict]:
        op = req.get("op")
        handler = getattr(self, f"_op_{op}", None)
        if handler is None:
            raise ValueError(f"unknown control op {op!r}")
        return await handler(req)

    # -- ops -----------------------------------------------------------------

    async def _op_ping(self, req: dict) -> dict:
        return {"pid": os.getpid(), "node_id": self.cfg.node_id,
                "generation": self.cfg.generation}

    async def _op_join(self, req: dict) -> dict:
        joined, errors = 0, []
        for addr in req.get("addrs", []):
            try:
                await self.serf.join(addr)
                joined += 1
            except Exception as e:  # noqa: BLE001
                errors.append(f"{addr}: {e}")
        return {"joined": joined, "errors": errors}

    async def _op_user_event(self, req: dict) -> dict:
        await self.serf.user_event(req["name"],
                                   ctl.unb64(req.get("payload_b64")),
                                   coalesce=bool(req.get("coalesce", False)))
        return {}

    async def _op_query(self, req: dict) -> dict:
        from serf_tpu.host.query import QueryParam
        resp = await self.serf.query(
            req["name"], ctl.unb64(req.get("payload_b64")),
            QueryParam(timeout=float(req.get("timeout", 0.0))))
        out = []
        async for r in resp.responses():
            out.append({"from": r.from_id, "payload_b64": ctl.b64(r.payload)})
        return {"responses": out,
                "overloaded": sorted(resp.overloaded_responders)}

    async def _op_load(self, req: dict) -> dict:
        """Batched offered load (the executor's storm phases): fire
        ``events``/``queries`` calls back-to-back, count admitted vs
        shed.  Queries do not await their responses — offered-rate
        fidelity beats response collection here."""
        from serf_tpu.host.admission import OverloadError
        from serf_tpu.host.query import QueryParam
        prefix = req.get("prefix", "load")
        counts = {"events_admitted": 0, "events_shed": 0,
                  "queries_admitted": 0, "queries_shed": 0}
        for i in range(int(req.get("events", 0))):
            try:
                await self.serf.user_event(f"{prefix}-e{i}", b"proc-load",
                                           coalesce=False)
                counts["events_admitted"] += 1
            except OverloadError:
                counts["events_shed"] += 1
        for i in range(int(req.get("queries", 0))):
            try:
                await self.serf.query(f"{prefix}-q{i}", b"q",
                                      QueryParam(timeout=0.25))
                counts["queries_admitted"] += 1
            except OverloadError:
                counts["queries_shed"] += 1
        return counts

    async def _op_stats(self, req: dict) -> dict:
        from serf_tpu.obs import metrics_snapshot
        s = self.serf
        return {
            "node_id": s.local_id,
            "generation": self.cfg.generation,
            "members": s.num_members(),
            "failed": len(s._failed),
            "left": len(s._left),
            "health_score": s.memberlist.health_score(),
            "member_time": int(s.clock.time()),
            "event_time": int(s.event_clock.time()),
            "query_time": int(s.query_clock.time()),
            "metrics": metrics_snapshot(),
        }

    async def _op_members(self, req: dict) -> dict:
        return {"members": [
            {"id": m.node.id, "addr": ctl.addr_key(m.node.addr),
             "status": m.status.name}
            for m in self.serf.members()]}

    async def _op_health(self, req: dict) -> dict:
        return {"health": self.serf.health_report().to_dict()}

    async def _op_lifecycle(self, req: dict) -> dict:
        from serf_tpu.obs import lifecycle as lc
        return {"lifecycle": lc.global_ledger().snapshot()}

    async def _op_chaos(self, req: dict) -> dict:
        """Install (or clear, rule=None) a compiled chaos rule on the
        real transport's sender seam — the executor lowers partition/
        loss/corruption phases to THIS op on every live agent."""
        rule = ctl.chaos_rule_from_dict(req.get("rule"))
        self.transport._chaos_rule = rule
        metrics.incr("serf.proc.chaos_installs", 1, self._labels)
        return {"installed": rule is not None}

    async def _op_keys(self, req: dict) -> dict:
        """Keyring ops over the control channel (the proc-plane rotation
        driver): ``install``/``use``/``remove``/``list`` run CLUSTER-wide
        through this agent's KeyManager; ``digest`` reads the LOCAL
        ring.  Responses carry non-secret key digests only — raw key
        material rides the request (``key_b64``) but never a response."""
        from serf_tpu.host.keyring import key_digest
        action = req.get("action")
        if action == "digest":
            ring = self.serf.memberlist.keyring()
            if ring is None:
                raise RuntimeError("encryption is not enabled")
            return {"digest": ring.digest()}
        km = self.serf.key_manager()
        if km is None:
            raise RuntimeError("encryption is not enabled")
        if action == "install":
            r = await km.install_key(ctl.unb64(req.get("key_b64")))
        elif action == "use":
            r = await km.use_key(ctl.unb64(req.get("key_b64")))
        elif action == "remove":
            r = await km.remove_key(ctl.unb64(req.get("key_b64")))
        elif action == "list":
            r = await km.list_keys()
        else:
            raise ValueError(f"unknown keys action {action!r}")
        return {
            "num_nodes": r.num_nodes, "num_resp": r.num_resp,
            "num_err": r.num_err, "attempts": r.attempts,
            "quorum_ok": r.quorum_ok, "messages": r.messages,
            "keys": {key_digest(k): c for k, c in r.keys.items()},
            "primary_keys": {key_digest(k): c
                             for k, c in r.primary_keys.items()},
        }

    async def _op_blackbox(self, req: dict) -> dict:
        if self.box is None:
            raise RuntimeError("agent has no blackbox_dir configured")
        path = self.box.dump(reason=req.get("reason", "ctl-request"),
                             detail=req.get("detail", ""))
        return {"bundle": path, "directory": self.cfg.blackbox_dir}

    async def _op_leave(self, req: dict) -> dict:
        # retained so the exit task is never GC'd mid-leave; exceptions
        # surface through spawn_logged's done-callback
        self._leave_task = task_hooks.spawn_logged(
            self._graceful_exit(), "agent-leave")
        return {"leaving": True}

    async def _op_shutdown(self, req: dict) -> dict:
        # hard stop: no leave broadcast, no Left status — peers must
        # detect the disappearance (the polite sibling of SIGKILL)
        self._stop.set()
        return {"stopping": True}


async def _amain(cfg: AgentConfig) -> int:
    agent = Agent(cfg)
    agent.install_signal_handlers()
    try:
        await agent.start()
    except Exception:
        log.exception("agent %s failed to start", cfg.node_id)
        await agent._teardown()
        return 1
    return await agent.run_until_stopped()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="serf agent: one cluster member as an OS process")
    p.add_argument("--config", required=True,
                   help="path to an AgentConfig JSON file")
    args = p.parse_args(argv)
    cfg = AgentConfig.load(args.config)
    return asyncio.run(_amain(cfg))


if __name__ == "__main__":
    sys.exit(main())
