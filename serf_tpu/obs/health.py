"""Node health scoring: one Lifeguard-style 0-100 number per node.

The memberlist layer already keeps a Lifeguard *awareness* score (how
often our own probes time out — a signal that WE are the slow one), and
PR 1 left depth gauges, flight-recorder overflow counters, and transport
diagnostics all over the engine.  This module folds those local signals
into a single operator-facing score:

    score = 100 - sum(weight_c * min(1, load_c / saturation_c))

Each component contributes a *load* in [0, 1] (0 = healthy, 1 = the
signal is saturated) scaled by its weight; weights total 100, so a node
with every signal pegged scores 0.  Counter-shaped signals (flight-ring
drops, transport retransmits) are scored on their GROWTH since the last
*consuming* sample (the periodic monitor's tick) — a burst of drops
hurts now and heals once it stops, instead of poisoning the score
forever, and on-demand reads never shrink the measurement window.

The scorer is engine-agnostic: it samples named zero-argument callables.
``serf_sources(serf)`` wires the standard set for a running Serf engine
(duck-typed — obs stays importable without the host plane):

- ``probe``       awareness score / ceiling — our probes are timing out
- ``queue``       max broadcast-queue depth / ``max_queue_depth``
- ``tee``         event tee-queue fill (the snapshot/delivery pipeline)
- ``loop-lag``    event-loop lag EWMA (ms) from the engine's monitor
- ``flight-drop`` flight-ring + subscriber drop growth per sample
- ``transport``   dstream out-of-order drops + retransmit growth

``Serf.health_report()`` samples the scorer, exports ``serf.health.score``
plus per-component ``serf.health.component.<name>`` load gauges (labeled
with the node id so in-process clusters stay distinguishable), and the
``_serf_stats`` internal query ships the report cluster-wide
(``serf_tpu.obs.cluster``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Optional

from serf_tpu.utils import metrics


@dataclass(frozen=True)
class ComponentSpec:
    """How one signal maps into the score.

    ``saturation`` is the raw value at which the component's full
    ``weight`` is deducted; ``delta=True`` marks a monotone counter whose
    growth-per-sample (not lifetime total) is scored.
    """

    weight: float
    saturation: float
    delta: bool = False


#: default component weights (sum = 100) and saturation points
DEFAULT_SPECS: Dict[str, ComponentSpec] = {
    # awareness fraction: 1.0 = Lifeguard ceiling (all probes timing out)
    "probe": ComponentSpec(weight=30.0, saturation=1.0),
    # broadcast queue fill fraction: 1.0 = at the prune limit
    "queue": ComponentSpec(weight=20.0, saturation=1.0),
    # event tee fill fraction: 1.0 = snapshot/delivery pipeline is wedged
    "tee": ComponentSpec(weight=10.0, saturation=1.0),
    # event-loop lag EWMA in ms: 100ms sustained lag = fully degraded
    "loop-lag": ComponentSpec(weight=15.0, saturation=100.0),
    # flight-ring + subscriber drops per sample window
    "flight-drop": ComponentSpec(weight=10.0, saturation=64.0, delta=True),
    # transport-plane OOO drops + retransmits per sample window
    "transport": ComponentSpec(weight=15.0, saturation=32.0, delta=True),
}

#: below this score a node lands on the ClusterSnapshot unhealthy list
UNHEALTHY_THRESHOLD = 70


@dataclass(frozen=True)
class HealthComponent:
    name: str
    raw: float        # the sampled signal (delta for counter components)
    load: float       # normalized [0, 1]
    weight: float
    penalty: float    # load * weight

    def to_dict(self) -> Dict[str, float]:
        return {"raw": round(self.raw, 4), "load": round(self.load, 4),
                "weight": self.weight, "penalty": round(self.penalty, 2)}


@dataclass(frozen=True)
class HealthReport:
    score: int
    components: Dict[str, HealthComponent]

    @property
    def unhealthy(self) -> bool:
        return self.score < UNHEALTHY_THRESHOLD

    def to_dict(self) -> Dict[str, object]:
        return {"score": self.score,
                "components": {n: c.to_dict()
                               for n, c in sorted(self.components.items())}}


class HealthScorer:
    """Samples named signal sources into a :class:`HealthReport`.

    Stateful only for ``delta`` components (the previous counter
    baselines); everything else is recomputed from the live sources each
    call.  Baselines advance only on ``sample(consume=True)`` — the
    periodic monitor's fixed cadence — so on-demand callers
    (``Serf.stats()``, the ``_serf_stats`` responder) read the growth
    since the last monitor tick WITHOUT shrinking anyone's window: the
    score cannot be flattened by polling it often (a burst of drops
    scores the same however many observers are watching).  A source that
    raises contributes zero load — a broken signal must never take the
    health plane down with it.
    """

    def __init__(self, sources: Dict[str, Callable[[], float]],
                 specs: Optional[Dict[str, ComponentSpec]] = None):
        self.sources = dict(sources)
        self.specs = dict(specs or DEFAULT_SPECS)
        self._last: Dict[str, float] = {}

    def sample(self, consume: bool = True) -> HealthReport:
        components: Dict[str, HealthComponent] = {}
        total_penalty = 0.0
        for name, source in self.sources.items():
            spec = self.specs.get(name)
            if spec is None:
                continue
            try:
                raw = float(source())
            except Exception:  # noqa: BLE001 - degraded signal, not a crash
                raw = 0.0
            if spec.delta:
                prev = self._last.get(name)
                if prev is None:
                    # first observation establishes the baseline
                    self._last[name] = raw
                    raw = 0.0
                else:
                    if consume:
                        self._last[name] = raw
                    raw = max(0.0, raw - prev)
            load = min(1.0, max(0.0, raw / spec.saturation)) \
                if spec.saturation > 0 else 0.0
            penalty = load * spec.weight
            total_penalty += penalty
            components[name] = HealthComponent(
                name, raw, load, spec.weight, penalty)
        score = int(round(max(0.0, min(100.0, 100.0 - total_penalty))))
        return HealthReport(score, components)


def serf_sources(serf) -> Dict[str, Callable[[], float]]:
    """The standard signal set for a Serf engine (duck-typed: the host
    plane is never imported here).  Transport counters are read from the
    process-global metrics sink — in an in-process multi-node cluster
    they are shared across co-located nodes (documented caveat)."""
    ml_opts = serf.opts.memberlist

    def probe() -> float:
        ceiling = max(1, ml_opts.awareness_max_multiplier - 1)
        return serf.memberlist.health_score() / ceiling

    def queue() -> float:
        depth = max(len(serf.intent_broadcasts), len(serf.event_broadcasts),
                    len(serf.query_broadcasts))
        return depth / max(1, serf.opts.max_queue_depth)

    def tee() -> float:
        return serf.event_tee_fill()

    def loop_lag() -> float:
        return serf.loop_lag_ms()

    def flight_drop() -> float:
        from serf_tpu.obs import flight
        dropped = float(flight.global_recorder().dropped)
        sub = getattr(serf, "_subscriber", None)
        if sub is not None:
            dropped += float(getattr(sub, "dropped", 0))
        return dropped

    def transport() -> float:
        sink = metrics.global_sink()
        return (sink.counter("serf.dstream.ooo_dropped")
                + sink.counter("serf.dstream.retransmits"))

    return {"probe": probe, "queue": queue, "tee": tee,
            "loop-lag": loop_lag, "flight-drop": flight_drop,
            "transport": transport}
