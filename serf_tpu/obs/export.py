"""Exporters: Prometheus text format and JSON snapshots.

``prometheus_text()`` renders the process MetricsSink in the Prometheus
text exposition format (v0.0.4): counters as ``_total``, gauges as-is,
histograms as summaries (``quantile`` series from the bounded sample
ring plus ``_sum``/``_count``).  Metric names are sanitized
(``serf.member.join`` -> ``serf_member_join``), label values escaped
(backslash, double-quote, newline), and label keys emitted in sorted
order — the sink already stores label sets sorted, so output ordering is
deterministic.

``parse_prometheus_text()`` is the matching minimal parser: it exists so
tests (and operators' smoke scripts) can round-trip the export without a
prometheus client library in the image.

``json_snapshot()`` bundles metrics + trace spans + flight events into
one JSON-ready dict — the payload ``Serf.stats()`` surfaces.
"""

from __future__ import annotations

import math
import re
from typing import Any, Dict, List, Optional, Tuple

from serf_tpu.obs import flight as _flight
from serf_tpu.obs import trace as _trace
from serf_tpu.utils import metrics
from serf_tpu.utils.metrics import MetricsSink

_NAME_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")
_QUANTILES = (50.0, 95.0, 99.0)


def _prom_name(name: str) -> str:
    out = _NAME_SANITIZE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _escape_label_value(v: str) -> str:
    return (v.replace("\\", "\\\\")
             .replace("\"", "\\\"")
             .replace("\n", "\\n"))


def _render_labels(labels: Tuple[Tuple[str, str], ...],
                   extra: Tuple[Tuple[str, str], ...] = ()) -> str:
    pairs = list(labels) + list(extra)
    if not pairs:
        return ""
    body = ",".join(
        f'{_prom_name(str(k))}="{_escape_label_value(str(v))}"'
        for k, v in pairs)
    return "{" + body + "}"


def _fmt_value(v: float) -> str:
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if f == float("inf"):
        return "+Inf"
    if f == float("-inf"):
        return "-Inf"
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def prometheus_text(sink: Optional[MetricsSink] = None) -> str:
    """Render the sink as Prometheus text exposition format."""
    sink = sink or metrics.global_sink()
    lines: List[str] = []

    with sink._lock:
        counters = dict(sink.counters)
        gauges = dict(sink.gauges)
        histograms = {k: (h.count, h.total, h.min, h.max, h.recent())
                      for k, h in sink.histograms.items()}

    seen_types: set = set()

    def type_line(pname: str, kind: str) -> None:
        if pname not in seen_types:
            seen_types.add(pname)
            lines.append(f"# TYPE {pname} {kind}")

    for (name, labels) in sorted(counters):
        pname = _prom_name(name) + "_total"
        type_line(pname, "counter")
        lines.append(f"{pname}{_render_labels(labels)} "
                     f"{_fmt_value(counters[(name, labels)])}")

    for (name, labels) in sorted(gauges):
        pname = _prom_name(name)
        type_line(pname, "gauge")
        lines.append(f"{pname}{_render_labels(labels)} "
                     f"{_fmt_value(gauges[(name, labels)])}")

    for (name, labels) in sorted(histograms):
        count, total, mn, mx, recent = histograms[(name, labels)]
        pname = _prom_name(name)
        type_line(pname, "summary")
        ordered = sorted(recent)
        for q in _QUANTILES:
            qv = metrics.percentile_of(ordered, q)
            qlabel = (("quantile", _fmt_value(q / 100.0)),)
            lines.append(f"{pname}{_render_labels(labels, qlabel)} "
                         f"{_fmt_value(qv)}")
        lines.append(f"{pname}_sum{_render_labels(labels)} "
                     f"{_fmt_value(total)}")
        lines.append(f"{pname}_count{_render_labels(labels)} "
                     f"{_fmt_value(count)}")
        lines.append(f"{pname}_min{_render_labels(labels)} "
                     f"{_fmt_value(mn)}")
        lines.append(f"{pname}_max{_render_labels(labels)} "
                     f"{_fmt_value(mx)}")

    return "\n".join(lines) + ("\n" if lines else "")


_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?\s+(?P<value>\S+)$")
_LABEL_RE = re.compile(
    r'(?P<key>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<value>(?:\\.|[^"\\])*)"')


_UNESCAPE_RE = re.compile(r"\\(.)")


def _unescape_label_value(v: str) -> str:
    # one left-to-right scan: naive chained .replace() corrupts values
    # containing a literal backslash followed by 'n'
    return _UNESCAPE_RE.sub(
        lambda m: "\n" if m.group(1) == "n" else m.group(1), v)


def parse_prometheus_text(text: str) -> Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float]:
    """Minimal exposition-format parser: ``{(name, labelset): value}``.

    Raises ``ValueError`` on any line that is neither a comment, blank,
    nor a well-formed sample — the round-trip guard the tests pin.
    """
    out: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"unparseable sample line: {line!r}")
        raw_labels = m.group("labels")
        labels: Tuple[Tuple[str, str], ...] = ()
        if raw_labels:
            consumed = 0
            pairs = []
            for lm in _LABEL_RE.finditer(raw_labels):
                pairs.append((lm.group("key"),
                              _unescape_label_value(lm.group("value"))))
                consumed = lm.end()
            # anything left beyond label pairs + separators is a parse bug
            if _LABEL_RE.sub("", raw_labels).strip(", ") != "":
                raise ValueError(f"unparseable labels: {raw_labels!r}")
            del consumed
            labels = tuple(pairs)
        value = m.group("value")
        if value == "+Inf":
            num = float("inf")
        elif value == "-Inf":
            num = float("-inf")
        else:
            num = float(value)
        out[(m.group("name"), labels)] = num
    return out


def metrics_snapshot(sink: Optional[MetricsSink] = None) -> Dict[str, Any]:
    """JSON-ready view of the sink: counters/gauges flat, histograms with
    count/sum/min/max/mean and p50/p95/p99 from the sample ring."""
    sink = sink or metrics.global_sink()
    with sink._lock:
        counters = dict(sink.counters)
        gauges = dict(sink.gauges)
        # materialize histogram scalars under the lock: a concurrent
        # observe() must not skew count vs sum vs ring mid-snapshot
        hists = {}
        for k, h in sink.histograms.items():
            ordered = sorted(h.recent())
            hists[k] = {
                "count": h.count,
                "sum": h.total,
                "min": h.min,
                "max": h.max,
                "mean": h.mean,
                "p50": metrics.percentile_of(ordered, 50),
                "p95": metrics.percentile_of(ordered, 95),
                "p99": metrics.percentile_of(ordered, 99),
            }

    def key(name: str, labels) -> str:
        if not labels:
            return name
        return name + "{" + ",".join(f"{k}={v}" for k, v in labels) + "}"

    return {
        "counters": {key(n, ls): v for (n, ls), v in sorted(counters.items())},
        "gauges": {key(n, ls): v for (n, ls), v in sorted(gauges.items())},
        "histograms": {key(n, ls): h for (n, ls), h in sorted(hists.items())},
    }


def json_snapshot(sink: Optional[MetricsSink] = None,
                  trace_limit: Optional[int] = None,
                  flight_limit: Optional[int] = None) -> Dict[str, Any]:
    """The full observability picture in one JSON-ready dict."""
    return {
        "metrics": metrics_snapshot(sink),
        "trace": _trace.trace_dump(limit=trace_limit),
        "flight": _flight.flight_dump(last=flight_limit),
    }
