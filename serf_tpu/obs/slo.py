"""Declarative SLOs: one definition table, judged on BOTH planes.

Nine PRs of instrumentation produced numbers; this module produces
*judgments*.  :data:`SLO_TABLE` is THE list of service-level objectives
— convergence-within-settle-budget, false-DEAD rate, shed ratio, query
p99, and measured-rps-vs-analytic-ceiling — and the chaos CLI, the
obswatch CLI, and the bench regression gate all evaluate it through the
same :func:`judge` path:

- **multi-window burn rates** (SRE style): a ring series is judged over
  a short and a long window; ``burn = window_value / objective``
  (normalized so >1 = out of objective whichever direction "good"
  points).  A breach on the *final* value is the verdict; sustained
  multi-window burn and EWMA/MAD anomaly flags ride along as evidence.
- **EWMA/MAD anomaly flags**: residuals against an exponentially
  weighted moving average, scored in robust (median absolute deviation)
  units — "did this series do something it never does?" without
  hand-tuned thresholds per metric.
- Every breach fires a ``slo-breach`` flight event and bumps
  ``serf.slo.breach``; every evaluation lands ``serf.slo.ok`` and
  ``serf.slo.burn`` gauges, so the SLO plane is itself observable
  (and sample-able into rings).

Objectives judged against *measured capability* rather than wishes:
``sustained-rps-ceiling`` compares a measured rounds/sec against the
analytic bandwidth ceiling (``models/accounting``) — the
hierarchy-aware comm-cost stance of "A Model for Communication in
Clusters of Multi-core Machines" (PAPERS.md): a measurement that beats
physics is a *measurement* bug (the round-1 179k-rps artifact class).

The serflint registry cross-checks this table (``slo-metric-unknown`` /
``slo-decl-drift``): every SLO must watch declared metrics, and the
``SLOS`` declaration in ``analysis/registry.py`` plus the README SLO
table must match these definitions exactly, both ways.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from serf_tpu.obs import flight
from serf_tpu.obs.timeseries import SeriesStore, TimeSeries
from serf_tpu.utils import metrics

#: burn-rate windows (ring points): short catches a fresh regression,
#: long confirms it is sustained rather than a blip
BURN_WINDOWS: Tuple[int, ...] = (8, 32)
#: burn values are clamped here (a zero objective would otherwise put
#: literal inf into JSON artifacts)
BURN_CAP = 1e6
_EPS = 1e-9


@dataclass(frozen=True)
class SLODef:
    """One service-level objective, plane-neutral.

    ``objective`` is in normalized units (see ``unit``); ``better``
    says which direction is good.  ``metrics`` names the declared
    registry metrics whose series carry the evidence (serflint's
    ``slo-metric-unknown`` holds every name to the registry)."""

    name: str
    metrics: Tuple[str, ...]
    planes: Tuple[str, ...]
    better: str                      # "lower" | "higher"
    objective: float
    unit: str
    description: str


#: THE table.  tools/chaos.py, tools/obswatch.py and bench.py all judge
#: from here; the README "Time series & SLOs" section documents each row
#: (enforced both ways, like the metrics table).
SLO_TABLE: Tuple[SLODef, ...] = (
    SLODef(
        name="convergence-settle",
        metrics=("serf.model.gossip.agreement",),
        planes=("host", "device"),
        better="lower", objective=1.0, unit="fraction of settle budget",
        description="post-heal re-convergence (full knowledge agreement "
                    "/ agreeing membership views) completes within the "
                    "plan's settle budget"),
    SLODef(
        name="false-dead",
        metrics=("serf.model.swim.false-dead",),
        planes=("host", "device"),
        better="lower", objective=0.0, unit="nodes",
        description="no responsive node is still believed DEAD after "
                    "heal (Lifeguard refutation must win)"),
    SLODef(
        name="shed-ratio",
        metrics=("serf.overload.ingress_shed",
                 "serf.overload.device_dropped"),
        planes=("host", "device"),
        better="lower", objective=0.95, unit="shed/offered",
        description="overload shedding stays a fraction of offered load "
                    "— even a storm must leave headroom admitted"),
    SLODef(
        name="query-p99",
        metrics=("serf.query.rtt-ms",),
        planes=("host",),
        better="lower", objective=750.0, unit="ms",
        description="query p99 round-trip over the retained sample ring "
                    "(loopback/LAN budget)"),
    SLODef(
        name="sustained-rps-ceiling",
        metrics=("serf.shard.rps", "serf.model.traffic.ceiling-rps"),
        planes=("device",),
        better="lower", objective=1.0, unit="measured/ceiling",
        description="measured sustained rounds/sec never exceeds the "
                    "analytic bandwidth ceiling — a number past physics "
                    "is a measurement bug, not a win"),
    # stage-latency SLOs (obs/lifecycle.py ledger — host hot path)
    SLODef(
        name="apply-stage-p99",
        metrics=("serf.lifecycle.stage-ms",),
        planes=("host",),
        better="lower", objective=50.0, unit="ms",
        description="p99 of the event-apply stage over sampled messages "
                    "(the serial-application budget ROADMAP item 1's "
                    "parallel-apply rebuild must beat)"),
    SLODef(
        name="queue-wait-share",
        metrics=("serf.lifecycle.stage-ms", "serf.lifecycle.e2e-ms"),
        planes=("host",),
        better="lower", objective=0.8, unit="fraction of e2e",
        description="queue-wait's share of sampled end-to-end message "
                    "latency — backpressure must not dominate the host "
                    "hot path"),
    # propagation-observatory SLOs (obs/propagation.py — both planes)
    SLODef(
        name="coverage-settle",
        metrics=("serf.propagation.cov-min", "serf.propagation.coverage"),
        planes=("host", "device"),
        better="lower", objective=1.0, unit="fraction of budget",
        description="traced facts reach 99% of alive nodes within the "
                    "run (device: t99 as a fraction of rounds run; "
                    "host: probe time-to-all as a fraction of the "
                    "settle budget) — a fact that never covers is a "
                    "dissemination regression"),
    SLODef(
        name="redundancy-ceiling",
        metrics=("serf.propagation.redundancy",
                 "serf.propagation.dup-ratio"),
        planes=("host", "device"),
        better="lower", objective=0.995, unit="redundant/sent",
        description="gossip redundancy stays below the ceiling — a "
                    "ratio at ~1.0 means the fabric ships only slots "
                    "nobody learns from (epidemic overhead is expected; "
                    "total waste is a regression)"),
    # key-rotation SLO (host/keyring + key_manager — encrypted runs)
    SLODef(
        name="rotation-latency",
        metrics=("serf.rotation.latency-ms",),
        planes=("host", "proc"),
        better="lower", objective=5.0, unit="s",
        description="post-heal keyring reconvergence — every live ring "
                    "on the rotation's next key as sole primary, old "
                    "key retired — completes within the bound (an "
                    "encrypted run that never reconverges judges inf)"),
)


def slo_names() -> Tuple[str, ...]:
    return tuple(d.name for d in SLO_TABLE)


def slo_def(name: str) -> SLODef:
    for d in SLO_TABLE:
        if d.name == name:
            return d
    raise KeyError(f"unknown SLO {name!r}; have {slo_names()}")


# ---------------------------------------------------------------------------
# burn rates + anomaly flags
# ---------------------------------------------------------------------------


def _burn_of(value: float, objective: float, better: str) -> float:
    """Normalized burn: >1 = out of objective, whichever direction is
    good.  Zero-objective SLOs (false-dead) burn 0 or the cap."""
    if better == "lower":
        if objective <= _EPS:
            return 0.0 if value <= _EPS else BURN_CAP
        return min(BURN_CAP, max(0.0, value) / objective)
    if value <= _EPS:
        return BURN_CAP if objective > _EPS else 0.0
    return min(BURN_CAP, objective / value)


def burn_rates(series: TimeSeries, objective: float, better: str,
               windows: Sequence[int] = BURN_WINDOWS) -> Dict[str, float]:
    """Multi-window burn: the series aggregated over each window (mean
    for gauges, sum for deltas), normalized against the objective."""
    out: Dict[str, float] = {}
    for w in windows:
        out[str(w)] = round(_burn_of(series.window(w), objective, better), 4)
    return out


def ewma_mad_flags(values: Sequence[float], alpha: float = 0.3,
                   k: float = 4.0, min_points: int = 8) -> List[int]:
    """Indices whose residual against the running EWMA deviates more
    than ``k`` robust standard deviations (1.4826·MAD) from the median
    residual.  Returns ``[]`` for short or flat series — a constant
    series can never be anomalous."""
    vs = [float(v) for v in values]
    if len(vs) < min_points:
        return []
    resid: List[float] = []
    ewma = vs[0]
    for v in vs[1:]:
        resid.append(v - ewma)
        ewma = alpha * v + (1 - alpha) * ewma
    med = sorted(resid)[len(resid) // 2]
    mad = sorted(abs(r - med) for r in resid)[len(resid) // 2]
    scale = 1.4826 * mad
    if scale <= _EPS:
        return []
    # resid[i] belongs to values index i+1
    return [i + 1 for i, r in enumerate(resid)
            if abs(r - med) > k * scale]


# ---------------------------------------------------------------------------
# verdicts
# ---------------------------------------------------------------------------


@dataclass
class SLOVerdict:
    slo: str
    plane: str
    ok: bool
    value: Optional[float]
    objective: float
    better: str
    unit: str
    detail: str = ""
    skipped: bool = False
    burn: Dict[str, float] = field(default_factory=dict)
    anomalies: int = 0

    def to_dict(self) -> Dict[str, Any]:
        v = self.value
        if v is not None and not math.isfinite(v):
            v = None
        return {"slo": self.slo, "plane": self.plane, "ok": self.ok,
                "skipped": self.skipped,
                "value": (round(v, 6) if v is not None else None),
                "objective": self.objective, "better": self.better,
                "unit": self.unit, "detail": self.detail,
                "burn": dict(self.burn), "anomalies": self.anomalies}


def judge(defn: SLODef, plane: str, value: Optional[float],
          series: Optional[TimeSeries] = None, detail: str = "",
          emit: bool = True) -> SLOVerdict:
    """Judge one SLO on one plane.  ``value=None`` = not measured in
    this run → a skipped (green-but-marked) verdict.  ``series``
    (optional ring evidence) adds multi-window burn rates and EWMA/MAD
    anomaly counts.  ``emit`` lands ``serf.slo.*`` gauges and — on
    breach — a ``slo-breach`` flight event + breach counter."""
    labels = {"slo": defn.name, "plane": plane}
    if value is None:
        return SLOVerdict(slo=defn.name, plane=plane, ok=True, value=None,
                          objective=defn.objective, better=defn.better,
                          unit=defn.unit, skipped=True,
                          detail=detail or "not measured in this run")
    value = float(value)
    if defn.better == "lower":
        ok = value <= defn.objective + _EPS
    else:
        ok = value >= defn.objective - _EPS
    burn: Dict[str, float] = {}
    anomalies = 0
    if series is not None and len(series):
        burn = burn_rates(series, defn.objective, defn.better)
        anomalies = len(ewma_mad_flags(series.values()))
    v = SLOVerdict(slo=defn.name, plane=plane, ok=ok, value=value,
                   objective=defn.objective, better=defn.better,
                   unit=defn.unit, detail=detail, burn=burn,
                   anomalies=anomalies)
    if emit:
        metrics.gauge("serf.slo.ok", 1.0 if ok else 0.0, labels)
        for w, b in burn.items():
            metrics.gauge("serf.slo.burn", b, dict(labels, window=w))
        if not ok:
            metrics.incr("serf.slo.breach", 1, labels)
            flight.record("slo-breach", slo=defn.name, plane=plane,
                          value=(value if math.isfinite(value) else None),
                          objective=defn.objective, unit=defn.unit,
                          detail=detail)
    return v


def all_ok(verdicts: Sequence[SLOVerdict]) -> bool:
    return all(v.ok for v in verdicts)


def format_verdicts(verdicts: Sequence[SLOVerdict], plane: str) -> str:
    """Same shape as ``InvariantReport.format`` so the chaos report
    reads as one column of judgments."""
    lines = [f"[{plane}] SLOs: "
             f"{'GREEN' if all_ok(verdicts) else 'BREACHED'}"]
    for v in verdicts:
        mark = "SKIP" if v.skipped else ("ok  " if v.ok else "FAIL")
        val = ("n/a" if v.value is None or not math.isfinite(v.value)
               else f"{v.value:.4g}")
        extra = ""
        if v.burn:
            extra = " burn " + "/".join(
                f"{w}:{b:g}" for w, b in sorted(v.burn.items(),
                                                key=lambda kv: int(kv[0])))
        if v.anomalies:
            extra += f" anomalies={v.anomalies}"
        lines.append(
            f"  {mark}  {v.slo} — {val} vs {v.objective:g} {v.unit}"
            + (f" ({v.detail})" if v.detail else "") + extra)
    return "\n".join(lines)


def verdicts_to_dict(verdicts: Sequence[SLOVerdict]) -> List[Dict[str, Any]]:
    return [v.to_dict() for v in verdicts]


# ---------------------------------------------------------------------------
# plane judges (chaos + obswatch drive these)
# ---------------------------------------------------------------------------


def _host_query_p99(sink: Optional[metrics.MetricsSink] = None
                    ) -> Optional[float]:
    """p99 over every retained ``serf.query.rtt-ms`` sample, merged
    across label sets; None when no query ever ran."""
    sink = sink or metrics.global_sink()
    samples: List[float] = []
    with sink._lock:
        for (name, _labels), h in sink.histograms.items():
            if name == "serf.query.rtt-ms":
                samples.extend(h.recent())
    if not samples:
        return None
    return metrics.percentile_of(sorted(samples), 99)


def judge_host_run(result, plan, emit: bool = True) -> List[SLOVerdict]:
    """SLO verdicts for a finished host chaos run
    (``faults.host.HostChaosResult``) — the same table the device judge
    uses, fed by the host runner's measurements."""
    out: List[SLOVerdict] = []
    for d in SLO_TABLE:
        if "host" not in d.planes:
            continue
        if d.name == "convergence-settle":
            # getattr throughout: chaos tests drive main() with stub
            # result objects — an SLO the stub can't answer is a
            # skipped verdict, never a crash
            settle_s = getattr(result, "settle_convergence_s", None)
            if settle_s is None:
                out.append(judge(d, "host", None, emit=emit))
            elif getattr(result, "settle_converged", True):
                value = settle_s / max(plan.settle_s, _EPS)
                out.append(judge(
                    d, "host", value,
                    detail=f"settled in {settle_s:.2f}s of "
                           f"{plan.settle_s:.2f}s", emit=emit))
            else:
                out.append(judge(
                    d, "host", math.inf,
                    detail="views never re-converged within the settle "
                           "budget", emit=emit))
        elif d.name == "false-dead":
            fd = getattr(result, "false_dead", 0)
            out.append(judge(
                d, "host", float(fd),
                detail=f"{fd} responsive node(s) held FAILED", emit=emit))
        elif d.name == "shed-ratio":
            load = getattr(result, "load", None)
            if load is None:
                out.append(judge(d, "host", 0.0,
                                 detail="no load offered", emit=emit))
            else:
                offered = load.events_offered + load.queries_offered
                ratio = load.ingress_shed / max(1, offered)
                out.append(judge(
                    d, "host", ratio,
                    series=_host_ratio_series(result),
                    detail=f"shed {load.ingress_shed} of {offered} "
                           "offered", emit=emit))
        elif d.name == "query-p99":
            out.append(judge(d, "host", _host_query_p99(), emit=emit))
        elif d.name == "apply-stage-p99":
            lc = getattr(result, "lifecycle", None)
            apply_row = _lifecycle_stage(lc, "apply")
            if apply_row is None:
                out.append(judge(d, "host", None,
                                 detail="no sampled messages", emit=emit))
            else:
                out.append(judge(
                    d, "host", apply_row["p99_ms"],
                    detail=f"over {apply_row['count']} sampled "
                           "message(s)", emit=emit))
        elif d.name == "queue-wait-share":
            lc = getattr(result, "lifecycle", None)
            share = (lc or {}).get("queue_wait_share")
            if share is None:
                out.append(judge(d, "host", None,
                                 detail="no sampled messages", emit=emit))
            else:
                out.append(judge(
                    d, "host", share,
                    detail=f"queue-wait owns {share:.0%} of sampled "
                           "e2e latency", emit=emit))
        elif d.name == "coverage-settle":
            prop = getattr(result, "propagation", None)
            if not prop or prop.get("trace") is None:
                out.append(judge(d, "host", None,
                                 detail="no propagation probe",
                                 emit=emit))
            elif prop.get("coverage", 0.0) < 1.0 - _EPS:
                out.append(judge(
                    d, "host", math.inf,
                    detail=f"probe reached {prop.get('reached', 0)} of "
                           f"{prop.get('nodes', 0)} node(s)", emit=emit))
            else:
                t_ms = prop.get("time_to_all_ms") or 0.0
                value = (t_ms / 1e3) / max(plan.settle_s, _EPS)
                out.append(judge(
                    d, "host", value,
                    detail=f"probe covered {prop.get('nodes', 0)} "
                           f"node(s) in {t_ms:.1f}ms of "
                           f"{plan.settle_s:.1f}s budget", emit=emit))
        elif d.name == "redundancy-ceiling":
            prop = getattr(result, "propagation", None)
            if not prop or (prop.get("seen", 0)
                            + prop.get("duplicates", 0)) <= 0:
                out.append(judge(d, "host", None,
                                 detail="no events disseminated",
                                 emit=emit))
            else:
                dr = prop["dup_ratio"]
                out.append(judge(
                    d, "host", dr,
                    detail=f"{prop['duplicates']} duplicate(s) of "
                           f"{prop['seen'] + prop['duplicates']} "
                           "delivered", emit=emit))
        elif d.name == "rotation-latency":
            rot = getattr(result, "rotation", None)
            if rot is None:
                out.append(judge(d, "host", None,
                                 detail="plan not encrypted", emit=emit))
            elif not rot.get("converged", False):
                out.append(judge(
                    d, "host", math.inf,
                    detail="keyrings never reconverged within "
                           f"{rot.get('reconcile_s')}s", emit=emit))
            else:
                out.append(judge(
                    d, "host", float(rot.get("latency_s", 0.0)),
                    detail=f"{len(rot.get('keyrings', {}))} ring(s) on "
                           f"primary {rot.get('expected_primary')} in "
                           f"{rot.get('reconcile_rounds')} round(s)",
                    emit=emit))
    return out


def _lifecycle_stage(lc, stage: str):
    """The named stage's row from a lifecycle ledger snapshot
    (``obs.lifecycle.LifecycleLedger.snapshot()``); None when the run
    carried no snapshot or the stage was never stamped."""
    if not lc:
        return None
    for row in lc.get("stages", ()):
        if row.get("stage") == stage and row.get("count"):
            return row
    return None


def _series_of(result, name: str) -> Optional[TimeSeries]:
    store = getattr(result, "series", None)
    return store.get(name) if isinstance(store, SeriesStore) else None


def _tail_after(series: Optional[TimeSeries],
                t0: float) -> Optional[TimeSeries]:
    """Derived series holding only points with ``t > t0`` — burn/anomaly
    evidence for objectives that only bind AFTER heal (a node believed
    dead mid-partition is the protocol working, not a breach)."""
    if series is None:
        return None
    out = TimeSeries(series.name, kind=series.kind,
                     capacity=series.capacity)
    for t, v in series.points():
        if t > t0:
            out.append(t, v)
    return out


def _ratio_series(store: Optional[SeriesStore]) -> Optional[TimeSeries]:
    """Derived shed/offered ratio series from the cumulative device
    ledgers — the burn-rate evidence must be in the SLO's own units
    (a ratio), not raw monotone counters."""
    if store is None:
        return None
    dropped = store.get("serf.overload.device_dropped")
    offered = store.get("serf.overload.device_offered")
    if dropped is None or offered is None:
        return None
    ratio = TimeSeries("shed-ratio", kind="gauge",
                       capacity=max(dropped.capacity, 8))
    for (t, dv), (_, ov) in zip(dropped.points(), offered.points()):
        ratio.append(t, dv / max(1.0, ov))
    return ratio


def _host_ratio_series(result) -> Optional[TimeSeries]:
    """Derived per-tick shed/(admitted+shed) ratio from the host
    sampler's delta rings — same rule as the device path: burn evidence
    in the SLO's own units, never raw event counts against a ratio
    objective."""
    shed = _series_of(result, "serf.overload.ingress_shed")
    admitted = _series_of(result, "serf.overload.ingress_admitted")
    if shed is None or admitted is None:
        return None
    # RUNNING cumulative ratio, aligned by a two-pointer timestamp walk:
    # the two counter rings start ticks apart and downsample on
    # different schedules, so per-index (or equal-stamp) pairing reads
    # time-misaligned, stride-mismatched deltas.  Delta-kind
    # downsampling preserves SUMS exactly, so prefix totals are
    # stride-independent — the ratio stays correct however either ring
    # has been merged.
    ratio = TimeSeries("shed-ratio", kind="gauge",
                       capacity=max(shed.capacity, 8))
    adm_pts = admitted.points()
    ai = 0
    cum_adm = 0.0
    cum_shed = 0.0
    for t, sv in shed.points():
        while ai < len(adm_pts) and adm_pts[ai][0] <= t:
            cum_adm += adm_pts[ai][1]
            ai += 1
        cum_shed += sv
        total = cum_shed + cum_adm
        ratio.append(t, cum_shed / total if total > 0 else 0.0)
    return ratio


def judge_device_run(result, plan, rps: Optional[float] = None,
                     ceiling: Optional[float] = None,
                     emit: bool = True) -> List[SLOVerdict]:
    """SLO verdicts for a finished device chaos run
    (``faults.device.DeviceChaosResult`` with telemetry collected).
    ``rps``/``ceiling`` feed the measurement-integrity SLO when the
    caller timed the run (obswatch/bench do; plain chaos runs skip it).
    """
    store: Optional[SeriesStore] = getattr(result, "telemetry", None)
    # point verdicts come from the EXACT final row the executor stashed
    # (DeviceChaosResult.telemetry_final) — the ring is burn/anomaly
    # EVIDENCE only, because its overflow downsampling pair-merges
    # values (a ≥capacity-round converged run would read 1.0 averaged
    # with its last converging neighbor and be misjudged)
    final: Optional[Dict[str, float]] = getattr(result, "telemetry_final",
                                                None)
    out: List[SLOVerdict] = []
    settle_start = getattr(result, "rounds_run", 0) - plan.settle_rounds
    for d in SLO_TABLE:
        if "device" not in d.planes:
            continue
        if d.name == "convergence-settle":
            # NOTE: the agreement ring is deliberately NOT passed to
            # judge() as burn evidence — its values (agreement, higher
            # = better) are not in this SLO's units (fraction of settle
            # budget, lower = better), so window burns computed from it
            # would read inverted.  It still drives the where-in-the-
            # window estimate below.
            series = store.get("serf.model.gossip.agreement") \
                if store is not None else None
            if final is None or "agreement" not in final:
                out.append(judge(d, "device", None,
                                 detail="telemetry not collected",
                                 emit=emit))
                continue
            final_v = final["agreement"]
            if final_v < 1.0 - 1e-6:
                out.append(judge(
                    d, "device", math.inf,
                    detail=f"final agreement {final_v:.4f} < 1.0",
                    emit=emit))
                continue
            # last (possibly merged) ring point that still had anything
            # to learn, relative to the settle window — an estimate of
            # WHERE in the settle budget convergence landed (values
            # before settle don't count: faults legitimately hold
            # agreement down)
            last_short = settle_start
            for t, v in (series.points() if series is not None else ()):
                if v < 1.0 - 1e-6:
                    last_short = t
            # clamp to the window: the final row already proved
            # convergence completed, and a pair-merged ring point can
            # blur the boundary by up to one stride
            used = min(max(0.0, last_short - settle_start + 1),
                       float(plan.settle_rounds))
            value = used / max(1, plan.settle_rounds)
            out.append(judge(
                d, "device", value,
                detail=f"converged ~{used:.0f} round(s) into the "
                       f"{plan.settle_rounds}-round settle window",
                emit=emit))
        elif d.name == "false-dead":
            if final is None or "false_dead" not in final:
                out.append(judge(d, "device", None,
                                 detail="telemetry not collected",
                                 emit=emit))
                continue
            fd = final["false_dead"]
            series = store.get("serf.model.swim.false-dead") \
                if store is not None else None
            out.append(judge(
                d, "device", fd,
                series=_tail_after(series, settle_start),
                detail=f"{fd:.0f} alive node(s) believed dead",
                emit=emit))
        elif d.name == "shed-ratio":
            dropped = getattr(result, "dropped", 0)
            offered = getattr(result, "offered", 0)
            out.append(judge(
                d, "device", dropped / max(1, offered),
                series=_ratio_series(store),
                detail=f"{dropped} clobbered in-window of {offered} "
                       "injected", emit=emit))
        elif d.name == "sustained-rps-ceiling":
            if rps is None or ceiling is None or ceiling <= 0:
                out.append(judge(d, "device", None,
                                 detail="throughput not timed in this "
                                        "run", emit=emit))
            else:
                out.append(judge(
                    d, "device", rps / ceiling,
                    detail=f"measured {rps:.1f} rps vs analytic ceiling "
                           f"{ceiling:.1f} rps", emit=emit))
        elif d.name == "coverage-settle":
            prop = getattr(result, "propagation", None)
            summary = (prop or {}).get("summary")
            if not summary:
                out.append(judge(d, "device", None,
                                 detail="propagation not traced",
                                 emit=emit))
                continue
            t99 = (summary.get("time_to") or {}).get("99")
            rounds = max(1, summary.get("rounds", 1))
            if t99 is None:
                out.append(judge(
                    d, "device", math.inf,
                    detail=f"sentinels never reached 99% coverage "
                           f"(final min "
                           f"{summary.get('final_coverage', 0):.3f})",
                    emit=emit))
            else:
                out.append(judge(
                    d, "device", t99 / rounds,
                    detail=f"99% coverage at round {t99} of {rounds}",
                    emit=emit))
        elif d.name == "redundancy-ceiling":
            prop = getattr(result, "propagation", None)
            summary = (prop or {}).get("summary")
            if not summary or summary.get("slots_sent", 0) <= 0:
                out.append(judge(d, "device", None,
                                 detail="propagation not traced",
                                 emit=emit))
            else:
                series = store.get("serf.propagation.redundancy") \
                    if store is not None else None
                out.append(judge(
                    d, "device", summary["redundancy"], series=series,
                    detail=f"{summary['slots_sent'] - summary['slots_learned']:.0f} "
                           f"redundant of {summary['slots_sent']:.0f} "
                           "slots sent", emit=emit))
    return out


# ---------------------------------------------------------------------------
# bench regression gate (bench.py embeds the verdict in BENCH_DETAIL.json)
# ---------------------------------------------------------------------------


def _lookup(detail: Dict[str, Any], dotted: str) -> Optional[float]:
    cur: Any = detail
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    try:
        return float(cur)
    except (TypeError, ValueError):
        return None


def score_bench(detail: Dict[str, Any], bands: Optional[Dict[str, Any]],
                platform: str) -> Dict[str, Any]:
    """Score a bench ``detail`` dict against the committed BASELINE.json
    bands for ``platform`` ("cpu" | "tpu").

    Band format (documented in README "Time series & SLOs")::

        "bands": {"cpu": {"cluster_round_sustained_rps": {"min": 2.0},
                          "sharded.sustained_rps": {"min": 1.0}}, ...}

    Keys are dotted paths into the detail dict; each band may carry
    ``min`` and/or ``max``.  A metric absent from the run is reported
    (never a violation — CPU fallbacks legitimately skip TPU-only
    sections).  No bands for the platform → ``rebaseline: true`` and a
    green verdict: the first round re-baselines instead of failing.
    """
    plat_bands = (bands or {}).get(platform) or {}
    checked: List[Dict[str, Any]] = []
    violations: List[str] = []
    missing: List[str] = []
    for dotted in sorted(plat_bands):
        band = plat_bands[dotted] or {}
        value = _lookup(detail, dotted)
        if value is None:
            missing.append(dotted)
            continue
        lo = band.get("min")
        hi = band.get("max")
        ok = ((lo is None or value >= lo)
              and (hi is None or value <= hi))
        checked.append({"metric": dotted, "value": value,
                        "min": lo, "max": hi, "ok": ok})
        if not ok:
            violations.append(dotted)
    return {
        "platform": platform,
        "rebaseline": not plat_bands,
        "checked": checked,
        "missing": missing,
        "violations": violations,
        "ok": not violations,
    }
