"""Bounded per-metric ring time series + the host-plane metrics sampler.

The obs stack before this module was *flat*: every metric is a
point-in-time value read at ``stats()`` time, so nothing could answer
"is convergence getting slower?" or "did shed rate spike during phase
2?" — the questions a production cluster gets asked continuously.  This
module is the time axis:

- :class:`TimeSeries` — a fixed-capacity ring of ``(t, value)`` points
  with **power-of-two downsampling on overflow**: when the ring fills,
  adjacent pairs merge (gauges average, deltas sum) and the append
  stride doubles, so a series that has absorbed a million points still
  holds ≤ ``capacity`` points *spanning the whole history* in O(capacity)
  memory.  Timestamps are monotonic by construction (a regressing clock
  is clamped and counted, never stored out of order).  JSON serde both
  ways (``to_dict``/``from_dict``) so rings ride chaos artifacts and
  ``BENCH_DETAIL.json``.

- :class:`SeriesStore` — a named collection of rings.  Producers append
  under one short lock per point (the bounded multi-producer hand-off
  shaped by Virtual-Link's ring architecture, PAPERS.md: telemetry must
  never become the load), readers snapshot.

- :class:`MetricsSampler` — the host-plane producer: snapshots the
  process :class:`~serf_tpu.utils.metrics.MetricsSink` at a cadence
  (counters land as per-interval **deltas**, gauges as levels) and
  drains the :class:`~serf_tpu.obs.flight.FlightRecorder` through its
  ``dump(since_seq=)`` cursor so per-kind flight-event rates become
  series too — the ring can answer "when did the drops start?" even
  after the flight ring itself evicted the events.

The device plane feeds the SAME ring format through the scan-carried
per-round telemetry rows (``models/swim.round_telemetry`` →
:func:`telemetry_to_store`): one ``device_get`` per *run*, never per
round, same pattern as the PR-9 digest plane.
"""

from __future__ import annotations

import json
import math
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from serf_tpu.obs import flight as _flight
from serf_tpu.utils import metrics

#: default ring capacity (power of two).  At the sampler's default
#: 250 ms cadence a fresh ring spans ~64 s at full resolution; each
#: downsample doubles the span.
DEFAULT_CAPACITY = 256
#: series value-kind: how pairs merge on downsample and how windows
#: aggregate — "gauge" (levels: mean) or "delta" (rates: sum).
KINDS = ("gauge", "delta")


class TimeSeries:
    """Fixed-capacity monotonic ring with power-of-two downsampling."""

    __slots__ = ("name", "kind", "capacity", "stride", "downsamples",
                 "appended", "clamped", "_t", "_v",
                 "_pend_n", "_pend_t", "_pend_v")

    def __init__(self, name: str, kind: str = "gauge",
                 capacity: int = DEFAULT_CAPACITY):
        if kind not in KINDS:
            raise ValueError(f"unknown series kind {kind!r} (one of {KINDS})")
        if capacity < 8 or capacity & (capacity - 1):
            raise ValueError(
                f"capacity must be a power of two >= 8, got {capacity}")
        self.name = name
        self.kind = kind
        self.capacity = capacity
        #: offered points per stored point (doubles at each downsample)
        self.stride = 1
        self.downsamples = 0
        #: total points ever offered to append()
        self.appended = 0
        #: timestamps clamped to keep the ring monotonic
        self.clamped = 0
        self._t: List[float] = []
        self._v: List[float] = []
        # pending accumulation bucket (stride > 1): points land here
        # until `stride` of them merge into one stored point
        self._pend_n = 0
        self._pend_t = 0.0
        self._pend_v = 0.0

    def __len__(self) -> int:
        return len(self._t)

    def append(self, t: float, value: float) -> None:
        """Offer one point.  ``t`` must be monotonic; a regressing clock
        is clamped to the last stored timestamp (and counted) rather
        than stored out of order — the serde/window math may assume
        sorted time."""
        self.appended += 1
        last = self._pend_t if self._pend_n else (
            self._t[-1] if self._t else float("-inf"))
        if t < last:
            t = last
            self.clamped += 1
        self._pend_n += 1
        self._pend_t = t
        self._pend_v += float(value)
        if self._pend_n < self.stride:
            return
        v = self._pend_v if self.kind == "delta" \
            else self._pend_v / self._pend_n
        self._pend_n = 0
        self._pend_v = 0.0
        self._t.append(t)
        self._v.append(v)
        if len(self._t) >= self.capacity:
            self._downsample()

    def _downsample(self) -> None:
        """Merge adjacent pairs in place: gauges average, deltas sum;
        the pair keeps the LATER timestamp (a delta bucket covers the
        interval ending at its stamp).  Stride doubles so the ring
        keeps spanning the whole history at halved resolution."""
        t, v = self._t, self._v
        nt: List[float] = []
        nv: List[float] = []
        i = 0
        while i + 1 < len(t):
            nt.append(t[i + 1])
            nv.append(v[i] + v[i + 1] if self.kind == "delta"
                      else 0.5 * (v[i] + v[i + 1]))
            i += 2
        if i < len(t):                  # odd tail carries over unmerged
            nt.append(t[i])
            nv.append(v[i])
        self._t, self._v = nt, nv
        self.stride *= 2
        self.downsamples += 1

    # -- reads ---------------------------------------------------------------

    def points(self, last: Optional[int] = None) -> List[Tuple[float, float]]:
        out = list(zip(self._t, self._v))
        return out[-last:] if last is not None else out

    def values(self, last: Optional[int] = None) -> List[float]:
        return self._v[-last:] if last is not None else list(self._v)

    def last(self) -> Optional[float]:
        return self._v[-1] if self._v else None

    def window(self, last: int) -> float:
        """Aggregate of the newest ``last`` stored points: mean for
        gauges, sum for deltas; 0.0 when empty."""
        vs = self.values(last=last)
        if not vs:
            return 0.0
        return sum(vs) if self.kind == "delta" else sum(vs) / len(vs)

    def summary(self) -> Dict[str, Any]:
        vs = self._v
        return {
            "name": self.name, "kind": self.kind, "points": len(vs),
            "appended": self.appended, "stride": self.stride,
            "downsamples": self.downsamples,
            "first_t": self._t[0] if vs else None,
            "last_t": self._t[-1] if vs else None,
            "last": vs[-1] if vs else None,
            "min": min(vs) if vs else None,
            "max": max(vs) if vs else None,
            "mean": sum(vs) / len(vs) if vs else None,
        }

    # -- serde ---------------------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name, "kind": self.kind, "capacity": self.capacity,
            "stride": self.stride, "downsamples": self.downsamples,
            "appended": self.appended, "clamped": self.clamped,
            "t": list(self._t), "v": list(self._v),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TimeSeries":
        ts = cls(d["name"], kind=d.get("kind", "gauge"),
                 capacity=int(d.get("capacity", DEFAULT_CAPACITY)))
        t = [float(x) for x in d.get("t", ())]
        v = [float(x) for x in d.get("v", ())]
        if len(t) != len(v):
            raise ValueError(
                f"series {d.get('name')!r}: len(t) {len(t)} != len(v) "
                f"{len(v)}")
        if any(b < a for a, b in zip(t, t[1:])):
            raise ValueError(
                f"series {d.get('name')!r}: non-monotonic timestamps")
        if len(t) > ts.capacity:
            raise ValueError(
                f"series {d.get('name')!r}: {len(t)} points exceed "
                f"capacity {ts.capacity}")
        ts._t, ts._v = t, v
        ts.stride = max(1, int(d.get("stride", 1)))
        ts.downsamples = int(d.get("downsamples", 0))
        ts.appended = int(d.get("appended", len(t)))
        ts.clamped = int(d.get("clamped", 0))
        return ts

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    @classmethod
    def from_json(cls, s: str) -> "TimeSeries":
        return cls.from_dict(json.loads(s))


class SeriesStore:
    """A named collection of rings with one short lock per operation."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._series: Dict[str, TimeSeries] = {}

    def series(self, name: str, kind: str = "gauge") -> TimeSeries:
        """Get-or-create; an existing series keeps its original kind."""
        with self._lock:
            ts = self._series.get(name)
            if ts is None:
                ts = TimeSeries(name, kind=kind, capacity=self.capacity)
                self._series[name] = ts
            return ts

    def append(self, name: str, t: float, value: float,
               kind: str = "gauge") -> None:
        ts = self.series(name, kind=kind)
        with self._lock:
            ts.append(t, value)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def get(self, name: str) -> Optional[TimeSeries]:
        with self._lock:
            return self._series.get(name)

    def __len__(self) -> int:
        with self._lock:
            return len(self._series)

    def summaries(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {n: s.summary() for n, s in sorted(self._series.items())}

    def total_downsamples(self) -> int:
        """Sum of downsample events across every series — an O(series)
        attribute read (the sampler polls this every tick; summaries()
        would be O(series × capacity) of throwaway arithmetic)."""
        with self._lock:
            return sum(s.downsamples for s in self._series.values())

    def tail(self, last: int = 32) -> Dict[str, List[Tuple[float, float]]]:
        """Newest ``last`` points per series — the obstop/obswatch
        ``--json`` ring-tail payload."""
        with self._lock:
            return {n: s.points(last=last)
                    for n, s in sorted(self._series.items())}

    def to_dict(self) -> Dict[str, Any]:
        with self._lock:
            return {"capacity": self.capacity,
                    "series": {n: s.to_dict()
                               for n, s in sorted(self._series.items())}}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SeriesStore":
        st = cls(capacity=int(d.get("capacity", DEFAULT_CAPACITY)))
        for n, sd in d.get("series", {}).items():
            st._series[n] = TimeSeries.from_dict(sd)
        return st


# ---------------------------------------------------------------------------
# sparklines (obstop --watch)
# ---------------------------------------------------------------------------

_SPARK = "▁▂▃▄▅▆▇█"


def sparkline(values: Sequence[float], width: int = 16) -> str:
    """Unicode block sparkline of the newest ``width`` values."""
    vs = [float(v) for v in values][-width:]
    if not vs:
        return ""
    lo, hi = min(vs), max(vs)
    if not math.isfinite(lo) or not math.isfinite(hi) or hi <= lo:
        return _SPARK[0] * len(vs)
    span = hi - lo
    return "".join(
        _SPARK[min(len(_SPARK) - 1, int((v - lo) / span * len(_SPARK)))]
        for v in vs)


# ---------------------------------------------------------------------------
# the host-plane sampler
# ---------------------------------------------------------------------------

#: sampler flight-rate series are namespaced so they can never collide
#: with sink metric names
FLIGHT_SERIES_PREFIX = "flight."
#: default sampler cadence (seconds)
DEFAULT_INTERVAL_S = 0.25


class MetricsSampler:
    """Snapshots the metrics sink + flight recorder into ring series.

    One :meth:`sample` call is one tick: every counter in the sink lands
    as a per-tick **delta** (rate numerator), every gauge as a level
    (multiple label sets of one name aggregate: counters sum, gauges
    average), and the flight recorder's new events since the last tick
    (via the ``dump(since_seq=)`` cursor) land as per-kind delta series
    ``flight.<kind>``.  Drive it either manually (tests, chaos runners)
    or as an asyncio task via :meth:`start`/:meth:`stop`.

    Sampler self-telemetry: ``serf.ts.samples`` (ticks),
    ``serf.ts.points`` (points appended), ``serf.ts.downsamples``
    (ring downsample events across the store).
    """

    def __init__(self, store: Optional[SeriesStore] = None,
                 sink: Optional[metrics.MetricsSink] = None,
                 recorder: Optional[_flight.FlightRecorder] = None,
                 interval_s: float = DEFAULT_INTERVAL_S,
                 clock=time.monotonic):
        self.store = store if store is not None else SeriesStore()
        self._sink = sink
        self._recorder = recorder
        self.interval_s = max(0.01, float(interval_s))
        self._clock = clock
        # baseline BOTH cursors at construction: deltas mean "since this
        # sampler started", so counter totals accumulated by earlier
        # runs on a shared (process-global) sink can never land as a
        # bogus first-tick rate spike — same rule as the flight cursor
        self._prev_counters: Dict[str, float] = self._counter_totals()
        self._cursor = self._rec().last_seq
        self._prev_downsamples = self.store.total_downsamples()
        self.ticks = 0
        self._task = None
        self._stop = None

    def _rec(self) -> _flight.FlightRecorder:
        return self._recorder if self._recorder is not None \
            else _flight.global_recorder()

    def _sink_now(self) -> metrics.MetricsSink:
        return self._sink if self._sink is not None else metrics.global_sink()

    def _counter_totals(self) -> Dict[str, float]:
        sink = self._sink_now()
        out: Dict[str, float] = {}
        with sink._lock:
            for (name, _labels), v in sink.counters.items():
                out[name] = out.get(name, 0.0) + v
        return out

    def sample(self, now: Optional[float] = None) -> Dict[str, float]:
        """One tick; returns the values appended this tick (by name)."""
        now = self._clock() if now is None else float(now)
        sink = self._sink_now()
        with sink._lock:
            counters: Dict[str, float] = {}
            for (name, _labels), v in sink.counters.items():
                counters[name] = counters.get(name, 0.0) + v
            gauges: Dict[str, List[float]] = {}
            for (name, _labels), v in sink.gauges.items():
                gauges.setdefault(name, []).append(v)

        appended: Dict[str, float] = {}
        for name in sorted(counters):
            delta = counters[name] - self._prev_counters.get(name, 0.0)
            # a reset sink (tests) must not record a huge negative rate
            if delta < 0:
                delta = counters[name]
            self.store.append(name, now, delta, kind="delta")
            appended[name] = delta
        self._prev_counters = counters
        for name in sorted(gauges):
            vs = gauges[name]
            level = sum(vs) / len(vs)
            self.store.append(name, now, level, kind="gauge")
            appended[name] = level

        # flight-event rates through the since_seq cursor: per-kind
        # counts of events recorded since the previous tick.  The cursor
        # guarantees each retained event is counted exactly once; under
        # ring eviction (a burst larger than the flight ring between
        # ticks) the per-kind rate is a floor — evicted events are
        # unattributable by design (their total still shows in the
        # recorder's ``dropped`` property)
        rec = self._rec()
        events = rec.dump(since_seq=self._cursor)
        self._cursor = rec.last_seq
        by_kind: Dict[str, int] = {}
        for e in events:
            by_kind[e["kind"]] = by_kind.get(e["kind"], 0) + 1
        for kind in sorted(by_kind):
            name = FLIGHT_SERIES_PREFIX + kind
            self.store.append(name, now, float(by_kind[kind]), kind="delta")
            appended[name] = float(by_kind[kind])

        self.ticks += 1
        metrics.incr("serf.ts.samples")
        metrics.incr("serf.ts.points", float(len(appended)))
        total_ds = self.store.total_downsamples()
        if total_ds > self._prev_downsamples:
            metrics.incr("serf.ts.downsamples",
                         float(total_ds - self._prev_downsamples))
            self._prev_downsamples = total_ds
        return appended

    # -- asyncio driver ------------------------------------------------------

    def start(self):
        """Spawn the periodic sampling task on the running loop."""
        import asyncio

        from serf_tpu.utils.tasks import spawn_logged

        if self._task is not None:
            return self._task
        self._stop = asyncio.Event()

        async def run() -> None:
            while not self._stop.is_set():
                try:
                    await asyncio.wait_for(self._stop.wait(),
                                           timeout=self.interval_s)
                except asyncio.TimeoutError:
                    pass
                else:
                    break
                self.sample()

        self._task = spawn_logged(run(), "metrics-sampler")
        return self._task

    async def stop(self) -> None:
        """Stop the task and take one final sample (so short runs still
        land their tail in the rings)."""
        import asyncio

        if self._task is None:
            return
        self._stop.set()
        self._task.cancel()
        try:
            await self._task
        except (asyncio.CancelledError, Exception):  # noqa: BLE001
            pass
        self._task = None
        self.sample()


# ---------------------------------------------------------------------------
# device-plane telemetry rows -> the same ring format
# ---------------------------------------------------------------------------

#: TELEMETRY_FIELDS (models/swim.py) -> declared metric names; cumulative
#: ledgers keep their raw (monotone) values as gauge series — the judge
#: diffs them when it needs rates
TELEMETRY_SERIES: Tuple[Tuple[str, str], ...] = (
    ("alive", "serf.model.gossip.alive"),
    ("facts_valid", "serf.model.gossip.facts-valid"),
    ("agreement", "serf.model.gossip.agreement"),
    ("coverage", "serf.model.gossip.coverage"),
    ("overflow", "serf.overload.device_dropped"),
    ("injected", "serf.overload.device_offered"),
    ("suspicions", "serf.model.swim.live-suspicions"),
    ("false_dead", "serf.model.swim.false-dead"),
)


def telemetry_to_store(rows, base_round: int = 0,
                       store: Optional[SeriesStore] = None,
                       capacity: int = DEFAULT_CAPACITY) -> SeriesStore:
    """Convert stacked per-round telemetry rows (``f32[R, F]``, already on
    host — the caller did its one ``device_get``) into ring series keyed
    by the declared metric names; timestamps are absolute round indices
    (``base_round + i + 1``: row i describes the state AFTER that round).
    """
    from serf_tpu.models.swim import TELEMETRY_FIELDS

    store = store if store is not None else SeriesStore(capacity=capacity)
    name_of = dict(TELEMETRY_SERIES)
    for i, row in enumerate(rows):
        t = float(base_round + i + 1)
        for j, field in enumerate(TELEMETRY_FIELDS):
            store.append(name_of[field], t, float(row[j]), kind="gauge")
    return store


