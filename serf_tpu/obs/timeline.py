"""Unified cross-plane timeline: every observability surface on ONE
correlated timebase, exported as Chrome-trace-event JSON.

The repo grew its observability surfaces across six PRs — trace spans
(obs/trace), flight events (obs/flight), message-lifecycle stage clocks
(obs/lifecycle), per-round device telemetry (models/swim →
obs/timeseries), control decisions (serf_tpu/control), SLO verdicts
(obs/slo), and propagation tracing (obs/propagation: coverage /
redundancy curves + traced-probe provenance) — each excellent alone and
none correlated with the others.
This module is the single view a real fleet consumes: one
Perfetto-loadable JSON bundle (the Chrome ``traceEvents`` format) where
a probe span, the flight event it caused, the lifecycle stage waterfall
of the message it delayed, the device round that judged the fallout,
the control decision that reacted, and the SLO breach that recorded it
all sit on one wall-clock axis.

**Lanes** (stable, deterministic): each NODE is a trace *process*
(pid), with per-surface *threads* — spans, flight, per-lifecycle-STAGE
lanes, control, SLO.  The device plane is its own process; its
round-indexed series are mapped onto the host wall clock through the
run's start/stop anchors (:class:`DeviceRunAnchors` — round r of R
lands at ``t0 + r/R · (t1 - t0)``, exact at the endpoints, linear
between: the scan is round-synchronous so this is the honest
within-run interpolation).

**Event shapes**: finished spans export as matched ``B``/``E`` pairs
(sub-microsecond spans are stretched to 1 µs so viewers render them);
flight events, control decisions and SLO verdicts as instant (``i``)
events; device telemetry and lifecycle aggregates as counter (``C``)
tracks; ``slow-message`` flight events additionally reconstruct their
per-stage waterfall as ``X`` events on the owning node's stage lanes
(the stage breakdown rides the flight event — obs/lifecycle).

:func:`validate_timeline` is the schema check the tier-1 test pins:
monotonic timestamps, every ``B`` matched by an ``E`` on its lane,
every referenced pid/tid carrying name metadata.  ``tools/obsexport.py``
is the CLI; ``tools/chaos.py --export-timeline`` and ``bench.py
--export-timeline`` ship a bundle beside their reports.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any, Dict, Iterable, List, Optional, Sequence

#: the surfaces a full bundle carries (each is an event ``cat``); the
#: all-surface tier-1 test holds an exported chaos bundle to this tuple
SURFACES = ("span", "flight", "lifecycle", "device", "control", "slo",
            "propagation", "watchdog")

#: fixed per-process thread lanes (lifecycle stages get 10 + stage idx;
#: overlapping-span overflow lanes get 100 + lane idx)
TID_SPANS = 1
TID_FLIGHT = 2
TID_CONTROL = 3
TID_SLO = 4
TID_PROPAGATION = 5
TID_WATCHDOG = 6
TID_STAGE_BASE = 10
TID_SPAN_EXTRA = 100

#: process ids: 1 = the cluster-scope host process (events with no node
#: attribution), 2.. = nodes in sorted-id order, 1000 = the device plane
PID_CLUSTER = 1
PID_DEVICE = 1000

#: flight kinds that belong to dedicated lanes rather than the flight one
_FLIGHT_ROUTES = {"control-decision": ("control", TID_CONTROL),
                  "slo-breach": ("slo", TID_SLO),
                  "propagation-trace": ("propagation", TID_PROPAGATION),
                  "watchdog-breach": ("watchdog", TID_WATCHDOG)}

#: minimum exported span duration (µs): matched B/E pairs must be
#: strictly orderable even for sub-µs spans
_MIN_SPAN_US = 1.0


@dataclass(frozen=True)
class DeviceRunAnchors:
    """Wall-clock anchors of one device run: rounds ``base_round ..
    base_round + rounds`` ran between ``wall_start`` and ``wall_end``."""

    wall_start: float
    wall_end: float
    rounds: int
    base_round: int = 0

    def round_wall(self, round_index: float) -> float:
        """Absolute round index -> wall seconds (clamped linear map)."""
        if self.rounds <= 0:
            return self.wall_start
        frac = (float(round_index) - self.base_round) / self.rounds
        frac = min(1.0, max(0.0, frac))
        return self.wall_start + frac * (self.wall_end - self.wall_start)


class PiecewiseAnchors:
    """Round→wall mapping from per-scan-chunk wall stamps
    (``DeviceChaosResult.scan_walls``: ``(base_round, rounds, t0, t1)``
    per chunk): each chunk maps its rounds linearly across its OWN
    window, so a first-chunk compile skews only that chunk instead of
    stretching the whole run (the coarse single-window
    :class:`DeviceRunAnchors` failure mode).  Implements the same
    ``round_wall``/``wall_end`` protocol."""

    def __init__(self, scan_walls: Sequence[tuple]):
        if not scan_walls:
            raise ValueError("PiecewiseAnchors needs at least one chunk")
        self._chunks = [
            (int(b), int(r), float(t0), float(t1))
            for b, r, t0, t1 in sorted(scan_walls, key=lambda c: c[0])]

    @property
    def wall_end(self) -> float:
        return self._chunks[-1][3]

    def round_wall(self, round_index: float) -> float:
        r = float(round_index)
        for base, rounds, t0, t1 in self._chunks:
            if r <= base + rounds or (base, rounds, t0, t1) == \
                    self._chunks[-1]:
                return DeviceRunAnchors(
                    wall_start=t0, wall_end=t1, rounds=rounds,
                    base_round=base).round_wall(r)
        return self._chunks[-1][3]


class TimelineBuilder:
    """Accumulates surface events (wall-clock seconds), then ``build()``
    normalizes to one sorted ``traceEvents`` list with stable pid/tid
    metadata.  Node names map to pids deterministically (sorted order),
    so two exports of the same run produce the same mapping."""

    def __init__(self, meta: Optional[Dict[str, Any]] = None):
        self._events: List[Dict[str, Any]] = []
        self._seq = 0
        self._nodes: set = set()
        #: stage-lane registry, shared across processes: stage name ->
        #: tid offset is GLOBAL so "queue-wait" is the same lane index
        #: on every node's process
        self._stages: List[str] = []
        self._device_used = False
        self.meta = dict(meta or {})

    # -- lane bookkeeping ----------------------------------------------------

    def _stage_tid(self, stage: str) -> int:
        if stage not in self._stages:
            self._stages.append(stage)
        return TID_STAGE_BASE + self._stages.index(stage)

    def _push(self, ph: str, cat: str, name: str, ts: float, pid_key,
              tid: int, *, dur_us: Optional[float] = None,
              args: Optional[Dict[str, Any]] = None,
              tie: int = 0) -> None:
        # pid_key: None/"" = cluster process, PID_DEVICE = device plane,
        # any other value = a node id (registered for the deterministic
        # sorted-order pid assignment at build())
        if pid_key in (None, ""):
            pid_key = None
        elif pid_key != PID_DEVICE:
            pid_key = str(pid_key)
            self._nodes.add(pid_key)
        self._seq += 1
        ev = {"ph": ph, "cat": cat, "name": name, "_wall": float(ts),
              "_pid_key": pid_key, "tid": int(tid), "_tie": tie,
              "_seq": self._seq}
        if dur_us is not None:
            ev["dur"] = max(float(dur_us), _MIN_SPAN_US)
        if args:
            ev["args"] = args
        if ph == "i":
            ev["s"] = "t"
        self._events.append(ev)

    # -- surfaces ------------------------------------------------------------

    def add_spans(self, spans: Iterable[Dict[str, Any]]) -> None:
        """Finished trace spans (``obs.trace.trace_dump()`` dicts) as
        matched B/E pairs.  A lane's B/E stream must nest strictly, but
        asyncio interleaves spans that merely OVERLAP (two concurrent
        queries on one node), so spans are greedily packed onto
        sub-lanes: a span shares a lane only when the lane is idle or
        its innermost open span fully contains it — nesting per lane
        holds by construction, whatever the source interleaving."""
        by_node: Dict[Any, List[tuple]] = {}
        for s in spans:
            node = (s.get("attrs") or {}).get("node")
            start = float(s.get("start", 0.0))
            dur_us = max(float(s.get("duration_ms", 0.0)) * 1e3,
                         _MIN_SPAN_US)
            by_node.setdefault(node, []).append(
                (start, start + dur_us / 1e6, s))
        for node, items in by_node.items():
            items.sort(key=lambda t: (t[0], -t[1]))
            lanes: List[List[float]] = []       # per-lane open-end stacks
            for start, end, s in items:
                lane = None
                for li, ends in enumerate(lanes):
                    while ends and ends[-1] <= start:
                        ends.pop()              # those spans closed
                    if not ends or ends[-1] >= end:
                        lane = li
                        break
                if lane is None:
                    lanes.append([])
                    lane = len(lanes) - 1
                depth = len(lanes[lane])
                lanes[lane].append(end)
                tid = TID_SPANS if lane == 0 else TID_SPAN_EXTRA + lane
                args = {k: _jsonable(v)
                        for k, v in (s.get("attrs") or {}).items()}
                args["status"] = s.get("status", "ok")
                self._push("B", "span", s.get("name", "?"), start, node,
                           tid, args=args, tie=depth)
                self._push("E", "span", s.get("name", "?"), end, node,
                           tid, tie=-depth)

    def add_flight(self, events: Iterable[Dict[str, Any]],
                   reconstruct_slow: bool = True) -> None:
        """Flight-recorder events as instants.  ``control-decision`` and
        ``slo-breach`` kinds route to their own lanes; ``slow-message``
        events additionally reconstruct the per-stage waterfall carried
        in their ``stages_ms`` payload onto the node's stage lanes."""
        for ev in events:
            kind = ev.get("kind", "?")
            node = ev.get("node")
            cat, tid = _FLIGHT_ROUTES.get(kind, ("flight", TID_FLIGHT))
            args = {k: _jsonable(v) for k, v in ev.items()
                    if k not in ("kind", "time", "monotonic", "node")}
            self._push("i", cat, kind, float(ev.get("time", 0.0)),
                       node, tid, args=args)
            if reconstruct_slow and kind == "slow-message" \
                    and isinstance(ev.get("stages_ms"), dict):
                self._reconstruct_slow(ev, node)

    def _reconstruct_slow(self, ev: Dict[str, Any],
                          node: Optional[str]) -> None:
        """One sampled slow message's stage clocks as X events ending at
        the flight event's wall time, laid back-to-back in hot-path
        stage order (the chain contract: stages partition end-to-end)."""
        from serf_tpu.obs.lifecycle import STAGES
        stages = ev["stages_ms"]
        ordered = [s for s in STAGES if s in stages] \
            + sorted(set(stages) - set(STAGES))
        end = float(ev.get("time", 0.0))
        start = end - sum(float(stages[s]) for s in ordered) / 1e3
        t = start
        for s in ordered:
            dur_us = float(stages[s]) * 1e3
            self._push("X", "lifecycle", s, t, node,
                       self._stage_tid(s), dur_us=dur_us,
                       args={"message": ev.get("message"),
                             "e2e_ms": ev.get("e2e_ms")})
            t += dur_us / 1e6

    def add_lifecycle(self, snapshot: Dict[str, Any], at_wall: float,
                      node: Optional[str] = None) -> None:
        """A lifecycle-ledger snapshot as counter tracks (per-stage mean
        and p99 ms + the e2e percentiles) stamped at ``at_wall`` — the
        aggregate view that is always present even when no sampled
        message crossed the slow threshold."""
        for row in snapshot.get("stages") or ():
            self._push("C", "lifecycle", f"stage.{row['stage']}", at_wall,
                       node, self._stage_tid(row["stage"]),
                       args={"mean_ms": row.get("mean_ms"),
                             "p99_ms": row.get("p99_ms"),
                             "share": row.get("share")})
        e2e = snapshot.get("e2e") or {}
        if e2e:
            self._push("C", "lifecycle", "e2e", at_wall, node,
                       TID_STAGE_BASE - 1,
                       args={"p50_ms": e2e.get("p50_ms"),
                             "p99_ms": e2e.get("p99_ms")})

    def add_device_telemetry(self, rows: Sequence[Sequence[float]],
                             anchors: DeviceRunAnchors,
                             fields: Optional[Sequence[str]] = None,
                             base_round: Optional[int] = None) -> None:
        """Per-round device telemetry rows (``f32[R, F]`` on host) as
        one multi-series counter track in the device process, rounds
        mapped onto the wall clock through ``anchors``."""
        if fields is None:
            from serf_tpu.models.swim import TELEMETRY_FIELDS
            fields = TELEMETRY_FIELDS
        self._device_used = True
        base = anchors.base_round if base_round is None else base_round
        for i, row in enumerate(rows):
            t = anchors.round_wall(base + i + 1)
            args = {f: float(v) for f, v in zip(fields, row)}
            args["round"] = base + i + 1
            self._push("C", "device", "telemetry", t, PID_DEVICE,
                       TID_SPANS, args=args)

    def add_device_series(self, store, anchors: DeviceRunAnchors) -> None:
        """A round-indexed ``SeriesStore`` (DeviceChaosResult.telemetry)
        as per-metric counter tracks in the device process.  The
        propagation observatory's ``serf.propagation.*`` series route to
        their own lane (the Perfetto "propagation" thread) so coverage
        and redundancy curves read beside — not under — the telemetry
        row."""
        self._device_used = True
        for name in store.names():
            ts = store.get(name)
            prop = name.startswith("serf.propagation.")
            cat = "propagation" if prop else "device"
            tid = TID_PROPAGATION if prop else TID_SPANS
            for t_round, v in ts.points():
                self._push("C", cat, name,
                           anchors.round_wall(t_round), PID_DEVICE,
                           tid, args={"value": float(v),
                                      "round": t_round})

    def add_control_decisions(self, decisions: Iterable[Dict[str, Any]],
                              anchors: DeviceRunAnchors) -> None:
        """Device-plane control decisions (round-stamped dicts from
        ``DeviceChaosResult.control_decisions``) as instants on the
        device process's control lane.  (Host-plane decisions already
        arrive as ``control-decision`` flight events.)"""
        self._device_used = True
        for d in decisions:
            self._push("i", "control", "control-decision",
                       anchors.round_wall(d.get("round", 0)), PID_DEVICE,
                       TID_CONTROL, args={k: _jsonable(v)
                                          for k, v in d.items()})

    def add_control_values(self, values: Dict[str, Any], at_wall: float,
                           plane: str = "host") -> None:
        """Final controller knob values as one counter sample on the
        control lane — present whenever a controller was ATTACHED, even
        if it never actuated (zero decisions is itself evidence)."""
        pid_key = PID_DEVICE if plane == "device" else None
        if plane == "device":
            self._device_used = True
        self._push("C", "control", "knobs", at_wall, pid_key, TID_CONTROL,
                   args={str(k): _jsonable(v) for k, v in values.items()})

    def add_slo_verdicts(self, verdicts: Iterable[Dict[str, Any]],
                         at_wall: float, plane: str = "host") -> None:
        """SLO verdict dicts (``obs.slo.verdicts_to_dict`` rows) as
        instants — breaches AND greens, so the lane always exists and a
        breach is visible as the odd one out."""
        pid_key = PID_DEVICE if plane == "device" else None
        if plane == "device":
            self._device_used = True
        for v in verdicts:
            name = v.get("slo", v.get("name", "?"))
            self._push("i", "slo",
                       f"{name}:{'ok' if v.get('ok') else 'BREACH'}",
                       at_wall, pid_key, TID_SLO,
                       args={k: _jsonable(x) for k, x in v.items()})

    def add_watchdog(self, state: Dict[str, Any], at_wall: float) -> None:
        """A host watchdog run record (``obs.watchdog.Watchdog.state()``)
        on the dedicated watchdog lane: every retained verdict as an
        instant at ITS OWN wall time (breaches read as the odd ones out,
        like the SLO lane), plus one summary counter sample at
        ``at_wall`` so the lane exists even for a zero-tick run."""
        for v in state.get("history") or ():
            breaches = v.get("breaches") or []
            name = ("tick:ok" if not breaches
                    else "BREACH:" + ",".join(breaches))
            self._push("i", "watchdog", name,
                       float(v.get("wall_time", at_wall)), None,
                       TID_WATCHDOG,
                       args={k: _jsonable(x) for k, x in v.items()})
        self._push("C", "watchdog", "watchdog", at_wall, None,
                   TID_WATCHDOG,
                   args={"ticks": state.get("ticks", 0),
                         "breaches": state.get("breaches", 0),
                         "bundles": len(state.get("bundles") or ())})

    def add_device_invariants(self, rows: Sequence[Sequence[float]],
                              anchors: DeviceRunAnchors,
                              base_round: Optional[int] = None) -> None:
        """Per-round device invariant rows (the in-scan watchdog output,
        ``f32[R, F]``) as a counter track on the device process's
        watchdog lane, rounds mapped like the telemetry track."""
        from serf_tpu.obs.watchdog import INVARIANT_FIELDS
        self._device_used = True
        base = anchors.base_round if base_round is None else base_round
        for i, row in enumerate(rows):
            args = {f: float(v)
                    for f, v in zip(INVARIANT_FIELDS, row)}
            args["round"] = base + i + 1
            self._push("C", "watchdog", "invariants",
                       anchors.round_wall(base + i + 1), PID_DEVICE,
                       TID_WATCHDOG, args=args)

    # -- assembly ------------------------------------------------------------

    def build(self) -> Dict[str, Any]:
        """Normalize: assign node pids (sorted order), convert wall
        seconds to relative microseconds, sort with B/E-safe
        tie-breaking, prepend process/thread name metadata."""
        pid_of: Dict[Any, int] = {None: PID_CLUSTER, PID_DEVICE: PID_DEVICE}
        for i, node in enumerate(sorted(self._nodes)):
            pid_of[node] = 2 + i
        walls = [e["_wall"] for e in self._events]
        t0 = min(walls) if walls else 0.0
        out: List[Dict[str, Any]] = []
        used: Dict[int, set] = {}
        for e in self._events:
            pid = pid_of.get(e["_pid_key"], PID_CLUSTER)
            ev = {k: v for k, v in e.items()
                  if not k.startswith("_")}
            ev["pid"] = pid
            ev["ts"] = round((e["_wall"] - t0) * 1e6, 3)
            used.setdefault(pid, set()).add(ev["tid"])
            out.append((e["_wall"], _PH_RANK.get(e["ph"], 1), e["_tie"],
                        e["_seq"], ev))
        out.sort(key=lambda t: t[:4])
        events = [e for *_k, e in out]
        meta_events: List[Dict[str, Any]] = []
        stage_names = self._stages
        for pid in sorted(used):
            pname = "device-plane" if pid == PID_DEVICE else (
                "cluster" if pid == PID_CLUSTER else
                f"node:{sorted(self._nodes)[pid - 2]}")
            meta_events.append(_meta("process_name", pid, 0,
                                     {"name": pname}))
            meta_events.append(_meta("process_sort_index", pid, 0,
                                     {"sort_index": pid}))
            for tid in sorted(used[pid]):
                if tid == TID_SPANS:
                    tname = "telemetry" if pid == PID_DEVICE else "spans"
                elif tid == TID_FLIGHT:
                    tname = "flight"
                elif tid == TID_CONTROL:
                    tname = "control"
                elif tid == TID_SLO:
                    tname = "slo"
                elif tid == TID_PROPAGATION:
                    tname = "propagation"
                elif tid == TID_WATCHDOG:
                    tname = "watchdog"
                elif tid == TID_STAGE_BASE - 1:
                    tname = "lifecycle.e2e"
                elif tid >= TID_SPAN_EXTRA:
                    tname = f"spans-{tid - TID_SPAN_EXTRA + 1}"
                elif tid >= TID_STAGE_BASE and \
                        tid - TID_STAGE_BASE < len(stage_names):
                    tname = f"stage.{stage_names[tid - TID_STAGE_BASE]}"
                else:
                    tname = f"lane-{tid}"
                meta_events.append(_meta("thread_name", pid, tid,
                                         {"name": tname}))
        return {
            "traceEvents": meta_events + events,
            "displayTimeUnit": "ms",
            "otherData": dict(self.meta, wall_t0=t0,
                              surfaces=sorted({e["cat"] for e in events})),
        }


#: same-timestamp ordering: close (E) before open (B) so a span ending
#: exactly when a sibling starts keeps the lane stack balanced; the
#: per-span depth tie (B: parent first, E: child first) handles shared
#: endpoints inside one nest
_PH_RANK = {"E": 0, "M": 0, "C": 1, "i": 1, "X": 1, "B": 2}


def _meta(name: str, pid: int, tid: int, args: Dict[str, Any]) -> Dict:
    return {"ph": "M", "name": name, "pid": pid, "tid": tid, "args": args,
            "cat": "__metadata", "ts": 0}


def _jsonable(v: Any) -> Any:
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    return repr(v)


# ---------------------------------------------------------------------------
# validation (the tier-1 schema pin)
# ---------------------------------------------------------------------------

def validate_timeline(doc: Dict[str, Any]) -> List[str]:
    """Schema check for an exported bundle; returns problem strings
    (empty = valid).  Holds exactly what a trace viewer needs: sorted
    timestamps, matched B/E pairs per (pid, tid) lane, and name
    metadata for every referenced pid/tid."""
    problems: List[str] = []
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents missing or not a list"]
    named_pids, named_tids = set(), set()
    for e in events:
        if e.get("ph") == "M":
            if e.get("name") == "process_name":
                named_pids.add(e.get("pid"))
            elif e.get("name") == "thread_name":
                named_tids.add((e.get("pid"), e.get("tid")))
    last_ts = None
    stacks: Dict[tuple, List[str]] = {}
    for i, e in enumerate(events):
        ph = e.get("ph")
        if ph == "M":
            continue
        ts = e.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            problems.append(f"event {i}: bad ts {ts!r}")
            continue
        if last_ts is not None and ts < last_ts:
            problems.append(f"event {i}: ts {ts} < previous {last_ts} "
                            "(not sorted)")
        last_ts = ts
        pid, tid = e.get("pid"), e.get("tid")
        if pid not in named_pids:
            problems.append(f"event {i}: pid {pid} has no process_name")
        if (pid, tid) not in named_tids:
            problems.append(f"event {i}: tid {pid}/{tid} has no "
                            "thread_name")
        lane = (pid, tid)
        if ph == "B":
            stacks.setdefault(lane, []).append(e.get("name", "?"))
        elif ph == "E":
            stack = stacks.get(lane)
            if not stack:
                problems.append(f"event {i}: E with empty stack on "
                                f"lane {lane}")
            else:
                top = stack.pop()
                if top != e.get("name", "?"):
                    problems.append(
                        f"event {i}: E {e.get('name')!r} closes "
                        f"B {top!r} on lane {lane}")
        elif ph == "X" and not isinstance(e.get("dur"), (int, float)):
            problems.append(f"event {i}: X without numeric dur")
    for lane, stack in stacks.items():
        if stack:
            problems.append(f"lane {lane}: {len(stack)} unmatched B "
                            f"event(s) ({stack[-1]!r} open)")
    return problems


def write_timeline(doc: Dict[str, Any], path: str) -> str:
    with open(path, "w") as f:
        json.dump(doc, f, separators=(",", ":"))
    return path


# ---------------------------------------------------------------------------
# one-call collectors (chaos / obsexport / bench share these)
# ---------------------------------------------------------------------------

def export_run_timeline(path: str, *,
                        host_result=None, host_verdicts=None,
                        device_result=None,
                        device_anchors: Optional[DeviceRunAnchors] = None,
                        device_verdicts=None,
                        meta: Optional[Dict[str, Any]] = None,
                        builder: Optional[TimelineBuilder] = None,
                        spans=None, flight=None) -> str:
    """Assemble the full six-surface bundle for a finished run and write
    it.  Spans and flight events come from the process-global rings
    (added ONCE, host and device legs share them) unless the caller
    passes ``spans``/``flight`` snapshots taken earlier — a driver that
    runs MORE work between the interesting run and the export (bench's
    obs_overhead calibration legs) must snapshot the drop-oldest rings
    right after the run it is exporting, or the bundle carries (and the
    wrapped rings may have evicted everything but) the later runs'
    events.  The host leg contributes its lifecycle snapshot + SLO
    verdicts, the device leg its telemetry series, control decisions
    and SLO verdicts mapped through ``device_anchors``."""
    import time as _time

    from serf_tpu.obs import flight as _flight
    from serf_tpu.obs import trace as _trace
    from serf_tpu.obs.slo import verdicts_to_dict

    b = builder if builder is not None else TimelineBuilder(meta=meta)
    b.add_spans(spans if spans is not None else _trace.trace_dump())
    b.add_flight(flight if flight is not None
                 else _flight.flight_dump())
    now = _time.time()
    if host_result is not None:
        lc = getattr(host_result, "lifecycle", None)
        if lc:
            b.add_lifecycle(lc, now)
        ctl = getattr(host_result, "control", None)
        if ctl and ctl.get("values"):
            b.add_control_values(ctl["values"], now, plane="host")
        if host_verdicts:
            b.add_slo_verdicts(verdicts_to_dict(host_verdicts), now,
                               plane="host")
        wd_state = getattr(host_result, "watchdog", None)
        if wd_state:
            b.add_watchdog(wd_state, now)
    if device_result is not None and device_anchors is not None:
        store = getattr(device_result, "telemetry", None)
        if store is not None:
            b.add_device_series(store, device_anchors)
        decisions = getattr(device_result, "control_decisions", None)
        if decisions:
            b.add_control_decisions(decisions, device_anchors)
        ctl_final = getattr(device_result, "control_final", None)
        if ctl_final:
            b.add_control_values(ctl_final, device_anchors.wall_end,
                                 plane="device")
        if device_verdicts:
            b.add_slo_verdicts(verdicts_to_dict(device_verdicts),
                               device_anchors.wall_end, plane="device")
        dev_wd = getattr(device_result, "watchdog", None)
        if dev_wd and dev_wd.get("rows") is not None:
            b.add_device_invariants(dev_wd["rows"], device_anchors)
    return write_timeline(b.build(), path)
