"""Unified observability: trace spans, flight recorder, exporters.

The reference serf leans on the Rust ``metrics`` facade plus
``tracing`` subscribers for its operational surface (SURVEY.md §5); this
package is that surface for the reproduction, spanning BOTH planes:

- :mod:`serf_tpu.obs.trace` — ``span(name, **attrs)`` context manager
  with parent/child nesting (contextvars) and a bounded in-memory buffer
  of finished spans, instrumented around the host plane's hot protocol
  paths (probe round, push/pull, gossip drain, query, user event,
  snapshot compaction, wire encode/decode).
- :mod:`serf_tpu.obs.flight` — a fixed-size ring of structured protocol
  events (member state transitions, queue overflows, rejected
  coordinates, retransmit exhaustion) with a ``dump()`` API: the
  after-the-fact debugging surface write-only counters cannot be.
- :mod:`serf_tpu.obs.export` — Prometheus text-format and JSON snapshot
  renderers over the :mod:`serf_tpu.utils.metrics` sink plus the trace
  and flight buffers; ``Serf.stats()`` surfaces all three.
- :mod:`serf_tpu.obs.device` — wall-clock dispatch timers for the JAX
  device plane with a jit-compile-vs-steady-state split, used by
  ``serf_tpu/ops/round_kernels.py`` and ``bench.py``; the per-model
  metric emitters live next to their states (``models/*.emit_*``).
- :mod:`serf_tpu.obs.health` — Lifeguard-style 0-100 node health score
  folded from local signals (probe awareness, queue/tee pressure,
  event-loop lag, flight/transport drop growth); ``serf.health.*`` gauges.
- :mod:`serf_tpu.obs.cluster` — the cluster plane: the ``_serf_stats``
  internal query scatters over the gossip fabric and folds every node's
  health + key metrics into one ``ClusterSnapshot``
  (``Serf.cluster_stats()``; rendered by ``tools/obstop.py``).  Trace
  contexts (``obs.trace.TraceContext``) ride query/user-event wire
  messages so spans and flight events correlate across nodes.
- :mod:`serf_tpu.obs.timeseries` — the TIME axis: bounded per-metric
  ring series (power-of-two downsampling on overflow, JSON serde), the
  host-plane ``MetricsSampler`` (sink snapshots + flight ``since_seq``
  cursor at a cadence), and the device plane's per-round telemetry-row
  → ring conversion.
- :mod:`serf_tpu.obs.slo` — the JUDGMENT layer: one declarative SLO
  table evaluated on both planes (multi-window burn rates, EWMA/MAD
  anomaly flags, ``slo-breach`` flight events, ``serf.slo.*`` gauges)
  plus the bench regression gate (``score_bench``).
- :mod:`serf_tpu.obs.lifecycle` — the message lifecycle ledger: sampled
  (1-in-N) per-message stage clocks decomposing the host hot path
  (transport → decode → dispatch → apply → queue-wait → tee), with
  always-on cheap counters, per-stage latency histograms, a
  critical-path attribution table, and ``slow-message`` flight events.
- :mod:`serf_tpu.obs.timeline` — the CORRELATED view: every surface
  above (plus device round telemetry mapped onto the wall clock through
  run anchors, control decisions, and SLO verdicts) exported as one
  Chrome-trace-event / Perfetto-loadable JSON bundle with per-node
  process lanes and per-surface thread lanes; ``tools/obsexport.py``,
  ``tools/chaos.py --export-timeline`` and ``bench.py
  --export-timeline`` are the drivers, ``validate_timeline`` the
  tier-1-pinned schema gate.

Everything is process-global with swap-out setters, mirroring the
``metrics`` facade already in place.
"""

from serf_tpu.obs.trace import (  # noqa: F401
    Span,
    TraceBuffer,
    TraceContext,
    current_trace,
    global_tracer,
    new_trace,
    set_global_tracer,
    span,
    trace_dump,
    trace_scope,
)
from serf_tpu.obs.flight import (  # noqa: F401
    FlightRecorder,
    flight_dump,
    global_recorder,
    record,
    set_global_recorder,
)
from serf_tpu.obs.export import (  # noqa: F401
    json_snapshot,
    metrics_snapshot,
    parse_prometheus_text,
    prometheus_text,
)
from serf_tpu.obs.device import (  # noqa: F401
    dispatch_summary,
    dispatch_timer,
    record_dispatch,
    reset_dispatch_registry,
)
from serf_tpu.obs.health import (  # noqa: F401
    HealthReport,
    HealthScorer,
    UNHEALTHY_THRESHOLD,
    serf_sources,
)
from serf_tpu.obs.cluster import (  # noqa: F401
    ClusterSnapshot,
    STATS_QUERY,
    collect_cluster_stats,
    render_table,
)
from serf_tpu.obs.timeseries import (  # noqa: F401
    MetricsSampler,
    SeriesStore,
    TimeSeries,
    sparkline,
    telemetry_to_store,
)
from serf_tpu.obs.slo import (  # noqa: F401
    SLO_TABLE,
    SLODef,
    SLOVerdict,
    judge_device_run,
    judge_host_run,
    score_bench,
    slo_names,
)
from serf_tpu.obs.lifecycle import (  # noqa: F401
    STAGES as LIFECYCLE_STAGES,
    LifecycleLedger,
    StageClock,
    format_waterfall,
    global_ledger,
    set_global_ledger,
)
from serf_tpu.obs.propagation import (  # noqa: F401
    PROPAGATION_FIELDS,
    PROPAGATION_MERGE,
    PROPAGATION_SERIES,
    PropagationLedger,
    PropagationSummary,
    analytic_redundancy,
    analytic_rounds_to_coverage,
    fold_propagation,
    format_propagation,
    propagation_to_store,
    render_coverage,
    summarize_propagation,
)

__all__ = [
    "Span", "TraceBuffer", "span", "trace_dump",
    "global_tracer", "set_global_tracer",
    "TraceContext", "new_trace", "current_trace", "trace_scope",
    "FlightRecorder", "record", "flight_dump",
    "global_recorder", "set_global_recorder",
    "prometheus_text", "parse_prometheus_text",
    "json_snapshot", "metrics_snapshot",
    "dispatch_timer", "dispatch_summary", "record_dispatch",
    "reset_dispatch_registry",
    "HealthScorer", "HealthReport", "UNHEALTHY_THRESHOLD", "serf_sources",
    "ClusterSnapshot", "STATS_QUERY", "collect_cluster_stats",
    "render_table",
    "TimeSeries", "SeriesStore", "MetricsSampler", "sparkline",
    "telemetry_to_store",
    "SLO_TABLE", "SLODef", "SLOVerdict", "judge_host_run",
    "judge_device_run", "score_bench", "slo_names",
    "LIFECYCLE_STAGES", "LifecycleLedger", "StageClock",
    "format_waterfall", "global_ledger", "set_global_ledger",
    "PROPAGATION_FIELDS", "PROPAGATION_MERGE", "PROPAGATION_SERIES",
    "PropagationLedger", "PropagationSummary", "analytic_redundancy",
    "analytic_rounds_to_coverage", "fold_propagation",
    "format_propagation", "propagation_to_store", "render_coverage",
    "summarize_propagation",
]
