"""Unified observability: trace spans, flight recorder, exporters.

The reference serf leans on the Rust ``metrics`` facade plus
``tracing`` subscribers for its operational surface (SURVEY.md §5); this
package is that surface for the reproduction, spanning BOTH planes:

- :mod:`serf_tpu.obs.trace` — ``span(name, **attrs)`` context manager
  with parent/child nesting (contextvars) and a bounded in-memory buffer
  of finished spans, instrumented around the host plane's hot protocol
  paths (probe round, push/pull, gossip drain, query, user event,
  snapshot compaction, wire encode/decode).
- :mod:`serf_tpu.obs.flight` — a fixed-size ring of structured protocol
  events (member state transitions, queue overflows, rejected
  coordinates, retransmit exhaustion) with a ``dump()`` API: the
  after-the-fact debugging surface write-only counters cannot be.
- :mod:`serf_tpu.obs.export` — Prometheus text-format and JSON snapshot
  renderers over the :mod:`serf_tpu.utils.metrics` sink plus the trace
  and flight buffers; ``Serf.stats()`` surfaces all three.
- :mod:`serf_tpu.obs.device` — wall-clock dispatch timers for the JAX
  device plane with a jit-compile-vs-steady-state split, used by
  ``serf_tpu/ops/round_kernels.py`` and ``bench.py``; the per-model
  metric emitters live next to their states (``models/*.emit_*``).

Everything is process-global with swap-out setters, mirroring the
``metrics`` facade already in place.
"""

from serf_tpu.obs.trace import (  # noqa: F401
    Span,
    TraceBuffer,
    global_tracer,
    set_global_tracer,
    span,
    trace_dump,
)
from serf_tpu.obs.flight import (  # noqa: F401
    FlightRecorder,
    flight_dump,
    global_recorder,
    record,
    set_global_recorder,
)
from serf_tpu.obs.export import (  # noqa: F401
    json_snapshot,
    metrics_snapshot,
    parse_prometheus_text,
    prometheus_text,
)
from serf_tpu.obs.device import (  # noqa: F401
    dispatch_summary,
    dispatch_timer,
    record_dispatch,
    reset_dispatch_registry,
)

__all__ = [
    "Span", "TraceBuffer", "span", "trace_dump",
    "global_tracer", "set_global_tracer",
    "FlightRecorder", "record", "flight_dump",
    "global_recorder", "set_global_recorder",
    "prometheus_text", "parse_prometheus_text",
    "json_snapshot", "metrics_snapshot",
    "dispatch_timer", "dispatch_summary", "record_dispatch",
    "reset_dispatch_registry",
]
