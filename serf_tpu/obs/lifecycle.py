"""Message lifecycle ledger: per-stage latency decomposition of the host
hot path.

The device plane has per-round telemetry and SLO burn rates (PRs 10-11);
this module is the same discipline for the HOST plane — the asyncio +
per-message-codec path real user traffic hits.  Before rebuilding that
seam for volume (ROADMAP item 1: batched codec, MPMC hand-off, parallel
apply), we need to know *where a message's wall time actually goes*:
queue-wait vs codec vs serial event application.  The ledger answers
that, stage by stage, for a 1-in-N sample of live traffic.

**Stages** (one message's hops through the host hot path)::

    transport   packet arrival -> serf codec decode start
                (wire decrypt/checksum/decompress + SWIM decode)
    decode      serf message codec decode (types/messages.decode_message)
    dispatch    decoded message -> handler entry (type dispatch)
    apply       the synchronous handler body: Lamport witness, dedup
                ring, member-table / event-buffer mutation, up to the
                event-inbox enqueue (or handler return)
    queue-wait  event-inbox enqueue -> delivery-pipeline dequeue
    tee         dequeue -> snapshotter observe + tee hop + subscriber
                push complete (the delivery pipeline's service time)

Locally-originated messages (``Serf.user_event``/``query`` — right
beside the PR-9 ingress tap) start their clock at API entry with no
``transport``/``decode`` stages; remote messages start at the packet
timestamp the memberlist packet loop noted.  Stages are stamped as a
chain (each stamp attributes the interval since the previous one), so
the sum of stages equals end-to-end by construction *wherever the
wiring is complete* — the ≥90% attribution self-check
(tests/test_lifecycle.py) is therefore a wiring-completeness pin, the
host twin of the roundprof byte-attribution pin: a new hop that delays
messages without stamping shows up as unattributed time.

**Sampling contract** (the PR-5 health-gate rule — measurement must
never become the load): every message bumps plain-int always-on
counters (``serf.lifecycle.messages``); only every ``sample_n``-th
message gets a :class:`StageClock` that rides the event object through
the async pipeline.  ``sample_n=0`` disables clocks entirely.  A
sampled message whose end-to-end exceeds ``slow_ms`` fires a
``slow-message`` flight event carrying the full stage breakdown.

Aggregation: per-stage :class:`~serf_tpu.utils.metrics.HistogramSummary`
latency stats, ``serf.lifecycle.*`` metrics (sampled into ring series by
the PR-10 ``MetricsSampler``), a critical-path attribution table
(:meth:`LifecycleLedger.critical_path` — which stage owns p50 vs p99),
and :meth:`LifecycleLedger.snapshot` for chaos/bench artifacts.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

from serf_tpu.obs import flight
from serf_tpu.utils import metrics
from serf_tpu.utils.metrics import HistogramSummary

#: stage names, hot-path order (the chain a fully-delivered user event
#: walks; non-event messages end at ``apply``)
STAGES = ("transport", "decode", "dispatch", "apply", "queue-wait", "tee")

#: default sampling rate: one full stage clock per N messages
DEFAULT_SAMPLE_N = 32
#: default slow-message threshold (ms end-to-end) for the flight event
DEFAULT_SLOW_MS = 250.0

#: attribute the clock rides on the event object between pipeline hops
_ATTR = "_lifecycle_clock"


class StageClock:
    """Monotonic stage stamps for ONE sampled message.

    ``stamp(stage)`` attributes the interval since the previous stamp
    (or ``t0``) to ``stage``; repeated stamps accumulate.  The clock is
    created by :meth:`LifecycleLedger.begin`, travels on the emitted
    event object (:func:`attach_current` / :func:`event_stamp`), and is
    finished exactly once by the ledger."""

    __slots__ = ("kind", "origin", "t0", "last", "stages", "finished")

    def __init__(self, kind: str, origin: str, t0: Optional[float] = None):
        now = time.monotonic()
        self.kind = kind
        self.origin = origin                  # "remote" | "local"
        self.t0 = now if t0 is None else min(t0, now)
        self.last = self.t0
        self.stages: Dict[str, float] = {}    # stage -> seconds
        self.finished = False

    def stamp(self, stage: str) -> None:
        now = time.monotonic()
        self.stages[stage] = self.stages.get(stage, 0.0) + (now - self.last)
        self.last = now


class LifecycleLedger:
    """Sampled per-message stage clocks + always-on cheap counters.

    All mutation happens on the event-loop thread (the host hot path is
    single-threaded asyncio), so the counters are plain ints and the
    ``current`` slot — the clock for the message being *synchronously*
    processed right now — needs no lock: it is set and consumed within
    one call frame (``notify_message`` / ``user_event`` / ``query``).
    """

    def __init__(self, sample_n: int = DEFAULT_SAMPLE_N,
                 slow_ms: float = DEFAULT_SLOW_MS):
        #: 1-in-N sampling (0 = clocks off; counters stay on)
        self.sample_n = max(0, int(sample_n))
        self.slow_ms = float(slow_ms)
        self.seen = 0            # messages offered to the hot path
        self.sampled = 0         # messages that got a stage clock
        self.finished = 0        # clocks that completed (any outcome)
        self.delivered = 0       # clocks that reached the tee stage
        self.slow = 0            # slow-message flight events fired
        self.shed = 0            # sampled messages shed at the inbox
        self._hist: Dict[str, HistogramSummary] = {
            s: HistogramSummary() for s in STAGES}
        self._e2e = HistogramSummary()
        self._attr_s = 0.0       # total stage-attributed seconds
        self._e2e_s = 0.0        # total end-to-end seconds
        self._current: Optional[StageClock] = None
        self._packet_t0: Optional[float] = None

    # -- hot-path producer API ----------------------------------------------

    def note_packet(self, t_recv: float) -> None:
        """The transport seam's receive timestamp for the packet whose
        messages are about to be handled — ``begin(origin="remote")``
        backdates the next clock's ``t0`` to it so wire/SWIM decode land
        in the ``transport`` stage."""
        self._packet_t0 = t_recv

    def begin(self, origin: str, kind: str = "?") -> Optional[StageClock]:
        """Count one message; every ``sample_n``-th gets a clock (which
        becomes the *current* clock for the synchronous handler chain).
        Remote clocks immediately stamp ``transport`` from the noted
        packet timestamp."""
        self.seen += 1
        metrics.incr("serf.lifecycle.messages", 1, {"origin": origin})
        # consume the packet note unconditionally: it anchors exactly
        # ONE message — a later caller that reaches begin() without its
        # own note (e.g. a future ingress path) must start at now()
        # instead of backdating to some unrelated packet's timestamp
        noted, self._packet_t0 = self._packet_t0, None
        if self.sample_n <= 0 or self.seen % self.sample_n:
            self._current = None
            return None
        self.sampled += 1
        metrics.incr("serf.lifecycle.sampled")
        t0 = noted if origin == "remote" else None
        clk = StageClock(kind, origin, t0)
        if origin == "remote":
            clk.stamp("transport")
        self._current = clk
        return clk

    def stamp_current(self, stage: str) -> None:
        if self._current is not None:
            self._current.stamp(stage)

    def take_current(self) -> Optional[StageClock]:
        clk, self._current = self._current, None
        return clk

    def discard_current(self) -> None:
        """Drop the current clock without aggregating (undecodable
        message: it never entered the measured pipeline)."""
        self._current = None

    def finish_current(self) -> None:
        """End of the synchronous handler chain for a message that never
        emitted an event (intents, query responses, dedup drops): the
        residue since the last stamp is the handler's apply work."""
        clk = self.take_current()
        if clk is not None:
            clk.stamp("apply")
            self.finish(clk)

    def attach_current(self, ev: Any, shed: bool = False) -> None:
        """The handler emitted ``ev``: stamp ``apply`` and ride the event
        into the delivery pipeline (or finish now if the inbox shed it)."""
        clk = self.take_current()
        if clk is None:
            return
        clk.stamp("apply")
        if shed:
            self.shed += 1
            self.finish(clk)
            return
        try:
            object.__setattr__(ev, _ATTR, clk)
        except (AttributeError, TypeError):   # slotted/foreign event type
            self.finish(clk)

    def event_stamp(self, ev: Any, stage: str) -> None:
        """Pipeline hop: attribute time since the event's previous stamp
        to ``stage`` (no-op for unsampled events)."""
        clk = getattr(ev, _ATTR, None)
        if clk is not None and not clk.finished:
            clk.stamp(stage)

    def event_finish(self, ev: Any, stage: Optional[str] = None) -> None:
        """Delivery complete: optionally stamp a final ``stage``, then
        aggregate the clock (exactly once)."""
        clk = getattr(ev, _ATTR, None)
        if clk is None or clk.finished:
            return
        if stage is not None:
            clk.stamp(stage)
        if "tee" in clk.stages:
            self.delivered += 1
        self.finish(clk)

    # -- aggregation ---------------------------------------------------------

    def finish(self, clk: StageClock) -> None:
        if clk.finished:
            return
        clk.finished = True
        self.finished += 1
        e2e_s = clk.last - clk.t0
        attr_s = sum(clk.stages.values())
        self._e2e_s += e2e_s
        self._attr_s += attr_s
        e2e_ms = e2e_s * 1e3
        self._e2e.observe(e2e_ms)
        metrics.observe("serf.lifecycle.e2e-ms", e2e_ms)
        for stage, dur in clk.stages.items():
            h = self._hist.get(stage)
            if h is not None:
                h.observe(dur * 1e3)
            metrics.observe("serf.lifecycle.stage-ms", dur * 1e3,
                            {"stage": stage})
        if e2e_ms > self.slow_ms:
            self.slow += 1
            metrics.incr("serf.lifecycle.slow")
            # "kind" is the flight-record positional; the message's own
            # type travels as "message"
            flight.record(
                "slow-message", message=clk.kind, origin=clk.origin,
                e2e_ms=round(e2e_ms, 3), threshold_ms=self.slow_ms,
                stages_ms={s: round(d * 1e3, 3)
                           for s, d in sorted(clk.stages.items())})

    # -- reads ---------------------------------------------------------------

    def attribution(self) -> Optional[float]:
        """Fraction of sampled end-to-end seconds attributed to named
        stages (None before any clock finished).  The wiring-
        completeness number the self-check pins at >= 0.9."""
        if self._e2e_s <= 0.0:
            return None if self.finished == 0 else 1.0
        return min(1.0, self._attr_s / self._e2e_s)

    def queue_wait_share(self) -> Optional[float]:
        """Queue-wait seconds / end-to-end seconds over every finished
        clock — the backpressure share of the hot path (an SLO row)."""
        if self._e2e_s <= 0.0:
            return None
        h = self._hist["queue-wait"]
        return min(1.0, h.total / 1e3 / self._e2e_s)

    def stage_summary(self, stage: str) -> HistogramSummary:
        return self._hist[stage]

    def critical_path(self) -> list:
        """Per-stage attribution rows (hot-path order): count, mean,
        p50, p99 latency, and ``share`` — the stage's fraction of ALL
        attributed time (rows sum to ~1 when wiring is complete).  The
        snapshot's ``owner_p50``/``owner_p99`` name the stage with the
        largest median / tail latency — *which stage owns p50 vs p99*.
        """
        total_s = self._attr_s
        rows = []
        for stage in STAGES:
            h = self._hist[stage]
            if not h.count:
                continue
            rows.append({
                "stage": stage,
                "count": h.count,
                "mean_ms": round(h.mean, 4),
                "p50_ms": round(h.percentile(50), 4),
                "p99_ms": round(h.percentile(99), 4),
                "share": round(h.total / 1e3 / total_s, 4)
                if total_s > 0 else 0.0,
            })
        return rows

    def snapshot(self) -> Dict[str, Any]:
        """JSON-ready ledger state: counters, e2e stats, the critical-
        path table, owners, attribution — what chaos/bench artifacts
        embed and the SLO judge reads."""
        table = self.critical_path()
        owner_p50 = max(table, key=lambda r: r["p50_ms"])["stage"] \
            if table else None
        owner_p99 = max(table, key=lambda r: r["p99_ms"])["stage"] \
            if table else None
        attr = self.attribution()
        qshare = self.queue_wait_share()
        return {
            "sample_n": self.sample_n,
            "slow_ms": self.slow_ms,
            "seen": self.seen,
            "sampled": self.sampled,
            "finished": self.finished,
            "delivered": self.delivered,
            "slow": self.slow,
            "shed": self.shed,
            "e2e": {
                "count": self._e2e.count,
                "mean_ms": round(self._e2e.mean, 4),
                "p50_ms": round(self._e2e.percentile(50), 4),
                "p99_ms": round(self._e2e.percentile(99), 4),
                "max_ms": round(self._e2e.max, 4),
            },
            "stages": table,
            "owner_p50": owner_p50,
            "owner_p99": owner_p99,
            "attributed_frac": round(attr, 4) if attr is not None else None,
            "queue_wait_share": (round(qshare, 4)
                                 if qshare is not None else None),
        }


def format_waterfall(snap: Dict[str, Any], width: int = 28) -> str:
    """Render a snapshot's critical-path table as an ASCII stage
    waterfall (mean-ms bars, hot-path order) — the ``obstop --watch``
    and ``tools/chaos.py`` view."""
    rows = snap.get("stages") or []
    if not rows:
        return "lifecycle: no sampled messages yet"
    lines = [
        "message lifecycle (%d sampled / %d seen; e2e p50 %.2f ms, "
        "p99 %.2f ms; p50 owner %s, p99 owner %s; attributed %.0f%%)" % (
            snap["sampled"], snap["seen"],
            snap["e2e"]["p50_ms"], snap["e2e"]["p99_ms"],
            snap.get("owner_p50"), snap.get("owner_p99"),
            100 * (snap.get("attributed_frac") or 0.0))]
    top = max(r["mean_ms"] for r in rows)
    for r in rows:
        bar = "#" * max(1, int(round(width * r["mean_ms"] / top))) \
            if top > 0 else "#"
        lines.append(
            "  %-10s %9.3f ms mean  p99 %9.3f ms  share %5.1f%%  %s"
            % (r["stage"], r["mean_ms"], r["p99_ms"],
               100 * r["share"], bar))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# process-global ledger (swap-out setter, like metrics/flight)
# ---------------------------------------------------------------------------

_global = LifecycleLedger()


def global_ledger() -> LifecycleLedger:
    return _global


def set_global_ledger(led: LifecycleLedger) -> LifecycleLedger:
    """Install ``led`` as the process ledger; returns the previous one
    (chaos/bench runs install a fresh, hotter-sampling ledger for the
    run and restore after)."""
    global _global
    prev = _global
    _global = led
    return prev
