"""Flight recorder: a fixed-size ring of structured protocol events.

The debugging surface the write-only counters never were: when a round
misbehaves, ``dump()`` answers *why* — which member flapped, which queue
overflowed and what it dropped, which coordinate sample was rejected and
for what reason, which broadcast exhausted its retransmit budget — in
order, with timestamps, bounded in memory (drop-oldest, like a cockpit
flight recorder).

Event kinds emitted by the engine (see README "Observability"):

- ``member-state``    serf-level member status transitions
- ``swim-state``      memberlist-level alive/suspect/dead/left moves
- ``queue-overflow``  TransmitLimitedQueue prune dropped broadcasts
- ``subscriber-drop`` event subscriber overflow dropped an event
- ``coordinate-rejected``  a Vivaldi sample was refused (reason field)
- ``broadcast-retired``    a broadcast exhausted its transmit budget
- ``probe-failed``    direct+indirect probe round failed (suspect next)
- ``packet-dropped``  wire decode/decrypt failure dropped a packet
- ``query-received``  a query reached this node (stamped with its trace id)
- ``query-response``  a response/ack came back to the originating node
- ``user-event``      a fresh user event was accepted locally
- ``pallas-fallback`` use_pallas requested but ``pallas_ok`` rejected the
  shape — the round silently used the XLA path (r5 TPU_PROOF lesson:
  invisible fallbacks hid MosaicErrors)
- ``fault-phase``      a chaos-plan phase was installed/healed (faults)
- ``circuit-breaker``  a per-peer circuit opened/reopened/closed
- ``dial-retry``       a stream dial / join retried after backoff
- ``corrupt-frame``    an undecodable stream frame was quarantined
- ``snapshot-torn-tail``  snapshot replay skipped a torn tail
- ``replay-recorded``  a record/replay recording artifact was written
- ``replay-divergence`` the replay differ found two digest streams apart
- ``slo-breach``       an SLO verdict came back out of objective (obs/slo)
- ``slow-message``     a lifecycle-sampled message exceeded the slow
  threshold — the event carries the full per-stage breakdown
  (obs/lifecycle)
- ``watchdog-breach``  the always-on watchdog tripped an invariant or a
  sustained SLO burn (obs/watchdog) — names the first violating device
  round / host tick and, on the host plane, the black-box bundle dumped

Events recorded while a cross-node trace is active (``obs.trace
.trace_scope``) carry a ``trace`` field — the hex trace id shared by
every node the traced operation touched.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from serf_tpu.obs import trace as _trace

#: events retained (ring, drop-oldest)
FLIGHT_RING_SIZE = 512


class FlightRecorder:
    def __init__(self, capacity: int = FLIGHT_RING_SIZE):
        self.capacity = max(1, capacity)
        self._lock = threading.Lock()
        self._ring: List[Optional[Dict[str, Any]]] = [None] * self.capacity
        self._pos = 0
        #: total events ever recorded (``recorded - len(self)`` = dropped)
        self.recorded = 0

    def record(self, kind: str, node: Optional[str] = None,
               **fields: Any) -> None:
        ev = {
            "seq": 0,                      # patched under the lock below
            "time": time.time(),
            "monotonic": time.monotonic(),
            "kind": kind,
        }
        if node is not None:
            ev["node"] = node
        # cross-node correlation: stamp the active trace id (if any) so
        # flight events on every node a query/event touches share one key
        tc = _trace.current_trace()
        if tc is not None and "trace" not in fields:
            ev["trace"] = tc.hex_id
        ev.update(fields)
        with self._lock:
            self.recorded += 1
            ev["seq"] = self.recorded
            self._ring[self._pos] = ev
            self._pos = (self._pos + 1) % self.capacity

    def dump(self, kind: Optional[str] = None, node: Optional[str] = None,
             last: Optional[int] = None,
             since_seq: Optional[int] = None) -> List[Dict[str, Any]]:
        """Retained events oldest-first, optionally filtered by ``kind``
        and/or ``node``; ``last`` keeps only the newest N after filtering.

        ``since_seq`` returns only events with ``seq > since_seq`` — the
        incremental-poll contract: every record carries a monotonic
        per-recorder sequence number, so a poller (or a multi-node dump
        merger) can resume from the last ``seq`` it saw and merge
        streams in a stable ``(time, seq)`` order even after ring
        eviction discarded the overlap (``last_seq`` is the cursor to
        resume from)."""
        with self._lock:
            if self.recorded >= self.capacity:
                ordered = self._ring[self._pos:] + self._ring[:self._pos]
            else:
                ordered = self._ring[:self._pos]
            out = [dict(e) for e in ordered if e is not None]
        if since_seq is not None:
            out = [e for e in out if e["seq"] > since_seq]
        if kind is not None:
            out = [e for e in out if e["kind"] == kind]
        if node is not None:
            out = [e for e in out if e.get("node") == node]
        return out[-last:] if last is not None else out

    @property
    def last_seq(self) -> int:
        """Sequence number of the newest record (0 = none yet) — the
        ``since_seq`` cursor for incremental dumps."""
        return self.recorded

    def __len__(self) -> int:
        return min(self.recorded, self.capacity)

    @property
    def dropped(self) -> int:
        return max(0, self.recorded - self.capacity)

    def reset(self) -> None:
        with self._lock:
            self._ring = [None] * self.capacity
            self._pos = 0
            self.recorded = 0


_global = FlightRecorder()


def global_recorder() -> FlightRecorder:
    return _global


def set_global_recorder(rec: FlightRecorder) -> None:
    global _global
    _global = rec


def record(kind: str, node: Optional[str] = None, **fields: Any) -> None:
    _global.record(kind, node, **fields)


def flight_dump(kind: Optional[str] = None, node: Optional[str] = None,
                last: Optional[int] = None,
                since_seq: Optional[int] = None) -> List[Dict[str, Any]]:
    return _global.dump(kind, node, last, since_seq=since_seq)
