"""Always-on watchdog: continuous invariant + SLO verification.

Every earlier verification surface — ``faults/invariants.py``, the SLO
judges (``obs/slo.py``), chaos verdicts — runs post-hoc at run end.
This module is the seventh observability surface and the first one that
*acts*: it watches a live run on BOTH planes and, on breach, triggers
the black-box forensic dump (``obs/blackbox.py``) so the moment of
failure is captured, not reconstructed.

**Device plane** — the invariant predicates the post-hoc checker judges
once (overflow accounting, the ltime-window guard, the no-false-DEAD
evidence gate, propagation coverage monotonicity) become a per-round
boolean row (:data:`INVARIANT_FIELDS`) computed INSIDE the jitted scan
(``models/swim.invariant_row``), riding the telemetry unpack the
PR-15/16 rows already share: zero extra per-round transfers, off path
jaxpr-identical, on path bit-exact on every GossipState leaf.  The
stacked rows come back in the run's single ``device_get`` and
:func:`summarize_invariants` names the **first violating round** from
scan output — no post-hoc device computation at all.

**Host plane** — :class:`Watchdog` ticks on the ``MetricsSampler``
cadence: armed invariant predicates (clock monotonicity, shed-counter
accounting, bounded buffers via the ``serf.queue.*``/``serf.pipeline.*``
gauges), live SLO burn rates over the sampler's ring series, and the
Lifeguard health floor.  A breach (or a process-fatal task exception
via the ``utils/tasks`` failure-hook seam) fires a ``watchdog-breach``
flight event and triggers every registered black box.

Self-telemetry: ``serf.watchdog.ticks`` / ``serf.watchdog.ok`` /
``serf.watchdog.armed`` / ``serf.watchdog.breach``.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from serf_tpu.obs import flight
from serf_tpu.utils import metrics

#: field order of the per-round device invariant row (``f32[F]``,
#: ``models/swim.invariant_row`` hardcodes the stack in this order —
#: the ``propagation_row`` convention).  Every field but ``viol_mask``
#: is a boolean (1.0 = the invariant HELD this round):
#:
#: - ``overflow_ok``      — the shed ledger stays accounted:
#:   ``0 <= overflow <= injected`` (a clobber that no ledger saw, or a
#:   ledger past its own injection count, is an accounting regression);
#: - ``ltime_ok``         — valid fact ltimes stay inside the 2^31
#:   window (the wrap story's fail-loud guard, judged every round);
#: - ``no_false_dead``    — no alive node is believed dead THIS round
#:   (raw per-round evidence: mid-fault rounds legitimately violate it,
#:   so the first-violation semantics name where the protocol first
#:   diverged — the judge decides which rounds bind);
#: - ``coverage_monotone`` — no still-resident sentinel fact's coverage
#:   regressed (propagation-traced runs; a recycled ring slot
#:   legitimately reads 0 and is exempt.  Trivially 1.0 untraced);
#: - ``stamp_staleness_ok`` — deferred-stamp configs only: pending
#:   overlay learns are never older than the current stamp quarter
#:   (the cohort flush fires within STAMP_UNIT rounds of any learn —
#:   a pending learn predating the quarter floor means a missed flush
#:   and a lying age-0 read-through.  Trivially 1.0 per-round);
#: - ``viol_mask``        — bitmask of the violated fields above
#:   (bit i = field i), one scalar a breach scanner can threshold.
INVARIANT_FIELDS = ("overflow_ok", "ltime_ok", "no_false_dead",
                    "coverage_monotone", "stamp_staleness_ok",
                    "viol_mask")

#: the row's globalization contract (serflint ``invariant-field-drift``
#: holds this dict, INVARIANT_FIELDS and the README table to each other
#: both ways): every field folds from the ALREADY globally-reduced
#: telemetry/propagation operands (the ``round_telemetry(with_cols=
#: True)`` unpack) plus replicated scalar ledgers and fact-table
#: K-planes — identical on every chip, no collective of its own.
INVARIANT_MERGE = {
    "overflow_ok": "replicated",
    "ltime_ok": "replicated",
    "no_false_dead": "replicated",
    "coverage_monotone": "replicated",
    "stamp_staleness_ok": "replicated",
    "viol_mask": "replicated",
}

#: INVARIANT_FIELDS -> declared metric names for the boolean fields the
#: rings carry (viol_mask is a bitmask, not a level — it stays out of
#: the ring and in the summary)
INVARIANT_SERIES: Tuple[Tuple[str, str], ...] = ()

#: bit weights of ``viol_mask`` (field i of INVARIANT_FIELDS ->
#: ``1 << i``); exact in f32 far past the field count
VIOL_BITS = tuple(1 << i for i in range(len(INVARIANT_FIELDS) - 1))


# ---------------------------------------------------------------------------
# device plane: first-violation extraction from the stacked scan rows
# ---------------------------------------------------------------------------


def summarize_invariants(rows, base_round: int = 0) -> Dict[str, Any]:
    """Fold stacked per-round invariant rows (``f32[R, F]`` on host —
    the caller did its one ``device_get``) into the live device
    watchdog verdict: per-field first violating round, the overall
    first breach, and violation counts.  Round indices are absolute
    (``base_round + i + 1``: row i describes the state AFTER that
    round — the ``telemetry_to_store`` stamp convention)."""
    import numpy as np

    rows = np.asarray(rows, np.float32)
    flags = INVARIANT_FIELDS[:-1]
    ok_plane = rows[:, : len(flags)] >= 0.5 if len(rows) else \
        np.ones((0, len(flags)), bool)
    per_field: Dict[str, Any] = {}
    first_round = None
    first_fields: List[str] = []
    for j, name in enumerate(flags):
        bad = np.flatnonzero(~ok_plane[:, j])
        r = int(base_round + bad[0] + 1) if len(bad) else None
        per_field[name] = {
            "first_violation_round": r,
            "violations": int(len(bad)),
        }
        if r is not None and (first_round is None or r < first_round):
            first_round = r
            first_fields = [name]
        elif r is not None and r == first_round:
            first_fields.append(name)
    ok = first_round is None
    return {
        "plane": "device",
        "ok": ok,
        "rounds": int(len(rows)),
        "fields": list(flags),
        "per_field": per_field,
        "first_violation": None if ok else {
            "round": first_round, "fields": first_fields},
        "violations": int((~ok_plane).sum()),
    }


def emit_device_watchdog(summary: Dict[str, Any],
                         labels: Optional[Dict[str, str]] = None) -> None:
    """Land the device watchdog verdict on the observability planes:
    the ``serf.watchdog.*`` gauges/counters plus — on breach — a
    ``watchdog-breach`` flight event naming the first violating round."""
    labels = dict(labels or {}, plane="device")
    metrics.incr("serf.watchdog.ticks", float(summary.get("rounds", 0)),
                 labels)
    metrics.gauge("serf.watchdog.ok",
                  1.0 if summary.get("ok") else 0.0, labels)
    metrics.gauge("serf.watchdog.armed",
                  float(len(summary.get("fields", ()))), labels)
    first = summary.get("first_violation")
    if first is not None:
        metrics.incr("serf.watchdog.breach", 1, labels)
        flight.record("watchdog-breach", plane="device",
                      round=first["round"],
                      invariants=list(first["fields"]),
                      violations=int(summary.get("violations", 0)))


def format_invariants(summary: Dict[str, Any],
                      plane: str = "device") -> str:
    """One report block, the ``InvariantReport.format`` shape, so the
    chaos/obswatch output reads as one column of judgments."""
    lines = [f"[{plane}] watchdog: "
             f"{'GREEN' if summary.get('ok') else 'BREACHED'} "
             f"({summary.get('rounds', 0)} round(s) judged in-scan)"]
    for name in summary.get("fields", ()):
        row = summary["per_field"][name]
        r = row["first_violation_round"]
        mark = "ok  " if r is None else "FAIL"
        detail = ("held every round" if r is None else
                  f"first violated at round {r} "
                  f"({row['violations']} round(s) total)")
        lines.append(f"  {mark}  {name} — {detail}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# host plane: the continuous watchdog task
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class WatchdogConfig:
    """Host watchdog thresholds.  ``health_floor`` is in health-SCORE
    units (higher = healthier; breach when any node drops BELOW it —
    the scorer's own ``UNHEALTHY_THRESHOLD`` by default).  SLO burn
    breaches only when BOTH windows burn past 1 (the sustained-not-blip
    rule).  Dumps are debounced: at most one black-box dump per
    ``dump_every_ticks``."""

    health_floor: float = 70.0
    dump_every_ticks: int = 8
    queue_bytes_cap: int = 8 << 20
    pipeline_depth_cap: int = 8192


@dataclass
class WatchdogVerdict:
    tick: int
    ok: bool
    wall_time: float
    breaches: List[str] = field(default_factory=list)
    detail: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return {"tick": self.tick, "ok": self.ok,
                "wall_time": self.wall_time,
                "breaches": list(self.breaches), "detail": self.detail}


class Watchdog:
    """Continuous host-plane verifier, ticked on the sampler cadence.

    Arm invariant predicates with :meth:`arm` (``fn() -> (ok, detail)``),
    SLO burn watches with :meth:`watch_slo` (``fn() -> value-series`` in
    the SLO's own units), register black boxes with :meth:`add_blackbox`.
    Every :meth:`tick` evaluates everything armed; the first breach of a
    quiet period fires a ``watchdog-breach`` flight event, bumps
    ``serf.watchdog.breach`` and triggers every registered black box.
    The flight cursor handed to the boxes is watchdog-owned
    (``FlightRecorder.dump(since_seq=)``), so consecutive dumps carry
    disjoint flight tails."""

    def __init__(self, cfg: WatchdogConfig = WatchdogConfig(),
                 store=None, recorder=None, clock=time.time):
        self.cfg = cfg
        self.store = store
        self._recorder = recorder
        self._clock = clock
        self._invariants: List[Tuple[str, Callable]] = []
        self._slo_watches: List[Tuple[str, Callable]] = []
        self._blackboxes: List[Any] = []
        self.ticks = 0
        self.breaches = 0
        self.history: List[WatchdogVerdict] = []
        self.first_breach: Optional[WatchdogVerdict] = None
        self.last_verdict: Optional[WatchdogVerdict] = None
        self._last_dump_tick: Optional[int] = None
        self._cursor = self._rec().last_seq
        self._hook = None

    def _rec(self):
        return self._recorder if self._recorder is not None \
            else flight.global_recorder()

    # -- arming --------------------------------------------------------------

    def arm(self, name: str, fn: Callable[[], Tuple[bool, str]]) -> None:
        self._invariants.append((name, fn))

    def watch_slo(self, slo_name: str,
                  series_fn: Callable[[], Optional[Sequence[float]]]
                  ) -> None:
        """Watch one SLO live: ``series_fn`` returns the recent evidence
        in the SLO's OWN units (the burn-rate rule); burn is judged over
        the standard short/long windows each tick."""
        self._slo_watches.append((slo_name, series_fn))

    def add_blackbox(self, box) -> None:
        self._blackboxes.append(box)

    @property
    def armed(self) -> List[str]:
        return [n for n, _ in self._invariants] + \
            [f"slo:{n}" for n, _ in self._slo_watches]

    # -- the tick ------------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> WatchdogVerdict:
        from serf_tpu.obs import slo as _slo

        now = self._clock() if now is None else float(now)
        self.ticks += 1
        breaches: List[str] = []
        details: List[str] = []
        for name, fn in self._invariants:
            try:
                ok, detail = fn()
            except Exception as e:  # noqa: BLE001 — a broken predicate
                ok, detail = False, f"predicate raised: {e!r}"
            if not ok:
                breaches.append(name)
                details.append(f"{name}: {detail}")
        for slo_name, series_fn in self._slo_watches:
            try:
                values = series_fn()
            except Exception as e:  # noqa: BLE001
                values = None
                breaches.append(f"slo:{slo_name}")
                details.append(f"slo:{slo_name}: extractor raised {e!r}")
            if not values:
                continue
            d = _slo.slo_def(slo_name)
            burns = []
            vs = [float(v) for v in values]
            for w in _slo.BURN_WINDOWS:
                win = vs[-w:]
                agg = sum(win) / len(win)
                burns.append(_slo._burn_of(agg, d.objective, d.better))
            if burns and all(b > 1.0 for b in burns):
                breaches.append(f"slo:{slo_name}")
                details.append(
                    f"slo:{slo_name}: sustained burn "
                    + "/".join(f"{b:.2f}" for b in burns)
                    + f" vs objective {d.objective:g} {d.unit}")
        verdict = WatchdogVerdict(tick=self.ticks, ok=not breaches,
                                  wall_time=now, breaches=breaches,
                                  detail="; ".join(details))
        self.last_verdict = verdict
        if len(self.history) < 256:
            self.history.append(verdict)
        labels = {"plane": "host"}
        metrics.incr("serf.watchdog.ticks", 1, labels)
        metrics.gauge("serf.watchdog.ok", 1.0 if verdict.ok else 0.0,
                      labels)
        metrics.gauge("serf.watchdog.armed", float(len(self.armed)),
                      labels)
        if breaches:
            self.breaches += 1
            if self.first_breach is None:
                self.first_breach = verdict
            metrics.incr("serf.watchdog.breach", 1, labels)
            flight.record("watchdog-breach", plane="host",
                          tick=verdict.tick, invariants=list(breaches),
                          detail=verdict.detail[:512])
            self._maybe_dump("breach", verdict)
        return verdict

    # -- forensics -----------------------------------------------------------

    def _maybe_dump(self, reason: str, verdict: WatchdogVerdict) -> None:
        if self._last_dump_tick is not None and \
                self.ticks - self._last_dump_tick < \
                max(1, self.cfg.dump_every_ticks):
            return
        self._last_dump_tick = self.ticks
        self.dump(reason=reason, detail=verdict.detail)

    def dump(self, reason: str, detail: str = "") -> List[str]:
        """Trigger every registered black box with the watchdog-owned
        flight cursor; returns the bundle paths written."""
        rec = self._rec()
        events = rec.dump(since_seq=self._cursor)
        self._cursor = rec.last_seq
        paths = []
        for box in self._blackboxes:
            try:
                paths.append(box.dump(reason=reason, detail=detail,
                                      flight_events=events,
                                      watchdog=self.state()))
            except Exception as e:  # noqa: BLE001 — forensics must
                # never take the run down with it
                details = f"blackbox dump failed: {e!r}"
                flight.record("watchdog-breach", plane="host",
                              tick=self.ticks, invariants=["blackbox"],
                              detail=details)
        return paths

    def on_task_failure(self, name: str, exc: BaseException) -> None:
        """The ``utils/tasks`` failure-hook target: a process-fatal task
        exception is itself a breach — verdict + dump, undebounced."""
        self.breaches += 1
        verdict = WatchdogVerdict(
            tick=self.ticks, ok=False, wall_time=self._clock(),
            breaches=["task-exception"],
            detail=f"task {name!r} died: {exc!r}")
        if self.first_breach is None:
            self.first_breach = verdict
        self.last_verdict = verdict
        if len(self.history) < 256:
            self.history.append(verdict)
        metrics.incr("serf.watchdog.breach", 1, {"plane": "host"})
        flight.record("watchdog-breach", plane="host", tick=self.ticks,
                      invariants=["task-exception"],
                      detail=verdict.detail[:512])
        self._last_dump_tick = None
        self._maybe_dump("task-exception", verdict)

    def install_task_hook(self):
        """Register :meth:`on_task_failure` with the ``spawn_logged``
        seam; returns the hook handle (pass to ``remove_failure_hook``,
        or call :meth:`uninstall_task_hook`)."""
        from serf_tpu.utils.tasks import add_failure_hook

        if self._hook is None:
            self._hook = self.on_task_failure
            add_failure_hook(self._hook)
        return self._hook

    def uninstall_task_hook(self) -> None:
        from serf_tpu.utils.tasks import remove_failure_hook

        if self._hook is not None:
            remove_failure_hook(self._hook)
            self._hook = None

    # -- reads ---------------------------------------------------------------

    def state(self) -> Dict[str, Any]:
        """The live watchdog state (obswatch/obstop surface it; the
        black box embeds it)."""
        return {
            "plane": "host",
            "ok": self.breaches == 0,
            "ticks": self.ticks,
            "breaches": self.breaches,
            "armed": self.armed,
            "first_breach": (self.first_breach.to_dict()
                             if self.first_breach else None),
            "last_verdict": (self.last_verdict.to_dict()
                             if self.last_verdict else None),
            "bundles": [p for box in self._blackboxes
                        for p in box.bundle_paths()],
            "history": [v.to_dict() for v in self.history[-16:]],
        }

    def format(self) -> str:
        st = self.state()
        lines = [f"[host] watchdog: "
                 f"{'GREEN' if st['ok'] else 'BREACHED'} "
                 f"({st['ticks']} tick(s), "
                 f"{len(st['armed'])} armed, "
                 f"{len(st['bundles'])} bundle(s))"]
        fb = st["first_breach"]
        if fb is not None:
            lines.append(f"  FAIL  first breach at tick {fb['tick']}: "
                         f"{', '.join(fb['breaches'])}"
                         + (f" — {fb['detail']}" if fb["detail"] else ""))
        for name in st["armed"]:
            if fb is None or name not in fb["breaches"]:
                lines.append(f"  ok    {name}")
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# standard host armings (faults/host + obstop share these)
# ---------------------------------------------------------------------------


def arm_serf_invariants(wd: Watchdog, nodes,
                        sink: Optional[metrics.MetricsSink] = None
                        ) -> None:
    """Arm the standard live host invariants over a set of Serf nodes
    (``nodes``: a key->Serf mapping, or a zero-arg callable returning
    one — the chaos executor passes its live view so crashed/paused
    nodes never false-breach):

    - **clock-monotonicity** — every node's Lamport/event/query clocks
      never regress between ticks (per node, per generation: a restart
      resets the baseline);
    - **shed-accounting** — the ``serf.overload.*`` admission ledgers
      are monotone counters (a regressing ledger is broken accounting);
    - **bounded-buffers** — no ``serf.queue.bytes.<name>`` gauge past
      the cap, no ``serf.pipeline.depth`` past its cap (overload must
      degrade service, never memory);
    - **health-floor** — the worst node health score stays below the
      Lifeguard unhealthy threshold.
    """
    nodes_fn = nodes if callable(nodes) else (lambda: nodes)
    last_clocks: Dict[Any, tuple] = {}

    def clock_monotonic():
        bad = []
        for key, s in list(nodes_fn().items()):
            try:
                cur = (s.clock.time(), s.event_clock.time(),
                       s.query_clock.time())
            except Exception:  # noqa: BLE001 — a node mid-shutdown
                last_clocks.pop(key, None)
                continue
            gen = id(s)   # a restart swaps in a new Serf object under
            # the same key — new generation, fresh clock baseline
            prev = last_clocks.get(key)
            if prev is not None and prev[0] == gen \
                    and any(c < p for c, p in zip(cur, prev[1])):
                bad.append(f"{key}: {prev[1]} -> {cur}")
            last_clocks[key] = (gen, cur)
        return (not bad,
                "; ".join(bad) if bad
                else f"{len(last_clocks)} node clock(s) monotone")

    last_counters: Dict[str, float] = {}

    def shed_accounting():
        s = sink if sink is not None else metrics.global_sink()
        totals: Dict[str, float] = {}
        with s._lock:
            for (name, _labels), v in s.counters.items():
                if name.startswith("serf.overload."):
                    totals[name] = totals.get(name, 0.0) + v
        bad = [f"{n} regressed {last_counters[n]:g} -> {v:g}"
               for n, v in totals.items()
               if n in last_counters and v < last_counters[n]]
        last_counters.update(totals)
        return (not bad, "; ".join(bad) if bad
                else f"{len(totals)} overload ledger(s) monotone")

    def bounded_buffers():
        s = sink if sink is not None else metrics.global_sink()
        over = []
        with s._lock:
            for (name, _labels), v in s.gauges.items():
                if name.startswith("serf.queue.bytes.") \
                        and v > wd.cfg.queue_bytes_cap:
                    over.append(f"{name}={v:g} > "
                                f"{wd.cfg.queue_bytes_cap}")
                elif name == "serf.pipeline.depth" \
                        and v > wd.cfg.pipeline_depth_cap:
                    over.append(f"{name}={v:g} > "
                                f"{wd.cfg.pipeline_depth_cap}")
        return (not over, "; ".join(over) if over else
                "queue/pipeline gauges inside caps")

    def health_floor():
        worst = None
        worst_node = None
        for key, s in list(nodes_fn().items()):
            try:
                rep = s.health_report()
            except Exception:  # noqa: BLE001
                continue
            if worst is None or rep.score < worst:
                worst, worst_node = rep.score, key
        if worst is None:
            return True, "no health reports yet"
        ok = worst >= wd.cfg.health_floor
        return ok, (f"worst node {worst_node} score {worst:.0f} "
                    f"{'>=' if ok else '<'} floor "
                    f"{wd.cfg.health_floor:.0f}")

    wd.arm("clock-monotonicity", clock_monotonic)
    wd.arm("shed-accounting", shed_accounting)
    wd.arm("bounded-buffers", bounded_buffers)
    wd.arm("health-floor", health_floor)


def arm_shed_ratio_watch(wd: Watchdog, store) -> None:
    """Watch the ``shed-ratio`` SLO live: running cumulative
    shed/(admitted+shed) folded from the sampler's delta rings (the
    ``obs/slo._host_ratio_series`` rule: burn evidence in the SLO's own
    units, never raw counters against a ratio objective)."""

    def series() -> Optional[List[float]]:
        shed = store.get("serf.overload.ingress_shed")
        adm = store.get("serf.overload.ingress_admitted")
        if shed is None or adm is None:
            return None
        cum_s = cum_a = 0.0
        out = []
        a_pts = adm.points()
        ai = 0
        for t, sv in shed.points():
            while ai < len(a_pts) and a_pts[ai][0] <= t:
                cum_a += a_pts[ai][1]
                ai += 1
            cum_s += sv
            total = cum_s + cum_a
            out.append(cum_s / total if total > 0 else 0.0)
        return out

    wd.watch_slo("shed-ratio", series)


def arm_rotation_latency_watch(wd: Watchdog, store) -> None:
    """Watch the ``rotation-latency`` SLO live over the sampled
    ``serf.rotation.latency-ms`` gauge (each ``KeyManager`` op gauges
    its wall latency; the sampler folds gauge levels into the store) —
    converted to the SLO's own seconds so a key op stuck re-querying a
    partitioned cluster burns while the run is still going, not only
    at the post-run judgment."""

    def series() -> Optional[List[float]]:
        ts = store.get("serf.rotation.latency-ms")
        if ts is None:
            return None
        return [v / 1e3 for v in ts.values()]

    wd.watch_slo("rotation-latency", series)


def arm_false_dead_watch(wd: Watchdog, store) -> None:
    """Watch the ``false-dead`` SLO live over the device telemetry ring
    (obswatch's device leg folds rows into the same store) — any
    sustained nonzero false-DEAD level burns."""

    def series() -> Optional[List[float]]:
        ts = store.get("serf.model.swim.false-dead")
        return ts.values() if ts is not None else None

    wd.watch_slo("false-dead", series)
