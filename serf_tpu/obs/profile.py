"""Per-phase round profiler: where does the flagship round's time go?

Round-5's verdict was that the 1M-node bench ran ~450× below the HBM
roofline with "no profile that explains where the time goes".  This
module is that profile: it jits each ``cluster_round`` phase IN
ISOLATION (inject, gossip select/exchange/merge, probe, refute, declare,
push-pull, vivaldi — the same module-level phase functions the
production round composes, so there is nothing to drift), times each
with a device→host transfer barrier (the only trustworthy completion
barrier on this tunnel — see bench.py), pulls XLA's own
``cost_analysis()`` bytes/flops for the compiled phase, and cross-checks
against the analytic byte model (``accounting.round_traffic`` — whose
entries cite the same code paths).

Per phase it reports wall-clock, compiled bytes/flops, modeled bytes,
achieved GB/s, and the achieved-vs-roofline fraction; for the whole
round it reports how much of the compiled bytes the named phases
attribute (the tier-1 self-check pins ≥ 90% — an unattributed byte
blob is exactly the "no profile exists" failure mode recurring), and it
flags the ANOMALOUS phase: the one whose share of wall time most
exceeds its share of bytes — time a bandwidth model cannot explain
(dispatch overhead, serial lowering, host sync) and therefore the first
place to look when measured rps sits far under the byte ceiling.

Used by ``tools/roundprof.py`` (CLI, ``--json`` contract) and embedded
in ``BENCH_DETAIL.json`` by bench.py on every run (CPU fallback
included).

The SHARDED flagship path profiles the same way (``profile_round(...,
mesh=, schedule=)`` / ``roundprof --mesh``): phases jit on node-sharded
inputs, the exchange phase is the explicit ``parallel.ring`` leg, and —
the compiled module being SPMD — every cost-analysis byte column reads
per chip, with the ≥90% attribution self-check preserved.

The profile self-identifies which KERNEL DISPATCH PATH it exercised
(``kernel_path``: xla | kernels | fused — the pallas families of
``ops/round_kernels.py``) and carries the byte model's amortized
``full_plane_passes`` per plane for that path, which is how
``tools/roundprof.py --fused`` shows the fused family streaming the
packed stamp plane strictly fewer times per round than the phased
kernels (the removed selection read).
"""

from __future__ import annotations

import functools
import time
from typing import Any, Dict, List, Optional

#: phases profiled, in protocol order (names match accounting.by_phase)
PHASE_NAMES = ("inject", "selection", "exchange", "merge", "probe",
               "refute", "declare", "push_pull", "vivaldi")


def _sync(out) -> None:
    """Device→host transfer of one element — the completion barrier."""
    import jax
    import numpy as np

    leaves = jax.tree_util.tree_leaves(out)
    np.asarray(jax.device_get(leaves[0]))


def _kernel_path(cfg, mesh_devices: int) -> str:
    """Which dispatch path (accounting.KERNEL_PATHS) this config runs on
    — THE production decision (``dissemination.pallas_dispatch_mode``,
    the pure half of ``_pallas_mode``), so the profile's path label and
    byte model can never drift from what the phases actually dispatch.
    ``mesh_devices=0`` = unsharded."""
    from serf_tpu.models.dissemination import pallas_dispatch_mode

    return pallas_dispatch_mode(cfg.gossip, mesh_devices)[0] or "xla"


def _cost(compiled) -> Dict[str, float]:
    try:
        ca = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 - backend-dependent surface
        return {}
    if isinstance(ca, list):
        ca = ca[0] if ca else {}
    return ca or {}


def _seeded_cluster(cfg, key, events_per_round: int, warm_rounds: int,
                    mesh=None):
    """A populated steady-ish state: seeded facts + churn, then a warm
    sustained scan (compiles once; plays the detection cycle out).
    ``mesh`` shards the state and warms on the sharded flagship path."""
    import jax
    import jax.numpy as jnp

    from serf_tpu.models.dissemination import K_USER_EVENT, inject_fact
    from serf_tpu.models.swim import make_cluster, run_cluster_sustained

    n = cfg.n
    state = make_cluster(cfg, key)
    g = state.gossip
    spacing = max(1, n // 8)
    for i in range(8):
        g = inject_fact(g, cfg.gossip, subject=(i * spacing) % n,
                        kind=K_USER_EVENT, incarnation=0, ltime=i + 1,
                        origin=(i * spacing) % n)
    n_dead = min(8, n // 100)
    if n_dead:
        ids = [(i * (n // n_dead) + 1) % n for i in range(n_dead)]
        g = g._replace(alive=g.alive.at[jnp.asarray(ids)].set(False))
    state = state._replace(gossip=g)
    if mesh is not None:
        from serf_tpu.parallel.mesh import shard_state
        state = shard_state(state, mesh)
    run = jax.jit(functools.partial(run_cluster_sustained, cfg=cfg,
                                    events_per_round=events_per_round,
                                    mesh=mesh),
                  static_argnames=("num_rounds",))
    state = run(state, key=jax.random.key(7), num_rounds=warm_rounds)
    _sync(state.gossip.round)
    return state


def _phase_callables(state, cfg, events_per_round: int, mesh=None,
                     schedule: str = "ring"):
    """(name, jitted_fn, args) per phase — each jits EXACTLY the
    production phase function on the warmed state.  With ``mesh`` the
    inputs are sharded and the exchange phase is the explicit
    ``parallel.ring.exchange_sharded`` leg under ``schedule``."""
    import jax
    import jax.numpy as jnp

    from serf_tpu.models import antientropy, dissemination, failure
    from serf_tpu.models.swim import vivaldi_phase

    gcfg, fcfg = cfg.gossip, cfg.failure
    g = state.gossip
    key = jax.random.key(11)
    m = events_per_round
    eids = (g.round * m + jnp.arange(m, dtype=jnp.int32) + 1)
    origins = jax.random.randint(jax.random.key(12), (m,), 0, cfg.n,
                                 dtype=jnp.int32)

    if mesh is not None:
        from serf_tpu.parallel.ring import exchange_sharded
        exchange_fn = functools.partial(exchange_sharded, mesh=mesh,
                                        schedule=schedule)
    else:
        exchange_fn = dissemination.exchange_phase

    def inject(g, key):
        return dissemination.inject_facts_batch(
            g, gcfg, eids, dissemination.K_USER_EVENT,
            incarnations=jnp.zeros((m,), jnp.uint32),
            ltimes=eids.astype(jnp.uint32), origins=origins,
            active=jnp.ones((m,), bool))

    # phase inputs are materialized once so each phase is timed alone;
    # mesh threads into select/merge so the fused pallas kernels run
    # under shard_map exactly as the production sharded round does
    packets = jax.jit(functools.partial(dissemination.select_phase,
                                        cfg=gcfg, mesh=mesh))(g)
    incoming = jax.jit(functools.partial(exchange_fn,
                                         cfg=gcfg))(packets, key=key)
    _sync(incoming)

    phases = [
        ("inject", inject, (g,)),
        ("selection",
         lambda g, key: dissemination.select_phase(g, gcfg, mesh=mesh),
         (g,)),
        ("exchange",
         lambda p, key: exchange_fn(p, gcfg, key),
         (packets,)),
        ("merge",
         lambda g, key: dissemination.merge_phase(g, incoming, gcfg,
                                                  mesh=mesh),
         (g,)),
        ("probe",
         lambda g, key: failure.probe_round(g, gcfg, fcfg, key), (g,)),
        ("refute",
         lambda g, key: failure.refute_round(g, gcfg, fcfg, key), (g,)),
        ("declare",
         lambda g, key: failure.declare_round(g, gcfg, fcfg, key), (g,)),
        ("push_pull",
         lambda g, key: antientropy.push_pull_round(g, gcfg, key), (g,)),
        ("vivaldi",
         lambda s, key: vivaldi_phase(s, cfg, key, key), (state,)),
    ]
    return [(name, jax.jit(fn), args) for name, fn, args in phases]


def profile_round(cfg, events_per_round: int = 2, timed_calls: int = 3,
                  warm_rounds: int = 24,
                  hbm_bytes_per_s: Optional[float] = None,
                  mesh=None, schedule: str = "ring") -> Dict[str, Any]:
    """Profile one sustained flagship round phase-by-phase.

    With ``mesh`` the profile runs the SHARDED flagship path: state is
    node-sharded, the exchange phase is the explicit shard_map leg under
    ``schedule``, and — because the compiled module is SPMD — XLA's
    cost-analysis bytes are per-chip, so every byte column (and the
    ≥90% attribution self-check) reads per chip.  ``devices``/
    ``schedule`` in the output say which flavor ran.

    Returns the JSON-ready dict documented in the module docstring
    (``tools/roundprof.py --json`` prints it verbatim)."""
    import jax

    from serf_tpu.models.accounting import (
        V5E_HBM_BYTES_PER_S,
        round_traffic,
    )
    from serf_tpu.models.swim import sustained_round
    from serf_tpu.obs.device import dispatch_timer

    if hbm_bytes_per_s is None:
        hbm_bytes_per_s = V5E_HBM_BYTES_PER_S
    n_devices = 1
    if mesh is not None:
        from serf_tpu.parallel.mesh import NODE_AXIS
        n_devices = mesh.shape[NODE_AXIS]
        if cfg.n % n_devices != 0:
            # the per-chip byte columns assume exactly N/P per chip and
            # the authored exchange schedule; an indivisible N would
            # silently profile the GSPMD fallback under those labels
            raise ValueError(
                f"sharded profile needs n divisible by the mesh: "
                f"n={cfg.n}, devices={n_devices}")
    key = jax.random.key(5)
    state = _seeded_cluster(cfg, jax.random.key(0), events_per_round,
                            warm_rounds, mesh=mesh)

    # which dispatch path this profile actually exercises (the fused
    # pallas family, the standalone kernels, or plain XLA) — the pure
    # production decision, no fallback side effects
    kernel_path = _kernel_path(cfg, 0 if mesh is None else n_devices)

    # analytic model, per-OCCURRENCE bytes per phase (isolated phase
    # calls pay the full occurrence; the amortized column is what one
    # average round pays at the configured cadences)
    report = round_traffic(cfg, regime="sustained",
                           sustained_rate=events_per_round,
                           path=kernel_path)
    model_occur: Dict[str, float] = {}
    model_amort: Dict[str, float] = {}
    for e in report.entries:
        model_occur[e.phase] = model_occur.get(e.phase, 0.0) + e.nbytes
        model_amort[e.phase] = model_amort.get(e.phase, 0.0) + e.amortized

    rows: List[Dict[str, Any]] = []
    for name, jfn, args in _phase_callables(state, cfg, events_per_round,
                                            mesh=mesh, schedule=schedule):
        lowered = jfn.lower(*args, key=key)
        compiled = lowered.compile()
        ca = _cost(compiled)
        with dispatch_timer(f"profile.{name}", signature=cfg.n):
            _sync(compiled(*args, key=key))          # warm dispatch
        t0 = time.perf_counter()
        for _ in range(timed_calls):
            _sync(compiled(*args, key=key))
        wall_ms = (time.perf_counter() - t0) * 1e3 / timed_calls
        xla_bytes = float(ca.get("bytes accessed", 0.0))
        rows.append({
            "phase": name,
            "wall_ms": round(wall_ms, 4),
            "xla_bytes": xla_bytes,
            "xla_flops": float(ca.get("flops", 0.0)),
            "model_bytes": round(model_occur.get(name, 0.0), 1),
            "model_amortized_bytes": round(model_amort.get(name, 0.0), 1),
            "achieved_gbps": round(xla_bytes / max(wall_ms, 1e-9) / 1e6,
                                   3),
            "roofline_frac": round(
                xla_bytes / max(wall_ms, 1e-9) * 1e3 / hbm_bytes_per_s,
                6),
        })

    # the whole compiled round, same workload (inject + cluster_round)
    whole = jax.jit(functools.partial(
        sustained_round, cfg=cfg, events_per_round=events_per_round,
        mesh=mesh))
    lowered = whole.lower(state, key=key)
    compiled = lowered.compile()
    wca = _cost(compiled)
    _sync(compiled(state, key=key))
    t0 = time.perf_counter()
    for _ in range(timed_calls):
        _sync(compiled(state, key=key))
    whole_wall = (time.perf_counter() - t0) * 1e3 / timed_calls
    whole_bytes = float(wca.get("bytes accessed", 0.0))

    total_phase_ms = sum(r["wall_ms"] for r in rows) or 1e-9
    total_phase_bytes = sum(r["xla_bytes"] for r in rows) or 1e-9
    anomaly = None
    for r in rows:
        r["wall_share"] = round(r["wall_ms"] / total_phase_ms, 4)
        byte_share = r["xla_bytes"] / total_phase_bytes
        r["byte_share"] = round(byte_share, 4)
        # time a bandwidth model cannot explain: wall share far above
        # byte share — dispatch/serialization, not HBM streaming
        r["excess"] = round(r["wall_share"] / max(byte_share, 1e-4), 2)
        if anomaly is None or r["excess"] > anomaly["excess"]:
            anomaly = r

    # per-phase model bytes on a mesh are per chip (the planes are
    # node-sharded), matching the SPMD cost-analysis column
    if n_devices > 1:
        for r in rows:
            r["model_bytes"] = round(r["model_bytes"] / n_devices, 1)
            r["model_amortized_bytes"] = round(
                r["model_amortized_bytes"] / n_devices, 1)

    out = {
        "n": cfg.n,
        "k": cfg.gossip.k_facts,
        "regime": "sustained",
        "events_per_round": events_per_round,
        "backend": jax.default_backend(),
        "pack_stamp": cfg.gossip.pack_stamp,
        # which kernel dispatch path ran (accounting.KERNEL_PATHS) and
        # the byte model's amortized full-plane streaming passes per
        # round for it — the fused-vs-phased "removed pass" evidence
        # (tools/roundprof.py --fused prints the delta)
        "kernel_path": kernel_path,
        "full_plane_passes": {p: round(v, 3)
                              for p, v in report.passes_by_plane().items()},
        "hbm_bytes_per_s": hbm_bytes_per_s,
        # sharded flavor: >1 devices means every byte column is PER CHIP
        # (SPMD module) and the exchange ran the explicit schedule
        "devices": n_devices,
        "schedule": schedule if n_devices > 1 else None,
        "phases": rows,
        "whole_round": {
            "wall_ms": round(whole_wall, 4),
            "xla_bytes": whole_bytes,
            "model_amortized_bytes": round(
                report.total_bytes / n_devices, 1),
            "roofline_frac": round(
                whole_bytes / max(whole_wall, 1e-9) * 1e3
                / hbm_bytes_per_s, 6),
            "measured_rps_bound": round(1e3 / max(whole_wall, 1e-9), 2),
            "model_ceiling_rps": round(
                report.ceiling_rounds_per_sec(hbm_bytes_per_s), 1),
        },
        # the acceptance metric: how much of the whole round's compiled
        # bytes the named phases explain (≥ 0.9 pinned in tier-1)
        "attributed_bytes_frac": round(
            total_phase_bytes / whole_bytes, 4) if whole_bytes else None,
        "anomalous_phase": {
            "phase": anomaly["phase"], "excess": anomaly["excess"],
            "reason": "wall share exceeds byte share by this factor — "
                      "time HBM streaming cannot explain",
        } if anomaly else None,
    }
    return out


def profile_table(profile: Dict[str, Any]) -> str:
    """Human rendering of a :func:`profile_round` result."""
    shard = (f" devices={profile['devices']}"
             f" schedule={profile['schedule']} (per-chip bytes)"
             if profile.get("devices", 1) > 1 else "")
    lines = [
        f"per-phase round profile: n={profile['n']} k={profile['k']} "
        f"backend={profile['backend']} regime={profile['regime']} "
        f"pack_stamp={profile['pack_stamp']} "
        f"path={profile.get('kernel_path', 'xla')}" + shard,
        f"{'phase':<10} {'wall ms':>9} {'XLA MB':>9} {'model MB':>9} "
        f"{'GB/s':>8} {'roofline':>9} {'excess':>7}",
    ]
    for r in profile["phases"]:
        lines.append(
            f"{r['phase']:<10} {r['wall_ms']:>9.3f} "
            f"{r['xla_bytes'] / 1e6:>9.2f} "
            f"{r['model_bytes'] / 1e6:>9.2f} {r['achieved_gbps']:>8.2f} "
            f"{r['roofline_frac']:>9.4f} {r.get('excess', 0):>7.2f}")
    w = profile["whole_round"]
    lines.append(
        f"{'ROUND':<10} {w['wall_ms']:>9.3f} {w['xla_bytes'] / 1e6:>9.2f} "
        f"{w['model_amortized_bytes'] / 1e6:>9.2f} — roofline "
        f"{w['roofline_frac']:.4f}, bound {w['measured_rps_bound']} rps "
        f"(model ceiling {w['model_ceiling_rps']})")
    frac = profile.get("attributed_bytes_frac")
    lines.append(f"attributed bytes: "
                 f"{'n/a' if frac is None else f'{frac:.1%}'} of the "
                 f"compiled round explained by named phases")
    an = profile.get("anomalous_phase")
    if an:
        lines.append(f"anomalous phase: {an['phase']} "
                     f"(excess ×{an['excess']}) — {an['reason']}")
    return "\n".join(lines)
