"""Black-box forensics: the bounded "what the plane looked like when it
broke" artifact.

When the watchdog (``obs/watchdog.py``) sees a breach — an invariant
violated, an SLO burning on both windows, a process-fatal task
exception — reconstructing the moment post-hoc is already too late: the
flight ring rolls, the sampler rings downsample, the health scorer
consumes its own accumulators.  :class:`BlackBox` freezes the whole
observability plane at the moment of breach into ONE versioned JSON
bundle per node: the flight-ring tail (via the watchdog-owned
``FlightRecorder.dump(since_seq=)`` cursor, so consecutive bundles carry
disjoint tails), timeseries ring tails, the lifecycle snapshot, the
health report, the SLO verdict history, the live watchdog state, and the
active record/replay window.  Bundles rotate under a max-bundles /
max-bytes budget — repeated breaches can never fill a disk.

The bundle format is a persisted cross-version artifact exactly like a
checkpoint or a recording, so it is drift-pinned: :data:`BLACKBOX_SCHEMA`
(section -> ordered field list) is AST-fingerprinted by
``serf_tpu.analysis.schema`` and pinned in ``schema_pins.json``; every
bundle stamps the pinned version and :func:`validate_bundle` fails
closed on a mismatch.  Changing the layout without
``python tools/serflint.py --bump-schema`` is a lint failure.

Cluster collection rides the gossip plane itself: the
``_serf_blackbox`` internal query (same mergeable-partials discipline as
``_serf_stats``, ``obs/cluster.py``) scatters, every node answers with a
compact bundle inventory, and :func:`collect_cluster_blackbox` folds the
answers — any node can pull "where are everyone's crash dumps" without
a side channel.  ``tools/blackbox.py`` renders/diffs bundles and exports
them as a Perfetto lane.

Self-telemetry: ``serf.blackbox.bundles`` / ``serf.blackbox.bytes`` /
``serf.blackbox.rotated``.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from serf_tpu.obs import flight
from serf_tpu.utils import metrics

#: bundle layout: section -> ordered field list.  serflint AST-extracts
#: this literal (``analysis/schema.blackbox_spec``), fingerprints it and
#: holds it to the ``blackbox`` pin — bump with ``--bump-schema``.
BLACKBOX_SCHEMA = {
    "meta": ("schema", "version", "node", "seq", "reason", "detail",
             "wall_time"),
    "watchdog": ("state",),
    "flight": ("events", "since_seq", "last_seq", "dropped"),
    "series": ("tails",),
    "lifecycle": ("snapshot",),
    "health": ("report",),
    "slo": ("verdicts",),
    "recording": ("active",),
}

#: the meta.schema marker every bundle carries
BLACKBOX_MARKER = "serf-blackbox"

DEFAULT_MAX_BUNDLES = 8
DEFAULT_MAX_BYTES = 4 << 20
#: ring-tail points captured per series (bounded bundle, not a full dump)
SERIES_TAIL_POINTS = 32

#: the internal query name (rides the ``_serf_`` dispatch prefix)
BLACKBOX_QUERY = "_serf_blackbox"
BLACKBOX_QUERY_VERSION = 1


def blackbox_schema_version() -> int:
    """The pinned bundle-format version (stamped into every bundle;
    validation fails closed on mismatch)."""
    from serf_tpu.analysis.schema import blackbox_schema_version as v

    return v()


class BlackBox:
    """One node's bounded forensic dump target.

    Sources are callables read lazily at dump time (a source that raises
    yields ``None`` for its section — forensics must capture what it
    can, never crash the breach path): ``store`` a ``SeriesStore`` for
    ring tails, ``lifecycle`` -> snapshot dict, ``health`` -> report
    dict, ``slo_verdicts`` -> verdict dict list, ``recording`` -> active
    record/replay window info."""

    def __init__(self, directory: str, node: str = "local",
                 max_bundles: int = DEFAULT_MAX_BUNDLES,
                 max_bytes: int = DEFAULT_MAX_BYTES,
                 recorder=None, store=None,
                 lifecycle: Optional[Callable[[], Any]] = None,
                 health: Optional[Callable[[], Any]] = None,
                 slo_verdicts: Optional[Callable[[], Any]] = None,
                 recording: Optional[Callable[[], Any]] = None,
                 clock=time.time):
        self.directory = directory
        self.node = node
        self.max_bundles = max(1, int(max_bundles))
        self.max_bytes = max(1, int(max_bytes))
        self._recorder = recorder
        self.store = store
        self._lifecycle = lifecycle
        self._health = health
        self._slo_verdicts = slo_verdicts
        self._recording = recording
        self._clock = clock
        self._seq = 0
        self._cursor = 0   # own flight cursor (watchdog-less dumps)
        self.rotated = 0
        os.makedirs(directory, exist_ok=True)

    def _rec(self):
        return self._recorder if self._recorder is not None \
            else flight.global_recorder()

    @staticmethod
    def _try(fn: Optional[Callable[[], Any]]) -> Any:
        if fn is None:
            return None
        try:
            return fn()
        except Exception:  # noqa: BLE001 — capture what we can
            return None

    def dump(self, reason: str, detail: str = "",
             flight_events: Optional[List[Dict[str, Any]]] = None,
             watchdog: Optional[Dict[str, Any]] = None) -> str:
        """Write one bundle; returns its path.  ``flight_events`` (from
        the watchdog's owned cursor) wins over the box's own incremental
        cursor; ``watchdog`` is the live ``Watchdog.state()`` dict."""
        rec = self._rec()
        if flight_events is None:
            flight_events = rec.dump(since_seq=self._cursor)
        since = self._cursor
        self._cursor = rec.last_seq
        tails = None
        if self.store is not None:
            try:
                tails = self.store.tail(last=SERIES_TAIL_POINTS)
            except Exception:  # noqa: BLE001
                tails = None
        self._seq += 1
        bundle = {
            "meta": {
                "schema": BLACKBOX_MARKER,
                "version": blackbox_schema_version(),
                "node": self.node,
                "seq": self._seq,
                "reason": reason,
                "detail": detail,
                "wall_time": self._clock(),
            },
            "watchdog": {"state": watchdog},
            "flight": {
                "events": flight_events,
                "since_seq": since,
                "last_seq": rec.last_seq,
                "dropped": rec.dropped,
            },
            "series": {"tails": tails},
            "lifecycle": {"snapshot": self._try(self._lifecycle)},
            "health": {"report": self._try(self._health)},
            "slo": {"verdicts": self._try(self._slo_verdicts)},
            "recording": {"active": self._try(self._recording)},
        }
        path = os.path.join(
            self.directory, f"blackbox-{self.node}-{self._seq:06d}.json")
        with open(path, "w", encoding="utf-8") as f:
            json.dump(bundle, f, indent=1, sort_keys=True)
            f.write("\n")
        metrics.incr("serf.blackbox.bundles", 1, {"node": self.node})
        self._rotate()
        return path

    # -- rotation ------------------------------------------------------------

    def bundle_paths(self) -> List[str]:
        """Retained bundle paths, oldest first."""
        try:
            names = sorted(
                n for n in os.listdir(self.directory)
                if n.startswith(f"blackbox-{self.node}-")
                and n.endswith(".json"))
        except OSError:
            return []
        return [os.path.join(self.directory, n) for n in names]

    def _rotate(self) -> None:
        paths = self.bundle_paths()
        sizes = {p: os.path.getsize(p) for p in paths
                 if os.path.exists(p)}
        total = sum(sizes.values())
        while paths and (len(paths) > self.max_bundles
                         or total > self.max_bytes):
            victim = paths.pop(0)
            total -= sizes.get(victim, 0)
            try:
                os.remove(victim)
            except OSError:
                pass
            self.rotated += 1
            metrics.incr("serf.blackbox.rotated", 1,
                         {"node": self.node})
        metrics.gauge("serf.blackbox.bytes", float(total),
                      {"node": self.node})


# ---------------------------------------------------------------------------
# bundle load + validation (fail closed, like checkpoint/recording)
# ---------------------------------------------------------------------------


def validate_bundle(bundle: Any) -> List[str]:
    """Hold a parsed bundle to :data:`BLACKBOX_SCHEMA`; returns the
    problem list (empty = valid).  A version mismatch is a problem —
    loading fails closed exactly like a recording header mismatch."""
    problems: List[str] = []
    if not isinstance(bundle, dict):
        return [f"bundle is {type(bundle).__name__}, not an object"]
    for section, fields in BLACKBOX_SCHEMA.items():
        sec = bundle.get(section)
        if not isinstance(sec, dict):
            problems.append(f"missing section {section!r}")
            continue
        for f in fields:
            if f not in sec:
                problems.append(f"section {section!r} missing {f!r}")
        for extra in sorted(set(sec) - set(fields)):
            problems.append(f"section {section!r} has undeclared "
                            f"field {extra!r}")
    for extra in sorted(set(bundle) - set(BLACKBOX_SCHEMA)):
        problems.append(f"undeclared section {extra!r}")
    meta = bundle.get("meta")
    if isinstance(meta, dict):
        if meta.get("schema") != BLACKBOX_MARKER:
            problems.append(f"meta.schema {meta.get('schema')!r} != "
                            f"{BLACKBOX_MARKER!r}")
        v = meta.get("version")
        if v != blackbox_schema_version():
            problems.append(
                f"bundle is schema v{v!r}, this build reads "
                f"v{blackbox_schema_version()} (see MIGRATION.md; "
                "bump with `python tools/serflint.py --bump-schema`)")
    return problems


def load_bundle(path: str) -> Dict[str, Any]:
    """Parse + validate one bundle file; raises ``ValueError`` with the
    full problem list on anything malformed."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            bundle = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise ValueError(f"unreadable bundle {path}: {e}") from e
    problems = validate_bundle(bundle)
    if problems:
        raise ValueError(f"invalid bundle {path}: " + "; ".join(problems))
    return bundle


# ---------------------------------------------------------------------------
# the _serf_blackbox internal query (mergeable partials, like _serf_stats)
# ---------------------------------------------------------------------------


def node_blackbox_payload(serf) -> bytes:
    """This node's ``_serf_blackbox`` answer: a compact bundle inventory
    (NOT bundle contents — those stay on disk; the inventory fits the
    1 KiB response budget)::

        {"v": 1, "id": node_id, "n": bundle count, "rotated": n,
         "dir": bundle directory,
         "latest": {"seq", "reason", "wall_time", "path"} | null}
    """
    box = getattr(serf, "blackbox", None)
    inv: Dict[str, Any] = {
        "v": BLACKBOX_QUERY_VERSION,
        "id": serf.local_id,
        "n": 0,
        "rotated": 0,
        "dir": None,
        "latest": None,
    }
    if box is not None:
        paths = box.bundle_paths()
        inv["n"] = len(paths)
        inv["rotated"] = box.rotated
        inv["dir"] = box.directory
        if paths:
            latest = paths[-1]
            entry: Dict[str, Any] = {"path": latest}
            try:
                meta = load_bundle(latest)["meta"]
                entry.update(seq=meta["seq"], reason=meta["reason"],
                             wall_time=meta["wall_time"])
            except ValueError:
                entry["invalid"] = True
            inv["latest"] = entry
    return json.dumps(inv, separators=(",", ":"), sort_keys=True).encode()


def decode_node_blackbox(raw: bytes) -> Dict[str, Any]:
    """Parse and validate one responder inventory; raises ``ValueError``
    on anything malformed (the folder skips bad responders)."""
    try:
        d = json.loads(raw.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ValueError(f"bad blackbox payload: {e}") from e
    if not isinstance(d, dict) or d.get("v") != BLACKBOX_QUERY_VERSION:
        raise ValueError(
            f"unsupported blackbox payload version "
            f"{d.get('v') if isinstance(d, dict) else None!r}")
    if not isinstance(d.get("id"), str) or not d["id"]:
        raise ValueError("blackbox payload missing node id")
    if not isinstance(d.get("n"), int) or d["n"] < 0:
        raise ValueError("blackbox payload missing bundle count")
    d.setdefault("rotated", 0)
    d.setdefault("dir", None)
    d.setdefault("latest", None)
    return d


@dataclass(frozen=True)
class ClusterBlackbox:
    """The folded cluster bundle inventory one ``cluster_blackbox()``
    call produces."""

    origin: str
    expected: int
    nodes: Dict[str, Dict[str, Any]]

    @property
    def responders(self) -> int:
        return len(self.nodes)

    @property
    def complete(self) -> bool:
        return self.responders >= self.expected

    @property
    def bundles(self) -> int:
        return sum(d.get("n", 0) for d in self.nodes.values())

    def to_dict(self) -> Dict[str, Any]:
        return {
            "origin": self.origin,
            "expected": self.expected,
            "responders": self.responders,
            "complete": self.complete,
            "bundles": self.bundles,
            "nodes": {nid: dict(d)
                      for nid, d in sorted(self.nodes.items())},
        }


@dataclass(frozen=True)
class BlackboxPartial:
    """A mergeable partial fold of ``_serf_blackbox`` answers — the
    ``StatsPartial`` contract verbatim: partials over disjoint responder
    sets combine associatively and commutatively (node-id-keyed dict
    union; one node answers with one inventory) to exactly the fold of
    the union, so a relay tier can fold its subtree locally and ship one
    partial upward."""

    nodes: Dict[str, Dict[str, Any]] = field(default_factory=dict)

    @classmethod
    def of(cls, reports: Dict[str, Dict[str, Any]]) -> "BlackboxPartial":
        return cls(nodes=dict(reports))

    def merge(self, other: "BlackboxPartial") -> "BlackboxPartial":
        merged = dict(other.nodes)
        merged.update(self.nodes)
        return BlackboxPartial(nodes=merged)

    def finish(self, origin: str, expected: int) -> ClusterBlackbox:
        return ClusterBlackbox(origin=origin, expected=expected,
                               nodes=self.nodes)


async def collect_cluster_blackbox(serf, params=None) -> ClusterBlackbox:
    """Scatter ``_serf_blackbox`` and fold every valid answer (plus this
    node's own inventory — the originator is authoritative about itself)
    into a :class:`ClusterBlackbox`."""
    from serf_tpu.obs.trace import span
    from serf_tpu.types.member import MemberStatus

    with span("serf.cluster.blackbox", node=serf.local_id) as sp:
        local = decode_node_blackbox(node_blackbox_payload(serf))
        nodes: Dict[str, Dict[str, Any]] = {local["id"]: local}
        alive = {m.node.id for m in serf.members()
                 if m.status == MemberStatus.ALIVE}
        resp = await serf.query(BLACKBOX_QUERY, b"", params)
        async for r in resp.responses():
            try:
                d = decode_node_blackbox(r.payload)
            except ValueError:
                continue
            nodes.setdefault(d["id"], d)
            if alive <= set(nodes):
                break
        expected = len(alive) if alive else 1
        sp.attrs["responders"] = len(nodes)
        sp.attrs["bundles"] = sum(d.get("n", 0) for d in nodes.values())
        return BlackboxPartial.of(nodes).finish(serf.local_id, expected)
