"""Device-plane dispatch timing: compile-vs-steady wall-clock split.

JAX work is opaque to host metrics — a jitted call's first invocation
pays tracing + XLA compilation, later ones only dispatch.  Benchmarks
that cannot attribute that split report compile time as throughput
(round-3 lesson).  ``dispatch_timer(op, signature)`` times the wrapped
host-side call and classifies it: the first call for a given
``(op, signature)`` is ``phase=compile`` (tracing/compilation happens
there), the rest ``phase=steady``.  ``signature`` should carry whatever
forces recompilation (shapes, static args), so a re-trace at a new shape
is honestly re-labeled compile.

Timings land in two places: the ``serf.device.dispatch-ms`` histogram
(labels ``op``/``phase``) on the global sink, and an in-module registry
``dispatch_summary()`` renders for ``bench.py`` to embed in
``BENCH_DETAIL.json``.

NOTE: a wall clock around an async dispatch measures host-side cost
only; for device-complete timings the caller must end with a host
transfer (see bench.py's ``_time_rounds`` barrier discussion) — which is
exactly how bench.py drives this module.

This module deliberately imports no JAX: the per-model metric emitters
that DO touch device arrays live beside their states
(``serf_tpu/models/*.emit_*_metrics``).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Hashable, Optional, Tuple

from serf_tpu.utils import metrics

_lock = threading.Lock()
#: (op, signature) pairs whose compile call has been observed
_seen: set = set()
#: op -> {"compile_ms": float, "steady_ms": [..bounded..], "calls": int}
_registry: Dict[str, Dict[str, Any]] = {}
_STEADY_KEEP = 64


def reset_dispatch_registry() -> None:
    with _lock:
        _seen.clear()
        _registry.clear()


def record_dispatch(op: str, elapsed_ms: float,
                    signature: Hashable = None,
                    labels: Optional[Dict[str, str]] = None) -> Tuple[str, float]:
    """Record one timed dispatch; returns ``(phase, elapsed_ms)``."""
    key = (op, signature)
    with _lock:
        if key not in _seen:
            _seen.add(key)
            phase = "compile"
        else:
            phase = "steady"
        ent = _registry.setdefault(
            op, {"compile_ms": 0.0, "steady_ms": [], "calls": 0})
        ent["calls"] += 1
        if phase == "compile":
            # a re-trace (new signature) accumulates: total compile cost
            ent["compile_ms"] += elapsed_ms
        else:
            ent["steady_ms"].append(elapsed_ms)
            if len(ent["steady_ms"]) > _STEADY_KEEP:
                del ent["steady_ms"][0]
    lab = {"op": op, "phase": phase}
    if labels:
        lab.update(labels)
    metrics.observe("serf.device.dispatch-ms", elapsed_ms, lab)
    metrics.incr("serf.device.dispatch.calls", 1, {"op": op})
    return phase, elapsed_ms


@contextmanager
def dispatch_timer(op: str, signature: Hashable = None,
                   labels: Optional[Dict[str, str]] = None):
    """Time a device-plane dispatch (or trace) on the host wall clock."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        record_dispatch(op, (time.perf_counter() - t0) * 1e3,
                        signature, labels)


def dispatch_summary() -> Dict[str, Dict[str, float]]:
    """Per-op summary for benchmark artifacts: total compile ms, mean
    steady ms, call count."""
    out: Dict[str, Dict[str, float]] = {}
    with _lock:
        for op, ent in sorted(_registry.items()):
            steady = ent["steady_ms"]
            out[op] = {
                "compile_ms": round(ent["compile_ms"], 3),
                "steady_ms_mean": round(sum(steady) / len(steady), 4)
                if steady else 0.0,
                "calls": ent["calls"],
            }
    return out
