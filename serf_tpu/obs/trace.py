"""Trace spans: lightweight monotonic timing with parent/child nesting.

``span(name, **attrs)`` is a (sync) context manager cheap enough for the
hot protocol paths: one ``perf_counter`` pair, a contextvar swap, and one
ring append on exit.  Nesting rides :mod:`contextvars`, so spans nest
correctly across ``await`` points — each asyncio task sees its own
current-span chain (the same reason the reference uses ``tracing``'s
task-local subscriber contexts rather than a thread-local).

Finished spans land in a bounded :class:`TraceBuffer` (drop-oldest), and
every finished span also feeds the ``serf.trace.span-ms`` histogram
(label ``span=<name>``) so aggregate latencies survive after the raw
spans rotate out of the ring.

Cross-node propagation (PR 2): a :class:`TraceContext` — 16-byte random
trace id, origin node id, hop count — rides query and user-event wire
messages (``serf_tpu.types.messages``).  ``trace_scope(ctx)`` installs it
in a contextvar; while active, every span opened AND every flight-recorder
event recorded (``obs.flight``) is stamped with the trace id, so one
query fired on node A produces correlated spans/flight events on every
node that relays or answers it.  The context is observability metadata
only: a missing or malformed context never affects protocol behavior.
"""

from __future__ import annotations

import contextvars
import itertools
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

from serf_tpu.types.trace import TRACE_ID_LEN, TraceContext  # noqa: F401
from serf_tpu.utils import metrics

#: finished spans retained (ring, drop-oldest)
TRACE_BUFFER_SIZE = 1024

#: per-packet span names would flood the ring at gossip rates, evicting
#: the rare spans (probe failures, compactions) the ring exists to keep
#: after an incident — retain only 1-in-N of these (the first of each
#: name always; every span still feeds the latency histogram)
RING_SAMPLE_EVERY: Dict[str, int] = {"wire.encode": 16, "wire.decode": 16}
_ring_counts: Dict[str, int] = {}

_current_span: contextvars.ContextVar[Optional["Span"]] = \
    contextvars.ContextVar("serf_tpu_current_span", default=None)
_ids = itertools.count(1)

_current_trace: contextvars.ContextVar[Optional[TraceContext]] = \
    contextvars.ContextVar("serf_tpu_current_trace", default=None)


def new_trace(origin: str) -> TraceContext:
    """Mint a fresh trace context rooted at ``origin`` (hop 0)."""
    return TraceContext(os.urandom(TRACE_ID_LEN), origin, 0)


def current_trace() -> Optional[TraceContext]:
    return _current_trace.get()


@contextmanager
def trace_scope(ctx: Optional[TraceContext]):
    """Install ``ctx`` as the active trace for the block; spans opened and
    flight events recorded inside are stamped with its trace id.  A None
    context is a no-op scope (callers never need to branch)."""
    if ctx is None:
        yield None
        return
    token = _current_trace.set(ctx)
    try:
        yield ctx
    finally:
        _current_trace.reset(token)


class Span:
    """One timed operation.  ``duration_ms`` is valid after ``finish``."""

    __slots__ = ("span_id", "parent_id", "name", "attrs", "depth",
                 "start", "end", "status", "_t0")

    def __init__(self, name: str, parent: Optional["Span"],
                 attrs: Dict[str, Any]):
        self.span_id = next(_ids)
        self.parent_id = parent.span_id if parent is not None else 0
        self.depth = parent.depth + 1 if parent is not None else 0
        self.name = name
        self.attrs = attrs
        self.start = time.time()
        self._t0 = time.perf_counter()
        self.end: Optional[float] = None
        self.status = "ok"

    @property
    def duration_ms(self) -> float:
        if self.end is None:
            return (time.perf_counter() - self._t0) * 1e3
        return (self.end - self._t0) * 1e3

    def finish(self) -> None:
        self.end = time.perf_counter()

    def to_dict(self) -> Dict[str, Any]:
        return {
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "depth": self.depth,
            "name": self.name,
            "attrs": dict(self.attrs),
            "start": self.start,
            "duration_ms": round(self.duration_ms, 4),
            "status": self.status,
        }


class TraceBuffer:
    """Bounded ring of finished spans, oldest dropped first."""

    def __init__(self, capacity: int = TRACE_BUFFER_SIZE):
        self.capacity = max(1, capacity)
        self._lock = threading.Lock()
        self._ring: List[Optional[Span]] = [None] * self.capacity
        self._pos = 0
        self.recorded = 0

    def add(self, s: Span) -> None:
        with self._lock:
            self._ring[self._pos] = s
            self._pos = (self._pos + 1) % self.capacity
            self.recorded += 1

    def spans(self, name: Optional[str] = None) -> List[Span]:
        """Retained spans, oldest first (optionally filtered by name)."""
        with self._lock:
            if self.recorded >= self.capacity:
                ordered = self._ring[self._pos:] + self._ring[:self._pos]
            else:
                ordered = self._ring[:self._pos]
        out = [s for s in ordered if s is not None]
        if name is not None:
            out = [s for s in out if s.name == name]
        return out

    def dump(self, name: Optional[str] = None,
             limit: Optional[int] = None) -> List[Dict[str, Any]]:
        out = [s.to_dict() for s in self.spans(name)]
        return out[-limit:] if limit is not None else out

    def __len__(self) -> int:
        return min(self.recorded, self.capacity)

    def reset(self) -> None:
        with self._lock:
            self._ring = [None] * self.capacity
            self._pos = 0
            self.recorded = 0


_global = TraceBuffer()


def global_tracer() -> TraceBuffer:
    return _global


def set_global_tracer(buf: TraceBuffer) -> None:
    global _global
    _global = buf


def trace_dump(name: Optional[str] = None,
               limit: Optional[int] = None) -> List[Dict[str, Any]]:
    return _global.dump(name, limit)


def current_span() -> Optional[Span]:
    return _current_span.get()


class _LiteSpan:
    """Stand-in yielded by sampled-out spans: accepts attr/status writes
    like a full Span but allocates no ids and joins no parent chain."""

    __slots__ = ("name", "attrs", "status")

    def __init__(self, name: str, attrs: Dict[str, Any]):
        self.name = name
        self.attrs = attrs
        self.status = "ok"


@contextmanager
def span(name: str, **attrs):
    """Time a block; nest under the caller's active span (if any).  When a
    cross-node trace is active (``trace_scope``), the span is stamped with
    its trace id under the ``trace`` attr."""
    tc = _current_trace.get()
    if tc is not None and "trace" not in attrs:
        attrs["trace"] = tc.hex_id
    every = RING_SAMPLE_EVERY.get(name, 1)
    if every > 1:
        n = _ring_counts.get(name, 0)
        _ring_counts[name] = n + 1
        if n % every:
            # sampled out of the ring: histogram-only fast path — no Span
            # allocation, no contextvar swap, no ring lock.  These names
            # fire per packet; this keeps the hot path cheap.
            t0 = time.perf_counter()
            s = _LiteSpan(name, attrs)
            try:
                yield s
            except BaseException:
                s.status = "error"
                raise
            finally:
                metrics.observe("serf.trace.span-ms",
                                (time.perf_counter() - t0) * 1e3,
                                {"span": name})
            return
    parent = _current_span.get()
    s = Span(name, parent, attrs)
    token = _current_span.set(s)
    try:
        yield s
    except BaseException:
        s.status = "error"
        raise
    finally:
        _current_span.reset(token)
        s.finish()
        _global.add(s)
        metrics.observe("serf.trace.span-ms", s.duration_ms,
                        {"span": name})
