"""Gossip propagation observatory: per-fact dissemination tracing,
redundancy accounting, and coverage-curve judgment on both planes
(ISSUE 16 tentpole).

Every other observability surface watches the *machinery* (queues,
latencies, liveness counters); this module watches the *protocol* — a
fact traced through the cluster, and the fraction of the wire budget
that re-teaches what receivers already know.

**Device plane.**  ``models/swim.run_cluster_sustained(...,
collect_propagation=True)`` tags the first injected batch as M sentinel
facts and stacks one :data:`PROPAGATION_FIELDS` row per round inside
the jitted scan: the redundancy-ledger pair from the gossip exchange
(``models/dissemination.round_step``'s ``collect_propagation`` flag —
wire slots shipped vs. slots actually learned, the merge pass's learn
plane recounted definitionally) plus per-sentinel coverage folded from
the SAME ``colcnt`` partials the PR-15 telemetry row already reduces
(``round_telemetry(with_cols=True)`` — one known-plane unpack serves
both rows, and the rows ride the run's ONE ``device_get``).  This
module is the host-side consumer: coverage curves, time-to-50/90/99%,
first-learn rounds, cumulative redundancy, ring series, metrics.

**Host plane.**  :class:`PropagationLedger` counts per-broadcast
provenance off the PR-2 ``TraceContext`` ids riding user-event wire
messages — accepts, dedup hits, rebroadcasts, and a bounded
recent-trace map with first-seen clocks — and
:func:`fold_propagation` merges the per-node ledger summaries through
the ``_serf_stats`` mergeable-partials contract into
``ClusterSnapshot.propagation``.

The analytic companions (:func:`analytic_redundancy`,
:func:`analytic_rounds_to_coverage`) give the model-predicted numbers
the measured curves are judged against — ``models/accounting
.propagation_split`` prices the same split in bytes against the
217 MB/round flagship floor.
"""

from __future__ import annotations

import dataclasses
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

#: field order of the per-round device propagation row (``f32[P]``) —
#: assembled by ``models/swim.propagation_row`` (hardcoded stack, the
#: ``telemetry_finish`` convention); :data:`PROPAGATION_SERIES` maps
#: each field to its declared metric name.  ``slots_*`` are exact
#: integer counts carried in f32 (exact up to 2^24 per round — the 1M
#: flagship ships ~2·10^8 slots/round, within range).
PROPAGATION_FIELDS = ("slots_sent", "slots_learned", "slots_redundant",
                      "redundancy", "alive", "cov_min", "cov_mean",
                      "cov_max")

#: the propagation row's merge contract — how each field's per-shard
#: partial combines to the global value, mirroring the telemetry row's
#: ``TELEMETRY_MERGE`` (models/swim.py) and held to
#: :data:`PROPAGATION_FIELDS` + the README propagation table by
#: serflint's ``propagation-field-drift`` rule:
#:
#: - ``"sum"`` — an integer count summed over the node axis (the ledger
#:   pair and its derived ``slots_redundant``; ``redundancy`` is the
#:   ratio of the summed counts, divided AFTER the reduce on integers
#:   every chip agrees on — the ``agreement`` precedent);
#: - ``"replicated"`` — folded from already-reduced/replicated operands
#:   only (the ``cov_*`` fields read the post-psum ``colcnt`` against
#:   the replicated fact table): no collective of its own.
#:
#: On the sharded flagship the "sum" fields are in fact reduced by
#: GSPMD itself (the ledger reductions run on global sharded planes
#: outside the shard_map leg), which satisfies the same associativity
#: contract with zero explicit collectives.
PROPAGATION_MERGE = {
    "slots_sent": "sum",
    "slots_learned": "sum",
    "slots_redundant": "sum",
    "redundancy": "sum",
    "alive": "sum",
    "cov_min": "replicated",
    "cov_mean": "replicated",
    "cov_max": "replicated",
}

#: per-round ring-series names for the propagation row.  ``alive`` is
#: deliberately absent: it already rides the telemetry row's
#: ``serf.model.gossip.alive`` series, and the two rows commonly land
#: in the same store.
PROPAGATION_SERIES: Tuple[Tuple[str, str], ...] = (
    ("slots_sent", "serf.propagation.slots-sent"),
    ("slots_learned", "serf.propagation.slots-learned"),
    ("slots_redundant", "serf.propagation.slots-redundant"),
    ("redundancy", "serf.propagation.redundancy"),
    ("cov_min", "serf.propagation.cov-min"),
    ("cov_mean", "serf.propagation.cov-mean"),
    ("cov_max", "serf.propagation.cov-max"),
)

#: the coverage-curve SLO thresholds (percent) every surface renders
COVERAGE_MARKS = (50, 90, 99)


def propagation_to_store(rows, base_round: int = 0, store=None,
                         capacity: Optional[int] = None):
    """Convert stacked per-round propagation rows (``f32[R, P]``,
    already on host) into ring series keyed by the declared
    ``serf.propagation.*`` names — the exact
    ``timeseries.telemetry_to_store`` shape, absolute round timestamps
    (``base_round + i + 1``)."""
    from serf_tpu.obs.timeseries import DEFAULT_CAPACITY, SeriesStore

    if store is None:
        store = SeriesStore(capacity=capacity or DEFAULT_CAPACITY)
    name_of = dict(PROPAGATION_SERIES)
    idx = {f: i for i, f in enumerate(PROPAGATION_FIELDS)}
    for i, row in enumerate(rows):
        t = float(base_round + i + 1)
        for field, name in name_of.items():
            store.append(name, t, float(row[idx[field]]), kind="gauge")
    return store


def monotone_coverage(cov) -> List[List[float]]:
    """Per-sentinel running-max coverage curve.  The raw per-round
    sentinel coverage reads 0 once a sentinel's ring slot recycles (the
    fact-identity match finds nothing) — dissemination itself is
    monotone, so the cummax IS the true curve and the cliff is just the
    observation window closing."""
    out: List[List[float]] = []
    best: Optional[List[float]] = None
    for row in cov:
        vals = [float(v) for v in row]
        best = vals if best is None else \
            [max(b, v) for b, v in zip(best, vals)]
        out.append(list(best))
    return out


def time_to_coverage(curve: Sequence[Sequence[float]], frac: float
                     ) -> Optional[int]:
    """Rounds (1-based, relative to the traced window) until EVERY
    sentinel's monotone coverage reaches ``frac`` — the worst sentinel
    defines the batch's time-to-X%.  None if the window closed first."""
    for i, row in enumerate(curve):
        if row and min(row) >= frac:
            return i + 1
    return None


def first_learn_rounds(curve: Sequence[Sequence[float]],
                       alive: Sequence[float]) -> List[Optional[int]]:
    """Per-sentinel round (1-based) at which anyone beyond the origin
    learned the fact: first round with coverage count >= 2 nodes."""
    if not curve:
        return []
    out: List[Optional[int]] = [None] * len(curve[0])
    for i, (row, n_alive) in enumerate(zip(curve, alive)):
        for j, v in enumerate(row):
            if out[j] is None and v * max(float(n_alive), 1.0) >= 2.0:
                out[j] = i + 1
    return out


@dataclasses.dataclass(frozen=True)
class PropagationSummary:
    """Host-side digest of a traced device run — what the SLO judges,
    the CLI renders, and the bench pins."""
    rounds: int                           # traced rounds
    sentinels: int                        # M
    time_to: Dict[int, Optional[int]]     # {50: r, 90: r, 99: r}
    first_learn: List[Optional[int]]      # per sentinel, 1-based
    final_coverage: float                 # min monotone coverage at end
    slots_sent: float                     # run totals
    slots_learned: float
    redundancy: float                     # cumulative (sent-learned)/sent
    curve: List[float]                    # per-round mean monotone cov

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["time_to"] = {str(k): v for k, v in self.time_to.items()}
        return d


def summarize_propagation(rows, cov) -> PropagationSummary:
    """Fold the device scan outputs (``rows f32[R, P]``, per-sentinel
    coverage ``cov f32[R, M]``, both already on host) into the
    :class:`PropagationSummary` every surface consumes."""
    idx = {f: i for i, f in enumerate(PROPAGATION_FIELDS)}
    rows = [[float(v) for v in r] for r in rows]
    curve = monotone_coverage(cov)
    sent = sum(r[idx["slots_sent"]] for r in rows)
    learned = sum(r[idx["slots_learned"]] for r in rows)
    alive = [r[idx["alive"]] for r in rows]
    return PropagationSummary(
        rounds=len(rows),
        sentinels=len(curve[0]) if curve else 0,
        time_to={m: time_to_coverage(curve, m / 100.0)
                 for m in COVERAGE_MARKS},
        first_learn=first_learn_rounds(curve, alive),
        final_coverage=min(curve[-1]) if curve and curve[-1] else 0.0,
        slots_sent=sent,
        slots_learned=learned,
        redundancy=(sent - learned) / sent if sent > 0 else 0.0,
        curve=[sum(r) / len(r) if r else 0.0 for r in curve],
    )


def analytic_redundancy(window_rounds: int, fanout: int) -> float:
    """The model-predicted steady-state redundancy of transmit-limited
    gossip: each knower re-ships a fact for ``window_rounds`` rounds at
    ``fanout`` reads per round, but each node learns it exactly once —
    useful fraction ``1/(window · fanout)``, redundancy the complement.
    ~0.988 at the 1M flagship (window 28, fanout 3): the protocol's
    byte floor is overwhelmingly re-teaching, which is the epidemic
    robustness being paid for — the point of measuring it is to judge
    *changes* (zone-aware peer selection, deferred stamp flushes)
    against the floor, not to drive it to zero."""
    return 1.0 - 1.0 / float(max(window_rounds * fanout, 1))


def analytic_rounds_to_coverage(n: int, fanout: int,
                                frac: float = 0.99) -> int:
    """Model-predicted rounds for one fact to reach ``frac`` coverage
    under pull gossip: iterate the mean-field map ``p' = p + (1-p)·(1 -
    (1-p)^f)`` (a non-knower learns iff any of its ``f`` pulls hits a
    knower) from a single origin.  Deterministic — the 1M-model number
    BASELINE.json pins."""
    p = 1.0 / max(n, 2)
    rounds = 0
    while p < frac:
        p = p + (1.0 - p) * (1.0 - (1.0 - p) ** fanout)
        rounds += 1
        if rounds > 10_000:     # unreachable for sane (n, fanout)
            break
    return rounds


def emit_propagation_metrics(summary: PropagationSummary,
                             labels=None) -> dict:
    """Emit the device-plane propagation gauges onto the process sink
    (pull-based, between scans — the jit discipline of every other
    ``emit_*_metrics``).  Returns the ``{name: value}`` dict."""
    from serf_tpu.utils import metrics

    t99 = summary.time_to.get(99)
    vals = {
        "serf.propagation.slots-sent": summary.slots_sent,
        "serf.propagation.slots-learned": summary.slots_learned,
        "serf.propagation.slots-redundant":
            summary.slots_sent - summary.slots_learned,
        "serf.propagation.redundancy": summary.redundancy,
        "serf.propagation.cov-min": summary.final_coverage,
        "serf.propagation.cov-mean":
            summary.curve[-1] if summary.curve else 0.0,
        "serf.propagation.cov-max":
            summary.curve[-1] if summary.curve else 0.0,
        "serf.propagation.t99-rounds":
            float(t99) if t99 is not None else float("nan"),
    }
    for name, value in vals.items():
        metrics.gauge(name, value, labels)
    return vals


def format_propagation(summary, plane: str = "device") -> str:
    """One coverage-curve verdict line for the chaos/obswatch reports,
    printed beside the invariant/SLO verdicts.  Accepts a
    :class:`PropagationSummary`, its dict form, or the host-plane
    propagation dict."""
    if isinstance(summary, PropagationSummary):
        summary = summary.to_dict()
    if summary is None:
        return f"propagation[{plane}]: not traced"
    if "time_to" in summary:               # device summary
        tt = summary["time_to"]
        marks = " ".join(
            f"t{m}={tt.get(str(m), tt.get(m))}r" for m in COVERAGE_MARKS)
        return (f"propagation[{plane}]: {marks} over "
                f"{summary['rounds']}r ({summary['sentinels']} sentinels,"
                f" final cov {summary['final_coverage']:.3f}), "
                f"redundancy {summary['redundancy']:.3f}")
    cov = summary.get("coverage", 0.0)     # host probe dict
    tta = summary.get("time_to_all_ms")
    tta_s = f"{tta:.0f}ms" if tta is not None else "never"
    return (f"propagation[{plane}]: probe reached "
            f"{summary.get('reached', 0)}/{summary.get('nodes', 0)} "
            f"nodes (cov {cov:.2f}) in {tta_s}, "
            f"dup-ratio {summary.get('dup_ratio', 0.0):.3f}")


_BARS = " ▁▂▃▄▅▆▇█"


def render_coverage(curve: Sequence[float], width: int = 60,
                    height: int = 8) -> str:
    """ASCII coverage-curve render for ``tools/gossipscope.py``: rounds
    on x (resampled to ``width``), coverage 0..1 on y, with the
    :data:`COVERAGE_MARKS` thresholds as labeled gridlines."""
    vals = [min(max(float(v), 0.0), 1.0) for v in curve]
    if not vals:
        return "(no coverage data)"
    if len(vals) > width:
        step = len(vals) / width
        vals = [vals[min(int(i * step), len(vals) - 1)]
                for i in range(width)]
    lines = []
    for level in range(height, 0, -1):
        lo = (level - 1) / height
        mark = next((m for m in reversed(COVERAGE_MARKS)
                     if lo < m / 100.0 <= level / height), None)
        label = f"{mark:>3d}%" if mark is not None else "    "
        row = "".join(
            "█" if v >= level / height else
            _BARS[max(0, min(8, int((v - lo) * height * 8)))]
            if v > lo else " "
            for v in vals)
        lines.append(f"{label} ┤{row}")
    lines.append("     └" + "─" * len(vals)
                 + f"  rounds 1..{len(curve)}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# host plane: per-broadcast provenance
# ---------------------------------------------------------------------------

#: bounded recent-trace map size — provenance is a debugging tail, not
#: a log (the flight-recorder sizing philosophy)
RECENT_TRACES = 8
#: recent traces shipped in the ``_serf_stats`` payload (the 1 KiB
#: payload budget caps the per-node contribution)
PAYLOAD_TRACES = 4


class PropagationLedger:
    """Per-node user-event propagation provenance (host plane).

    Counts how the gossip fabric treats broadcasts at THIS node —
    ``seen`` (first-sight accepts), ``duplicates`` (dedup-ring hits:
    the host analog of a redundant wire slot), ``rebroadcasts``
    (re-queued onto the event broadcast queue) — and keeps a bounded
    map of recently seen ``TraceContext`` ids with first-seen
    monotonic clocks and hop counts, so a traced event's
    time-to-all-nodes can be folded cluster-wide
    (:func:`fold_propagation` via the ``_serf_stats`` partials).

    Wired into ``host/serf.py``'s ``_handle_user_event`` (accept +
    dedup branches) and ``_dispatch`` (rebroadcast decision); every
    method is O(1) on the hot path.
    """

    def __init__(self, recent: int = RECENT_TRACES):
        self.seen = 0
        self.duplicates = 0
        self.rebroadcasts = 0
        self._recent: "OrderedDict[str, Dict]" = OrderedDict()
        self._cap = recent

    def _note(self, tctx) -> None:
        if tctx is None:
            return
        key = tctx.hex_id
        if key not in self._recent:
            self._recent[key] = {"first_seen": time.monotonic(),
                                 "hops": int(tctx.hops)}
            while len(self._recent) > self._cap:
                self._recent.popitem(last=False)

    def accept(self, tctx=None) -> None:
        self.seen += 1
        self._note(tctx)

    def duplicate(self, tctx=None) -> None:
        self.duplicates += 1

    def rebroadcast(self, tctx=None) -> None:
        self.rebroadcasts += 1

    def first_seen(self, trace_hex: str) -> Optional[float]:
        e = self._recent.get(trace_hex)
        return None if e is None else e["first_seen"]

    @property
    def dup_ratio(self) -> float:
        total = self.seen + self.duplicates
        return self.duplicates / total if total else 0.0

    def summary(self) -> list:
        """The ``_serf_stats`` payload contribution: ``[seen, dup,
        rebroadcast, {trace_hex: age_ms}]`` — ages instead of absolute
        clocks so the fold needs no cross-node clock agreement beyond
        the stats query's own skew."""
        now = time.monotonic()
        traces = {k: round((now - e["first_seen"]) * 1e3, 1)
                  for k, e in list(self._recent.items())[-PAYLOAD_TRACES:]}
        return [self.seen, self.duplicates, self.rebroadcasts, traces]


def fold_propagation(nodes: Dict[str, Sequence]) -> dict:
    """Fold per-node ledger summaries (``decode_node_stats``'s ``prop``
    field, any merge order) into the cluster propagation aggregate for
    ``ClusterSnapshot.propagation`` — pure sums plus per-trace
    node-count/age-spread, so fold(union) == fold(fold(parts)) holds
    by associativity (the ``_serf_stats`` partial-merge contract)."""
    seen = dup = rebroadcast = 0
    traces: Dict[str, Dict] = {}
    for payload in nodes.values():
        if not isinstance(payload, (list, tuple)) or len(payload) < 3:
            continue
        seen += int(payload[0])
        dup += int(payload[1])
        rebroadcast += int(payload[2])
        tr = payload[3] if len(payload) > 3 else {}
        if isinstance(tr, dict):
            for hex_id, age_ms in tr.items():
                t = traces.setdefault(hex_id,
                                      {"nodes": 0, "spread_ms": 0.0,
                                       "_min": None, "_max": None})
                t["nodes"] += 1
                age = float(age_ms)
                t["_min"] = age if t["_min"] is None else min(t["_min"], age)
                t["_max"] = age if t["_max"] is None else max(t["_max"], age)
    for t in traces.values():
        # age spread across nodes = propagation spread of that event
        # (oldest first-sight minus newest), loopback-grade precision
        t["spread_ms"] = round((t.pop("_max") or 0.0)
                               - (t.pop("_min") or 0.0), 1)
    total = seen + dup
    return {
        "seen": seen,
        "duplicates": dup,
        "rebroadcasts": rebroadcast,
        "dup_ratio": dup / total if total else 0.0,
        "traces": traces,
    }
