"""Cluster-scope stats aggregation: scatter ``_serf_stats``, fold the answers.

No single node's ``stats()`` can show cluster behavior — convergence,
dissemination coverage, fleet health.  This module computes those
summaries *inside the communication fabric itself* (the in-network
aggregation stance of the Ultracomputer lineage, PAPERS.md): the
``_serf_stats`` internal query scatters over the gossip plane like any
other query, every node answers with a compact JSON self-report (health
score + components, member counts, clocks, queue depths, a membership
view digest), and the originator folds the responses into one
:class:`ClusterSnapshot` — min/p50/max per key metric, the unhealthy-node
list, and membership-view divergence across responders.

Surfaces: ``Serf.cluster_stats()`` (the API), the ``_serf_stats`` handler
in ``serf_tpu.host.internal_query`` (the responder), and
``tools/obstop.py`` (the CLI renderer).

Payload format (versioned; kept compact so it fits the default 1 KiB
``query_response_size_limit``)::

    {"v": 1, "id": node_id, "health": 0-100,
     "hc": {component: load 0-1, ...},
     "members": n, "failed": n, "left": n,
     "mt": member_ltime, "et": event_ltime, "qt": query_ltime,
     "q": [intent_depth, event_depth, query_depth],
     "lag": loop_lag_ms, "digest": 12-hex membership view digest,
     "prop": [seen, duplicates, rebroadcasts, {trace_hex: age_ms}]}

The ``prop`` field is the node's propagation-ledger summary
(``obs.propagation.PropagationLedger.summary``): dissemination
provenance folded cluster-wide by :func:`fold_propagation` into
``ClusterSnapshot.propagation``.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Sequence, Tuple

from serf_tpu.obs.health import UNHEALTHY_THRESHOLD
from serf_tpu.obs.propagation import fold_propagation
from serf_tpu.obs.trace import span
from serf_tpu.utils.metrics import percentile_of

#: the internal query name (rides the ``_serf_`` dispatch prefix)
STATS_QUERY = "_serf_stats"
STATS_VERSION = 1

#: per-node scalars folded into min/p50/max aggregates
AGGREGATE_KEYS = ("health", "members", "queue", "lag")


def membership_digest(pairs: Sequence[Tuple[str, str]]) -> str:
    """12-hex digest of a membership view: sorted ``(node_id, status)``
    pairs.  Two nodes whose views agree produce the same digest, so the
    snapshot can report view divergence without shipping whole member
    lists through the 1 KiB response budget."""
    h = hashlib.sha256()
    for node_id, status in sorted(pairs):
        h.update(node_id.encode("utf-8", errors="replace"))
        h.update(b"\x00")
        h.update(status.encode("ascii", errors="replace"))
        h.update(b"\x01")
    return h.hexdigest()[:12]


def node_stats_payload(serf) -> bytes:
    """This node's ``_serf_stats`` answer (compact JSON, sorted keys)."""
    report = serf.health_report()
    digest = membership_digest(
        [(ms.id, ms.member.status.name) for ms in serf._members.values()])
    st = {
        "v": STATS_VERSION,
        "id": serf.local_id,
        "health": report.score,
        "hc": {n: round(c.load, 3) for n, c in report.components.items()},
        "members": len(serf._members),
        "failed": len(serf._failed),
        "left": len(serf._left),
        "mt": int(serf.clock.time()),
        "et": int(serf.event_clock.time()),
        "qt": int(serf.query_clock.time()),
        "q": [len(serf.intent_broadcasts), len(serf.event_broadcasts),
              len(serf.query_broadcasts)],
        "lag": round(serf.loop_lag_ms(), 2),
        "digest": digest,
    }
    ledger = getattr(serf, "prop_ledger", None)
    if ledger is not None:
        st["prop"] = ledger.summary()
    return json.dumps(st, separators=(",", ":"), sort_keys=True).encode()


def decode_node_stats(raw: bytes) -> Dict[str, Any]:
    """Parse and validate one responder payload; raises ``ValueError`` on
    anything malformed (the folder skips bad responders, never crashes)."""
    try:
        d = json.loads(raw.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ValueError(f"bad stats payload: {e}") from e
    if not isinstance(d, dict) or d.get("v") != STATS_VERSION:
        raise ValueError(f"unsupported stats payload version "
                         f"{d.get('v') if isinstance(d, dict) else None!r}")
    if not isinstance(d.get("id"), str) or not d["id"]:
        raise ValueError("stats payload missing node id")
    if not isinstance(d.get("health"), (int, float)):
        raise ValueError("stats payload missing health score")
    d.setdefault("hc", {})
    d.setdefault("q", [0, 0, 0])
    d.setdefault("lag", 0.0)
    d.setdefault("digest", "")
    d.setdefault("prop", [0, 0, 0, {}])
    return d


@dataclass(frozen=True)
class ClusterSnapshot:
    """The folded cluster view one ``cluster_stats()`` call produces."""

    origin: str
    expected: int                      # alive members at fold time
    nodes: Dict[str, Dict[str, Any]]   # node id -> decoded self-report
    aggregates: Dict[str, Dict[str, float]] = field(default_factory=dict)
    unhealthy: List[str] = field(default_factory=list)
    digests: Dict[str, str] = field(default_factory=dict)
    divergent: bool = False
    #: cluster-wide propagation aggregate (obs.propagation
    #: .fold_propagation over the per-node ``prop`` ledger summaries):
    #: seen/duplicates/rebroadcasts sums, dup_ratio, per-trace
    #: node-count + first-sight age spread
    propagation: Dict[str, Any] = field(default_factory=dict)

    @property
    def responders(self) -> int:
        return len(self.nodes)

    @property
    def complete(self) -> bool:
        return self.responders >= self.expected

    def to_dict(self) -> Dict[str, Any]:
        return {
            "origin": self.origin,
            "expected": self.expected,
            "responders": self.responders,
            "complete": self.complete,
            "nodes": {nid: dict(d) for nid, d in sorted(self.nodes.items())},
            "aggregates": self.aggregates,
            "unhealthy": list(self.unhealthy),
            "digests": dict(sorted(self.digests.items())),
            "divergent": self.divergent,
            "propagation": dict(self.propagation),
        }


def _scalar(d: Dict[str, Any], key: str) -> float:
    if key == "queue":
        q = d.get("q") or [0, 0, 0]
        return float(sum(q))
    return float(d.get(key, 0.0))


@dataclass(frozen=True)
class StatsPartial:
    """A mergeable partial fold of ``_serf_stats`` answers — the host
    twin of the device plane's in-collective telemetry partials
    (``models/swim.TELEMETRY_MERGE``): both aggregation planes share
    ONE contract — *partials over disjoint responder sets combine
    associatively and commutatively to exactly the fold of the union*.

    The partial carries the decoded per-node reports keyed by node id
    (bounded by responder count — the same 1 KiB-payload scale the
    query plane already bounds), so ``merge`` is a node-id-keyed dict
    union and ``finish`` computes min/p50/max over the merged reports —
    EXACT, not an approximation, which is exactly why the reports ride
    the partial instead of a (non-mergeable) pre-computed percentile.
    Associativity/commutativity holds over partials whose shared node
    ids carry the same report (one node answers with one payload; a
    node reached through two relay paths is the same answer) — pinned
    by tests/test_cluster_obs.py: any grouping and order of merges
    finishes to the direct fold of the union.  A relay tier (the
    multi-host DCN direction, ROADMAP item 4) can therefore fold its
    subtree's answers locally and ship one partial upward, exactly like
    the device row rides the exchange collective."""

    nodes: Dict[str, Dict[str, Any]] = field(default_factory=dict)

    @classmethod
    def of(cls, reports: Dict[str, Dict[str, Any]]) -> "StatsPartial":
        return cls(nodes=dict(reports))

    def merge(self, other: "StatsPartial") -> "StatsPartial":
        """Associative + commutative: dict union keyed by node id (a
        node id answering through two paths is the SAME answer — first
        writer wins, order-independent for well-formed responders)."""
        merged = dict(other.nodes)
        merged.update(self.nodes)
        return StatsPartial(nodes=merged)

    def finish(self, origin: str, expected: int) -> ClusterSnapshot:
        """Close the fold: exact min/p50/max per aggregate key over the
        merged multiset, unhealthy list, digest divergence."""
        nodes = self.nodes
        aggregates: Dict[str, Dict[str, float]] = {}
        for key in AGGREGATE_KEYS:
            vals = sorted(_scalar(d, key) for d in nodes.values())
            if not vals:
                continue
            aggregates[key] = {
                "min": vals[0],
                "p50": percentile_of(vals, 50),
                "max": vals[-1],
            }
        unhealthy = sorted(nid for nid, d in nodes.items()
                           if d["health"] < UNHEALTHY_THRESHOLD)
        digests = {nid: d.get("digest", "") for nid, d in nodes.items()}
        divergent = len(set(digests.values())) > 1
        propagation = fold_propagation(
            {nid: d.get("prop", [0, 0, 0, {}])
             for nid, d in nodes.items()})
        return ClusterSnapshot(origin=origin, expected=expected,
                               nodes=nodes, aggregates=aggregates,
                               unhealthy=unhealthy, digests=digests,
                               divergent=divergent,
                               propagation=propagation)


def fold_snapshot(origin: str, expected: int,
                  nodes: Dict[str, Dict[str, Any]]) -> ClusterSnapshot:
    """Fold decoded self-reports into one snapshot: min/p50/max per
    aggregate key, unhealthy-node list, view-digest divergence.  One
    call = build a partial and finish it; multi-tier callers build
    partials per subtree and ``merge`` before ``finish``."""
    return StatsPartial.of(nodes).finish(origin, expected)


async def collect_cluster_stats(serf, params=None) -> ClusterSnapshot:
    """Scatter ``_serf_stats`` over the cluster and fold every valid
    answer (plus this node's own report — the originator is authoritative
    about itself and must not depend on self-delivery) into a
    :class:`ClusterSnapshot`.  ``params`` is an optional
    ``QueryParam`` — pass one with a longer timeout for large clusters."""
    from serf_tpu.types.member import MemberStatus

    with span("serf.cluster.stats", node=serf.local_id) as sp:
        local = decode_node_stats(node_stats_payload(serf))
        nodes: Dict[str, Dict[str, Any]] = {local["id"]: local}
        alive = {m.node.id for m in serf.members()
                 if m.status == MemberStatus.ALIVE}
        resp = await serf.query(STATS_QUERY, b"", params)
        async for r in resp.responses():
            try:
                d = decode_node_stats(r.payload)
            except ValueError:
                continue
            nodes.setdefault(d["id"], d)
            if alive <= set(nodes):
                break   # every alive member answered: no need to wait
                        # out the query deadline for stragglers
        expected = len(alive) if alive else 1
        sp.attrs["responders"] = len(nodes)
        sp.attrs["expected"] = expected
        return fold_snapshot(serf.local_id, expected, nodes)


def render_table(snap: ClusterSnapshot) -> str:
    """Plain-text table of a snapshot (the obstop CLI's output)."""
    header = (f"cluster stats from {snap.origin} — "
              f"{snap.responders}/{snap.expected} nodes, "
              f"{len(snap.unhealthy)} unhealthy, "
              f"views {'DIVERGENT' if snap.divergent else 'converged'}")
    cols = ("NODE", "HEALTH", "MEMBERS", "FAILED", "QUEUE", "LAG-MS",
            "DIGEST", "WORST-COMPONENT")
    rows: List[Tuple[str, ...]] = []
    for nid in sorted(snap.nodes):
        d = snap.nodes[nid]
        hc: Dict[str, float] = d.get("hc") or {}
        worst = max(hc.items(), key=lambda kv: kv[1], default=(None, 0.0))
        worst_s = (f"{worst[0]}={worst[1]:.2f}"
                   if worst[0] is not None and worst[1] >= 0.005 else "-")
        rows.append((
            nid, str(d["health"]), str(d.get("members", "?")),
            str(d.get("failed", "?")), str(int(_scalar(d, "queue"))),
            f"{d.get('lag', 0.0):.1f}", d.get("digest", "") or "-", worst_s,
        ))
    widths = [max(len(c), *(len(r[i]) for r in rows)) if rows else len(c)
              for i, c in enumerate(cols)]
    lines = [header,
             "  ".join(c.ljust(widths[i]) for i, c in enumerate(cols))]
    for r in rows:
        lines.append("  ".join(v.ljust(widths[i]) for i, v in enumerate(r)))
    if snap.aggregates:
        agg = "  ".join(
            f"{k}: {v['min']:g}/{v['p50']:g}/{v['max']:g}"
            for k, v in sorted(snap.aggregates.items()))
        lines.append(f"aggregates (min/p50/max): {agg}")
    if snap.unhealthy:
        lines.append(f"unhealthy (<{UNHEALTHY_THRESHOLD}): "
                     + ", ".join(snap.unhealthy))
    return "\n".join(lines)
