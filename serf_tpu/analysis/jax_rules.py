"""serflint pass family (b): JAX tracing discipline.

Scoped to the device plane (``serf_tpu/models``, ``ops``, ``parallel``):
a single Python-level branch on a tracer, a host concretization inside a
jitted body, or an unhashable argument to a jitted callable silently
breaks compile caching, forces a recompile per call, or raises a
ConcretizationTypeError three layers away from the bug.

All detection is pure-AST.  "Traced" is approximated as:

- a function decorated with anything mentioning ``jit`` (``@jax.jit``,
  ``@partial(jax.jit, ...)``);
- a function whose NAME is passed to a tracing entry point
  (``lax.scan``/``cond``/``while_loop``/``fori_loop``/``switch``,
  ``shard_map``, ``vmap``/``pmap``, ``pallas_call``) anywhere in the
  same module, or wrapped as ``g = jax.jit(f)``;
- any ``def`` nested inside a traced function.

Parameters named ``self``/``cfg``/``config``/``mesh`` or annotated with
a ``*Config`` type are treated as static (they are hashable config, the
codebase's convention), so ``if cfg.with_failure:`` never fires.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Set, Tuple

from serf_tpu.analysis.core import (
    Finding,
    Project,
    SourceFile,
    call_name,
    finding,
    names_in,
    rule,
)

#: device-plane scope (project-relative path prefixes)
JAX_SCOPE = ("serf_tpu/models/", "serf_tpu/ops/", "serf_tpu/parallel/")

_TRACING_ENTRIES = frozenset({
    "scan", "cond", "while_loop", "fori_loop", "switch", "shard_map",
    "vmap", "pmap", "pallas_call", "custom_vjp", "checkpoint", "remat",
})

_STATIC_PARAM_NAMES = frozenset({"self", "cls", "cfg", "config", "mesh",
                                 "schedule", "opts"})

_TRANSFER_CALLS = frozenset({"jax.device_get", "np.asarray", "np.array",
                             "numpy.asarray", "numpy.array",
                             "jax.device_put"})

#: round-step code: the jitted hot path where a host transfer is a
#: per-round device sync (emit_*_metrics pulls are batched, and live
#: outside these name shapes)
_ROUND_NAME = re.compile(r"(^|_)(round|phase|step|pass)(_|$|\d)")


def _in_scope(src: SourceFile) -> bool:
    return src.rel.startswith(JAX_SCOPE)


def _mentions(node: ast.AST, needles: Set[str]) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and n.id in needles:
            return True
        if isinstance(n, ast.Attribute) and n.attr in needles:
            return True
    return False


def _module_traced_names(tree: ast.AST) -> Tuple[Set[str], Set[str]]:
    """(names of functions that get traced, names bound to jitted
    callables) for one module."""
    traced: Set[str] = set()
    jitted: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fname = call_name(node.func)
            tail = fname.split(".")[-1]
            if tail == "jit":
                # jax.jit(f, ...) — f is traced; a name bound to the
                # result is a jitted callable
                if node.args and isinstance(node.args[0], ast.Name):
                    traced.add(node.args[0].id)
            elif tail in _TRACING_ENTRIES:
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        traced.add(arg.id)
        elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if call_name(node.value.func).split(".")[-1] == "jit":
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        jitted.add(t.id)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_mentions(d, {"jit"}) for d in node.decorator_list):
                traced.add(node.name)
                jitted.add(node.name)
    return traced, jitted


def _static_params(fn: ast.FunctionDef) -> Set[str]:
    static = set()
    args = fn.args
    for a in [*args.posonlyargs, *args.args, *args.kwonlyargs]:
        if a.arg in _STATIC_PARAM_NAMES:
            static.add(a.arg)
        elif a.annotation is not None and _static_annotation(a.annotation):
            static.add(a.arg)
    return static


def _static_annotation(ann: ast.AST) -> bool:
    """Annotations that mark hashable/static config: ``GossipConfig``,
    ``Mesh``, plain ``int``/``bool``/``str``."""
    for n in ast.walk(ann):
        ident = n.id if isinstance(n, ast.Name) else (
            n.attr if isinstance(n, ast.Attribute) else None)
        if ident is None:
            continue
        if ident in ("int", "bool", "str") or ident.endswith(
                ("Config", "Mesh", "Schedule")):
            return True
    return False


def _data_params(fn: ast.FunctionDef) -> Set[str]:
    args = fn.args
    all_params = {a.arg for a in
                  [*args.posonlyargs, *args.args, *args.kwonlyargs]}
    return all_params - _static_params(fn)


def _traced_functions(src: SourceFile) -> List[ast.FunctionDef]:
    """Every FunctionDef in a traced context: named-traced functions and
    all defs nested inside them."""
    traced_names, _ = _module_traced_names(src.tree)
    roots = [n for n in ast.walk(src.tree)
             if isinstance(n, ast.FunctionDef) and n.name in traced_names]
    out: List[ast.FunctionDef] = []
    seen = set()
    stack = list(roots)
    while stack:
        fn = stack.pop()
        if id(fn) in seen:
            continue
        seen.add(id(fn))
        out.append(fn)
        for sub in ast.walk(fn):
            if isinstance(sub, ast.FunctionDef) and sub is not fn:
                stack.append(sub)
    return out


def _own_nodes(fn: ast.FunctionDef):
    """Nodes of ``fn`` excluding nested defs (those are visited as their
    own traced functions, with their own parameter sets)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _is_none_check(test: ast.AST) -> bool:
    """``x is None`` / ``x is not None`` — legitimate Python-level
    dispatch on optional args, not a tracer branch."""
    return (isinstance(test, ast.Compare)
            and all(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops))


@rule("jax-python-branch",
      "Python `if`/`while` on a traced value inside a jit/scan/shard_map "
      "body — raises ConcretizationTypeError or silently specializes",
      "@jax.jit\ndef f(x):\n    if x > 0: ...")
def check_python_branch(src: SourceFile,
                        project: Project) -> Iterable[Finding]:
    if not _in_scope(src):
        return
    for fn in _traced_functions(src):
        data = _data_params(fn)
        if not data:
            continue
        for node in _own_nodes(fn):
            if not isinstance(node, (ast.If, ast.While, ast.IfExp)):
                continue
            test = node.test
            if _is_none_check(test):
                continue
            if any(isinstance(c, ast.Call) and call_name(c.func) in
                   ("isinstance", "hasattr", "callable")
                   for c in ast.walk(test)):
                continue
            hit = names_in(test) & data
            if hit:
                yield finding(
                    "jax-python-branch", src, node,
                    f"Python branch on traced {sorted(hit)} inside traced "
                    f"`{fn.name}` — use lax.cond/lax.select/jnp.where")


@rule("jax-host-concretize",
      "`.item()`/`bool()`/`int()`/`float()` on a traced value inside a "
      "traced body — forces a host sync or fails under jit",
      "@jax.jit\ndef f(x):\n    return float(x.sum())")
def check_host_concretize(src: SourceFile,
                          project: Project) -> Iterable[Finding]:
    if not _in_scope(src):
        return
    for fn in _traced_functions(src):
        data = _data_params(fn)
        for node in _own_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node.func)
            if name.endswith(".item") and not node.args:
                yield finding(
                    "jax-host-concretize", src, node,
                    f"`.item()` inside traced `{fn.name}` — keep the value "
                    "on device or move the read outside the traced region")
            elif name in ("bool", "int", "float") and node.args and \
                    names_in(node.args[0]) & data:
                yield finding(
                    "jax-host-concretize", src, node,
                    f"`{name}()` on traced value inside `{fn.name}` — "
                    "use jnp casts / keep it symbolic")


@rule("jax-host-transfer",
      "`jax.device_get`/`np.asarray` inside round-step code — a "
      "per-round device sync on the hot path",
      "def round_step(...):\n    np.asarray(state.known)")
def check_host_transfer(src: SourceFile,
                        project: Project) -> Iterable[Finding]:
    if not _in_scope(src):
        return
    for fn in ast.walk(src.tree):
        if not isinstance(fn, ast.FunctionDef) \
                or not _ROUND_NAME.search(fn.name) \
                or fn.name.startswith("emit_"):
            # emit_* is the sanctioned batched-pull pattern (obs device
            # emitters): one device_get per snapshot, off the hot path
            continue
        for node in _own_nodes(fn):
            if isinstance(node, ast.Call) \
                    and call_name(node.func) in _TRANSFER_CALLS:
                yield finding(
                    "jax-host-transfer", src, node,
                    f"host transfer `{call_name(node.func)}` inside "
                    f"round-step `{fn.name}` — batch reads outside the "
                    "round (obs device emitters pattern)")


@rule("jax-unhashable-arg",
      "list/dict/set literal passed to a jitted callable — unhashable "
      "static args force a recompile every call",
      "jitted_fn(x, [1, 2, 3])")
def check_unhashable_arg(src: SourceFile,
                         project: Project) -> Iterable[Finding]:
    if not _in_scope(src):
        return
    _, jitted = _module_traced_names(src.tree)
    if not jitted:
        return
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id in jitted:
            for arg in [*node.args, *[kw.value for kw in node.keywords]]:
                if isinstance(arg, (ast.List, ast.Dict, ast.Set)):
                    yield finding(
                        "jax-unhashable-arg", src, arg,
                        f"mutable literal passed to jitted "
                        f"`{node.func.id}` — pass a tuple or hoist to a "
                        "static config")
