"""serflint pass family (a): asyncio concurrency discipline.

The host plane is a large asyncio system (22 ``create_task`` sites,
locks, breakers, bounded queues).  These passes encode the concurrency
contracts that dynamic tests only catch probabilistically:

- a spawned task whose handle is dropped can die silently (its exception
  is swallowed until GC) and can be garbage-collected mid-flight;
- a blocking call inside ``async def`` stalls every coroutine on the
  loop — on this codebase that includes the SWIM probe path, i.e. a
  user-plane bug becomes a false DEAD (Lifeguard's core motivation);
- parking (``asyncio.sleep``/``.wait()``/``gather``) while holding a
  lock serializes every contender behind a timer;
- a mutable container mutated from several coroutines with no lock is
  only safe while no mutation spans an await — worth an explicit,
  reviewed annotation rather than an accident.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from serf_tpu.analysis.core import (
    Finding,
    Project,
    SourceFile,
    call_name,
    finding,
    rule,
    walk_shallow,
)

_SPAWN_CALLS = ("asyncio.create_task", "create_task", "asyncio.ensure_future",
                "ensure_future")

_BLOCKING_CALLS = frozenset({
    "time.sleep", "os.system", "os.wait", "subprocess.run",
    "subprocess.call", "subprocess.check_call", "subprocess.check_output",
    "socket.create_connection", "socket.getaddrinfo", "urllib.request.urlopen",
})

#: awaits that deliberately PARK while holding a lock
_PARKING = frozenset({"asyncio.sleep", "asyncio.gather", "asyncio.wait"})

_MUTATORS = frozenset({
    "append", "add", "pop", "popitem", "update", "clear", "extend",
    "remove", "insert", "setdefault", "appendleft", "discard",
})


def _is_spawn(call: ast.Call) -> bool:
    name = call_name(call.func)
    return name in _SPAWN_CALLS or name.endswith(".create_task")


@rule("async-fire-forget",
      "`create_task`/`ensure_future` whose handle is discarded — the task "
      "can be GC'd mid-flight and its exception is swallowed",
      "asyncio.create_task(self._probe())")
def check_fire_forget(src: SourceFile, project: Project) -> Iterable[Finding]:
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call) \
                and _is_spawn(node.value):
            yield finding(
                "async-fire-forget", src, node,
                "task handle discarded — retain it and attach a "
                "done-callback that logs exceptions "
                "(serf_tpu.utils.tasks.spawn_logged)")


@rule("async-blocking-call",
      "blocking call (`time.sleep`, `subprocess.*`, sync socket/DNS) inside "
      "`async def` — stalls the whole event loop incl. the probe path",
      "async def f():\n    time.sleep(1)")
def check_blocking_call(src: SourceFile,
                        project: Project) -> Iterable[Finding]:
    for fn in ast.walk(src.tree):
        if not isinstance(fn, ast.AsyncFunctionDef):
            continue
        for node in walk_shallow(fn):
            if isinstance(node, ast.Call) \
                    and call_name(node.func) in _BLOCKING_CALLS:
                yield finding(
                    "async-blocking-call", src, node,
                    f"blocking `{call_name(node.func)}` inside async "
                    f"`{fn.name}` — use the asyncio equivalent "
                    "(e.g. `await asyncio.sleep`) or run_in_executor")


def _lockish(expr: ast.AST) -> bool:
    """An `async with` context that names a lock (``self._state_lock``,
    ``lock``, ``self._sem``...)."""
    name = call_name(expr) if not isinstance(expr, ast.Call) \
        else call_name(expr.func)
    low = name.lower()
    return any(t in low for t in ("lock", "sem", "mutex"))


@rule("async-lock-await",
      "parking await (`asyncio.sleep`/`gather`/`.wait()`) while holding an "
      "async lock — every contender serializes behind the timer",
      "async with self._lock:\n    await asyncio.sleep(1)")
def check_lock_await(src: SourceFile, project: Project) -> Iterable[Finding]:
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.AsyncWith):
            continue
        if not any(_lockish(item.context_expr) for item in node.items):
            continue
        for stmt in node.body:
            # nested defs only run later, off the lock — stay shallow
            for sub in [stmt, *walk_shallow(stmt)]:
                if not isinstance(sub, ast.Await):
                    continue
                val = sub.value
                if not isinstance(val, ast.Call):
                    continue
                name = call_name(val.func)
                if name in _PARKING or name.endswith(".wait"):
                    yield finding(
                        "async-lock-await", src, sub,
                        f"`await {name}(...)` while holding a lock — park "
                        "outside the critical section")


@rule("async-shared-mut",
      "a dict/list attribute mutated from ≥2 async methods with no lock — "
      "safe only while no mutation spans an await; must be annotated",
      "self._peers[k] = v  # from two coroutines")
def check_shared_mut(src: SourceFile, project: Project) -> Iterable[Finding]:
    for cls in ast.walk(src.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        # mutable-container attrs assigned in __init__
        containers = {}
        for m in cls.body:
            if isinstance(m, ast.FunctionDef) and m.name == "__init__":
                for node in ast.walk(m):
                    if isinstance(node, ast.Assign):
                        targets = node.targets
                    elif isinstance(node, ast.AnnAssign) \
                            and node.value is not None:
                        targets = [node.target]
                    else:
                        continue
                    for t in targets:
                        if (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"
                                and _is_container(node.value)
                                and "lock" not in t.attr.lower()):
                            containers[t.attr] = node.lineno
        if not containers:
            continue
        # unlocked mutation sites per attr, per async method
        mutators: dict = {}
        for m in cls.body:
            if not isinstance(m, ast.AsyncFunctionDef):
                continue
            for attr in _unlocked_mutations(m, containers):
                mutators.setdefault(attr, set()).add(m.name)
        for attr, methods in sorted(mutators.items()):
            if len(methods) < 2:
                continue
            yield Finding(
                rule="async-shared-mut", path=src.rel,
                line=containers[attr],
                message=f"`{cls.name}.{attr}` mutated from async methods "
                        f"{sorted(methods)} with no lock — hold a lock or "
                        "annotate why interleaving is safe",
                key=f"{cls.name}.{attr}")


def _is_container(value: ast.AST) -> bool:
    if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                          ast.ListComp, ast.SetComp)):
        return True
    if isinstance(value, ast.Call):
        return call_name(value.func).split(".")[-1] in (
            "dict", "list", "set", "defaultdict", "OrderedDict", "deque")
    return False


def _self_attr(node: ast.AST):
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _unlocked_mutations(method: ast.AsyncFunctionDef,
                        containers: dict) -> List[str]:
    """Attrs of ``containers`` mutated in ``method`` outside any
    lock-holding ``async with`` block (nested defs included — a tee()
    closure mutating self.X belongs to its method)."""
    out: List[str] = []

    def visit(node: ast.AST, locked: bool) -> None:
        if isinstance(node, ast.AsyncWith) and \
                any(_lockish(i.context_expr) for i in node.items):
            locked = True
        attr = None
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Subscript):
                    attr = _self_attr(t.value)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    attr = _self_attr(t.value)
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATORS:
            attr = _self_attr(node.func.value)
        if attr is not None and attr in containers and not locked:
            out.append(attr)
        for child in ast.iter_child_nodes(node):
            visit(child, locked)

    visit(method, False)
    return out


# ---------------------------------------------------------------------------
# host event-pipeline seam (host-plane throughput rebuild)
# ---------------------------------------------------------------------------

#: host modules that legitimately OWN an asyncio queue seam: the MPMC
#: pipeline itself, the subscriber channel, the query response streams,
#: and the transport planes.  Everything else in serf_tpu/host must
#: hand events through ``EventPipeline.offer`` — a fresh queue or a
#: direct put is exactly the serial side-channel the rebuild removed.
_PIPELINE_OWNERS = frozenset({
    "pipeline.py", "events.py", "query.py",
    "transport.py", "net.py", "dstream.py",
})

_QUEUE_CTORS = frozenset({
    "asyncio.Queue", "Queue", "asyncio.PriorityQueue",
    "asyncio.LifoQueue",
})

#: EventPipeline internals no caller may reach through (`x._pipeline.
#: _ready` etc.) — the offer()/depth() surface is the API
_PIPELINE_INTERNALS = frozenset({"_chains", "_ready", "_wake", "_inflight"})


def _in_guarded_host_module(src: SourceFile) -> bool:
    return src.rel.startswith("serf_tpu/host/") \
        and src.rel.rsplit("/", 1)[-1] not in _PIPELINE_OWNERS


@rule("pipeline-bypass",
      "manual `asyncio.Queue` construction or direct `put_nowait`/`put` "
      "in a host module that doesn't own a queue seam, or a reach into "
      "`_pipeline` internals — events must go through "
      "`EventPipeline.offer`",
      "self.inbox = asyncio.Queue()\nself.inbox.put_nowait(ev)")
def check_pipeline_bypass(src: SourceFile,
                          project: Project) -> Iterable[Finding]:
    guarded = _in_guarded_host_module(src)
    for node in ast.walk(src.tree):
        if guarded and isinstance(node, ast.Call) \
                and call_name(node.func) in _QUEUE_CTORS:
            yield finding(
                "pipeline-bypass", src, node,
                "manual queue construction outside the queue-owning "
                "modules — hand events to the MPMC pipeline "
                "(EventPipeline.offer) instead of a side-channel queue")
        elif guarded and isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("put_nowait", "put"):
            yield finding(
                "pipeline-bypass", src, node,
                f"direct `{node.func.attr}` bypasses the MPMC hand-off "
                "API — use EventPipeline.offer (bounded, dependency-"
                "keyed, shed-accounted)")
        elif isinstance(node, ast.Attribute) \
                and node.attr in _PIPELINE_INTERNALS \
                and isinstance(node.value, ast.Attribute) \
                and node.value.attr == "_pipeline" \
                and src.rel.rsplit("/", 1)[-1] != "pipeline.py":
            yield finding(
                "pipeline-bypass", src, node,
                f"reach into pipeline internals (`._pipeline.{node.attr}`)"
                " — offer()/depth()/oldest_age() are the API surface")
