"""serflint docs pass: the README rule table is enforced both ways.

Same contract shape as the metrics table (PR 1): the ``## Static
analysis`` README section carries one row per rule (id, what it catches,
example); a registered rule without a row, or a row naming no registered
rule, is a finding.  The analyzer documents itself or fails itself.
"""

from __future__ import annotations

from typing import Iterable, List

from serf_tpu.analysis.core import (
    ALL_RULES,
    Finding,
    Project,
    SourceFile,
    project_rule,
)
from serf_tpu.analysis.registry import ROW_RE as _ROW_RE
SECTION = "## Static analysis"


def documented_rules(readme) -> dict:
    """{rule_id: line_no} from the README Static-analysis table."""
    out = {}
    in_section = False
    for i, line in enumerate(readme.read_text().splitlines(), start=1):
        if line.startswith("## "):
            in_section = line.strip() == SECTION
            continue
        if not in_section:
            continue
        m = _ROW_RE.match(line)
        if m and m.group(1) not in ("Rule", "id"):
            out[m.group(1)] = i
    return out


@project_rule("docs-rule-table",
              "README Static-analysis rule table out of sync with the "
              "registered rules (missing or stale row)",
              "shipping a rule with no README row")
def check_rule_table(files: List[SourceFile],
                     project: Project) -> Iterable[Finding]:
    if project.readme is None or not project.readme.exists():
        return
    rows = documented_rules(project.readme)
    readme_rel = project.readme.name
    for rid in ALL_RULES:
        if rid not in rows:
            yield Finding(
                rule="docs-rule-table", path=readme_rel, line=1,
                message=f"rule `{rid}` has no row in the README "
                        f"'{SECTION}' table",
                key=rid)
    for rid, line in sorted(rows.items()):
        if rid not in ALL_RULES:
            yield Finding(
                rule="docs-rule-table", path=readme_rel, line=line,
                message=f"README documents rule `{rid}` but no such rule "
                        "is registered — delete the row",
                key=rid)
