"""serflint pass family (c): the declared observability registry.

ONE registry of every metric name and flight-event kind the tree may
emit.  Three surfaces are cross-checked against it:

- **emit sites** — ``metrics.incr/gauge/observe`` call sites plus the
  device plane's ``emit_*_metrics`` name->value dict literals (the same
  extraction ``tools/metrics_lint.py`` shipped in PR 1; that tool is now
  a thin wrapper over this module);
- **flight-recorder kinds** — ``flight.record("kind", ...)`` /
  ``obs.record("kind", ...)`` call sites;
- **README rows** — the ``## Observability`` table operators build
  dashboards against.

Dynamic name segments normalize to ``<>`` on every surface (an f-string
``serf.queue.{name}`` and a documented ``serf.queue.<name>`` are the
same family).  Adding a metric now takes three deliberate edits — emit
it, declare it here, document it — and each half-done state is a
distinct finding.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set

from serf_tpu.analysis.core import (
    REPO,
    Finding,
    Project,
    SourceFile,
    project_rule,
)

# ---------------------------------------------------------------------------
# THE registry
# ---------------------------------------------------------------------------

#: every metric name the tree may emit (normalized: dynamic segments are
#: ``<>``).  Grouped by plane; the README Observability table carries
#: the per-name docs.
METRICS: tuple = (
    # memberlist plane
    "memberlist.node.dead",
    "memberlist.node.join",
    "memberlist.node.suspect",
    "memberlist.node.version_rejected",
    "memberlist.packet.<>_failed",
    "memberlist.packet.decrypt_failed",
    "memberlist.packet.received",
    "memberlist.packet.sent",
    "memberlist.probe.failed",
    # serf host plane
    "serf.coordinate.adjustment-ms",
    "serf.coordinate.rejected",
    "serf.coordinate.zero-rtt",
    "serf.degraded.breaker_fastfail",
    "serf.degraded.breaker_opened",
    "serf.degraded.corrupt_frame",
    "serf.degraded.dial_retry",
    "serf.degraded.join_retry",
    "serf.degraded.pushpull_skipped",
    # batched codec (host-plane throughput rebuild)
    "serf.codec.batch",
    "serf.codec.batch-messages",
    "serf.codec.decode-cache-hit",
    "serf.codec.decode-cache-miss",
    "serf.events",
    "serf.events.<>",
    "serf.events.tee_depth",
    "serf.health.component.<>",
    "serf.health.score",
    "serf.loop.lag-ms",
    "serf.member.failed",
    "serf.member.flap",
    "serf.member.join",
    "serf.member.leave",
    "serf.member.unleave",
    "serf.member.update",
    "serf.messages.received",
    "serf.messages.sent",
    "serf.queries",
    "serf.queries.<>",
    # MPMC event pipeline (host/pipeline.py)
    "serf.pipeline.depth",
    "serf.pipeline.keys",
    "serf.pipeline.batch",
    "serf.pipeline.occupancy",
    "serf.pipeline.inline-share",
    "serf.pipeline.ready-depth",
    "serf.pipeline.chain-p50",
    "serf.pipeline.chain-max",
    "serf.query.acks",
    "serf.query.duplicate_acks",
    "serf.query.duplicate_responses",
    "serf.query.responses",
    "serf.query.rtt-ms",
    "serf.queue.<>",
    "serf.queue.age.<>",
    "serf.queue.bytes.<>",
    "serf.snapshot.append_line",
    "serf.snapshot.compact",
    "serf.snapshot.lock_conflict",
    "serf.snapshot.torn_tail",
    "serf.snapshot.unknown_record",
    "serf.subscriber.dropped",
    "serf.subscriber.lossless_violation",
    "serf.trace.span-ms",
    # multi-process plane (host/agent.py control channel +
    # faults/proc.py real-process harness)
    "serf.proc.bind_retry",
    "serf.proc.chaos_installs",
    "serf.proc.crashed",
    "serf.proc.ctl.requests",
    "serf.proc.generation",
    "serf.proc.paused",
    "serf.proc.reaped",
    "serf.proc.restarted",
    "serf.proc.resumed",
    "serf.proc.spawned",
    "serf.proc.task_failures",
    # chaos / faults plane
    "serf.faults.corrupted",
    "serf.faults.delayed",
    "serf.faults.dropped",
    "serf.faults.duplicated",
    "serf.faults.phase",
    "serf.faults.reordered",
    # overload plane
    "serf.overload.device_dropped",
    "serf.overload.device_offered",
    "serf.overload.event_shed",
    "serf.overload.ingress_admitted",
    "serf.overload.ingress_shed",
    "serf.overload.paced_dropped",
    "serf.overload.query_fastfail",
    "serf.overload.query_responses",
    "serf.overload.query_responses_shed",
    "serf.overload.queue_shed",
    "serf.overload.queue_shed_bytes",
    "serf.overload.remote_overloaded",
    # device plane (emit_*_metrics)
    "serf.device.dispatch-ms",
    "serf.device.dispatch.calls",
    "serf.model.gossip.agreement",
    "serf.model.gossip.alive",
    "serf.model.gossip.coverage",
    "serf.model.gossip.facts-valid",
    "serf.model.gossip.fan-out",
    "serf.model.gossip.round",
    "serf.model.gossip.tombstones",
    "serf.model.swim.accusations-pending",
    "serf.model.swim.dead-facts",
    "serf.model.swim.false-dead",
    "serf.model.swim.live-suspicions",
    "serf.model.swim.undetected-deaths",
    "serf.model.traffic.bytes-per-round",
    "serf.model.traffic.ceiling-rps",
    "serf.model.traffic.plane-bytes",
    "serf.model.vivaldi.adjustment",
    "serf.model.vivaldi.error",
    "serf.model.vivaldi.height",
    "serf.pallas.fused_fallback",
    # sharded flagship
    "serf.shard.devices",
    "serf.shard.exchange-bytes-per-chip",
    "serf.shard.rps",
    # dstream transport
    "serf.dstream.ooo_dropped",
    "serf.dstream.retransmits",
    # static analysis (bench embeds the finding trajectory per round)
    "serf.analysis.findings",
    "serf.analysis.baselined",
    # record/replay plane (serf_tpu/replay)
    "serf.replay.records",
    "serf.replay.rounds",
    "serf.replay.divergence",
    # continuous-telemetry plane (obs/timeseries.py sampler)
    "serf.ts.samples",
    "serf.ts.points",
    "serf.ts.downsamples",
    # SLO plane (obs/slo.py)
    "serf.slo.ok",
    "serf.slo.burn",
    "serf.slo.breach",
    # propagation observatory (obs/propagation.py): device sentinel
    # tracer gauges + host provenance-ledger counters/probe gauges
    "serf.propagation.cov-max",
    "serf.propagation.cov-mean",
    "serf.propagation.cov-min",
    "serf.propagation.coverage",
    "serf.propagation.dup-ratio",
    "serf.propagation.duplicates",
    "serf.propagation.events-seen",
    "serf.propagation.rebroadcasts",
    "serf.propagation.redundancy",
    "serf.propagation.slots-learned",
    "serf.propagation.slots-redundant",
    "serf.propagation.slots-sent",
    "serf.propagation.t99-rounds",
    "serf.propagation.time-to-all-ms",
    # message lifecycle ledger (obs/lifecycle.py)
    "serf.lifecycle.messages",
    "serf.lifecycle.sampled",
    "serf.lifecycle.slow",
    "serf.lifecycle.stage-ms",
    "serf.lifecycle.e2e-ms",
    # adaptive control plane (serf_tpu/control)
    "serf.control.knob.<>",
    "serf.control.steps",
    "serf.control.shed",
    # continuous verification (obs/watchdog.py) + black box
    # (obs/blackbox.py)
    "serf.watchdog.ticks",
    "serf.watchdog.ok",
    "serf.watchdog.armed",
    "serf.watchdog.breach",
    "serf.blackbox.bundles",
    "serf.blackbox.bytes",
    "serf.blackbox.rotated",
    # encrypted transport + key rotation (host/keyring.py,
    # host/key_manager.py, faults/host.py rotation finale)
    "serf.keyring.encrypt",
    "serf.keyring.encrypt_amortized",
    "serf.keyring.decrypt_fallback",
    "serf.keyring.decrypt_fail",
    "serf.rotation.latency-ms",
    "serf.rotation.partial",
    "serf.rotation.reconcile-s",
    "serf.rotation.retry",
)

#: every flight-recorder event kind (obs/flight.py ``record`` call sites)
FLIGHT_KINDS: tuple = (
    "broadcast-retired",
    "circuit-breaker",
    "control-decision",
    "coordinate-rejected",
    "corrupt-frame",
    "dial-retry",
    "event-shed",
    "fault-phase",
    "ingress-shed",
    "key-rotation",
    "member-state",
    "paced-drop",
    "packet-dropped",
    "pallas-fallback",
    "probe-failed",
    "proc-agent",
    "propagation-trace",
    "query-fastfail",
    "query-overloaded-response",
    "query-received",
    "query-response",
    "query-responses-shed",
    "queue-overflow",
    "queue-shed",
    "replay-divergence",
    "replay-recorded",
    "shard-fallback",
    "slo-breach",
    "slow-message",
    "snapshot-torn-tail",
    "subscriber-drop",
    "swim-state",
    "user-event",
    "watchdog-breach",
)

#: every SLO name ``serf_tpu/obs/slo.py`` SLO_TABLE defines.  Checked
#: both ways (``slo-decl-drift``) like the metric registry; every SLO's
#: watched metrics must be declared above (``slo-metric-unknown``) —
#: the SLO plane cannot judge metrics nobody emits — and the README
#: "Time series & SLOs" table carries one row per name
#: (``slo-doc-drift``).
SLOS: tuple = (
    "apply-stage-p99",
    "convergence-settle",
    "coverage-settle",
    "false-dead",
    "query-p99",
    "queue-wait-share",
    "redundancy-ceiling",
    "rotation-latency",
    "shed-ratio",
    "sustained-rps-ceiling",
)

#: the README section the SLO table lives in
SLO_SECTION = "## Time series & SLOs"

#: every controller-writable knob the adaptive control plane may
#: actuate (serf_tpu/control: device ``KNOB_FIELDS`` + host
#: ``HOST_KNOBS``).  The ``control-knob-drift`` rule cross-checks both
#: ways: a knob field/law actuating an undeclared name, or a declared
#: name with no law, fails lint — a knob cannot exist without a control
#: law, and a law cannot actuate an undeclared knob.
CONTROL_KNOBS: tuple = (
    # device plane (control/device.py KNOB_FIELDS)
    "fanout",
    "probe_mult",
    "stretch_q",
    "inject_limit",
    "stamp_unit",
    # host plane (control/host.py HOST_KNOBS)
    "user_event_rate",
    "query_rate",
    "breaker_cooldown",
    "suspicion_mult",
    "probe_interval",
    "gossip_nodes",
    "gossip_interval",
)

#: the control-plane sources the drift rule fingerprints: file ->
#: (knob-tuple literal, law-table literal)
CONTROL_SOURCES = {
    "serf_tpu/control/device.py": ("KNOB_FIELDS", "DEVICE_LAWS"),
    "serf_tpu/control/host.py": ("HOST_KNOBS", "HOST_LAWS"),
}

#: the telemetry-row source the ``telemetry-field-drift`` rule
#: fingerprints: file -> (field-tuple literal, merge-dict literal).  The
#: README section below carries one table row per field (| `field` |
#: merge | ... ) — enforced both ways like the metric table.
TELEMETRY_SOURCES = {
    "serf_tpu/models/swim.py": ("TELEMETRY_FIELDS", "TELEMETRY_MERGE"),
}
TELEMETRY_SECTION = "## Zero-cost telemetry & timeline export"
#: the merge ops the in-collective legs implement
#: (parallel/ring.round_telemetry_sharded): psum / pmax / pmin legs, or
#: replicated per-chip computation
TELEMETRY_MERGE_OPS = ("sum", "max", "min", "replicated")

#: the propagation-row source the ``propagation-field-drift`` rule
#: fingerprints (ISSUE 16): file -> (field-tuple literal, merge-dict
#: literal), same shape as the telemetry contract — one README table
#: row per field under the section below, enforced both ways.
PROPAGATION_SOURCES = {
    "serf_tpu/obs/propagation.py": ("PROPAGATION_FIELDS",
                                    "PROPAGATION_MERGE"),
}
PROPAGATION_SECTION = "## Propagation observability"
#: the propagation row's globalization contract: count fields are
#: GSPMD-exact integer sums outside the shard_map body, coverage
#: fields fold the already-psum'd colcnt partials (replicated)
PROPAGATION_MERGE_OPS = ("sum", "replicated")

#: the invariant-row source the ``invariant-field-drift`` rule
#: fingerprints (ISSUE 17): file -> (field-tuple literal, merge-dict
#: literal), the telemetry/propagation contract shape — one README
#: table row per field under the section below, enforced both ways.
INVARIANT_SOURCES = {
    "serf_tpu/obs/watchdog.py": ("INVARIANT_FIELDS", "INVARIANT_MERGE"),
}
INVARIANT_SECTION = "## Continuous verification & black box"
#: the invariant row's globalization contract: every predicate folds
#: from already-reduced telemetry/propagation operands plus replicated
#: ledgers — identical on every chip, never a collective of its own
INVARIANT_MERGE_OPS = ("replicated",)


# ---------------------------------------------------------------------------
# extraction (the PR-1 metrics_lint scanner, now shared)
# ---------------------------------------------------------------------------

#: a string is a candidate metric name only under this grammar
NAME_RE = re.compile(r"^(serf|memberlist)\.[a-z0-9_.<>{}-]+$")
#: README table rows: | `name` | type | ...
ROW_RE = re.compile(r"^\|\s*`([^`]+)`\s*\|")
_DYNAMIC = re.compile(r"(\{[^{}]*\}|<[^<>]*>)")


def normalize(name: str) -> str:
    """Collapse every dynamic segment ({expr} or <doc>) to ``<>``."""
    return _DYNAMIC.sub("<>", name)


def _joined_str_pattern(node: ast.JoinedStr) -> str:
    parts = []
    for v in node.values:
        if isinstance(v, ast.Constant):
            parts.append(str(v.value))
        else:
            parts.append("{}")
    return "".join(parts)


def _obs_sites(f):
    """(metric_sites, flight_sites) for one source, each a list of
    (raw_name, rel, lineno).  One AST walk per file, cached on the
    SourceFile object so the four registry rules (metric/flight x
    unknown/unused) share it instead of re-walking the whole tree."""
    if isinstance(f, SourceFile):
        cached = getattr(f, "_obs_sites", None)
        if cached is not None:
            return cached
    tree, rel = _tree_of(f)
    metric_sites: List[tuple] = []
    flight_sites: List[tuple] = []
    for node in ast.walk(tree):
        # metrics.incr/gauge/observe("name"...) and f-string variants
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.args):
            if (node.func.attr in ("incr", "gauge", "observe")
                    and node.func.value.id == "metrics"):
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                    metric_sites.append((arg.value, rel, node.lineno))
                elif isinstance(arg, ast.JoinedStr):
                    metric_sites.append(
                        (_joined_str_pattern(arg), rel, node.lineno))
            # flight.record("kind", ...) / obs.record("kind", ...)
            elif (node.func.attr == "record"
                  and node.func.value.id in ("flight", "obs")
                  and isinstance(node.args[0], ast.Constant)
                  and isinstance(node.args[0].value, str)):
                flight_sites.append((node.args[0].value, rel, node.lineno))
        # device-plane emitters: {"name": value, ...} dict literals
        # inside emit_*_metrics functions (emitted via a loop)
        elif (isinstance(node, ast.FunctionDef)
              and node.name.startswith("emit_")
              and node.name.endswith("_metrics")):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Dict):
                    for key in sub.keys:
                        if (isinstance(key, ast.Constant)
                                and isinstance(key.value, str)):
                            metric_sites.append((key.value, rel, sub.lineno))
    out = (metric_sites, flight_sites)
    if isinstance(f, SourceFile):
        f._obs_sites = out
    return out


def emitted_metric_names(files: Iterable) -> Dict[str, Set[str]]:
    """{normalized_name: {file:line, ...}} across sources.  ``files``
    are paths or SourceFiles (paths keep the metrics_lint wrapper API)."""
    out: Dict[str, Set[str]] = {}
    for f in files:
        for raw, rel, lineno in _obs_sites(f)[0]:
            if NAME_RE.match(normalize(raw).replace("<>", "x")):
                out.setdefault(normalize(raw), set()).add(f"{rel}:{lineno}")
    return out


def flight_kinds_emitted(files: Iterable) -> Dict[str, Set[str]]:
    """{kind: {file:line, ...}}: first string arg of ``flight.record`` /
    ``obs.record`` call sites."""
    out: Dict[str, Set[str]] = {}
    for f in files:
        for kind, rel, lineno in _obs_sites(f)[1]:
            out.setdefault(kind, set()).add(f"{rel}:{lineno}")
    return out


def documented_metric_names(readme: Path) -> Dict[str, str]:
    """{normalized_name: raw_name} from the README Observability table."""
    out: Dict[str, str] = {}
    in_section = False
    for line in readme.read_text().splitlines():
        if line.startswith("## "):
            in_section = line.strip() == "## Observability"
            continue
        if not in_section:
            continue
        m = ROW_RE.match(line)
        if m and m.group(1) != "Metric":
            out[normalize(m.group(1))] = m.group(1)
    return out


def _tree_of(f):
    if isinstance(f, SourceFile):
        return f.tree, f.rel
    # bare-path callers (the metrics_lint wrapper API) get repo-relative
    # site strings, matching the PR-1 message format
    p = Path(f).resolve()
    try:
        rel = str(p.relative_to(REPO))
    except ValueError:
        rel = str(p)
    return ast.parse(p.read_text(), filename=str(p)), rel


def _metric_files(files: List[SourceFile],
                  project: Project) -> List[SourceFile]:
    prefixes = tuple(
        e + "/" if (project.root / e).is_dir() else e
        for e in project.metric_scan)
    return [f for f in files if f.rel.startswith(prefixes)]


# ---------------------------------------------------------------------------
# the cross-check rules
# ---------------------------------------------------------------------------

def _reg_finding(rule_id: str, path: str, line: int, name: str,
                 message: str) -> Finding:
    return Finding(rule=rule_id, path=path, line=line, message=message,
                   key=name)


@project_rule("reg-metric-unknown",
              "a metric is emitted but not declared in the registry",
              'metrics.incr("serf.new.counter") with no registry entry')
def check_metric_unknown(files: List[SourceFile],
                         project: Project) -> Iterable[Finding]:
    if project.registry is None:
        return
    emitted = emitted_metric_names(_metric_files(files, project))
    for name in sorted(set(emitted) - set(project.registry.metrics)):
        site = sorted(emitted[name])[0]
        path, _, line = site.rpartition(":")
        yield _reg_finding(
            "reg-metric-unknown", path, int(line), name,
            f"metric {name!r} emitted but not declared — add it to "
            "serf_tpu/analysis/registry.py METRICS (and the README table)")


@project_rule("reg-metric-unused",
              "a registry metric is never emitted anywhere",
              "a METRICS entry whose emit site was deleted")
def check_metric_unused(files: List[SourceFile],
                        project: Project) -> Iterable[Finding]:
    if project.registry is None:
        return
    emitted = emitted_metric_names(_metric_files(files, project))
    for name in sorted(set(project.registry.metrics) - set(emitted)):
        yield _reg_finding(
            "reg-metric-unused", "serf_tpu/analysis/registry.py", 1, name,
            f"registry metric {name!r} is never emitted — delete the "
            "entry or restore the emission")


@project_rule("reg-doc-drift",
              "README Observability table out of sync with the registry "
              "(missing or stale row)",
              "a registry metric with no README row")
def check_doc_drift(files: List[SourceFile],
                    project: Project) -> Iterable[Finding]:
    if project.registry is None or project.readme is None \
            or not project.readme.exists():
        return
    documented = documented_metric_names(project.readme)
    readme_rel = project.readme.name
    for name in sorted(set(project.registry.metrics) - set(documented)):
        yield _reg_finding(
            "reg-doc-drift", readme_rel, 1, name,
            f"registry metric {name!r} has no row in the README "
            "'## Observability' table")
    for name in sorted(set(documented) - set(project.registry.metrics)):
        yield _reg_finding(
            "reg-doc-drift", readme_rel, 1, name,
            f"README documents {documented[name]!r} but the registry "
            "does not declare it — delete the row or declare the metric")


@project_rule("reg-flight-unknown",
              "a flight-event kind is recorded but not declared",
              'flight.record("new-kind", ...) with no registry entry')
def check_flight_unknown(files: List[SourceFile],
                         project: Project) -> Iterable[Finding]:
    if project.registry is None:
        return
    kinds = flight_kinds_emitted(_metric_files(files, project))
    for kind in sorted(set(kinds) - set(project.registry.flight_kinds)):
        site = sorted(kinds[kind])[0]
        path, _, line = site.rpartition(":")
        yield _reg_finding(
            "reg-flight-unknown", path, int(line), kind,
            f"flight kind {kind!r} recorded but not declared — add it to "
            "serf_tpu/analysis/registry.py FLIGHT_KINDS")


@project_rule("reg-flight-unused",
              "a registry flight-event kind is never recorded",
              "a FLIGHT_KINDS entry whose record site was deleted")
def check_flight_unused(files: List[SourceFile],
                        project: Project) -> Iterable[Finding]:
    if project.registry is None:
        return
    kinds = flight_kinds_emitted(_metric_files(files, project))
    for kind in sorted(set(project.registry.flight_kinds) - set(kinds)):
        yield _reg_finding(
            "reg-flight-unused", "serf_tpu/analysis/registry.py", 1, kind,
            f"registry flight kind {kind!r} is never recorded — delete "
            "the entry or restore the record site")


# ---------------------------------------------------------------------------
# SLO cross-checks (pass family d): the SLO table is registry-governed
# ---------------------------------------------------------------------------

def _slo_sites(f):
    """``SLODef(...)`` call sites in one source: a list of
    ``(name, metrics_tuple, rel, lineno)``.  Pure AST — the SLO module
    is never imported; names/metrics must be literals (which the frozen
    dataclass table is by construction).  Cached on the SourceFile like
    ``_obs_sites`` so the three SLO rules share one walk."""
    if isinstance(f, SourceFile):
        cached = getattr(f, "_slo_sites", None)
        if cached is not None:
            return cached
    tree, rel = _tree_of(f)
    out: List[tuple] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        fn_name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else "")
        if fn_name != "SLODef":
            continue
        name = None
        mets: List[str] = []
        if node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            name = node.args[0].value
        for kw in node.keywords:
            if kw.arg == "name" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                name = kw.value.value
            elif kw.arg == "metrics" and isinstance(
                    kw.value, (ast.Tuple, ast.List)):
                mets = [e.value for e in kw.value.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)]
        if name is not None:
            out.append((name, tuple(mets), rel, node.lineno))
    if isinstance(f, SourceFile):
        f._slo_sites = out
    return out


def slo_defs(files: Iterable) -> List[tuple]:
    """Every SLODef site across sources, definition-ordered."""
    out: List[tuple] = []
    for f in files:
        out.extend(_slo_sites(f))
    return out


def documented_slo_names(readme: Path) -> Dict[str, int]:
    """{slo_name: line} from the README "Time series & SLOs" table."""
    out: Dict[str, int] = {}
    in_section = False
    for i, line in enumerate(readme.read_text().splitlines(), start=1):
        if line.startswith("## "):
            in_section = line.strip() == SLO_SECTION
            continue
        if not in_section:
            continue
        m = ROW_RE.match(line)
        if m and m.group(1) not in ("SLO", "Metric"):
            out[m.group(1)] = i
    return out


@project_rule("slo-metric-unknown",
              "an SLO definition watches a metric not declared in the "
              "registry",
              'SLODef(name="x", metrics=("serf.not.declared",), ...)')
def check_slo_metric_unknown(files: List[SourceFile],
                             project: Project) -> Iterable[Finding]:
    if project.registry is None:
        return
    for name, mets, rel, lineno in slo_defs(
            _metric_files(files, project)):
        for m in mets:
            if normalize(m) not in project.registry.metrics:
                yield _reg_finding(
                    "slo-metric-unknown", rel, lineno, f"{name}:{m}",
                    f"SLO {name!r} watches metric {m!r} which is not "
                    "declared in serf_tpu/analysis/registry.py METRICS "
                    "— declare (and emit + document) the metric, or fix "
                    "the SLO definition")


@project_rule("slo-decl-drift",
              "SLO definitions out of sync with the registry SLOS "
              "declaration (defined-but-undeclared or vice versa)",
              "an SLO_TABLE entry with no SLOS tuple entry")
def check_slo_decl_drift(files: List[SourceFile],
                         project: Project) -> Iterable[Finding]:
    if project.registry is None:
        return
    defined = {}
    for name, _mets, rel, lineno in slo_defs(
            _metric_files(files, project)):
        defined.setdefault(name, (rel, lineno))
    for name in sorted(set(defined) - set(project.registry.slos)):
        rel, lineno = defined[name]
        yield _reg_finding(
            "slo-decl-drift", rel, lineno, name,
            f"SLO {name!r} defined but not declared — add it to "
            "serf_tpu/analysis/registry.py SLOS (and the README table)")
    for name in sorted(set(project.registry.slos) - set(defined)):
        yield _reg_finding(
            "slo-decl-drift", "serf_tpu/analysis/registry.py", 1, name,
            f"registry SLO {name!r} has no SLODef anywhere — delete the "
            "SLOS entry or restore the definition")


@project_rule("slo-doc-drift",
              "README 'Time series & SLOs' table out of sync with the "
              "declared SLOs (missing or stale row)",
              "a declared SLO with no README row")
def check_slo_doc_drift(files: List[SourceFile],
                        project: Project) -> Iterable[Finding]:
    if project.registry is None or project.readme is None \
            or not project.readme.exists():
        return
    documented = documented_slo_names(project.readme)
    readme_rel = project.readme.name
    for name in sorted(set(project.registry.slos) - set(documented)):
        yield _reg_finding(
            "slo-doc-drift", readme_rel, 1, name,
            f"declared SLO {name!r} has no row in the README "
            f"'{SLO_SECTION[3:]}' table")
    for name, line in sorted(documented.items()):
        if name not in project.registry.slos:
            yield _reg_finding(
                "slo-doc-drift", readme_rel, line, name,
                f"README documents SLO {name!r} but the registry does "
                "not declare it — delete the row or declare the SLO")


# ---------------------------------------------------------------------------
# control-knob cross-check (pass family d, ISSUE 11): the adaptive
# control plane is registry-governed like the metrics and SLOs
# ---------------------------------------------------------------------------

def _tuple_literal(tree: ast.AST, name: str):
    """Top-level ``NAME = ("a", "b", ...)`` string-tuple literal, or
    None when absent."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == name \
                and isinstance(node.value, (ast.Tuple, ast.List)):
            return [(e.value, e.lineno) for e in node.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)]
    return None


def _law_knobs(tree: ast.AST, name: str):
    """Knob names actuated by a law-table literal ``NAME = ((signal,
    knob, direction), ...)`` — the middle element of each entry."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == name \
                and isinstance(node.value, (ast.Tuple, ast.List)):
            for entry in node.value.elts:
                if isinstance(entry, (ast.Tuple, ast.List)) \
                        and len(entry.elts) >= 2 \
                        and isinstance(entry.elts[1], ast.Constant) \
                        and isinstance(entry.elts[1].value, str):
                    out.append((entry.elts[1].value, entry.lineno))
    return out


@project_rule("control-knob-drift",
              "a control knob without a law, a law actuating an "
              "undeclared knob, or knob fields out of sync with the "
              "declared registry (checked both ways)",
              'KNOB_FIELDS gains "new_knob" with no DEVICE_LAWS entry')
def check_control_knob_drift(files: List[SourceFile],
                             project: Project) -> Iterable[Finding]:
    if project.registry is None:
        return
    declared = set(project.registry.control_knobs)
    if not declared:
        return
    by_rel = {f.rel: f for f in files}
    seen_fields: Dict[str, tuple] = {}
    seen_laws: Dict[str, tuple] = {}
    found_any = False
    for rel, (fields_name, laws_name) in CONTROL_SOURCES.items():
        src = by_rel.get(rel)
        if src is None:
            continue
        fields = _tuple_literal(src.tree, fields_name)
        laws = _law_knobs(src.tree, laws_name)
        if fields is None:
            continue
        found_any = True
        for knob, lineno in fields:
            seen_fields.setdefault(knob, (rel, lineno))
            if knob not in declared:
                yield _reg_finding(
                    "control-knob-drift", rel, lineno, f"field:{knob}",
                    f"control knob {knob!r} ({fields_name}) is not "
                    "declared — add it to serf_tpu/analysis/registry.py "
                    "CONTROL_KNOBS (and give it a law + README row)")
        law_set = {k for k, _ in laws}
        for knob, lineno in laws:
            seen_laws.setdefault(knob, (rel, lineno))
            if knob not in declared:
                yield _reg_finding(
                    "control-knob-drift", rel, lineno, f"law:{knob}",
                    f"a {laws_name} law actuates undeclared knob "
                    f"{knob!r} — declare it in CONTROL_KNOBS or fix "
                    "the law")
        for knob, lineno in fields:
            if knob not in law_set:
                yield _reg_finding(
                    "control-knob-drift", rel, lineno,
                    f"lawless:{knob}",
                    f"control knob {knob!r} has no {laws_name} entry — "
                    "a knob without a control law is dead config "
                    "(add a law or delete the knob)")
    if not found_any:
        return
    for knob in sorted(declared - set(seen_fields) - set(seen_laws)):
        yield _reg_finding(
            "control-knob-drift", "serf_tpu/analysis/registry.py", 1,
            f"undefined:{knob}",
            f"declared control knob {knob!r} appears in no knob-field "
            "tuple and no law table — delete the CONTROL_KNOBS entry "
            "or restore the knob")


# ---------------------------------------------------------------------------
# telemetry-row cross-check (pass family d, ISSUE 15): the in-collective
# telemetry contract is registry-governed like the knobs and SLOs
# ---------------------------------------------------------------------------

def _dict_literal(tree: ast.AST, name: str):
    """Top-level ``NAME = {"k": "v", ...}`` string-dict literal as
    ``[(key, value, lineno), ...]``, or None when absent."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == name \
                and isinstance(node.value, ast.Dict):
            out = []
            for k, v in zip(node.value.keys, node.value.values):
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    out.append((k.value,
                                v.value if isinstance(v, ast.Constant)
                                else None, k.lineno))
            return out
    return None


def documented_telemetry_fields(readme: Path) -> Dict[str, int]:
    """{field: line} from the README telemetry table (the
    ``TELEMETRY_SECTION`` section's first column)."""
    out: Dict[str, int] = {}
    in_section = False
    for i, line in enumerate(readme.read_text().splitlines(), start=1):
        if line.startswith("## "):
            in_section = line.strip() == TELEMETRY_SECTION
            continue
        if not in_section:
            continue
        m = ROW_RE.match(line)
        if m and m.group(1) not in ("Field", "Metric"):
            out[m.group(1)] = i
    return out


@project_rule("telemetry-field-drift",
              "the telemetry row, its in-collective merge contract, and "
              "the README telemetry table out of sync (a field added to "
              "the row but not reduced, reduced but undeclared, an "
              "unknown merge op, or a missing/stale README row)",
              'TELEMETRY_FIELDS gains "new_field" with no '
              "TELEMETRY_MERGE entry")
def check_telemetry_field_drift(files: List[SourceFile],
                                project: Project) -> Iterable[Finding]:
    by_rel = {f.rel: f for f in files}
    for rel, (fields_name, merge_name) in TELEMETRY_SOURCES.items():
        src = by_rel.get(rel)
        if src is None:
            continue
        fields = _tuple_literal(src.tree, fields_name)
        merge = _dict_literal(src.tree, merge_name)
        if fields is None:
            continue
        merge = merge or []
        merge_keys = {k for k, _v, _ln in merge}
        field_set = {f for f, _ln in fields}
        for f_name, lineno in fields:
            if f_name not in merge_keys:
                yield _reg_finding(
                    "telemetry-field-drift", rel, lineno,
                    f"unreduced:{f_name}",
                    f"telemetry field {f_name!r} ({fields_name}) has no "
                    f"{merge_name} entry — a row field the in-collective "
                    "legs do not reduce silently breaks the sharded row "
                    "(declare its merge op, or drop the field)")
        for k, op, lineno in merge:
            if k not in field_set:
                yield _reg_finding(
                    "telemetry-field-drift", rel, lineno,
                    f"undeclared:{k}",
                    f"{merge_name} reduces {k!r} which is not a "
                    f"{fields_name} entry — dead merge leg (add the row "
                    "field or delete the entry)")
            if op not in TELEMETRY_MERGE_OPS:
                yield _reg_finding(
                    "telemetry-field-drift", rel, lineno,
                    f"bad-op:{k}",
                    f"{merge_name}[{k!r}] declares unknown merge op "
                    f"{op!r} (one of {TELEMETRY_MERGE_OPS}) — the "
                    "collective legs cannot implement it")
        if project.readme is not None and project.readme.exists():
            documented = documented_telemetry_fields(project.readme)
            readme_rel = project.readme.name
            for f_name in sorted(field_set - set(documented)):
                yield _reg_finding(
                    "telemetry-field-drift", readme_rel, 1,
                    f"undocumented:{f_name}",
                    f"telemetry field {f_name!r} has no row in the "
                    f"README '{TELEMETRY_SECTION[3:]}' table")
            for f_name, line in sorted(documented.items()):
                if f_name not in field_set:
                    yield _reg_finding(
                        "telemetry-field-drift", readme_rel, line,
                        f"stale-row:{f_name}",
                        f"README documents telemetry field {f_name!r} "
                        "but the row does not carry it — delete the row "
                        "or restore the field")


# ---------------------------------------------------------------------------
# propagation-row cross-check (pass family d, ISSUE 16): the propagation
# observatory's row contract is registry-governed like the telemetry row
# ---------------------------------------------------------------------------

def documented_propagation_fields(readme: Path) -> Dict[str, int]:
    """{field: line} from the README propagation table (the
    ``PROPAGATION_SECTION`` section's first column)."""
    out: Dict[str, int] = {}
    in_section = False
    for i, line in enumerate(readme.read_text().splitlines(), start=1):
        if line.startswith("## "):
            in_section = line.strip() == PROPAGATION_SECTION
            continue
        if not in_section:
            continue
        m = ROW_RE.match(line)
        if m and m.group(1) not in ("Field", "Metric"):
            out[m.group(1)] = i
    return out


@project_rule("propagation-field-drift",
              "the propagation row, its merge contract, and the README "
              "propagation table out of sync (a field added to the row "
              "but not reduced, reduced but undeclared, an unknown merge "
              "op, or a missing/stale README row)",
              'PROPAGATION_FIELDS gains "new_field" with no '
              "PROPAGATION_MERGE entry")
def check_propagation_field_drift(files: List[SourceFile],
                                  project: Project) -> Iterable[Finding]:
    by_rel = {f.rel: f for f in files}
    for rel, (fields_name, merge_name) in PROPAGATION_SOURCES.items():
        src = by_rel.get(rel)
        if src is None:
            continue
        fields = _tuple_literal(src.tree, fields_name)
        merge = _dict_literal(src.tree, merge_name)
        if fields is None:
            continue
        merge = merge or []
        merge_keys = {k for k, _v, _ln in merge}
        field_set = {f for f, _ln in fields}
        for f_name, lineno in fields:
            if f_name not in merge_keys:
                yield _reg_finding(
                    "propagation-field-drift", rel, lineno,
                    f"unreduced:{f_name}",
                    f"propagation field {f_name!r} ({fields_name}) has "
                    f"no {merge_name} entry — a row field without a "
                    "declared globalization silently breaks the sharded "
                    "row (declare its merge op, or drop the field)")
        for k, op, lineno in merge:
            if k not in field_set:
                yield _reg_finding(
                    "propagation-field-drift", rel, lineno,
                    f"undeclared:{k}",
                    f"{merge_name} reduces {k!r} which is not a "
                    f"{fields_name} entry — dead merge leg (add the row "
                    "field or delete the entry)")
            if op not in PROPAGATION_MERGE_OPS:
                yield _reg_finding(
                    "propagation-field-drift", rel, lineno,
                    f"bad-op:{k}",
                    f"{merge_name}[{k!r}] declares unknown merge op "
                    f"{op!r} (one of {PROPAGATION_MERGE_OPS}) — the "
                    "propagation fold cannot implement it")
        if project.readme is not None and project.readme.exists():
            documented = documented_propagation_fields(project.readme)
            readme_rel = project.readme.name
            for f_name in sorted(field_set - set(documented)):
                yield _reg_finding(
                    "propagation-field-drift", readme_rel, 1,
                    f"undocumented:{f_name}",
                    f"propagation field {f_name!r} has no row in the "
                    f"README '{PROPAGATION_SECTION[3:]}' table")
            for f_name, line in sorted(documented.items()):
                if f_name not in field_set:
                    yield _reg_finding(
                        "propagation-field-drift", readme_rel, line,
                        f"stale-row:{f_name}",
                        f"README documents propagation field {f_name!r} "
                        "but the row does not carry it — delete the row "
                        "or restore the field")


# ---------------------------------------------------------------------------
# invariant-row cross-check (pass family d, ISSUE 17): the watchdog's
# device invariant row is registry-governed like the telemetry row
# ---------------------------------------------------------------------------

def documented_invariant_fields(readme: Path) -> Dict[str, int]:
    """{field: line} from the README invariant table (the
    ``INVARIANT_SECTION`` section's first column)."""
    out: Dict[str, int] = {}
    in_section = False
    for i, line in enumerate(readme.read_text().splitlines(), start=1):
        if line.startswith("## "):
            in_section = line.strip() == INVARIANT_SECTION
            continue
        if not in_section:
            continue
        m = ROW_RE.match(line)
        if m and m.group(1) not in ("Field", "Metric", "Predicate",
                                    "Knob", "Section"):
            out[m.group(1)] = i
    return out


@project_rule("invariant-field-drift",
              "the device invariant row, its merge contract, and the "
              "README invariant table out of sync (a field added to the "
              "row but not reduced, reduced but undeclared, an unknown "
              "merge op, or a missing/stale README row)",
              'INVARIANT_FIELDS gains "new_field" with no '
              "INVARIANT_MERGE entry")
def check_invariant_field_drift(files: List[SourceFile],
                                project: Project) -> Iterable[Finding]:
    by_rel = {f.rel: f for f in files}
    for rel, (fields_name, merge_name) in INVARIANT_SOURCES.items():
        src = by_rel.get(rel)
        if src is None:
            continue
        fields = _tuple_literal(src.tree, fields_name)
        merge = _dict_literal(src.tree, merge_name)
        if fields is None:
            continue
        merge = merge or []
        merge_keys = {k for k, _v, _ln in merge}
        field_set = {f for f, _ln in fields}
        for f_name, lineno in fields:
            if f_name not in merge_keys:
                yield _reg_finding(
                    "invariant-field-drift", rel, lineno,
                    f"unreduced:{f_name}",
                    f"invariant field {f_name!r} ({fields_name}) has "
                    f"no {merge_name} entry — a row field without a "
                    "declared globalization silently breaks the sharded "
                    "row (declare its merge op, or drop the field)")
        for k, op, lineno in merge:
            if k not in field_set:
                yield _reg_finding(
                    "invariant-field-drift", rel, lineno,
                    f"undeclared:{k}",
                    f"{merge_name} reduces {k!r} which is not a "
                    f"{fields_name} entry — dead merge leg (add the row "
                    "field or delete the entry)")
            if op not in INVARIANT_MERGE_OPS:
                yield _reg_finding(
                    "invariant-field-drift", rel, lineno,
                    f"bad-op:{k}",
                    f"{merge_name}[{k!r}] declares unknown merge op "
                    f"{op!r} (one of {INVARIANT_MERGE_OPS}) — the "
                    "invariant fold cannot implement it")
        if project.readme is not None and project.readme.exists():
            documented = documented_invariant_fields(project.readme)
            readme_rel = project.readme.name
            for f_name in sorted(field_set - set(documented)):
                yield _reg_finding(
                    "invariant-field-drift", readme_rel, 1,
                    f"undocumented:{f_name}",
                    f"invariant field {f_name!r} has no row in the "
                    f"README '{INVARIANT_SECTION[3:]}' table")
            for f_name, line in sorted(documented.items()):
                if f_name not in field_set:
                    yield _reg_finding(
                        "invariant-field-drift", readme_rel, line,
                        f"stale-row:{f_name}",
                        f"README documents invariant field {f_name!r} "
                        "but the row does not carry it — delete the row "
                        "or restore the field")


# ---------------------------------------------------------------------------
# metrics_lint compatibility (tools/metrics_lint.py delegates here)
# ---------------------------------------------------------------------------

def metric_drift_report(files: Iterable, readme: Path,
                        metrics: Iterable[str],
                        emitted: Optional[Dict[str, Set[str]]] = None,
                        ) -> List[str]:
    """The PR-1 metrics_lint contract as one function: emitted vs README
    both ways, routed through the declared registry.  Returns drift
    messages (empty = in sync).  Pass ``emitted`` (the
    ``emitted_metric_names`` map) to reuse an existing scan."""
    if emitted is None:
        emitted = emitted_metric_names(files)
    documented = documented_metric_names(readme)
    declared = set(metrics)
    out = []
    if not documented:
        return [f"no table rows found under '## Observability' in {readme}"]
    undeclared = set(emitted) - declared
    for name in sorted(undeclared):
        out.append(f"EMITTED BUT UNDECLARED: {name} "
                   f"(at {', '.join(sorted(emitted[name]))}) — add a "
                   "registry entry + a row to README.md '## Observability'")
    # undeclared names already tell the user to add the README row too —
    # don't report the same defect twice
    for name in sorted(set(emitted) - set(documented) - undeclared):
        out.append(f"EMITTED BUT UNDOCUMENTED: {name} "
                   f"(at {', '.join(sorted(emitted[name]))}) — add a row "
                   "to README.md '## Observability'")
    for name in sorted(set(documented) - set(emitted)):
        out.append(f"DOCUMENTED BUT NEVER EMITTED: {documented[name]} — "
                   "delete the README row or restore the emission")
    for name in sorted(declared - set(emitted)):
        out.append(f"DECLARED BUT NEVER EMITTED: {name} — delete the "
                   "registry entry or restore the emission")
    return out
