"""serflint pass family (d): schema-drift fingerprints.

Two schemas have silently broken consumers twice each (CHANGES.md):

- the **checkpoint pytree** — adding/removing a ``GossipState`` /
  ``ClusterState`` leaf makes every existing device checkpoint fail
  closed on restore ("pre-round-6 / pre-PR5 checkpoints fail closed"
  recurred in PR 3 and PR 5 as a *surprise*);
- the **wire-message field lists** — a re-numbered or added field skews
  the codec between mixed-version nodes.

A third persisted surface joined in PR 9: the **record/replay recording
format** (``serf_tpu/replay/recording.py`` ``RECORDING_SCHEMA`` — the
JSONL record kinds + field lists), pinned as ``recording`` and stamped
into every recording header, with load failing closed on a mismatch.

All are FINGERPRINTED from the AST (NamedTuple leaf names for the
pytree; dataclass field names + wire field numbers + enum registries for
the wire) and pinned with a version in
``serf_tpu/analysis/pins/schema_pins.json``.  Changing either schema
without bumping the pin is a lint failure; the deliberate bump is
``python tools/serflint.py --bump-schema`` (see MIGRATION.md).  The
pinned *version* is also the runtime guard: ``models/checkpoint.py``
stamps it into every checkpoint and refuses a mismatched restore with a
clear error instead of a shape surprise, and ``serf_tpu.codec`` exports
it as ``WIRE_SCHEMA_VERSION``.
"""

from __future__ import annotations

import ast
import hashlib
import json
from pathlib import Path
from typing import Dict, Iterable, List, Optional

from serf_tpu.analysis.core import (
    REPO,
    PINS_NAME,
    Finding,
    Project,
    SourceFile,
    project_rule,
)

#: the checkpoint pytree surface: {source file: [NamedTuple classes]}
PYTREE_SOURCES: Dict[str, List[str]] = {
    "serf_tpu/models/dissemination.py": ["FactTable", "GossipState"],
    "serf_tpu/models/vivaldi.py": ["VivaldiState"],
    "serf_tpu/models/swim.py": ["ClusterState"],
    # the adaptive control plane rides the cluster pytree (ISSUE 11)
    "serf_tpu/control/device.py": ["ControlState"],
}

#: the wire surface: the serf envelope plane, the SWIM packet plane AND
#: the shared node/member structs they nest — all cross-node wire
#: formats, so all are drift-pinned
WIRE_SOURCES: List[str] = [
    "serf_tpu/types/messages.py",
    "serf_tpu/host/messages.py",
    "serf_tpu/types/member.py",
]

#: wire-carried enum registries (member numbering IS wire semantics)
WIRE_REGISTRIES = ("MessageType", "QueryFlag", "SwimMessageType",
                   "SwimState", "MemberStatus")

#: the record/replay recording format: the declared record-kind -> field
#: lists literal in the replay plane (``RECORDING_SCHEMA``); a recording
#: is a persisted cross-version artifact exactly like a checkpoint
RECORDING_SOURCE = "serf_tpu/replay/recording.py"
RECORDING_DECL = "RECORDING_SCHEMA"

#: the black-box bundle format (PR 17): the declared section -> field
#: lists literal in ``obs/blackbox.py`` (``BLACKBOX_SCHEMA``); a bundle
#: is a persisted forensic artifact read by ``tools/blackbox.py`` across
#: versions, so it is drift-pinned exactly like a recording
BLACKBOX_SOURCE = "serf_tpu/obs/blackbox.py"
BLACKBOX_DECL = "BLACKBOX_SCHEMA"

#: the encrypted transport frame (PR 20): the declared frame layout +
#: encrypt-pipeline order + BATCH amortization literal in
#: ``host/keyring.py`` (``ENCRYPTION_FRAME_SCHEMA``).  The frame is a
#: cross-node wire format exactly like the message field lists — a
#: re-ordered pipeline stage or nonce-size change skews every
#: mixed-version encrypted cluster — so it folds into the WIRE
#: fingerprint (one pin, one version: ``WIRE_SCHEMA_VERSION`` already
#: guards packet compatibility and the frame rides packets)
ENCRYPTION_SOURCE = "serf_tpu/host/keyring.py"
ENCRYPTION_DECL = "ENCRYPTION_FRAME_SCHEMA"


def _fingerprint(obj) -> str:
    blob = json.dumps(obj, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


# ---------------------------------------------------------------------------
# spec extraction (pure AST)
# ---------------------------------------------------------------------------

def _class_fields(tree: ast.AST, names: List[str]) -> Dict[str, List[str]]:
    out: Dict[str, List[str]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name in names:
            fields = [s.target.id for s in node.body
                      if isinstance(s, ast.AnnAssign)
                      and isinstance(s.target, ast.Name)]
            out[node.name] = fields
    return out


def pytree_spec(root: Path) -> Dict[str, List[str]]:
    """Ordered leaf names of every checkpointed NamedTuple.  Order
    matters: the checkpoint flattens by field position."""
    spec: Dict[str, List[str]] = {}
    for rel, classes in PYTREE_SOURCES.items():
        p = root / rel
        if not p.exists():
            continue
        spec.update(_class_fields(ast.parse(p.read_text()), classes))
    return spec


def wire_spec(root: Path) -> Dict[str, dict]:
    """Per message class: dataclass field names + the wire field numbers
    its codec uses (both encode_* first args and decode ``f == N``
    comparisons), plus the wire-carried enum registries.  Covers every
    ``WIRE_SOURCES`` file — the serf envelope messages, the SWIM packet
    plane, and the nested node/member structs (class names are disjoint
    across the files)."""
    spec: Dict[str, dict] = {}
    for rel in WIRE_SOURCES:
        p = root / rel
        if p.exists():
            _wire_spec_of(ast.parse(p.read_text()), spec)
    # the encrypted frame is wire surface too (PR 20): frame layout,
    # encrypt-pipeline stage order, and the BATCH amortization contract
    # all skew mixed-version encrypted clusters when changed silently
    enc = _dict_literal_spec(root, ENCRYPTION_SOURCE, ENCRYPTION_DECL)
    if enc:
        spec["__encryption__"] = enc
    return spec


def _wire_spec_of(tree: ast.AST, spec: Dict[str, dict]) -> None:
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        if node.name in WIRE_REGISTRIES:
            members = {}
            for s in node.body:
                if isinstance(s, ast.Assign) and len(s.targets) == 1 \
                        and isinstance(s.targets[0], ast.Name) \
                        and isinstance(s.value, ast.Constant) \
                        and isinstance(s.value.value, int):
                    members[s.targets[0].id] = s.value.value
            spec[node.name] = {"members": members}
            continue
        fields = [s.target.id for s in node.body
                  if isinstance(s, ast.AnnAssign)
                  and isinstance(s.target, ast.Name)]
        wire_nums = set()
        for sub in ast.walk(node):
            # codec.encode_*_field(N, ...) / encode_length_delimited(N, ...)
            if isinstance(sub, ast.Call) and isinstance(sub.func,
                                                        ast.Attribute) \
                    and sub.func.attr.startswith("encode_") \
                    and sub.args \
                    and isinstance(sub.args[0], ast.Constant) \
                    and isinstance(sub.args[0].value, int):
                wire_nums.add(sub.args[0].value)
            # decode loop: ``if f == N`` / ``elif f == N``
            if isinstance(sub, ast.Compare) \
                    and isinstance(sub.left, ast.Name) \
                    and sub.left.id == "f" \
                    and len(sub.comparators) == 1 \
                    and isinstance(sub.comparators[0], ast.Constant) \
                    and isinstance(sub.comparators[0].value, int):
                wire_nums.add(sub.comparators[0].value)
        # a class is wire surface if it carries a TYPE tag OR actually
        # encodes/decodes numbered fields (catches nested structs like
        # PushNodeState/Node/Member that have codecs but no TYPE)
        has_type = any(
            isinstance(s, ast.Assign) and len(s.targets) == 1
            and isinstance(s.targets[0], ast.Name)
            and s.targets[0].id == "TYPE"
            for s in node.body)
        if has_type or wire_nums:
            spec[node.name] = {"fields": fields, "wire": sorted(wire_nums)}


def _dict_literal_spec(root: Path, source: str,
                       decl: str) -> Dict[str, List[str]]:
    """Extract a module-level ``NAME = {str: (str, ...)}`` literal as
    {key: ordered field list} — pure AST, like the other specs."""
    p = root / source
    if not p.exists():
        return {}
    for node in ast.walk(ast.parse(p.read_text())):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == decl \
                and isinstance(node.value, ast.Dict):
            out: Dict[str, List[str]] = {}
            for key, val in zip(node.value.keys, node.value.values):
                if isinstance(key, ast.Constant) \
                        and isinstance(val, (ast.Tuple, ast.List)):
                    out[key.value] = [
                        e.value for e in val.elts
                        if isinstance(e, ast.Constant)]
            return out
    return {}


def recording_spec(root: Path) -> Dict[str, List[str]]:
    """Record kinds and their ordered field lists from the
    ``RECORDING_SCHEMA`` literal."""
    return _dict_literal_spec(root, RECORDING_SOURCE, RECORDING_DECL)


def blackbox_spec(root: Path) -> Dict[str, List[str]]:
    """Bundle sections and their ordered field lists from the
    ``BLACKBOX_SCHEMA`` literal (``obs/blackbox.py``)."""
    return _dict_literal_spec(root, BLACKBOX_SOURCE, BLACKBOX_DECL)


def pytree_fingerprint(root: Path = REPO) -> str:
    return _fingerprint(pytree_spec(root))


def wire_fingerprint(root: Path = REPO) -> str:
    return _fingerprint(wire_spec(root))


def recording_fingerprint(root: Path = REPO) -> str:
    return _fingerprint(recording_spec(root))


def blackbox_fingerprint(root: Path = REPO) -> str:
    return _fingerprint(blackbox_spec(root))


# ---------------------------------------------------------------------------
# pins
# ---------------------------------------------------------------------------

def load_pins(path: Optional[Path] = None) -> dict:
    p = path or (REPO / PINS_NAME)
    return json.loads(p.read_text())


def save_pins(pins: dict, path: Optional[Path] = None) -> None:
    p = path or (REPO / PINS_NAME)
    p.write_text(json.dumps(pins, indent=1, sort_keys=True) + "\n")


def bump_pins(root: Path = REPO, path: Optional[Path] = None) -> dict:
    """The deliberate schema bump: recompute every fingerprint, bump the
    version of whichever changed (MIGRATION.md documents the workflow).
    A kind the pin file predates (e.g. ``recording``) starts at
    version 0 and bumps to 1 on first stamp."""
    p = path or (root / PINS_NAME)
    pins = json.loads(p.read_text()) if p.exists() else {}
    for kind, fp in (("pytree", pytree_fingerprint(root)),
                     ("wire", wire_fingerprint(root)),
                     ("recording", recording_fingerprint(root)),
                     ("blackbox", blackbox_fingerprint(root))):
        pins.setdefault(kind, {"version": 0, "fingerprint": ""})
        if pins[kind]["fingerprint"] != fp:
            pins[kind] = {"version": pins[kind]["version"] + 1,
                          "fingerprint": fp}
    save_pins(pins, p)
    return pins


def pytree_schema_version() -> int:
    """Runtime accessor (models/checkpoint.py stamps this into every
    checkpoint).  Reads the pin only — never recomputes the AST
    fingerprint at runtime."""
    return int(load_pins()["pytree"]["version"])


def wire_schema_version() -> int:
    """Runtime accessor (exported as ``serf_tpu.codec
    .WIRE_SCHEMA_VERSION``)."""
    return int(load_pins()["wire"]["version"])


def recording_schema_version() -> int:
    """Runtime accessor (stamped into every record/replay recording
    header by ``serf_tpu.replay.recording``)."""
    return int(load_pins()["recording"]["version"])


def blackbox_schema_version() -> int:
    """Runtime accessor (stamped into every black-box bundle's
    ``meta.version`` by ``serf_tpu.obs.blackbox``; validation fails
    closed on a mismatch)."""
    return int(load_pins()["blackbox"]["version"])


# ---------------------------------------------------------------------------
# the drift rules
# ---------------------------------------------------------------------------

def _drift_finding(kind: str, rule_id: str, project: Project,
                   current: str, pinned: dict, anchor: str) -> Finding:
    return Finding(
        rule=rule_id, path=anchor, line=1,
        message=(f"{kind} schema drifted: fingerprint {current} != pinned "
                 f"{pinned['fingerprint']} (version {pinned['version']}) — "
                 "if the change is deliberate run `python tools/serflint.py "
                 "--bump-schema` and note it per MIGRATION.md"),
        # the drifted fingerprint is part of the key: baselining one
        # drift (instead of --bump-schema) can never grandfather the
        # NEXT drift — each new schema shape is a fresh finding
        key=f"{kind}-schema@{current}")


@project_rule("schema-pytree-drift",
              "a GossipState/checkpoint pytree leaf changed without a "
              "pinned-version bump — old checkpoints would fail closed "
              "as a surprise",
              "adding a GossipState field, pin untouched")
def check_pytree_drift(files: List[SourceFile],
                       project: Project) -> Iterable[Finding]:
    if project.pins_path is None or not project.pins_path.exists():
        return
    pins = json.loads(project.pins_path.read_text())
    current = pytree_fingerprint(project.root)
    if current != pins["pytree"]["fingerprint"]:
        yield _drift_finding("pytree", "schema-pytree-drift", project,
                             current, pins["pytree"],
                             "serf_tpu/models/dissemination.py")


@project_rule("schema-recording-drift",
              "the record/replay recording format (RECORDING_SCHEMA) "
              "changed without a pinned-version bump — old recordings "
              "would stop loading as a surprise",
              "adding a record field, pin untouched")
def check_recording_drift(files: List[SourceFile],
                          project: Project) -> Iterable[Finding]:
    if project.pins_path is None or not project.pins_path.exists():
        return
    pins = json.loads(project.pins_path.read_text())
    current = recording_fingerprint(project.root)
    pinned = pins.get("recording")
    if pinned is None:
        if recording_spec(project.root):
            yield _drift_finding("recording", "schema-recording-drift",
                                 project, current,
                                 {"fingerprint": "<unpinned>",
                                  "version": 0},
                                 RECORDING_SOURCE)
        return
    if current != pinned["fingerprint"]:
        yield _drift_finding("recording", "schema-recording-drift",
                             project, current, pinned, RECORDING_SOURCE)


@project_rule("schema-blackbox-drift",
              "the black-box bundle format (BLACKBOX_SCHEMA) changed "
              "without a pinned-version bump — old bundles would stop "
              "validating as a surprise",
              "adding a bundle section, pin untouched")
def check_blackbox_drift(files: List[SourceFile],
                         project: Project) -> Iterable[Finding]:
    if project.pins_path is None or not project.pins_path.exists():
        return
    pins = json.loads(project.pins_path.read_text())
    current = blackbox_fingerprint(project.root)
    pinned = pins.get("blackbox")
    if pinned is None:
        if blackbox_spec(project.root):
            yield _drift_finding("blackbox", "schema-blackbox-drift",
                                 project, current,
                                 {"fingerprint": "<unpinned>",
                                  "version": 0},
                                 BLACKBOX_SOURCE)
        return
    if current != pinned["fingerprint"]:
        yield _drift_finding("blackbox", "schema-blackbox-drift",
                             project, current, pinned, BLACKBOX_SOURCE)


@project_rule("schema-wire-drift",
              "a wire-message field list / field number / envelope tag "
              "changed without a pinned-version bump — codec skew between "
              "mixed-version nodes",
              "re-numbering a JoinMessage field, pin untouched")
def check_wire_drift(files: List[SourceFile],
                     project: Project) -> Iterable[Finding]:
    if project.pins_path is None or not project.pins_path.exists():
        return
    pins = json.loads(project.pins_path.read_text())
    current = wire_fingerprint(project.root)
    if current != pins["wire"]["fingerprint"]:
        yield _drift_finding("wire", "schema-wire-drift", project,
                             current, pins["wire"],
                             "serf_tpu/types/messages.py")
