"""serflint core: the shared AST pass framework.

Design constraints (ISSUE 8):

- **pure AST** — no module under analysis is ever imported, so the whole
  repo lints in single-digit seconds against the tight tier-1 budget and
  a syntax-valid-but-crashing module still gets linted;
- **suppression with mandatory reason** — ``# serflint: ignore[rule-id]
  -- reason`` on the offending line (or alone on the line above).  A
  suppression without a reason, or one that matches nothing, is itself a
  finding, so the suppression surface can only shrink;
- **committed baseline** — grandfathered findings live in
  ``serflint_baseline.json`` with a per-entry reason; the tier-1 gate is
  *zero new findings*, not zero findings.  Baseline entries match on
  (rule, file, key) where the key is the normalized source line (or a
  rule-chosen stable symbol), so unrelated edits never invalidate them;
- **one parse per file** — every rule family walks the same parsed
  trees (``SourceFile``), collected once per run.

Rules register themselves via :func:`rule` (file scope — called once per
source file) or :func:`project_rule` (project scope — called once with
the whole file set: registry cross-checks, schema fingerprints, doc
tables).  ``serf_tpu.analysis.__init__`` imports every rule module so
importing the package yields the full registry.
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence, Tuple

#: repo root (analysis/ -> serf_tpu/ -> repo)
REPO = Path(__file__).resolve().parent.parent.parent

#: default file-rule scan set (repo-relative); tests are deliberately
#: excluded — fixture files intentionally violate every rule
DEFAULT_SCAN: Tuple[str, ...] = ("serf_tpu", "bench.py", "tools")

#: the metric/flight emission contract predates serflint (metrics_lint,
#: PR 1) and is pinned to exactly this set — tools/ CLIs print, they
#: don't emit
METRIC_SCAN: Tuple[str, ...] = ("serf_tpu", "bench.py")

BASELINE_NAME = "serflint_baseline.json"
PINS_NAME = "serf_tpu/analysis/pins/schema_pins.json"


@dataclass(frozen=True)
class Finding:
    """One lint finding.  ``key`` is the stable identity baseline entries
    match on (defaults to the normalized source line text)."""

    rule: str
    path: str          # repo-relative posix path
    line: int
    message: str
    key: str

    def location(self) -> str:
        return f"{self.path}:{self.line}"


@dataclass
class SourceFile:
    path: Path         # absolute
    rel: str           # project-relative posix path
    lines: List[str]
    tree: ast.AST

    def norm_line(self, lineno: int) -> str:
        """Whitespace-normalized source line (1-based), the default
        baseline key: stable under edits elsewhere in the file."""
        if 1 <= lineno <= len(self.lines):
            return re.sub(r"\s+", " ", self.lines[lineno - 1].strip())
        return ""


@dataclass(frozen=True)
class Registry:
    """The declared observability registry the registry passes check
    against (the repo's lives in ``serf_tpu.analysis.registry``; tests
    inject toys)."""

    metrics: frozenset
    flight_kinds: frozenset
    #: declared SLO names (obs/slo.py SLO_TABLE must match, both ways)
    slos: frozenset = frozenset()
    #: declared controller-writable knob names (the control plane's
    #: KNOB_FIELDS / HOST_KNOBS + law tables must match, both ways)
    control_knobs: frozenset = frozenset()


@dataclass
class Project:
    """Everything a run needs; ``default_project()`` builds the repo's."""

    root: Path
    scan: Sequence[str] = DEFAULT_SCAN
    metric_scan: Sequence[str] = METRIC_SCAN
    readme: Optional[Path] = None
    baseline_path: Optional[Path] = None
    pins_path: Optional[Path] = None
    registry: Optional[Registry] = None


def default_project() -> Project:
    from serf_tpu.analysis import registry as reg

    return Project(
        root=REPO,
        readme=REPO / "README.md",
        baseline_path=REPO / BASELINE_NAME,
        pins_path=REPO / PINS_NAME,
        registry=Registry(metrics=frozenset(reg.METRICS),
                          flight_kinds=frozenset(reg.FLIGHT_KINDS),
                          slos=frozenset(reg.SLOS),
                          control_knobs=frozenset(reg.CONTROL_KNOBS)),
    )


# ---------------------------------------------------------------------------
# rule registry
# ---------------------------------------------------------------------------

@dataclass
class Rule:
    id: str
    doc: str                      # one-line "what it catches" (README table)
    example: str                  # short bad-code example (README table)
    scope: str                    # "file" | "project" | "meta"
    fn: Optional[Callable] = None

#: id -> Rule, insertion-ordered; the docs pass enforces README parity
ALL_RULES: Dict[str, Rule] = {}


def _register(r: Rule) -> Rule:
    if r.id in ALL_RULES:
        raise ValueError(f"duplicate serflint rule id {r.id!r}")
    ALL_RULES[r.id] = r
    return r


def rule(id: str, doc: str, example: str):
    """Register a file-scope rule: ``fn(src: SourceFile, project) ->
    Iterable[Finding]``, called once per scanned file."""
    def deco(fn):
        _register(Rule(id=id, doc=doc, example=example, scope="file", fn=fn))
        return fn
    return deco


def project_rule(id: str, doc: str, example: str):
    """Register a project-scope rule: ``fn(files: List[SourceFile],
    project) -> Iterable[Finding]``, called once per run."""
    def deco(fn):
        _register(Rule(id=id, doc=doc, example=example, scope="project",
                       fn=fn))
        return fn
    return deco


def meta_rule(id: str, doc: str, example: str) -> None:
    """Register a framework-emitted rule id (suppression/baseline
    hygiene) so the README table covers it; has no check function."""
    _register(Rule(id=id, doc=doc, example=example, scope="meta"))


def finding(rule_id: str, src: SourceFile, node_or_line, message: str,
            key: Optional[str] = None) -> Finding:
    """Build a Finding anchored at an AST node (or explicit line)."""
    line = getattr(node_or_line, "lineno", node_or_line)
    return Finding(rule=rule_id, path=src.rel, line=int(line),
                   message=message, key=key or src.norm_line(int(line)))


# ---------------------------------------------------------------------------
# file collection
# ---------------------------------------------------------------------------

def collect_files(project: Project,
                  only: Optional[Sequence[Path]] = None) -> List[SourceFile]:
    """Parse the scan set once.  ``only`` restricts to explicit paths
    (CLI dev flow).  Unparseable files raise — a syntax error in the
    tree is a lint failure at a more basic layer."""
    paths: List[Path] = []
    if only:
        paths = [Path(p).resolve() for p in only]
    else:
        for entry in project.scan:
            p = project.root / entry
            if p.is_dir():
                paths.extend(sorted(p.rglob("*.py")))
            elif p.exists():
                paths.append(p)
    out = []
    for p in paths:
        if "__pycache__" in p.parts:
            continue
        text = p.read_text()
        try:
            rel = p.relative_to(project.root).as_posix()
        except ValueError:
            rel = p.as_posix()
        out.append(SourceFile(path=p, rel=rel, lines=text.splitlines(),
                              tree=ast.parse(text, filename=str(p))))
    return out


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

#: grammar (as a comment): ``serflint: ignore[rule-a, rule-b] -- reason``
_SUPPRESS_RE = re.compile(
    r"#\s*serflint:\s*ignore\[([a-zA-Z0-9_,\- ]+)\]\s*(?:--\s*(\S.*))?$")


@dataclass
class Suppression:
    src: SourceFile
    line: int            # line the comment is on
    covers: int          # line the suppression applies to
    rules: Tuple[str, ...]
    reason: str
    used: bool = False


def collect_suppressions(src: SourceFile) -> List[Suppression]:
    """Parse suppression comments via tokenize so the grammar appearing
    inside a string/docstring (this framework documents itself...) is
    never treated as a live suppression."""
    import io
    import tokenize

    out = []
    try:
        tokens = list(tokenize.generate_tokens(
            io.StringIO("\n".join(src.lines) + "\n").readline))
    except (tokenize.TokenError, IndentationError):
        # pragma: no cover - ast.parse succeeded, so this is unreachable
        # in practice; degrade to "no suppressions" rather than crash
        return out
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        i = tok.start[0]
        covers = i
        if src.lines[i - 1].strip().startswith("#"):
            # comment-only: covers the first CODE line after the comment
            # block (the reason may wrap onto continuation comment lines)
            covers = i + 1
            while covers <= len(src.lines) and (
                    not src.lines[covers - 1].strip()
                    or src.lines[covers - 1].strip().startswith("#")):
                covers += 1
        out.append(Suppression(
            src=src, line=i, covers=covers,
            rules=tuple(r.strip() for r in m.group(1).split(",") if r.strip()),
            reason=(m.group(2) or "").strip()))
    return out


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def load_baseline(path: Optional[Path]) -> List[dict]:
    if path is None or not path.exists():
        return []
    data = json.loads(path.read_text())
    return list(data.get("entries", []))


def save_baseline(path: Path, entries: List[dict]) -> None:
    entries = sorted(entries, key=lambda e: (e["rule"], e["file"], e["key"]))
    path.write_text(json.dumps(
        {"version": 1, "entries": entries}, indent=1) + "\n")


def _reason_missing(reason: str) -> bool:
    return not reason or reason.upper().startswith(("TODO", "FIXME"))


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------

@dataclass
class Report:
    findings: List[Finding]          # NEW findings (the gate judges these)
    baselined: List[Finding]         # matched a baseline entry
    suppressed: List[Finding]        # matched an inline suppression
    stale_baseline: List[dict]       # entries that matched nothing

    @property
    def ok(self) -> bool:
        return not self.findings


def run_rules(project: Project, files: Optional[List[SourceFile]] = None,
              rules: Optional[Sequence[str]] = None,
              file_scope_only: bool = False) -> Report:
    """The one entry point: collect -> rules -> suppressions -> baseline.

    ``rules`` filters by id (CLI ``--rule``); meta findings
    (suppress-/baseline-hygiene) are only emitted on unfiltered runs so
    a ``--rule`` drill-down never drags the hygiene plane in.

    ``file_scope_only`` is set when ``files`` is a path-restricted
    subset (CLI positional paths): project-scope rules are skipped —
    they judge the WHOLE tree, and running them against a partial file
    set would report every out-of-view emit site as missing — and
    baseline entries for out-of-view files are not reported stale.
    """
    if files is None:
        files = collect_files(project)
    selected = [r for r in ALL_RULES.values()
                if rules is None or r.id in rules]
    raw: List[Finding] = []
    for r in selected:
        if r.scope == "file":
            for src in files:
                raw.extend(r.fn(src, project))
        elif r.scope == "project" and not file_scope_only:
            raw.extend(r.fn(files, project))

    # inline suppressions
    sups = {src.rel: collect_suppressions(src) for src in files}
    kept: List[Finding] = []
    suppressed: List[Finding] = []
    for f in raw:
        hit = None
        for s in sups.get(f.path, ()):
            if s.covers == f.line and f.rule in s.rules:
                hit = s
                break
        if hit is not None:
            hit.used = True
            suppressed.append(f)
        else:
            kept.append(f)

    # suppression hygiene (unfiltered runs only; see docstring)
    if rules is None:
        for src in files:
            for s in sups[src.rel]:
                if _reason_missing(s.reason):
                    kept.append(Finding(
                        rule="suppress-no-reason", path=src.rel, line=s.line,
                        message="suppression without a reason — append "
                                "'-- <why this is safe>'",
                        key=src.norm_line(s.line)))
                if not s.used:
                    kept.append(Finding(
                        rule="suppress-unused", path=src.rel, line=s.line,
                        message=f"suppression for {list(s.rules)} matches no "
                                "finding — delete it",
                        key=src.norm_line(s.line)))

    # baseline
    entries = load_baseline(project.baseline_path)
    pool: Dict[Tuple[str, str, str], List[dict]] = {}
    for e in entries:
        pool.setdefault((e["rule"], e["file"], e["key"]), []).append(e)
    new: List[Finding] = []
    baselined: List[Finding] = []
    for f in kept:
        bucket = pool.get((f.rule, f.path, f.key))
        if bucket:
            e = bucket.pop()
            baselined.append(f)
            if rules is None and _reason_missing(e.get("reason", "")):
                new.append(Finding(
                    rule="baseline-no-reason", path=f.path, line=f.line,
                    message=f"baseline entry for {f.rule} has no reason — "
                            "annotate it in " + BASELINE_NAME,
                    key=f.key))
        else:
            new.append(f)
    # a filtered run (--rule / positional paths) leaves the non-selected
    # rules' pool buckets unmatched — that's not staleness
    stale = [] if (file_scope_only or rules is not None) else \
        [e for bucket in pool.values() for e in bucket]
    if rules is None:
        for e in stale:
            new.append(Finding(
                rule="baseline-stale", path=e["file"], line=0,
                message=f"baseline entry for {e['rule']} (key {e['key']!r}) "
                        "matches no finding — delete it from " + BASELINE_NAME,
                key=e["key"]))
    new.sort(key=lambda f: (f.path, f.line, f.rule))
    return Report(findings=new, baselined=baselined, suppressed=suppressed,
                  stale_baseline=stale)


def fix_baseline(project: Project,
                 files: Optional[List[SourceFile]] = None) -> int:
    """Rewrite the baseline to cover every current NEW finding (keeping
    reasons of entries that still match).  New entries get a TODO reason
    the gate refuses — a human must justify each grandfathered finding."""
    assert project.baseline_path is not None
    old = {(e["rule"], e["file"], e["key"]): e.get("reason", "")
           for e in load_baseline(project.baseline_path)}
    report = run_rules(project, files=files)
    entries = []
    for f in report.baselined + [
            f for f in report.findings
            if f.rule not in ("baseline-stale", "baseline-no-reason",
                              "suppress-no-reason", "suppress-unused")]:
        entries.append({
            "rule": f.rule, "file": f.path, "key": f.key,
            "detail": f.message,
            "reason": old.get((f.rule, f.path, f.key),
                              "TODO: justify or fix"),
        })
    save_baseline(project.baseline_path, entries)
    return len(entries)


# framework-emitted hygiene rules (registered for the README table)
meta_rule("suppress-no-reason",
          "`# serflint: ignore[...]` without a `-- reason`",
          "# serflint: ignore[async-fire-forget]")
meta_rule("suppress-unused",
          "a suppression comment that matches no finding",
          "stale ignore after the code was fixed")
meta_rule("baseline-stale",
          "a baseline entry that matches no finding",
          "entry left behind after the code was fixed")
meta_rule("baseline-no-reason",
          "a baseline entry whose reason is empty/TODO",
          '"reason": "TODO: justify or fix"')


# ---------------------------------------------------------------------------
# shared AST helpers (used by the rule modules)
# ---------------------------------------------------------------------------

def call_name(node: ast.AST) -> str:
    """Dotted name of a call target: ``asyncio.create_task`` -> that
    string, ``self.loop.create_task`` -> ``self.loop.create_task``;
    non-name shapes -> ''."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return ""


def names_in(node: ast.AST) -> set:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def walk_shallow(node: ast.AST):
    """Yield descendants WITHOUT descending into nested function/class
    definitions (each definition is analyzed in its own right)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))
