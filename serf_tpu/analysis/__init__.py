"""serflint: the repo's static-analysis plane (ISSUE 8).

An AST-based multi-pass analyzer over the whole tree — pure AST, no
module under analysis is ever imported, so a full run is single-digit
seconds.  Four pass families:

- **async-concurrency** (``async_rules``): fire-and-forget tasks,
  blocking calls in coroutines, parking awaits under locks, unlocked
  shared-container mutation;
- **JAX tracing** (``jax_rules``): Python branches / host
  concretization inside traced device-plane code, host transfers in
  round-step code, unhashable jitted-call args;
- **registry cross-check** (``registry``): ONE declared registry of
  every metric name and flight-event kind, checked against emit sites
  and the README table (subsumes PR 1's ``tools/metrics_lint.py``);
- **schema drift** (``schema``): the checkpoint pytree leaf-spec and
  the wire-message field lists are fingerprinted and version-pinned —
  changing either without a deliberate bump is a lint failure, not a
  fail-closed-checkpoint surprise.

Plus the self-referential docs pass (``docs``): the README rule table
is enforced both ways, like the metrics table.

Entry points: ``tools/serflint.py`` CLI; :func:`analyze_repo` for
embedding (bench.py tracks the finding trajectory per round); the
tier-1 gate is *zero new findings* over the committed baseline.
"""

from __future__ import annotations

from serf_tpu.analysis.core import (   # noqa: F401
    ALL_RULES,
    DEFAULT_SCAN,
    Finding,
    Project,
    Registry,
    Report,
    default_project,
    collect_files,
    fix_baseline,
    run_rules,
)

# importing the rule modules registers every rule
from serf_tpu.analysis import async_rules   # noqa: F401,E402
from serf_tpu.analysis import jax_rules     # noqa: F401,E402
from serf_tpu.analysis import registry      # noqa: F401,E402
from serf_tpu.analysis import schema        # noqa: F401,E402
from serf_tpu.analysis import docs          # noqa: F401,E402


def analyze_repo(rules=None) -> Report:
    """Run the full analyzer on the repo with the committed baseline."""
    return run_rules(default_project(), rules=rules)


__all__ = [
    "ALL_RULES", "DEFAULT_SCAN", "Finding", "Project", "Registry",
    "Report", "analyze_repo", "collect_files", "default_project",
    "fix_baseline", "run_rules",
]
