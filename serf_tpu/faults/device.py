"""Device-plane fault executor: FaultPlan -> per-round masks in the scan.

``lower_plan`` compiles the SAME :class:`~serf_tpu.faults.plan.FaultPlan`
the host executor runs into a :class:`DeviceFaultSchedule` — per-phase
partition-group vectors (``i32[P, N]``), loss rates (``f32[P]``) and
down-node masks (``bool[P, N]``) — and ``run_device_plan`` drives the
flagship ``cluster_round`` through the plan phase by phase, with the
masks consumed INSIDE the jitted scan (``models/swim.cluster_round``:
gossip exchange, probe adjacency, push/pull and Vivaldi all read them).

Lowering semantics (device deviations are explicit, not silent):

- partitions/crash/pause/restart/drop lower exactly;
- ``pause`` lowers like ``crash`` (the model's liveness bit IS its
  network presence — there is no separate process-alive state);
- ``corrupt`` folds into ``drop`` (a corrupted packet is quarantined by
  the receiver's wire pipeline — same observable outcome: not learned);
- ``duplicate``/``reorder``/``delay`` are no-ops under round-synchronous
  idempotent OR-merge delivery and lower to nothing;
- per-edge faults do not lower (no O(N^2) edge state on device);
  plans carrying them still run, with a note in the schedule.
"""

from __future__ import annotations

import functools
import time
from dataclasses import dataclass, field
from typing import List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from serf_tpu.faults.plan import FaultPlan
from serf_tpu.models.swim import (
    ClusterConfig,
    ClusterState,
    cluster_round,
    make_cluster,
)


def _NODE_DIGEST_CAP() -> int:
    # lazy: the replay plane is only imported when a recorder is attached
    from serf_tpu.replay.recording import NODE_DIGEST_CAP
    return NODE_DIGEST_CAP


class DeviceFaultSchedule(NamedTuple):
    """Per-phase fault tensors (P = number of phases, N = nodes)."""

    rounds: Tuple[int, ...]       # static per-phase round counts
    group: jnp.ndarray            # i32[P, N] partition id per node
    drop: jnp.ndarray             # f32[P]    per-delivery loss rate
    down: jnp.ndarray             # bool[P, N] nodes off the network
    notes: Tuple[str, ...] = ()   # lowering caveats (e.g. edges skipped)
    events: Tuple[int, ...] = ()  # extra fact injections per phase
                                  # (load lowering: offered event+query
                                  # ops over the phase's wall duration)


def lower_plan(plan: FaultPlan, n: Optional[int] = None
               ) -> DeviceFaultSchedule:
    """Compile ``plan`` to per-phase device masks.  ``n`` overrides the
    plan's node count (a plan written for 6 hosts can drive a 4096-node
    sim: groups/crash sets given as fractions of the plan's n scale by
    index stretching — node i of the plan covers indices
    ``[i*n/plan.n, (i+1)*n/plan.n)`` of the sim)."""
    plan.validate()
    sim_n = n or plan.n
    scale = sim_n / plan.n

    def span(i: int) -> range:
        return range(int(i * scale), max(int(i * scale) + 1,
                                         int((i + 1) * scale)))

    notes: List[str] = []
    p = len(plan.phases)
    group = np.zeros((p, sim_n), np.int32)
    drop = np.zeros((p,), np.float32)
    down = np.zeros((p, sim_n), bool)
    events: List[int] = []
    cur_down = np.zeros((sim_n,), bool)
    for pi, phase in enumerate(plan.phases):
        # load lowering (ISSUE 5): the offered user-plane ops over the
        # phase's HOST wall duration become extra fact injections —
        # query fan-out rides the same dissemination plane on device.
        # A burst past ring capacity is exactly what the model's
        # overflow accountant (GossipState.overflow) must catch.
        offered = phase.event_rate + phase.query_rate
        events.append(int(np.ceil(offered * phase.duration_s))
                      if offered > 0 else 0)
        if phase.query_rate > 0:
            notes.append(f"phase {pi}: query load lowered to "
                         "dissemination facts (device has no query RPC)")
        if phase.stall:
            notes.append(f"phase {pi}: {len(phase.stall)} consumer "
                         "stall(s) not lowered (host-plane only)")
        if phase.partitions:
            # nodes not listed in any group share one implicit extra
            # group (same rule as faults.host.compile_phase)
            for gi, g in enumerate(phase.partitions, start=1):
                for node in g:
                    for j in span(node):
                        group[pi, j] = gi
        eff_drop = phase.drop + phase.corrupt * (1.0 - phase.drop)
        drop[pi] = min(1.0, eff_drop)
        if phase.corrupt:
            notes.append(f"phase {pi}: corrupt folded into drop")
        if phase.edges:
            notes.append(f"phase {pi}: {len(phase.edges)} edge fault(s) "
                         "not lowered (host-plane only)")
        for node in (*phase.crash, *phase.pause):
            for j in span(node):
                cur_down[j] = True
        for node in phase.restart:
            for j in span(node):
                cur_down[j] = False
        down[pi] = cur_down
    return DeviceFaultSchedule(
        rounds=tuple(ph.rounds for ph in plan.phases),
        group=jnp.asarray(group),
        drop=jnp.asarray(drop),
        down=jnp.asarray(down),
        notes=tuple(notes),
        events=tuple(events),
    )


def run_phase(state: ClusterState, cfg: ClusterConfig, key: jax.Array,
              num_rounds: int, group: jnp.ndarray, drop,
              init_alive: jnp.ndarray, down: jnp.ndarray,
              mesh=None, collect_digests: bool = False,
              include_nodes: bool = True,
              collect_telemetry: bool = False,
              collect_control: bool = False,
              collect_propagation: bool = False,
              sentinels=None,
              collect_invariants: bool = False,
              inv_cov0=None):
    """Scan ``num_rounds`` chaos rounds with one phase's masks applied.
    Jit with ``num_rounds`` static; group/drop/down are traced, so equal-
    length phases reuse the compiled executable.  ``mesh`` runs every
    round on the sharded flagship path (the masks are per-node planes,
    so they shard with the state — nothing else changes).

    ``collect_digests`` (static) additionally emits the record/replay
    plane's per-round membership-view digest from inside the scan
    (``replay.digest.state_digest``) and returns
    ``(final_state, (digest u32[R], node_digests u32[R, N]))`` instead
    of the bare state.  ``include_nodes`` (static) gates the per-node
    plane: above ``NODE_DIGEST_CAP`` the recorders discard it anyway, so
    passing False avoids stacking an R×N scan output at flagship scale
    (the second element is then an empty ``()``).

    ``collect_telemetry`` (static) additionally stacks one per-round
    counters row (``models/swim.round_telemetry``: alive, agreement,
    coverage, overflow ledger, suspicions, false-DEAD) as a scan output
    — the continuous-telemetry plane's device feed, staying on device
    until the caller's single per-run ``device_get``.

    ``collect_control`` (static) additionally stacks one per-round
    control-trajectory row (``control.device.control_row``: the knob
    vector + shed/actuation ledgers) — the adaptive-control plane's
    evidence feed (stability invariant, recording ``control`` steps,
    the chaos A/B report).

    ``collect_propagation`` (static) additionally stacks the
    propagation observatory's per-round evidence (``models/swim
    .propagation_row``): the gossip exchange's redundancy-ledger pair
    plus per-sentinel coverage for the traced fact ids in ``sentinels``
    (i32[M], a traced operand — the executor passes the first injected
    batch's eids).  Shares the telemetry row's known-plane unpack
    (``round_telemetry(with_cols=True)``) and the same
    stay-on-device-until-one-device_get discipline.

    ``collect_invariants`` (static) additionally judges the always-on
    watchdog's invariant predicates every round (``models/swim
    .invariant_row``, ``obs/watchdog.INVARIANT_FIELDS`` order): one
    boolean/bitmask row per round folded from the SAME already-reduced
    telemetry/propagation operands — zero extra transfers, and the
    first violating round is named from the scan output instead of
    inferred post-hoc.  When the propagation tracer rides too, the
    coverage-monotonicity predicate threads the per-sentinel running
    coverage maximum through the scan carry, seeded by ``inv_cov0``
    (``f32[M]``) so chunked callers stay exact across chunk boundaries;
    the invariant aux entry is then ``(irows f32[R, F], cov_fin
    f32[M])`` instead of the bare ``irows``.

    Aux-output shape: exactly one flag returns its bare stream; several
    return a tuple in declared order (digests, telemetry, control,
    propagation, invariants) — callers that predate a flag unpack
    exactly what they always did.

    When ``cfg.control.enabled`` the control law ticks INSIDE the scan
    every round (``models/swim.control_tick``), sharing the telemetry
    row with ``collect_telemetry`` — controlled chaos rounds cost zero
    extra device_gets."""
    if collect_digests:
        # lazy for the same reason as _NODE_DIGEST_CAP: the replay plane
        # only rides along when digests are actually being collected
        from serf_tpu.replay.digest import state_digest
    from serf_tpu.models.swim import control_tick, round_telemetry
    if collect_control:
        from serf_tpu.control.device import control_row
    if collect_propagation:
        from serf_tpu.models.swim import propagation_row
    if collect_invariants:
        from serf_tpu.models.swim import invariant_row

    alive = init_alive & ~down
    st = state._replace(gossip=state.gossip._replace(alive=alive),
                        group=group)
    track_cov = collect_invariants and collect_propagation

    def body(carry, subkey):
        if track_cov:
            carry, prev_cov = carry
        if collect_propagation:
            nxt, pair = cluster_round(carry, cfg, subkey, drop_rate=drop,
                                      mesh=mesh, collect_propagation=True)
            row, colcnt, alive_cnt = round_telemetry(
                nxt, cfg, mesh=mesh, with_cols=True)
        else:
            nxt = cluster_round(carry, cfg, subkey, drop_rate=drop,
                                mesh=mesh)
            row = round_telemetry(nxt, cfg, mesh=mesh) \
                if (collect_telemetry or collect_invariants
                    or cfg.control.enabled) else None
        nxt, row = control_tick(nxt, cfg, row, mesh=mesh)
        aux = []
        if collect_digests:
            overall, node = state_digest(nxt.gossip, cfg.gossip)
            aux.append((overall, node) if include_nodes
                       else (overall, ()))
        if collect_telemetry:
            aux.append(row)
        if collect_control:
            aux.append(control_row(nxt.control))
        if collect_propagation:
            prop_out = propagation_row(nxt.gossip, pair, colcnt,
                                       alive_cnt, sentinels)
            aux.append(prop_out)
        if collect_invariants:
            irow, new_prev_cov = invariant_row(
                nxt.gossip, row,
                sentinels if track_cov else None,
                colcnt if track_cov else None,
                prev_cov if track_cov else None,
                deferred=cfg.gossip.stamp_deferred)
            aux.append(irow)
        ncarry = (nxt, new_prev_cov) if track_cov else nxt
        if not aux:
            return ncarry, ()
        return ncarry, (aux[0] if len(aux) == 1 else tuple(aux))

    keys = jax.random.split(key, num_rounds)
    carry0 = st
    if track_cov:
        if inv_cov0 is None:
            inv_cov0 = (jnp.zeros(sentinels.shape, jnp.float32),
                        jnp.float32(-1.0))
        carry0 = (st, inv_cov0)
    final, out = jax.lax.scan(body, carry0, keys)
    if track_cov:
        final, cov_fin = final
        # the carried-out coverage maxima ride the invariant aux entry
        # (always last, and never alone: track_cov implies propagation)
        out = tuple(out)
        out = out[:-1] + ((out[-1], cov_fin),)
    return (final, out) if (collect_digests or collect_telemetry
                            or collect_control
                            or collect_propagation
                            or collect_invariants) else final


@functools.lru_cache(maxsize=16)
def _inject_runner(cfg: ClusterConfig, gated: bool,
                   kind: Optional[int] = None):
    """ONE jitted injection-chunk executable per (cfg, gated, kind),
    shared across runs: the storm plans inject dozens of ring-capacity
    chunks per phase, and dispatching ``gate_injections`` +
    ``inject_facts_batch`` eagerly (~40 ops each) dominated chaos-run
    wall clock.  Two shapes at most per plan (full chunks + one
    remainder) — jit caches both.  Ltimes stay an explicit operand so a
    perturbed recording's ltimes replay perturbed (the PR-9 verbatim
    contract)."""
    from serf_tpu.models.dissemination import (
        K_USER_EVENT,
        inject_facts_batch,
    )
    k = K_USER_EVENT if kind is None else kind

    def run(gossip, control, eids, ltimes, origins, active):
        if gated:
            from serf_tpu.control.device import gate_injections
            active, control = gate_injections(control, active)
        g = inject_facts_batch(
            gossip, cfg.gossip, eids, k,
            incarnations=jnp.zeros(eids.shape, jnp.uint32),
            ltimes=ltimes,
            origins=origins, active=active)
        return g, control

    return jax.jit(run)


@functools.lru_cache(maxsize=8)
def phase_runner(cfg: ClusterConfig, mesh=None):
    """ONE jitted phase-scan executable per (cfg, mesh), shared across
    runs in the process: ``jax.jit`` caches on function identity, so a
    fresh ``partial`` per ``run_device_plan`` call was recompiling the
    scan every run — record, replay, perturbed replay and repeated
    chaos plans at the same config now share compiles."""
    return jax.jit(functools.partial(run_phase, cfg=cfg, mesh=mesh),
                   static_argnames=("num_rounds", "collect_digests",
                                    "include_nodes", "collect_telemetry",
                                    "collect_control",
                                    "collect_propagation",
                                    "collect_invariants"))


@dataclass
class DeviceChaosResult:
    plan: FaultPlan
    schedule: DeviceFaultSchedule
    state: ClusterState
    report: object                 # invariants.InvariantReport
    rounds_run: int = 0
    notes: Tuple[str, ...] = ()
    injected: List[int] = field(default_factory=list)
    #: the overload ledger (GossipState.injected/.overflow): total facts
    #: offered to the ring by ANY path (executor load + SWIM detection
    #: traffic) and how many were clobbered while still in-window —
    #: serf.overload.device_offered / serf.overload.device_dropped
    offered: int = 0
    dropped: int = 0
    #: per-round ring time series (obs.timeseries.SeriesStore keyed by
    #: declared metric names) when the run collected telemetry — the
    #: SLO plane's device-side evidence.  Timestamps are round indices.
    telemetry: object = None
    #: the EXACT final telemetry row ({field: float}, models/swim
    #: TELEMETRY_FIELDS) — point verdicts (final agreement, false-DEAD
    #: count) must come from here, not from the ring, whose overflow
    #: downsampling pair-merges values (a ≥capacity-round run would
    #: otherwise read a converged 1.0 averaged with its last
    #: converging neighbor)
    telemetry_final: Optional[dict] = None
    #: the adaptive-control plane's evidence (cfg.control.enabled runs
    #: only): the full per-round knob/ledger trajectory
    #: (np.ndarray[R, len(CONTROL_FIELDS)]), the final row as a dict,
    #: and the extracted DECISIONS (rounds where the knob vector moved)
    control_rows: object = None
    control_final: Optional[dict] = None
    control_decisions: List[dict] = field(default_factory=list)
    #: the propagation observatory's device evidence (runs with
    #: ``collect_propagation``): ``{"rows": np[R, P], "coverage":
    #: np[R, M], "summary": PropagationSummary.to_dict(),
    #: "base_round": int}`` — per-round redundancy-ledger rows
    #: (obs/propagation.PROPAGATION_FIELDS order) and the per-sentinel
    #: coverage curve, fetched by the SAME end-of-run device_get as the
    #: telemetry rows (zero extra transfers)
    propagation: Optional[dict] = None
    #: the live device watchdog verdict (runs with
    #: ``collect_invariants``): ``obs/watchdog.summarize_invariants``
    #: over the in-scan invariant rows — per-field first violating
    #: round, overall first breach, violation counts, plus the raw
    #: ``"rows"`` (np[R, F], INVARIANT_FIELDS order).  Judged from scan
    #: output, NOT post-hoc: ``report`` (above) re-derives run-end
    #: invariants from the final state; this names WHEN each one first
    #: broke.  Fetched by the same end-of-run device_get.
    watchdog: Optional[dict] = None
    #: per-scan-chunk wall stamps ``(base_round, rounds, t0, t1)`` —
    #: the timeline exporter's piecewise round→wall-clock anchors
    #: (obs/timeline.PiecewiseAnchors).  Stamps bracket the DISPATCH of
    #: each chunk (no added barrier — the one-device_get-per-run
    #: discipline holds), so on an async backend t1 trails dispatch,
    #: and the FIRST chunk's window includes the phase-scan compile;
    #: later chunks reuse the executable and map tightly.
    scan_walls: List[tuple] = field(default_factory=list)


def run_device_plan(plan: FaultPlan, cfg: ClusterConfig,
                    key: Optional[jax.Array] = None,
                    state: Optional[ClusterState] = None,
                    events_per_phase: int = 2,
                    mesh=None, recorder=None,
                    collect_telemetry: bool = False,
                    collect_propagation: bool = False,
                    collect_invariants: bool = False
                    ) -> DeviceChaosResult:
    """Run ``plan`` against the flagship device cluster and check the
    invariants.  Injects ``events_per_phase`` fresh user events at the
    start of every phase (plus the settle window) so there is always
    knowledge whose post-heal convergence the checker can judge.

    ``mesh`` runs the whole plan on the SHARDED flagship round: the
    initial state is node-sharded (``parallel.mesh.shard_state``), every
    phase scan exchanges under the explicit ICI schedule, and the
    invariant checkers consume the sharded final state unchanged (they
    are reductions — jax gathers on device_get).

    ``recorder`` (a ``replay.recording.RunRecorder``) makes this run a
    replayable recording: every injection batch and every phase scan's
    key material is logged as a step, and the scans additionally emit
    the per-round membership-view digest stream
    (``replay.replayer.replay_device`` re-executes it bit-exactly)."""
    from serf_tpu.faults import invariants as inv
    from serf_tpu.models.dissemination import K_USER_EVENT

    plan.validate()
    sched = lower_plan(plan, cfg.n)
    key = key if key is not None else jax.random.key(plan.seed)
    if recorder is not None:
        from serf_tpu.replay.recording import (
            device_config_to_dict,
            key_to_hex,
            plan_to_dict,
        )
        if state is not None:
            raise ValueError("recording requires the executor to build "
                             "the initial state (state= unsupported)")
        recorder.header(
            plane="device", plan=plan_to_dict(plan), seed=plan.seed,
            config=device_config_to_dict(cfg))
    if state is None:
        key, k0 = jax.random.split(key)
        state = make_cluster(cfg, k0)
        if recorder is not None:
            recorder.step("init", key=key_to_hex(k0),
                          events_per_phase=events_per_phase,
                          mesh_devices=(mesh.size if mesh is not None
                                        else 1))
    if mesh is not None:
        from serf_tpu.parallel.mesh import shard_state
        state = shard_state(state, mesh)
    init_alive = state.gossip.alive
    run = phase_runner(cfg, mesh)
    if cfg.control.enabled:
        # seed the decision extraction with the BASE control row so the
        # first in-scan row (no actuation yet) is not a spurious
        # "decision"
        import numpy as np

        from serf_tpu.control.device import knob_bounds
        base, _, _, _ = knob_bounds(cfg.control, cfg.gossip, cfg.failure)
        _ctl_base_row = np.concatenate(
            [np.asarray(base, np.float32), np.zeros(2, np.float32)])
    else:
        _ctl_base_row = None

    injected: List[int] = []
    next_eid = 1
    want_ctl = cfg.control.enabled
    if collect_propagation:
        if events_per_phase < 1:
            raise ValueError("collect_propagation traces the first "
                             "injected event batch as sentinel facts; "
                             "events_per_phase must be >= 1")
        # sentinels = the FIRST phase's injected batch: inject() assigns
        # eids sequentially from 1, so the first min(events, k_facts)
        # facts of the run are the traced population (a batch past ring
        # capacity wraps — only the resident slice is traceable)
        n_sent = min(events_per_phase, cfg.gossip.k_facts)
        sentinels = jnp.arange(1, n_sent + 1, dtype=jnp.int32)
    else:
        sentinels = None

    def inject(st: ClusterState, origins_key, m: int) -> ClusterState:
        """Inject ``m`` facts, CHUNKED at ring capacity: a load phase may
        offer far more facts than the ring holds (that is the storm) —
        each chunk recycles the previous one's slots and the model's
        overflow accountant counts every in-window clobber.

        Under adaptive control every chunk passes the controller's
        per-round admission budget first (``control.gate_injections``):
        refusals land in the ``shed`` ledger instead of the ring.  The
        recording still carries the OFFERED batch — the replayer runs
        the same gate against the same deterministic control state, so
        admission decisions replay bit-exactly."""
        nonlocal next_eid
        if m <= 0:
            return st
        k = cfg.gossip.k_facts
        run_inject = _inject_runner(cfg, want_ctl)
        while m > 0:
            chunk = min(m, k)
            m -= chunk
            origins_key, k_chunk = jax.random.split(origins_key)
            eid_list = list(range(next_eid, next_eid + chunk))
            eids = jnp.asarray(eid_list, jnp.int32)
            injected.extend(eid_list)
            next_eid += chunk
            origins = jax.random.randint(k_chunk, (chunk,), 0, cfg.n,
                                         dtype=jnp.int32)
            if recorder is not None:
                # the recording carries the REALIZED batch (not the key
                # that derived it): the replayer consumes these values
                # verbatim, so a perturbed recording replays perturbed
                recorder.step(
                    "inject", kind=int(K_USER_EVENT),
                    eids=eid_list, ltimes=eid_list,
                    origins=[int(o) for o in jax.device_get(origins)])
            g, ctrl = run_inject(st.gossip, st.control, eids,
                                 eids.astype(jnp.uint32), origins,
                                 jnp.ones((chunk,), bool))
            st = st._replace(gossip=g, control=ctrl)
        return st

    #: telemetry chunks: (base_round, device rows f32[R, F]) per scan —
    #: transferred by ONE device_get after the whole plan ran (never a
    #: per-round, never even a per-phase transfer).  Control chunks
    #: follow the same discipline.
    tele_chunks: List[tuple] = []
    ctl_chunks: List[tuple] = []
    prop_chunks: List[tuple] = []
    invar_chunks: List[tuple] = []
    scan_walls: List[tuple] = []
    #: the coverage-monotonicity carry threaded ACROSS chunked scans (a
    #: device array — handing it to the next scan is an operand, not a
    #: transfer), so the watchdog's monotone predicate stays exact at
    #: chunk boundaries.  Seeded eagerly: a None->array operand switch
    #: between the first and second chunk would break the
    #: one-compiled-phase-scan discipline (different treedef).
    inv_cov = [(jnp.zeros((n_sent,), jnp.float32), jnp.float32(-1.0))
               if (collect_invariants and collect_propagation) else None]
    #: the previous scan's last control row (host side) — the recorder's
    #: decision extraction is incremental across scans
    ctl_prev = [_ctl_base_row]

    def scan(st: ClusterState, k_run, num_rounds: int, phase: int,
             group, drop, down, base_round: int) -> ClusterState:
        """One phase (or settle-chunk) scan; records the step + the
        per-round digest stream when a recorder is attached, and stacks
        the per-round telemetry/control rows when the run collects
        them."""
        want_dig = recorder is not None
        if (not want_dig and not collect_telemetry and not want_ctl
                and not collect_propagation and not collect_invariants):
            t0 = time.time()
            st = run(st, key=k_run, num_rounds=num_rounds, group=group,
                     drop=drop, init_alive=init_alive, down=down)
            scan_walls.append((base_round, num_rounds, t0, time.time()))
            return st
        if want_dig:
            from serf_tpu.replay.recording import record_scan_views
            recorder.step("scan", phase=phase, rounds=num_rounds,
                          key=key_to_hex(k_run))
            include_nodes = cfg.n <= _NODE_DIGEST_CAP()
        t0 = time.time()
        st, out = run(st, key=k_run, num_rounds=num_rounds,
                      group=group, drop=drop, init_alive=init_alive,
                      down=down, collect_digests=want_dig,
                      include_nodes=(include_nodes if want_dig else True),
                      collect_telemetry=collect_telemetry,
                      collect_control=want_ctl,
                      collect_propagation=collect_propagation,
                      sentinels=sentinels,
                      collect_invariants=collect_invariants,
                      inv_cov0=inv_cov[0])
        scan_walls.append((base_round, num_rounds, t0, time.time()))
        parts = list(out) if sum((want_dig, collect_telemetry,
                                  want_ctl, collect_propagation,
                                  collect_invariants)) > 1 \
            else [out]
        dg = dn = rows = crows = prows = irows = None
        if want_dig:
            dg, dn = parts.pop(0)
        if collect_telemetry:
            rows = parts.pop(0)
        if want_ctl:
            crows = parts.pop(0)
        if collect_propagation:
            prows = parts.pop(0)
        if collect_invariants:
            ientry = parts.pop(0)
            if collect_propagation:
                irows, inv_cov[0] = ientry
            else:
                irows = ientry
        if want_dig:
            record_scan_views(recorder, base_round, dg, dn, include_nodes)
        if crows is not None:
            if want_dig:
                # a recorded controlled run interleaves its control
                # DECISIONS with the view stream per scan — the replayer
                # emits the same steps from its own re-derived rows
                # (replay.recording.record_scan_controls is the ONE
                # shared formatting path)
                from serf_tpu.replay.recording import record_scan_controls
                ctl_prev[0] = record_scan_controls(
                    recorder, base_round, jax.device_get(crows),
                    ctl_prev[0])
            ctl_chunks.append((base_round, crows))
        if rows is not None:
            tele_chunks.append((base_round, rows))
        if prows is not None:
            prop_chunks.append((base_round, prows))
        if irows is not None:
            invar_chunks.append((base_round, irows))
        return st

    total = 0
    # a phase burst past ring capacity MUST clobber in-window facts —
    # the checker then requires a nonzero overflow ledger (a tautology
    # guard: a regression zeroing the accountant must fail the run)
    expect_overflow = any(
        events_per_phase + (sched.events[pi] if pi < len(sched.events)
                            else 0) > cfg.gossip.k_facts
        for pi in range(len(sched.rounds)))
    no_group = jnp.zeros((cfg.n,), jnp.int32)
    no_down = jnp.zeros((cfg.n,), bool)
    for pi, num_rounds in enumerate(sched.rounds):
        key, k_inj, k_run = jax.random.split(key, 3)
        extra = sched.events[pi] if pi < len(sched.events) else 0
        # inject BEFORE the rounds check: a phase authored with only a
        # host wall duration (rounds=0) still lowered load — its facts
        # must land in the ring (and the overflow ledger), not vanish
        state = inject(state, k_inj, events_per_phase + extra)
        if num_rounds <= 0:
            continue
        state = scan(state, k_run, num_rounds, pi, sched.group[pi],
                     sched.drop[pi], sched.down[pi], total)
        total += num_rounds
    # settle: fault-free rounds for re-convergence (drop 0, no partition,
    # everyone the plan restarted is back up).  Chunked to the phases'
    # common round count when possible so the whole run reuses ONE
    # compiled phase scan (the named plans are authored for this).
    if plan.settle_rounds > 0:
        lens = {r for r in sched.rounds if r > 0}
        if len(lens) == 1 and plan.settle_rounds % next(iter(lens)) == 0:
            chunk = next(iter(lens))
        else:
            chunk = plan.settle_rounds
        key, k_inj, _ = jax.random.split(key, 3)
        state = inject(state, k_inj, events_per_phase)
        left = plan.settle_rounds
        while left > 0:
            step = min(chunk, left)
            key, k_run = jax.random.split(key)
            state = scan(state, k_run, step, -1, no_group,
                         jnp.float32(0.0), no_down, total)
            total += step
            left -= step

    if recorder is not None:
        recorder.finish()
    stretch_q = None
    control_rows = None
    control_final = None
    control_decisions: List[dict] = []
    if ctl_chunks:
        # the control trajectory rides the same single end-of-run
        # transfer as the telemetry rows
        import numpy as np

        from serf_tpu.control.device import (
            CONTROL_FIELDS,
            decisions_of,
            emit_control_metrics,
        )
        host_ctl = jax.device_get([rows for _, rows in ctl_chunks])
        control_rows = np.concatenate([np.asarray(r) for r in host_ctl])
        control_final = dict(zip(
            CONTROL_FIELDS, (float(v) for v in control_rows[-1])))
        prev = _ctl_base_row
        for (base, _), rows in zip(ctl_chunks, host_ctl):
            decs, prev = decisions_of(prev, rows, base)
            control_decisions.extend(decs)
        from serf_tpu.obs import flight
        for d in control_decisions:
            flight.record("control-decision", plane="device",
                          round=d["round"], knobs=d["knobs"],
                          shed=d["shed"])
        emit_control_metrics(control_rows[-1], {"plane": "device"})
        stretch_q = int(control_final["stretch_q"])
    report = inv.check_device(plan, state, cfg, init_alive,
                              rounds_run=total, offered=len(injected),
                              expect_overflow=expect_overflow,
                              stretch_q=stretch_q)
    if control_rows is not None:
        from serf_tpu.control.device import knob_bounds
        inv.check_control_device(report, control_rows, cfg.control,
                                 knob_bounds(cfg.control, cfg.gossip,
                                             cfg.failure))
    ledger = jax.device_get({"dropped": state.gossip.overflow,
                             "offered": state.gossip.injected})
    telemetry = None
    telemetry_final = None
    propagation = None
    watchdog = None
    if tele_chunks or prop_chunks or invar_chunks:
        # THE one telemetry transfer of the run: every scan's stacked
        # telemetry, propagation AND watchdog-invariant rows come back
        # in a single device_get (never a per-round, never even a
        # per-phase transfer — the riders come for free), then land in
        # the ring format keyed by declared metric names
        host_rows, host_prop, host_inv = jax.device_get(
            ([rows for _, rows in tele_chunks],
             [p for _, p in prop_chunks],
             [r for _, r in invar_chunks]))
        if tele_chunks:
            from serf_tpu.models.swim import TELEMETRY_FIELDS
            from serf_tpu.obs.timeseries import telemetry_to_store
            for (base, _), rows in zip(tele_chunks, host_rows):
                telemetry = telemetry_to_store(rows, base_round=base,
                                               store=telemetry)
            telemetry_final = dict(zip(
                TELEMETRY_FIELDS, (float(v) for v in host_rows[-1][-1])))
        if prop_chunks:
            import numpy as np

            from serf_tpu.obs import flight
            from serf_tpu.obs.propagation import (
                emit_propagation_metrics,
                propagation_to_store,
                summarize_propagation,
            )
            for (base, _), (prow, _) in zip(prop_chunks, host_prop):
                telemetry = propagation_to_store(prow, base_round=base,
                                                 store=telemetry)
            all_rows = np.concatenate([np.asarray(p) for p, _ in host_prop])
            all_cov = np.concatenate([np.asarray(c) for _, c in host_prop])
            summary = summarize_propagation(all_rows, all_cov)
            emit_propagation_metrics(summary, {"plane": "device"})
            flight.record(
                "propagation-trace", plane="device",
                sentinels=int(summary.sentinels),
                rounds=int(summary.rounds),
                t99=summary.time_to.get(99),
                redundancy=round(float(summary.redundancy), 4),
                final_coverage=round(float(summary.final_coverage), 4))
            propagation = {"rows": all_rows, "coverage": all_cov,
                           "summary": summary.to_dict(),
                           "base_round": prop_chunks[0][0]}
        if invar_chunks:
            import numpy as np

            from serf_tpu.obs import watchdog as wd
            all_inv = np.concatenate([np.asarray(r) for r in host_inv])
            # the LIVE verdict: first violating round named from scan
            # output (the post-hoc `report` above never sees per-round
            # evidence) — breach lands a watchdog-breach flight event
            watchdog = wd.summarize_invariants(
                all_inv, base_round=invar_chunks[0][0])
            watchdog["rows"] = all_inv
            wd.emit_device_watchdog(watchdog)
    return DeviceChaosResult(plan=plan, schedule=sched, state=state,
                             report=report, rounds_run=total,
                             notes=sched.notes, injected=injected,
                             offered=int(ledger["offered"]),
                             dropped=int(ledger["dropped"]),
                             telemetry=telemetry,
                             telemetry_final=telemetry_final,
                             control_rows=control_rows,
                             control_final=control_final,
                             control_decisions=control_decisions,
                             propagation=propagation,
                             watchdog=watchdog,
                             scan_walls=scan_walls)
