"""Unified chaos plane: seeded fault schedules across host + device.

One declarative, seeded :class:`FaultPlan` (``faults.plan``) drives both
planes — the host executor (``faults.host``) compiles phases to
transport-level :class:`~serf_tpu.host.transport.ChaosRule` objects and
runs loopback clusters through them; the device executor
(``faults.device``) lowers the same plan to per-round partition/loss/
liveness masks consumed inside the jitted scan.  ``faults.invariants``
judges convergence, false-death, clock-monotonicity and crash-restart
correctness afterwards.  ``tools/chaos.py`` is the operator CLI.
"""

from serf_tpu.faults.plan import (  # noqa: F401
    EdgeFault,
    FaultPhase,
    FaultPlan,
    named_plan,
    plan_names,
)
from serf_tpu.faults.invariants import (  # noqa: F401
    InvariantReport,
    InvariantResult,
)
from serf_tpu.faults.host import HostLoadReport  # noqa: F401
