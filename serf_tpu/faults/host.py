"""Host-plane fault executor: FaultPlan -> LoopbackNetwork / transports.

Two entry points:

- :class:`HostFaultExecutor` compiles :class:`~serf_tpu.faults.plan
  .FaultPlan` phases into :class:`serf_tpu.host.transport.ChaosRule`
  objects and installs them on a ``LoopbackNetwork`` (the one fault API
  the legacy ``partition``/``set_drop_rate`` knobs also delegate to).
  For clusters on REAL transports (net/dstream), ``wrap_transport``
  injects the same phase faults at the sender seam — drop, blocked
  edges/partitions, corruption — which is how the transport-storm tests
  drive TCP/TLS/udpstream clusters from a plan.

- :func:`run_host_plan` stands up an in-process loopback cluster, runs
  the plan end to end (crash = Serf shutdown, restart = re-create on the
  OLD address with the node's snapshot), keeps background traffic
  flowing, samples Lamport clocks throughout, then heals, waits the
  settle budget, and hands everything to the invariant checker.
"""

from __future__ import annotations

import asyncio
import hashlib
import random
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from serf_tpu.faults.plan import FaultPhase, FaultPlan
from serf_tpu.host.transport import (
    ChaosRule,
    EdgeRates,
    LoopbackNetwork,
    apply_edge_faults,
)
from serf_tpu.obs import flight
from serf_tpu.utils import metrics
from serf_tpu.utils.logging import get_logger
from serf_tpu.utils.tasks import spawn_logged

log = get_logger("faults")


def compile_phase(phase: FaultPhase, addr_of) -> ChaosRule:
    """Lower one plan phase to a transport-level chaos rule.
    ``addr_of(i)`` maps plan node indices to transport addresses."""
    groups: Optional[List[set]] = None
    if phase.partitions:
        groups = [set(addr_of(i) for i in g) for g in phase.partitions]
        listed = set().union(*groups) if groups else set()
        # unlisted nodes form one implicit extra group (plan semantics,
        # identical on the device plane)
        rest = {addr_of(i) for i in range(_plan_n(addr_of))} - listed
        if rest:
            groups.append(rest)
    edges: Dict[Tuple[object, object], EdgeRates] = {}
    for e in phase.edges:
        rates = EdgeRates(drop=e.drop, delay=e.delay, duplicate=e.duplicate,
                          reorder=e.reorder, corrupt=e.corrupt)
        edges[(addr_of(e.src), addr_of(e.dst))] = rates
        if e.bidirectional:
            edges[(addr_of(e.dst), addr_of(e.src))] = rates
    return ChaosRule(
        groups=groups,
        drop=phase.drop,
        delay=phase.delay,
        jitter=phase.jitter,
        duplicate=phase.duplicate,
        reorder=phase.reorder,
        corrupt=phase.corrupt,
        edges=edges,
    )


def _plan_n(addr_of) -> int:
    n = getattr(addr_of, "plan_n", None)
    if n is None:
        raise ValueError("addr_of must carry a .plan_n attribute "
                         "(use HostFaultExecutor or make_addr_of)")
    return n


def make_addr_of(n: int, mapping=None):
    """Index -> address mapper for ``compile_phase``.  Default address
    scheme is ``"n{i}"`` (the loopback runner's node names)."""
    def addr_of(i: int):
        return mapping[i] if mapping is not None else f"n{i}"
    addr_of.plan_n = n
    return addr_of


class HostFaultExecutor:
    """Drives a plan's phases against a ``LoopbackNetwork`` (and any
    wrapped real transports registered via :meth:`wrap_transport`)."""

    def __init__(self, plan: FaultPlan, net: Optional[LoopbackNetwork] = None,
                 mapping: Optional[Dict[int, object]] = None):
        plan.validate()
        self.plan = plan
        self.net = net
        self.addr_of = make_addr_of(plan.n, mapping)
        self.rng = random.Random(plan.seed)
        self.phase_index: Optional[int] = None
        self._down: set = set()          # node indices currently down
        self._paused: set = set()
        self._wrapped: List[object] = []

    # -- phase stepping ------------------------------------------------------

    def apply_phase(self, index: int) -> FaultPhase:
        """Install phase ``index``'s faults (and update the down/pause
        bookkeeping).  Crash/restart of real processes is the caller's
        job (run_host_plan does it); pause is enforced at the network."""
        phase = self.plan.phases[index]
        self._down |= set(phase.crash)
        self._paused |= set(phase.pause)
        self._down -= set(phase.restart)
        self._paused -= set(phase.restart)
        rule = compile_phase(phase, self.addr_of)
        rule.paused = frozenset(self.addr_of(i) for i in self._paused)
        self._install(rule)
        self.phase_index = index
        metrics.gauge("serf.faults.phase", index)
        flight.record("fault-phase", plan=self.plan.name, phase=index,
                      name=phase.name)
        return phase

    def clear(self) -> None:
        """Heal everything (end of plan): no partitions, no rates; nodes
        the plan left paused stay paused only if never restarted — the
        plan validator forbids that, so clear really is clear."""
        self._install(None)
        self.phase_index = None
        metrics.gauge("serf.faults.phase", -1)
        flight.record("fault-phase", plan=self.plan.name, phase=-1,
                      name="healed")

    def _install(self, rule: Optional[ChaosRule]) -> None:
        if self.net is not None:
            if rule is not None:
                self.net.rng = random.Random(
                    self.rng.randrange(1 << 30))
            self.net.apply_faults(rule)
        for t in self._wrapped:
            t._chaos_rule = rule

    def down_nodes(self) -> frozenset:
        return frozenset(self._down | self._paused)

    # -- real-transport seam -------------------------------------------------

    def wrap_transport(self, transport, node_index: int, addr_key=None):
        """Sender-side fault injection for a REAL transport against the
        CURRENT phase rule (see :func:`attach_transport_chaos`).
        ``addr_key(addr) -> plan address`` normalizes destination
        addresses to the plan's node addresses (default: identity)."""
        attach_transport_chaos(
            transport, self.addr_of(node_index), addr_key=addr_key,
            rng=random.Random(self.rng.randrange(1 << 30)))
        if transport not in self._wrapped:
            self._wrapped.append(transport)
        return transport


def attach_transport_chaos(transport, src, addr_key=None,
                           rng: Optional[random.Random] = None):
    """Idempotently wrap a REAL transport's sender seam with chaos-rule
    enforcement: ``send_packet`` (and dstream's segment-level
    ``_sendto``) gets probabilistic drop / bit-flip corruption plus
    partition/blackhole blocking, ``dial`` refuses partitioned or
    blackholed destinations.  The active rule lives in
    ``transport._chaos_rule`` (a :class:`ChaosRule` or None) — swap it
    per phase; the legacy storm helpers and ``HostFaultExecutor`` both
    drive this one seam."""
    if getattr(transport, "_chaos_wrapped", False):
        return transport
    transport._chaos_wrapped = True
    transport._chaos_rule = None
    keyfn = addr_key or (lambda a: a)
    rng = rng or random.Random(0)

    orig_send_packet = transport.send_packet
    orig_dial = transport.dial

    async def send_packet(addr, buf):
        rule: Optional[ChaosRule] = transport._chaos_rule
        if rule is not None:
            buf = apply_edge_faults(rule, rng, src, keyfn(addr), buf)
            if buf is None:
                return
        await orig_send_packet(addr, buf)

    async def dial(addr, timeout=None):
        rule: Optional[ChaosRule] = transport._chaos_rule
        if rule is not None:
            dst = keyfn(addr)
            if rule.group_blocked(src, dst) or rule.blackholed(src, dst):
                raise ConnectionError(
                    f"chaos: no route to {addr!r} (partition)")
        return await orig_dial(addr, timeout=timeout)

    transport.send_packet = send_packet
    transport.dial = dial
    # dstream sends segments through _sendto, not send_packet — fault
    # the segment plane too (same shared decision: drop AND corruption,
    # so the ARQ + keyring recovery paths see chaos under cluster load)
    orig_sendto = getattr(transport, "_sendto", None)
    if orig_sendto is not None:
        def _sendto(wire, addr):
            rule: Optional[ChaosRule] = transport._chaos_rule
            if rule is not None:
                wire = apply_edge_faults(rule, rng, src, keyfn(addr), wire)
                if wire is None:
                    return
            orig_sendto(wire, addr)
        transport._sendto = _sendto
    return transport


# ---------------------------------------------------------------------------
# loopback chaos runner
# ---------------------------------------------------------------------------


@dataclass
class ClockSample:
    mono: float
    generation: int
    clock: int
    event: int
    query: int


@dataclass
class HostLoadReport:
    """Overload accounting for one host chaos run (ISSUE 5).

    Offered counts are the runner's ground truth (every ``user_event``/
    ``query`` call it made); admitted/shed are the ENGINE's own
    ``serf.overload.ingress_*`` counter deltas — the accounting
    invariant (admitted + shed == offered) therefore cross-checks the
    engine's bookkeeping against an independent tally, not against
    itself.  Buffer maxima are sampled throughout the run, bounds are
    the configured limits they must stay under."""

    events_offered: int = 0
    queries_offered: int = 0
    ingress_admitted: int = 0
    ingress_shed: int = 0
    #: per-queue sampled byte maxima vs per-queue configured budgets —
    #: each queue is judged against ITS OWN bound (collapsing to one max
    #: would let a small-budget queue regress unseen under a large one)
    max_queue_bytes_by: Dict[str, int] = field(default_factory=dict)
    queue_bounds_by: Dict[str, int] = field(default_factory=dict)
    max_query_responses: int = 0
    query_responses_bound: int = 0
    max_event_inbox: int = 0
    event_inbox_bound: int = 0
    lossless_violations: int = 0
    quiet_convergence_s: float = 0.0
    settle_convergence_s: float = 0.0

    def to_dict(self) -> Dict[str, float]:
        return dict(self.__dict__)


@dataclass
class HostChaosResult:
    plan: FaultPlan
    report: object                      # invariants.InvariantReport
    clock_samples: Dict[str, List[ClockSample]] = field(default_factory=dict)
    counters: Dict[str, float] = field(default_factory=dict)
    events_sent: int = 0
    load: Optional[HostLoadReport] = None
    #: ring time series sampled throughout the run (obs.timeseries
    #: MetricsSampler on the traffic tick): counter deltas, gauge
    #: levels, flight-kind rates — the SLO judge's burn-rate evidence
    series: object = None
    #: adaptive-control evidence (controller=True runs): the
    #: ControllerTick's decision log / final values
    #: (``control.host.ControllerTick.to_dict``)
    control: Optional[Dict] = None
    #: convergence measurements every run carries (load or not): quiet
    #: join-convergence and post-heal settle, plus whether settle
    #: actually converged (the poll can time out at the deadline)
    quiet_convergence_s: float = 0.0
    settle_convergence_s: float = 0.0
    settle_converged: bool = True
    #: responsive-node false-DEAD count at judgment time (nodes the plan
    #: never downed, held FAILED in some live view) — the SLO plane's
    #: host-side false-dead evidence
    false_dead: int = 0
    #: message-lifecycle ledger snapshot for the run
    #: (``obs.lifecycle.LifecycleLedger.snapshot()``): per-stage latency
    #: decomposition, attribution, slow-message count — the evidence the
    #: stage-latency SLO rows are judged from
    lifecycle: Optional[Dict] = None
    #: propagation-observatory evidence (every run): a traced probe
    #: user_event fired after the settle barrier, polled to coverage
    #: across live nodes, plus the run's cumulative ledger fold —
    #: ``{"coverage", "time_to_all_ms", "reached", "nodes", "seen",
    #: "duplicates", "rebroadcasts", "dup_ratio", "trace"}``
    propagation: Optional[Dict] = None
    #: live watchdog verdict (``obs.watchdog.Watchdog.state()``): the
    #: run's continuous verification record — tick count, armed
    #: invariants/SLO watches, the FIRST breach (named by tick, judged
    #: as it happened, not reconstructed), and every black-box bundle
    #: written.  None when the run was launched with ``watchdog=False``.
    watchdog: Optional[Dict] = None
    #: key-rotation evidence (``plan.encrypted`` runs only): the per-op
    #: rows the phase driver issued, the post-heal message-loss probes,
    #: the reconcile verdict (converged? how long?), decrypt
    #: fallback/fail counter deltas, and every live node's keyring
    #: digest — what the keyring-divergence / no-message-loss
    #: invariants and the rotation-latency SLO row judge
    rotation: Optional[Dict] = None


async def measure_propagation(live, deadline_s: float = 5.0) -> Dict:
    """Host-plane dissemination probe: fire ONE traced user_event from a
    live node, then poll every live node's propagation ledger
    (``obs.propagation.PropagationLedger``) until the probe's trace id
    has been first-seen everywhere (or the deadline passes).  Returns
    the coverage verdict plus the run's cumulative cluster ledger fold
    — the evidence the host-side coverage-settle / redundancy-ceiling
    SLO rows are judged from — and emits the ``serf.propagation.*``
    gauges and a ``propagation-trace`` flight event."""
    from serf_tpu.obs.propagation import fold_propagation

    out: Dict = {"coverage": 0.0, "time_to_all_ms": None, "reached": 0,
                 "nodes": len(live), "seen": 0, "duplicates": 0,
                 "rebroadcasts": 0, "dup_ratio": 0.0, "trace": None}
    if not live:
        return out
    origin = live[0]
    t0 = time.monotonic()
    try:
        await origin.user_event("prop-probe", b"", coalesce=False)
    except Exception:  # noqa: BLE001 — admission shed / teardown race:
        return out     # no probe this run, the fold below still reports
    trace_hex = next(reversed(origin.prop_ledger._recent), None)
    out["trace"] = trace_hex
    reached = 0
    if trace_hex is not None:
        while True:
            reached = sum(
                1 for s in live
                if s.prop_ledger.first_seen(trace_hex) is not None)
            if reached >= len(live):
                out["time_to_all_ms"] = round(
                    (time.monotonic() - t0) * 1e3, 1)
                break
            if time.monotonic() - t0 > deadline_s:
                break
            await asyncio.sleep(0.02)
    out["reached"] = reached
    out["coverage"] = reached / len(live)
    fold = fold_propagation(
        {s.local_id: s.prop_ledger.summary() for s in live})
    out.update(seen=fold["seen"], duplicates=fold["duplicates"],
               rebroadcasts=fold["rebroadcasts"],
               dup_ratio=fold["dup_ratio"])
    metrics.gauge("serf.propagation.coverage", out["coverage"])
    if out["time_to_all_ms"] is not None:
        metrics.gauge("serf.propagation.time-to-all-ms",
                      out["time_to_all_ms"])
    metrics.gauge("serf.propagation.dup-ratio", out["dup_ratio"])
    flight.record("propagation-trace", plane="host", trace=trace_hex,
                  coverage=round(out["coverage"], 4),
                  time_to_all_ms=out["time_to_all_ms"],
                  reached=reached, nodes=len(live),
                  dup_ratio=round(out["dup_ratio"], 4))
    return out


async def _rotation_finale(plan, nodes, live, live_indices, rotation_ops,
                           rot_base: bytes, rot_next: bytes,
                           base_fallback: float, base_fail: float) -> Dict:
    """Post-heal rotation evidence for an encrypted plan.

    Three acts, in an order that matters: (1) message-loss probes fire
    BEFORE reconciling, so they cross whatever primary-key split the
    chaos left behind — delivery then proves decrypt fallback carried
    the cluster, not that the keys already matched; (2) a bounded
    reconcile loop re-issues use(next)/remove(base) until every live
    ring reports the next key as its sole primary (the convergence half
    of the keyring-divergence invariant); (3) every live node's keyring
    digest is read for the divergence comparison and red-run forensics.
    """
    from serf_tpu.host.admission import OverloadError
    from serf_tpu.host.keyring import key_digest

    deadline = max(2.0, plan.settle_s)
    # (1) one traced user_event per live node, polled to full coverage.
    # A storm plan leaves the admission buckets drained, so each probe
    # retries through OverloadError until its node's bucket refills —
    # shed probes would prove admission control, not message loss.
    traces: Dict[str, str] = {}
    offered = 0
    for s in live:
        offered += 1
        probe_end = time.monotonic() + min(3.0, deadline)
        sent = False
        while time.monotonic() < probe_end:
            try:
                await s.user_event(f"rot-probe-{s.local_id}", b"",
                                   coalesce=False)
                sent = True
                break
            except OverloadError:
                await asyncio.sleep(0.1)
            except Exception:  # noqa: BLE001 — an unsent probe counts
                break          # against delivered, which is the point
        if not sent:
            continue
        th = next(reversed(s.prop_ledger._recent), None)
        if th is not None:
            traces[s.local_id] = th
    t0 = time.monotonic()
    delivered = 0
    while time.monotonic() - t0 <= deadline:
        delivered = sum(
            1 for th in traces.values()
            if all(s.prop_ledger.first_seen(th) is not None for s in live))
        if delivered >= len(traces):
            break
        await asyncio.sleep(0.02)
    probes = {"offered": offered, "sent": len(traces),
              "delivered": delivered, "nodes": len(live),
              "probe_s": round(time.monotonic() - t0, 3)}
    # (2) reconcile: use(next) first (a node still on the base primary
    # would refuse the remove), then remove(base), then verify via
    # list_keys — every op is itself retried by the KeyManager
    km = nodes[min(live_indices())].key_manager()
    t1 = time.monotonic()
    converged = False
    rounds = 0
    while time.monotonic() - t1 <= deadline:
        rounds += 1
        try:
            await km.use_key(rot_next)
            await km.remove_key(rot_base)
            lk = await km.list_keys()
        except Exception:  # noqa: BLE001 — transient mid-heal failures
            await asyncio.sleep(0.1)
            continue
        want = len(live)
        if (lk.num_resp >= want
                and lk.primary_keys.get(rot_next, 0) >= want
                and rot_base not in lk.keys):
            converged = True
            break
        await asyncio.sleep(0.1)
    reconcile_s = round(time.monotonic() - t1, 3)
    metrics.gauge("serf.rotation.reconcile-s", reconcile_s)
    # (3) non-secret ring digests, straight off each live node
    keyrings = {}
    for s in live:
        ring = s.memberlist.keyring()
        if ring is not None:
            keyrings[s.local_id] = ring.digest()
    out = {
        "ops": rotation_ops,
        "probes": probes,
        "converged": converged,
        "reconcile_s": reconcile_s,
        "reconcile_rounds": rounds,
        "latency_s": reconcile_s,
        "expected_primary": key_digest(rot_next),
        "decrypt_fallback": int(
            _counter_total("serf.keyring.decrypt_fallback") - base_fallback),
        "decrypt_fail": int(
            _counter_total("serf.keyring.decrypt_fail") - base_fail),
        "keyrings": keyrings,
    }
    flight.record("key-rotation", op="finale", plan=plan.name,
                  converged=converged, reconcile_s=reconcile_s,
                  probes_delivered=delivered, probes_offered=offered,
                  decrypt_fallback=out["decrypt_fallback"],
                  decrypt_fail=out["decrypt_fail"])
    return out


def degradation_counters() -> Dict[str, float]:
    """Sum every ``serf.faults.*`` / ``serf.degraded.*`` /
    ``serf.overload.*`` counter in the global sink across label sets —
    the CLI's degradation + shedding report."""
    sink = metrics.global_sink()
    out: Dict[str, float] = {}
    for (name, _labels), v in sink.counters.items():
        if name.startswith(("serf.faults.", "serf.degraded.",
                            "serf.overload.", "serf.proc.")):
            out[name] = out.get(name, 0.0) + v
    return out


def _counter_total(name: str) -> float:
    """Sum one counter across every label set in the global sink."""
    sink = metrics.global_sink()
    return sum(v for (n, _l), v in sink.counters.items() if n == name)


def rotation_keys(seed: int) -> Tuple[bytes, bytes]:
    """Deterministic ``(base, next)`` 32-byte rotation keys for a plan
    seed: every executor (host loopback, proc agents, bench, the chaos
    CLI) derives the SAME pair, so cross-plane runs of one rotate-*
    plan move through identical keyrings and their digests compare."""
    base = hashlib.sha256(f"serf-rot-base-{seed}".encode()).digest()
    nxt = hashlib.sha256(f"serf-rot-next-{seed}".encode()).digest()
    return base, nxt


def _load_opts(plan: FaultPlan):
    """Default Options for a load-bearing plan: admission buckets sized
    well under the peak offered rate (so a storm MUST shed), and tight
    buffer bounds (so the bounded-buffers invariant exercises real
    pressure, not headroom).  Buckets are PER NODE while the plan's
    rates are cluster-aggregate spread over random origins — divide by
    n, or no single node ever sees enough load to shed."""
    from serf_tpu.options import Options

    per_node = plan.offered_rate() / max(1, plan.n)
    return Options.local(
        user_event_rate=max(4.0, 0.08 * per_node),
        user_event_burst=8,
        query_rate=max(3.0, 0.05 * per_node),
        query_burst=4,
        max_query_responses=64,
        event_queue_bytes=256 * 1024,
        query_queue_bytes=128 * 1024,
        event_inbox_max=2048,
    )


async def run_host_plan(plan: FaultPlan, tmp_dir: Optional[str] = None,
                        opts=None,
                        traffic_period: float = 0.08,
                        recorder=None,
                        controller: bool = False,
                        control_cfg=None,
                        lifecycle_sample_n: int = 4,
                        lifecycle_slow_ms: float = 50.0,
                        watchdog: bool = True,
                        watchdog_cfg=None
                        ) -> HostChaosResult:
    """Run ``plan`` against a fresh in-process loopback cluster and check
    the invariants.  ``tmp_dir`` enables per-node snapshots (crash →
    restart replays them); without it restarts come back cold.

    Plans with LOAD phases (event/query rates, stalls) additionally get:
    per-node subscribers with stallable consumers, a load generator
    firing the offered rates from random live nodes, buffer-bound
    sampling every tick, and a :class:`HostLoadReport` the overload
    invariants are judged against.

    ``recorder`` (a ``replay.recording.RunRecorder``) captures the run's
    full ingress — joins, every offered user_event/query (via the
    ``Serf.set_ingress_tap`` seam), phase/restart/heal transitions — plus
    a membership-view digest at each convergence barrier, so
    ``replay.replayer.replay_host`` can re-drive the same run from the
    recording with virtualized timing.

    Every run installs a fresh message-lifecycle ledger
    (``obs.lifecycle``, hotter 1-in-``lifecycle_sample_n`` sampling than
    the production default, slow threshold ``lifecycle_slow_ms``) for
    its duration and stashes the snapshot on
    ``HostChaosResult.lifecycle`` — the per-stage latency evidence the
    ``apply-stage-p99`` / ``queue-wait-share`` SLO rows judge.

    ``watchdog`` (default ON — the always-on contract) attaches the
    continuous verifier (``obs.watchdog.Watchdog``): one watchdog tick
    per sampler tick evaluates the live invariants (clock monotonicity,
    shed accounting, bounded buffers, health floor), the shed-ratio SLO
    burn, and the ``spawn_logged`` failure-hook seam; a breach triggers
    a black-box dump (``obs.blackbox``) on every node — bundles land
    under ``tmp_dir/blackbox`` (verdicts-only when ``tmp_dir`` is None:
    forensics need a disk home) and the verdict rides
    ``HostChaosResult.watchdog``.

    ``controller`` attaches the adaptive control plane
    (``control.host.ControllerTick``, config via ``control_cfg``): one
    controller tick per sampler tick reads the burn-rate evidence and
    actuates the admission buckets, breaker cooldown and probe/gossip/
    suspicion knobs on every live node.  Decisions ride the recording
    as ``control`` steps and the report grows the ``control-stability``
    invariant."""
    import os

    from serf_tpu.faults import invariants as inv
    from serf_tpu.host.admission import OverloadError
    from serf_tpu.host.events import EventSubscriber
    from serf_tpu.host.serf import Serf, SerfState
    from serf_tpu.options import Options

    plan.validate()
    n = plan.n
    with_load = plan.has_load()
    base_opts = opts or (_load_opts(plan) if with_load else Options.local())
    # encrypted plans: every node boots on the SAME deterministic base
    # key; phases rotate to the next key via KeyManager ops.  With a
    # tmp_dir each node also persists its ring, so a crash-restart
    # resumes from the snapshotted keyring (the crash-recovery proof).
    rot_base = rot_next = None
    rotation_ops: List[Dict] = []
    if plan.encrypted:
        rot_base, rot_next = rotation_keys(plan.seed)
    if recorder is not None:
        from serf_tpu.replay.recording import plan_to_dict
        recorder.header(
            plane="host", plan=plan_to_dict(plan), seed=plan.seed,
            # opts must be reconstructible on replay: None means "the
            # executor defaults" (Options.local / _load_opts per plan);
            # anything else is marked custom and the replayer refuses
            config={"options": "default" if opts is None else "custom",
                    "snapshots": tmp_dir is not None, "n": n})
    ingress_tap = recorder.ingress_tap() if recorder is not None else None
    barrier_index = 0

    def record_barrier(stage: str, serfs) -> None:
        nonlocal barrier_index
        if recorder is None:
            return
        from serf_tpu.replay.digest import host_view_digest
        recorder.step("barrier", stage=stage, deadline_s=plan.settle_s)
        digest, node_digests = host_view_digest(serfs)
        recorder.view(round_=barrier_index, digest=digest,
                      nodes=node_digests)
        barrier_index += 1

    net = LoopbackNetwork()
    ex = HostFaultExecutor(plan, net)

    def node_opts(i: int):
        if tmp_dir is None:
            return base_opts
        o = base_opts.replace(
            snapshot_path=os.path.join(tmp_dir, f"chaos-n{i}.snap"))
        if plan.encrypted:
            # keyring mutations persist through the internal-query
            # handlers' atomic save — a restart below reloads this file
            o = o.replace(keyring_file=os.path.join(
                tmp_dir, f"chaos-n{i}.keyring"))
        return o

    generation = {i: 0 for i in range(n)}
    nodes: Dict[int, Serf] = {}
    consumers: Dict[int, asyncio.Task] = {}
    gates: Dict[int, asyncio.Event] = {}

    async def consume(sub: EventSubscriber, gate: asyncio.Event) -> None:
        # a stalled gate models the wedged consumer: the subscriber queue
        # fills, drop-oldest fires (counted), and the engine's bounded
        # tee/inbox absorb the rest — memory must stay bounded throughout.
        # Deliberately try_next + sleep, NOT next(timeout=...): with a
        # backlogged queue (e.g. an admission-widened storm) wait_for's
        # inner get() completes instantly every iteration, and py3.10's
        # wait_for swallows a cancellation that lands in that window —
        # the executor's one-shot task.cancel() would be eaten and
        # teardown would hang on a task that never dies
        while True:
            await gate.wait()
            if sub.try_next() is None:
                await asyncio.sleep(0.05)
            else:
                await asyncio.sleep(0)   # cancellation point per drain

    async def make_node(i: int) -> Serf:
        sub = None
        if with_load:
            sub = EventSubscriber(maxsize=512)
            gate = gates.setdefault(i, asyncio.Event())
            gate.set()
            old = consumers.pop(i, None)
            if old is not None:
                old.cancel()
            consumers[i] = spawn_logged(consume(sub, gate),
                                        f"chaos-consume-n{i}")
        ring = None
        if plan.encrypted:
            from serf_tpu.host.keyring import SecretKeyring
            kf = node_opts(i).keyring_file
            if kf and os.path.exists(kf):
                # restart path: resume from the snapshotted keyring —
                # a node killed mid-rotation comes back with whatever
                # key state it had persisted and must catch up
                ring = SecretKeyring.load(kf)
            else:
                ring = SecretKeyring(rot_base)
                if kf:
                    ring.save(kf)
        s = await Serf.create(net.bind(f"n{i}"), node_opts(i), f"n{i}",
                              subscriber=sub, keyring=ring)
        if ingress_tap is not None:
            s.set_ingress_tap(ingress_tap)
        if wd is not None:
            s.watchdog = wd
            if blackbox_dir is not None:
                s.blackbox = _box_for(i)
        return s

    base_admitted = _counter_total("serf.overload.ingress_admitted")
    base_shed = _counter_total("serf.overload.ingress_shed")
    base_lossless = _counter_total("serf.subscriber.lossless_violation")
    base_fallback = _counter_total("serf.keyring.decrypt_fallback")
    base_fail = _counter_total("serf.keyring.decrypt_fail")

    # continuous telemetry: one sampler tick per traffic tick lands
    # counter deltas / gauge levels / flight-kind rates in ring series —
    # the SLO judge's burn-rate evidence for this run
    from serf_tpu.obs.timeseries import MetricsSampler
    sampler = MetricsSampler(interval_s=traffic_period)

    # continuous verification (obs.watchdog): constructed BEFORE the
    # nodes so make_node can attach each node's black box as it comes up
    # (a restart reuses the node's box — bundle sequence numbers must
    # not collide).  The armed predicates read the LIVE node view per
    # tick, so crashed/paused nodes never false-breach the health floor.
    wd = None
    boxes: Dict[int, object] = {}
    blackbox_dir = (os.path.join(tmp_dir, "blackbox")
                    if (watchdog and tmp_dir is not None) else None)
    if watchdog:
        from serf_tpu.obs.watchdog import (Watchdog, WatchdogConfig,
                                           arm_serf_invariants,
                                           arm_shed_ratio_watch)
        wd = Watchdog(cfg=watchdog_cfg or WatchdogConfig(),
                      store=sampler.store)
        arm_serf_invariants(
            wd, lambda: {i: nodes[i] for i in nodes if i not in down
                         and nodes[i].state == SerfState.ALIVE})
        arm_shed_ratio_watch(wd, sampler.store)
        if plan.encrypted:
            from serf_tpu.obs.watchdog import arm_rotation_latency_watch
            arm_rotation_latency_watch(wd, sampler.store)
        wd.install_task_hook()

    def _box_for(i: int):
        if i in boxes:
            return boxes[i]
        from serf_tpu.obs import lifecycle as lc
        from serf_tpu.obs.blackbox import BlackBox
        box = BlackBox(
            blackbox_dir, node=f"n{i}", store=sampler.store,
            lifecycle=lambda: lc.global_ledger().snapshot(),
            health=lambda i=i: nodes[i].health_report().to_dict(),
            slo_verdicts=lambda: [v.to_dict() for v in wd.history[-16:]],
            recording=lambda: (
                None if recorder is None else
                {"plane": "host", "steps": recorder._seq,
                 "finished": recorder._finished}))
        boxes[i] = box
        wd.add_blackbox(box)
        return box

    for i in range(n):
        nodes[i] = await make_node(i)

    ctl = None
    if controller:
        from serf_tpu.control.host import ControllerTick, HostControlConfig

        def _live_serfs():
            from serf_tpu.host.serf import SerfState as _SS
            return [nodes[i] for i in nodes
                    if i not in down and nodes[i].state == _SS.ALIVE]

        ctl = ControllerTick(_live_serfs, sampler.store,
                             cfg=control_cfg or HostControlConfig(
                                 enabled=True),
                             recorder=recorder)
    samples: Dict[str, List[ClockSample]] = {f"n{i}": [] for i in range(n)}
    events_sent = 0
    load = HostLoadReport(
        queue_bounds_by={"intent": base_opts.intent_queue_bytes,
                         "event": base_opts.event_queue_bytes,
                         "query": base_opts.query_queue_bytes},
        query_responses_bound=base_opts.max_query_responses,
        event_inbox_bound=base_opts.event_inbox_max,
    )
    down: frozenset = frozenset()
    rng = random.Random(plan.seed ^ 0x5EED)
    stop = asyncio.Event()
    current_phase: List[Optional[FaultPhase]] = [None]

    def sample_clocks() -> None:
        for i, s in nodes.items():
            if i in down or s.state == SerfState.SHUTDOWN:
                continue
            samples[s.local_id].append(ClockSample(
                mono=time.monotonic(), generation=generation[i],
                clock=int(s.clock.time()), event=int(s.event_clock.time()),
                query=int(s.query_clock.time())))

    def sample_buffers() -> None:
        for i, s in nodes.items():
            if i in down or s.state == SerfState.SHUTDOWN:
                continue
            for qname, q in (("intent", s.intent_broadcasts),
                             ("event", s.event_broadcasts),
                             ("query", s.query_broadcasts)):
                load.max_queue_bytes_by[qname] = max(
                    load.max_queue_bytes_by.get(qname, 0), q.bytes())
            load.max_query_responses = max(load.max_query_responses,
                                           len(s._query_responses))
            load.max_event_inbox = max(load.max_event_inbox,
                                       s.pipeline_depth())

    def live_indices() -> List[int]:
        return [i for i in nodes
                if i not in down and nodes[i].state == SerfState.ALIVE]

    async def issue_rotation(op: str, phase_name: str) -> None:
        """One phase-entry rotation op, issued by the lowest live node
        (install -> next key, use -> next key, remove -> base key).
        The row — success or failure — is evidence, not control flow:
        a partition is SUPPOSED to make these partial."""
        from serf_tpu.host.keyring import key_digest
        row: Dict = {"phase": phase_name, "op": op}
        live = live_indices()
        if not live:
            row["error"] = "no live node to issue from"
            rotation_ops.append(row)
            return
        km = nodes[min(live)].key_manager()
        key = rot_base if op == "remove" else rot_next
        row["key"] = key_digest(key)
        try:
            if op == "install":
                r = await km.install_key(key)
            elif op == "use":
                r = await km.use_key(key)
            else:
                r = await km.remove_key(key)
        except Exception as e:  # noqa: BLE001 — a failed op is evidence
            row["error"] = repr(e)[:200]
        else:
            row.update(num_nodes=r.num_nodes, num_resp=r.num_resp,
                       num_err=r.num_err, attempts=r.attempts,
                       quorum_ok=r.quorum_ok)
            if r.messages:
                row["messages"] = dict(list(r.messages.items())[:4])
        rotation_ops.append(row)

    async def background() -> None:
        nonlocal events_sent
        while not stop.is_set():
            await asyncio.sleep(traffic_period)
            sample_clocks()
            sample_buffers()
            sampler.sample()
            if ctl is not None:
                ctl.tick()
            if wd is not None:
                wd.tick()
            live = live_indices()
            if live:
                src = rng.choice(live)
                load.events_offered += 1
                try:
                    await nodes[src].user_event(
                        f"chaos-{events_sent}", b"x", coalesce=False)
                    events_sent += 1
                except OverloadError:
                    pass
                except Exception:  # noqa: BLE001 - traffic is best-effort
                    pass

    async def load_gen() -> None:
        """Fire the current phase's offered event/query rates from
        random live nodes.  Every call is counted as offered; the
        engine's own ingress counters provide admitted/shed."""
        from serf_tpu.host.query import QueryParam

        credit_e = credit_q = 0.0
        tick = 0.02
        seq = 0
        while not stop.is_set():
            await asyncio.sleep(tick)
            phase = current_phase[0]
            if phase is None or not phase.has_load():
                credit_e = credit_q = 0.0
                continue
            live = live_indices()
            if not live:
                continue
            credit_e += phase.event_rate * tick
            credit_q += phase.query_rate * tick
            while credit_e >= 1.0:
                credit_e -= 1.0
                seq += 1
                load.events_offered += 1
                try:
                    await nodes[rng.choice(live)].user_event(
                        f"storm-{seq}", b"storm-payload", coalesce=False)
                except OverloadError:
                    pass
                except Exception:  # noqa: BLE001
                    pass
            while credit_q >= 1.0:
                credit_q -= 1.0
                seq += 1
                load.queries_offered += 1
                try:
                    await nodes[rng.choice(live)].query(
                        f"storm-q-{seq}", b"q",
                        QueryParam(timeout=0.25))
                except OverloadError:
                    pass
                except Exception:  # noqa: BLE001
                    pass

    bg = spawn_logged(background(), "chaos-background")
    lg = spawn_logged(load_gen(), "chaos-load-gen") if with_load else None
    # message-lifecycle ledger (obs.lifecycle): a fresh, hotter-sampling
    # ledger for THIS run, installed as the LAST statement before the
    # guarded body (the spawned tasks only start running at the first
    # await, inside the try) so the finally restores it on EVERY exit
    # path; the pipelines resolve the process ledger per event, so a
    # post-creation install is picked up.  The snapshot rides the
    # result for the SLO judge.
    from serf_tpu.obs import lifecycle as _lc
    led = _lc.LifecycleLedger(sample_n=lifecycle_sample_n,
                              slow_ms=lifecycle_slow_ms)
    prev_led = _lc.set_global_ledger(led)
    try:
        t0 = time.monotonic()
        for i in range(1, n):
            if recorder is not None:
                recorder.step("join", node=i, target="n0")
            await nodes[i].join("n0")
        await inv.wait_host_convergence(
            [nodes[i] for i in range(n)], deadline_s=plan.settle_s)
        load.quiet_convergence_s = time.monotonic() - t0
        quiet_convergence_s = load.quiet_convergence_s
        record_barrier("quiet", [nodes[i] for i in range(n)])

        for pi, phase in enumerate(plan.phases):
            # crash BEFORE installing the phase rule so the rule never
            # references a half-dead node's traffic
            if recorder is not None:
                recorder.step("phase", index=pi, name=phase.name,
                              duration_s=phase.duration_s)
            for i in phase.crash:
                if nodes[i].state != SerfState.SHUTDOWN:
                    await nodes[i].shutdown()
            ex.apply_phase(pi)
            down = ex.down_nodes()
            for i in phase.restart:
                if nodes[i].state == SerfState.SHUTDOWN:
                    generation[i] += 1
                    nodes[i] = await make_node(i)
                    seeds = [j for j in nodes if j not in down and j != i
                             and nodes[j].state == SerfState.ALIVE]
                    seed_addr = f"n{rng.choice(seeds)}" if seeds else None
                    if recorder is not None:
                        recorder.step("restart", node=i, seed=seed_addr)
                    if seed_addr is not None:
                        try:
                            await nodes[i].join(seed_addr)
                        except (ConnectionError, TimeoutError, OSError):
                            pass
            down = ex.down_nodes()
            # rotation ops fire at phase ENTRY, after crash/restart and
            # under the phase's faults — a rotate issued into a
            # partition or beside a SIGKILL is the point of the plan
            for op in phase.rotate:
                await issue_rotation(op, phase.name)
            for i in phase.stall:
                gates.setdefault(i, asyncio.Event()).clear()
            current_phase[0] = phase
            await asyncio.sleep(phase.duration_s)
            current_phase[0] = None
            for i in phase.stall:
                gates[i].set()      # consumer resumes; backlog drains

        if recorder is not None:
            recorder.step("heal")
        ex.clear()
        down = frozenset()
        live = [nodes[i] for i in nodes
                if nodes[i].state == SerfState.ALIVE]
        t1 = time.monotonic()
        settle_converged = await inv.wait_host_convergence(
            live, deadline_s=plan.settle_s)
        load.settle_convergence_s = time.monotonic() - t1
        record_barrier("settle", live)
        sample_clocks()
        sample_buffers()
        sampler.sample()
        if wd is not None:
            wd.tick()   # one post-settle verdict rides the result
        # responsive-node false-DEAD count (same definition the
        # no-false-dead invariant judges): SLO-plane evidence on every
        # run, measured before shutdown tears the views down
        from serf_tpu.types.member import MemberStatus
        live_ids = {s.local_id for s in live}
        ever_down = {f"n{i}" for i in plan.ever_down()}
        false_dead = sum(
            1 for s in live for m in s.members()
            if m.status == MemberStatus.FAILED
            and m.node.id in live_ids and m.node.id not in ever_down)
        # quiesce the traffic tasks BEFORE reading the ingress deltas:
        # a call in flight between the offered tally and the engine's
        # counter would otherwise skew the accounting invariant
        stop.set()
        for t in (bg, lg):
            if t is not None:
                t.cancel()
                try:
                    await t
                except (asyncio.CancelledError, Exception):  # noqa: BLE001
                    pass
        load.ingress_admitted = int(
            _counter_total("serf.overload.ingress_admitted") - base_admitted)
        load.ingress_shed = int(
            _counter_total("serf.overload.ingress_shed") - base_shed)
        load.lossless_violations = int(
            _counter_total("serf.subscriber.lossless_violation")
            - base_lossless)
        # propagation probe: one traced user_event AFTER the heal +
        # settle barrier (the healed fabric is what the coverage SLO
        # judges), polled to full coverage across the live set — fired
        # only once the ingress deltas above are read, so the probe's
        # own admission does not skew the shed-accounting invariant
        propagation = await measure_propagation(
            live, deadline_s=max(1.0, min(plan.settle_s, 5.0)))
        # rotation finale (rotating plans): probe the possibly still
        # mixed-key fabric for message loss FIRST (decrypt fallback is
        # fine, loss is not), then reconcile every ring to the next key
        # and read the digests — runs after the ingress-delta read for
        # the same reason the propagation probe does.  Encrypted plans
        # WITHOUT rotate ops (e.g. the bench crypto-tax A/B) skip it:
        # their rings never leave the base key, so "converge to K2"
        # would wait out the full reconcile deadline and judge red
        rotation = None
        if plan.encrypted and plan.has_rotation():
            rotation = await _rotation_finale(
                plan, nodes, live, live_indices, rotation_ops,
                rot_base, rot_next, base_fallback, base_fail)
        if recorder is not None:
            recorder.finish()
        report = inv.check_host(plan, nodes, samples, generation,
                                snapshots=tmp_dir is not None,
                                load=load if with_load else None,
                                rotation=rotation)
        if ctl is not None:
            inv.check_control_host(report, ctl)
        return HostChaosResult(plan=plan, report=report,
                               clock_samples=samples,
                               counters=degradation_counters(),
                               events_sent=events_sent,
                               load=load if with_load else None,
                               series=sampler.store,
                               control=ctl.to_dict() if ctl is not None
                               else None,
                               quiet_convergence_s=quiet_convergence_s,
                               settle_convergence_s=load.settle_convergence_s,
                               settle_converged=settle_converged,
                               false_dead=false_dead,
                               lifecycle=led.snapshot(),
                               propagation=propagation,
                               watchdog=wd.state() if wd is not None
                               else None,
                               rotation=rotation)
    finally:
        stop.set()
        if wd is not None:
            wd.uninstall_task_hook()
        for t in (bg, lg, *consumers.values()):
            if t is None:
                continue
            t.cancel()
            try:
                # bounded: a task that survives its cancellation (e.g. a
                # wait_for race swallowing the request) must degrade to a
                # leaked-task warning, never hang the whole executor
                await asyncio.wait([t], timeout=2.0)
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            if not t.done():
                log.warning("chaos teardown: task %r survived cancel",
                            t.get_name())
        # the cluster must die on EVERY path — a raise mid-plan must not
        # leave n gossiping nodes running for the rest of the process
        for s in nodes.values():
            if s.state != SerfState.SHUTDOWN:
                await s.shutdown()
        # restore the process ledger only AFTER teardown: shutdown-time
        # messages must land on the run's scoped ledger, not leak onto
        # the restored one
        _lc.set_global_ledger(prev_led)
