"""Host-plane fault executor: FaultPlan -> LoopbackNetwork / transports.

Two entry points:

- :class:`HostFaultExecutor` compiles :class:`~serf_tpu.faults.plan
  .FaultPlan` phases into :class:`serf_tpu.host.transport.ChaosRule`
  objects and installs them on a ``LoopbackNetwork`` (the one fault API
  the legacy ``partition``/``set_drop_rate`` knobs also delegate to).
  For clusters on REAL transports (net/dstream), ``wrap_transport``
  injects the same phase faults at the sender seam — drop, blocked
  edges/partitions, corruption — which is how the transport-storm tests
  drive TCP/TLS/udpstream clusters from a plan.

- :func:`run_host_plan` stands up an in-process loopback cluster, runs
  the plan end to end (crash = Serf shutdown, restart = re-create on the
  OLD address with the node's snapshot), keeps background traffic
  flowing, samples Lamport clocks throughout, then heals, waits the
  settle budget, and hands everything to the invariant checker.
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from serf_tpu.faults.plan import FaultPhase, FaultPlan
from serf_tpu.host.transport import (
    ChaosRule,
    EdgeRates,
    LoopbackNetwork,
    apply_edge_faults,
)
from serf_tpu.obs import flight
from serf_tpu.utils import metrics
from serf_tpu.utils.logging import get_logger

log = get_logger("faults")


def compile_phase(phase: FaultPhase, addr_of) -> ChaosRule:
    """Lower one plan phase to a transport-level chaos rule.
    ``addr_of(i)`` maps plan node indices to transport addresses."""
    groups: Optional[List[set]] = None
    if phase.partitions:
        groups = [set(addr_of(i) for i in g) for g in phase.partitions]
        listed = set().union(*groups) if groups else set()
        # unlisted nodes form one implicit extra group (plan semantics,
        # identical on the device plane)
        rest = {addr_of(i) for i in range(_plan_n(addr_of))} - listed
        if rest:
            groups.append(rest)
    edges: Dict[Tuple[object, object], EdgeRates] = {}
    for e in phase.edges:
        rates = EdgeRates(drop=e.drop, delay=e.delay, duplicate=e.duplicate,
                          reorder=e.reorder, corrupt=e.corrupt)
        edges[(addr_of(e.src), addr_of(e.dst))] = rates
        if e.bidirectional:
            edges[(addr_of(e.dst), addr_of(e.src))] = rates
    return ChaosRule(
        groups=groups,
        drop=phase.drop,
        delay=phase.delay,
        jitter=phase.jitter,
        duplicate=phase.duplicate,
        reorder=phase.reorder,
        corrupt=phase.corrupt,
        edges=edges,
    )


def _plan_n(addr_of) -> int:
    n = getattr(addr_of, "plan_n", None)
    if n is None:
        raise ValueError("addr_of must carry a .plan_n attribute "
                         "(use HostFaultExecutor or make_addr_of)")
    return n


def make_addr_of(n: int, mapping=None):
    """Index -> address mapper for ``compile_phase``.  Default address
    scheme is ``"n{i}"`` (the loopback runner's node names)."""
    def addr_of(i: int):
        return mapping[i] if mapping is not None else f"n{i}"
    addr_of.plan_n = n
    return addr_of


class HostFaultExecutor:
    """Drives a plan's phases against a ``LoopbackNetwork`` (and any
    wrapped real transports registered via :meth:`wrap_transport`)."""

    def __init__(self, plan: FaultPlan, net: Optional[LoopbackNetwork] = None,
                 mapping: Optional[Dict[int, object]] = None):
        plan.validate()
        self.plan = plan
        self.net = net
        self.addr_of = make_addr_of(plan.n, mapping)
        self.rng = random.Random(plan.seed)
        self.phase_index: Optional[int] = None
        self._down: set = set()          # node indices currently down
        self._paused: set = set()
        self._wrapped: List[object] = []

    # -- phase stepping ------------------------------------------------------

    def apply_phase(self, index: int) -> FaultPhase:
        """Install phase ``index``'s faults (and update the down/pause
        bookkeeping).  Crash/restart of real processes is the caller's
        job (run_host_plan does it); pause is enforced at the network."""
        phase = self.plan.phases[index]
        self._down |= set(phase.crash)
        self._paused |= set(phase.pause)
        self._down -= set(phase.restart)
        self._paused -= set(phase.restart)
        rule = compile_phase(phase, self.addr_of)
        rule.paused = frozenset(self.addr_of(i) for i in self._paused)
        self._install(rule)
        self.phase_index = index
        metrics.gauge("serf.faults.phase", index)
        flight.record("fault-phase", plan=self.plan.name, phase=index,
                      name=phase.name)
        return phase

    def clear(self) -> None:
        """Heal everything (end of plan): no partitions, no rates; nodes
        the plan left paused stay paused only if never restarted — the
        plan validator forbids that, so clear really is clear."""
        self._install(None)
        self.phase_index = None
        metrics.gauge("serf.faults.phase", -1)
        flight.record("fault-phase", plan=self.plan.name, phase=-1,
                      name="healed")

    def _install(self, rule: Optional[ChaosRule]) -> None:
        if self.net is not None:
            if rule is not None:
                self.net.rng = random.Random(
                    self.rng.randrange(1 << 30))
            self.net.apply_faults(rule)
        for t in self._wrapped:
            t._chaos_rule = rule

    def down_nodes(self) -> frozenset:
        return frozenset(self._down | self._paused)

    # -- real-transport seam -------------------------------------------------

    def wrap_transport(self, transport, node_index: int, addr_key=None):
        """Sender-side fault injection for a REAL transport against the
        CURRENT phase rule (see :func:`attach_transport_chaos`).
        ``addr_key(addr) -> plan address`` normalizes destination
        addresses to the plan's node addresses (default: identity)."""
        attach_transport_chaos(
            transport, self.addr_of(node_index), addr_key=addr_key,
            rng=random.Random(self.rng.randrange(1 << 30)))
        if transport not in self._wrapped:
            self._wrapped.append(transport)
        return transport


def attach_transport_chaos(transport, src, addr_key=None,
                           rng: Optional[random.Random] = None):
    """Idempotently wrap a REAL transport's sender seam with chaos-rule
    enforcement: ``send_packet`` (and dstream's segment-level
    ``_sendto``) gets probabilistic drop / bit-flip corruption plus
    partition/blackhole blocking, ``dial`` refuses partitioned or
    blackholed destinations.  The active rule lives in
    ``transport._chaos_rule`` (a :class:`ChaosRule` or None) — swap it
    per phase; the legacy storm helpers and ``HostFaultExecutor`` both
    drive this one seam."""
    if getattr(transport, "_chaos_wrapped", False):
        return transport
    transport._chaos_wrapped = True
    transport._chaos_rule = None
    keyfn = addr_key or (lambda a: a)
    rng = rng or random.Random(0)

    orig_send_packet = transport.send_packet
    orig_dial = transport.dial

    async def send_packet(addr, buf):
        rule: Optional[ChaosRule] = transport._chaos_rule
        if rule is not None:
            buf = apply_edge_faults(rule, rng, src, keyfn(addr), buf)
            if buf is None:
                return
        await orig_send_packet(addr, buf)

    async def dial(addr, timeout=None):
        rule: Optional[ChaosRule] = transport._chaos_rule
        if rule is not None:
            dst = keyfn(addr)
            if rule.group_blocked(src, dst) or rule.blackholed(src, dst):
                raise ConnectionError(
                    f"chaos: no route to {addr!r} (partition)")
        return await orig_dial(addr, timeout=timeout)

    transport.send_packet = send_packet
    transport.dial = dial
    # dstream sends segments through _sendto, not send_packet — fault
    # the segment plane too (same shared decision: drop AND corruption,
    # so the ARQ + keyring recovery paths see chaos under cluster load)
    orig_sendto = getattr(transport, "_sendto", None)
    if orig_sendto is not None:
        def _sendto(wire, addr):
            rule: Optional[ChaosRule] = transport._chaos_rule
            if rule is not None:
                wire = apply_edge_faults(rule, rng, src, keyfn(addr), wire)
                if wire is None:
                    return
            orig_sendto(wire, addr)
        transport._sendto = _sendto
    return transport


# ---------------------------------------------------------------------------
# loopback chaos runner
# ---------------------------------------------------------------------------


@dataclass
class ClockSample:
    mono: float
    generation: int
    clock: int
    event: int
    query: int


@dataclass
class HostChaosResult:
    plan: FaultPlan
    report: object                      # invariants.InvariantReport
    clock_samples: Dict[str, List[ClockSample]] = field(default_factory=dict)
    counters: Dict[str, float] = field(default_factory=dict)
    events_sent: int = 0


def degradation_counters() -> Dict[str, float]:
    """Sum every ``serf.faults.*`` / ``serf.degraded.*`` counter in the
    global sink across label sets — the CLI's degradation report."""
    sink = metrics.global_sink()
    out: Dict[str, float] = {}
    for (name, _labels), v in sink.counters.items():
        if name.startswith(("serf.faults.", "serf.degraded.")):
            out[name] = out.get(name, 0.0) + v
    return out


async def run_host_plan(plan: FaultPlan, tmp_dir: Optional[str] = None,
                        opts=None,
                        traffic_period: float = 0.08) -> HostChaosResult:
    """Run ``plan`` against a fresh in-process loopback cluster and check
    the invariants.  ``tmp_dir`` enables per-node snapshots (crash →
    restart replays them); without it restarts come back cold."""
    import os

    from serf_tpu.faults import invariants as inv
    from serf_tpu.host.serf import Serf, SerfState
    from serf_tpu.options import Options

    plan.validate()
    n = plan.n
    base_opts = opts or Options.local()
    net = LoopbackNetwork()
    ex = HostFaultExecutor(plan, net)

    def node_opts(i: int):
        if tmp_dir is None:
            return base_opts
        return base_opts.replace(
            snapshot_path=os.path.join(tmp_dir, f"chaos-n{i}.snap"))

    generation = {i: 0 for i in range(n)}
    nodes: Dict[int, Serf] = {}
    for i in range(n):
        nodes[i] = await Serf.create(net.bind(f"n{i}"), node_opts(i),
                                     f"n{i}")
    samples: Dict[str, List[ClockSample]] = {f"n{i}": [] for i in range(n)}
    events_sent = 0
    down: frozenset = frozenset()
    rng = random.Random(plan.seed ^ 0x5EED)
    stop = asyncio.Event()

    def sample_clocks() -> None:
        for i, s in nodes.items():
            if i in down or s.state == SerfState.SHUTDOWN:
                continue
            samples[s.local_id].append(ClockSample(
                mono=time.monotonic(), generation=generation[i],
                clock=int(s.clock.time()), event=int(s.event_clock.time()),
                query=int(s.query_clock.time())))

    async def background() -> None:
        nonlocal events_sent
        while not stop.is_set():
            await asyncio.sleep(traffic_period)
            sample_clocks()
            live = [i for i in nodes
                    if i not in down
                    and nodes[i].state == SerfState.ALIVE]
            if live:
                src = rng.choice(live)
                try:
                    await nodes[src].user_event(
                        f"chaos-{events_sent}", b"x", coalesce=False)
                    events_sent += 1
                except Exception:  # noqa: BLE001 - traffic is best-effort
                    pass

    bg = asyncio.create_task(background())
    try:
        for i in range(1, n):
            await nodes[i].join("n0")
        await inv.wait_host_convergence(
            [nodes[i] for i in range(n)], deadline_s=plan.settle_s)

        for pi, phase in enumerate(plan.phases):
            # crash BEFORE installing the phase rule so the rule never
            # references a half-dead node's traffic
            for i in phase.crash:
                if nodes[i].state != SerfState.SHUTDOWN:
                    await nodes[i].shutdown()
            ex.apply_phase(pi)
            down = ex.down_nodes()
            for i in phase.restart:
                if nodes[i].state == SerfState.SHUTDOWN:
                    generation[i] += 1
                    nodes[i] = await Serf.create(
                        net.bind(f"n{i}"), node_opts(i), f"n{i}")
                    seeds = [j for j in nodes if j not in down and j != i
                             and nodes[j].state == SerfState.ALIVE]
                    if seeds:
                        try:
                            await nodes[i].join(f"n{rng.choice(seeds)}")
                        except (ConnectionError, TimeoutError, OSError):
                            pass
            down = ex.down_nodes()
            await asyncio.sleep(phase.duration_s)

        ex.clear()
        down = frozenset()
        live = [nodes[i] for i in nodes
                if nodes[i].state == SerfState.ALIVE]
        await inv.wait_host_convergence(live, deadline_s=plan.settle_s)
        sample_clocks()
        report = inv.check_host(plan, nodes, samples, generation,
                                snapshots=tmp_dir is not None)
        return HostChaosResult(plan=plan, report=report,
                               clock_samples=samples,
                               counters=degradation_counters(),
                               events_sent=events_sent)
    finally:
        stop.set()
        bg.cancel()
        try:
            await bg
        except (asyncio.CancelledError, Exception):  # noqa: BLE001
            pass
        # the cluster must die on EVERY path — a raise mid-plan must not
        # leave n gossiping nodes running for the rest of the process
        for s in nodes.values():
            if s.state != SerfState.SHUTDOWN:
                await s.shutdown()
