"""Convergence invariants judged after a chaos run — on either plane.

The checks encode what SWIM + Lifeguard actually promise under faults
(PAPERS.md): bounded-time convergence after heal, no false DEAD verdicts
for responsive nodes, Lamport-clock monotonicity, and crash-restart
rejoin correctness.  ``tools/chaos.py`` prints the report;
``tests/test_faults.py`` pins the acceptance plan green on both planes.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Dict, List

from serf_tpu.faults.plan import FaultPlan
from serf_tpu.utils.logging import get_logger

log = get_logger("faults.invariants")


@dataclass
class InvariantResult:
    name: str
    ok: bool
    detail: str = ""

    def to_dict(self) -> dict:
        return {"name": self.name, "ok": self.ok, "detail": self.detail}


@dataclass
class InvariantReport:
    plane: str
    plan: str
    results: List[InvariantResult] = field(default_factory=list)

    def add(self, name: str, ok: bool, detail: str = "") -> None:
        self.results.append(InvariantResult(name, bool(ok), detail))

    @property
    def ok(self) -> bool:
        return all(r.ok for r in self.results)

    def to_dict(self) -> dict:
        return {"plane": self.plane, "plan": self.plan, "ok": self.ok,
                "invariants": [r.to_dict() for r in self.results]}

    def format(self) -> str:
        lines = [f"[{self.plane}] plan {self.plan!r}: "
                 f"{'GREEN' if self.ok else 'RED'}"]
        for r in self.results:
            mark = "ok " if r.ok else "FAIL"
            lines.append(f"  {mark}  {r.name}"
                         + (f" — {r.detail}" if r.detail else ""))
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# host plane
# ---------------------------------------------------------------------------


def _alive_view(serf) -> set:
    from serf_tpu.types.member import MemberStatus
    return {m.node.id for m in serf.members()
            if m.status == MemberStatus.ALIVE}


async def wait_host_convergence(nodes, deadline_s: float,
                                poll_s: float = 0.05) -> bool:
    """Poll until every given node's ALIVE view covers all given nodes
    (or the deadline passes).  Returns whether convergence was reached —
    the caller's invariant check renders the verdict either way."""
    want = {s.local_id for s in nodes}
    loop = asyncio.get_running_loop()
    end = loop.time() + deadline_s
    while loop.time() < end:
        if all(_alive_view(s) >= want for s in nodes):
            return True
        await asyncio.sleep(poll_s)
    return all(_alive_view(s) >= want for s in nodes)


def check_host(plan: FaultPlan, nodes: Dict[int, object],
               samples: Dict[str, List], generation: Dict[int, int],
               snapshots: bool = False, load=None,
               rotation=None) -> InvariantReport:
    """Judge the host-plane invariants on a finished chaos run.

    ``nodes``: index -> Serf (some possibly SHUTDOWN); ``samples``:
    node id -> ClockSample list (faults.host); ``generation``: restart
    count per node index; ``load``: a ``faults.host.HostLoadReport``
    when the plan offered user-plane load — enables the overload
    invariants (bounded buffers, closed shed accounting, lossless
    contract intact, storm-bounded convergence).
    """
    from serf_tpu.host.serf import SerfState
    from serf_tpu.types.member import MemberStatus

    rep = InvariantReport(plane="host", plan=plan.name)
    live = {i: s for i, s in nodes.items() if s.state == SerfState.ALIVE}
    live_ids = {s.local_id for s in live.values()}

    # 1. post-heal membership convergence: every live node sees every
    # live node ALIVE (bounded by the runner's settle deadline)
    missing = {}
    for i, s in live.items():
        lack = live_ids - _alive_view(s)
        if lack:
            missing[s.local_id] = sorted(lack)
    rep.add("membership-convergence", not missing,
            f"views missing: {missing}" if missing
            else f"{len(live)} live nodes agree")

    # 2. no false DEAD: a node the plan never crashed/paused stayed
    # responsive throughout — no live view may hold it FAILED now
    ever_down = {f"n{i}" for i in plan.ever_down()}
    false_dead = {}
    for i, s in live.items():
        bad = sorted(m.node.id for m in s.members()
                     if m.status == MemberStatus.FAILED
                     and m.node.id in live_ids
                     and m.node.id not in ever_down)
        if bad:
            false_dead[s.local_id] = bad
    rep.add("no-false-dead", not false_dead,
            f"responsive nodes held FAILED: {false_dead}" if false_dead
            else f"{len(ever_down)} plan-downed nodes exempt")

    # 3. Lamport/event/query clock monotonicity per node per generation
    regressions = []
    for nid, series in samples.items():
        prev = None
        for s in series:
            if prev is not None and s.generation == prev.generation:
                if (s.clock < prev.clock or s.event < prev.event
                        or s.query < prev.query):
                    regressions.append(
                        (nid, s.generation,
                         (prev.clock, prev.event, prev.query),
                         (s.clock, s.event, s.query)))
            prev = s
    rep.add("clock-monotonicity", not regressions,
            f"regressions: {regressions[:3]}" if regressions
            else f"{sum(len(v) for v in samples.values())} samples")

    # 4. snapshot crash-restart rejoin: a restarted node came back into
    # the converged view (covered by invariant 1 — re-assert narrowly)
    # and, when snapshots persisted its clocks, did not regress them
    # across the restart boundary
    restarted = [i for i, g in generation.items() if g > 0]
    rejoin_ok = True
    detail = "no restarts in plan"
    if restarted:
        problems = []
        for i in restarted:
            s = nodes[i]
            nid = f"n{i}"
            if s.state != SerfState.ALIVE or not any(
                    nid in _alive_view(other) for other in live.values()):
                problems.append(f"{nid} did not rejoin")
                continue
            if snapshots:
                series = samples.get(nid, [])
                for g in range(1, generation[i] + 1):
                    before = [x for x in series if x.generation == g - 1]
                    after = [x for x in series if x.generation == g]
                    if before and after and (
                            after[0].clock < before[-1].clock
                            or after[0].event < before[-1].event):
                        problems.append(
                            f"{nid} gen{g} clock regressed across "
                            f"restart ({before[-1].clock},"
                            f"{before[-1].event}) -> ({after[0].clock},"
                            f"{after[0].event})")
        rejoin_ok = not problems
        detail = ("; ".join(problems) if problems
                  else f"{len(restarted)} restart(s), "
                       f"snapshots={'on' if snapshots else 'off'}")
    rep.add("crash-restart-rejoin", rejoin_ok, detail)

    if load is not None:
        _check_host_overload(rep, load)
    if rotation is not None:
        check_rotation(rep, rotation)
    return rep


def _check_host_overload(rep: InvariantReport, load) -> None:
    """The overload invariants (ISSUE 5) for a load-bearing host run.

    ``load`` is a ``faults.host.HostLoadReport``: offered counts are the
    runner's independent tally, admitted/shed are the engine's own
    ``serf.overload.ingress_*`` counter deltas, and buffer maxima were
    sampled every traffic tick for the whole run."""
    # 5. bounded buffers: EVERY queue's bytes (judged against its OWN
    # budget, not the loosest one) and the query handler map never
    # exceeded their configured bounds at ANY sample — overload degraded
    # service (shedding), never memory.  The event inbox may exceed its
    # bound by the member events it never sheds; allow that slack.
    inbox_slack = 64
    over = []
    for qname, seen in sorted(load.max_queue_bytes_by.items()):
        bound = load.queue_bounds_by.get(qname, 0)
        if bound > 0 and seen > bound:
            over.append(f"{qname} queue {seen}B > {bound}B")
    if load.max_query_responses > load.query_responses_bound:
        over.append(f"query handlers {load.max_query_responses} > "
                    f"{load.query_responses_bound}")
    if load.event_inbox_bound > 0 and load.max_event_inbox \
            > load.event_inbox_bound + inbox_slack:
        over.append(f"event inbox {load.max_event_inbox} > "
                    f"{load.event_inbox_bound}+{inbox_slack}")
    fills = ", ".join(
        f"{q} {load.max_queue_bytes_by.get(q, 0)}B/"
        f"{load.queue_bounds_by.get(q, 0)}B"
        for q in sorted(load.queue_bounds_by))
    rep.add("bounded-buffers", not over,
            "; ".join(over) if over else
            f"{fills}; handlers "
            f"{load.max_query_responses}/{load.query_responses_bound}, "
            f"inbox {load.max_event_inbox}/{load.event_inbox_bound}")

    # 6. shed accounting closes: every offered ingress op is accounted
    # as either admitted or shed by the ENGINE's own counters — no op
    # vanished untracked
    offered = load.events_offered + load.queries_offered
    accounted = load.ingress_admitted + load.ingress_shed
    rep.add("shed-accounting", accounted == offered,
            f"admitted {load.ingress_admitted} + shed "
            f"{load.ingress_shed} == offered {offered}"
            if accounted == offered else
            f"admitted {load.ingress_admitted} + shed "
            f"{load.ingress_shed} != offered {offered}")

    # 7. the lossless-subscriber contract survived the storm: shedding
    # happens at admission/inbox boundaries, never by violating a
    # lossless channel's no-drop promise
    rep.add("lossless-intact", load.lossless_violations == 0,
            f"{load.lossless_violations} lossless violation(s)"
            if load.lossless_violations else "no lossless violations")

    # 8. membership convergence under storm stays bounded: the post-plan
    # re-convergence took no more than 2x the quiet-baseline join
    # convergence (floored generously — sub-second baselines would make
    # scheduler jitter the verdict)
    allowance = max(2.0 * load.quiet_convergence_s, 3.0)
    rep.add("storm-convergence",
            load.settle_convergence_s <= allowance,
            f"settle {load.settle_convergence_s:.2f}s vs allowance "
            f"{allowance:.2f}s (quiet baseline "
            f"{load.quiet_convergence_s:.2f}s)")


# ---------------------------------------------------------------------------
# key-rotation invariants (ISSUE 20) — shared by the host and proc planes
# ---------------------------------------------------------------------------


def check_rotation(rep: InvariantReport, rotation: Dict) -> None:
    """Append the key-rotation invariants for an encrypted chaos run.

    ``rotation`` is the executor's rotation-evidence dict (host
    ``_rotation_finale`` / the proc runner's equivalent): phase-entry op
    rows, post-heal message-loss probes, the reconcile verdict, decrypt
    fallback/fail counter deltas, and every live node's NON-SECRET
    keyring digest (``keyring.SecretKeyring.digest``)."""
    # 9. keyring divergence: post-heal, every live ring converged to ONE
    # primary — the rotation's next key — and one identical key set.
    # A node left encrypting with a retired primary would partition the
    # cluster silently the moment the old key is removed elsewhere.
    rings = rotation.get("keyrings", {})
    expect = rotation.get("expected_primary")
    bad_primary = sorted(n for n, d in rings.items()
                         if d.get("primary") != expect)
    keysets = {tuple(d.get("keys", ())) for d in rings.values()}
    ok = (bool(rings) and rotation.get("converged", False)
          and not bad_primary and len(keysets) == 1)
    if ok:
        detail = (f"{len(rings)} live rings on primary {expect} "
                  f"(reconciled in {rotation.get('reconcile_s')}s, "
                  f"{rotation.get('reconcile_rounds')} round(s))")
    else:
        parts = []
        if not rings:
            parts.append("no keyring digests collected")
        if not rotation.get("converged", False):
            parts.append("reconcile did not converge "
                         f"within {rotation.get('reconcile_s')}s")
        if bad_primary:
            parts.append(f"wrong primary on {bad_primary}")
        if len(keysets) > 1:
            parts.append(f"{len(keysets)} distinct key sets")
        detail = "; ".join(parts)
    rep.add("keyring-divergence", ok, detail)

    # 10. no message loss mid-rotation: every probe offered into the
    # (possibly still mixed-key) post-heal window was delivered on
    # every live node.  Decrypt fallbacks are the MECHANISM (a peer on
    # an older/newer primary), decrypt fails are transient drops gossip
    # retransmit recovers — both are accounted in the detail, neither
    # may surface as a lost message.
    probes = rotation.get("probes", {})
    offered = probes.get("offered", 0)
    sent = probes.get("sent", 0)
    delivered = probes.get("delivered", 0)
    ok = offered > 0 and sent == offered and delivered == sent
    rep.add("no-message-loss-mid-rotation", ok,
            f"{delivered}/{offered} probes delivered to all "
            f"{probes.get('nodes', 0)} node(s); decrypt fallbacks "
            f"{rotation.get('decrypt_fallback', 0)}, fails "
            f"{rotation.get('decrypt_fail', 0)} (transient, accounted)")


# ---------------------------------------------------------------------------
# process plane (ISSUE 19) — judged from per-process artifacts
# ---------------------------------------------------------------------------


def check_proc(plan: FaultPlan, views: Dict[str, Dict[str, list]],
               samples: Dict[str, List], generation: Dict[int, int],
               survivor_counters: Optional[Dict[str, float]] = None,
               folded_counters: Optional[Dict[str, float]] = None,
               load=None, settle_converged: bool = True,
               rotation=None) -> InvariantReport:
    """Judge the SAME invariants as the host plane, but ACROSS process
    boundaries, from artifacts polled over each agent's control channel:

    ``views``: node_id -> final membership view
    (``{"alive": [...], "failed": [...], "left": [...]}``) of every
    agent that answered the final poll; ``samples``: node_id ->
    ClockSample list (stamped with the RESTART GENERATION the stats came
    from); ``generation``: restart count per node index;
    ``survivor_counters``: degradation counters folded from nodes the
    plan never downed (the SIGKILL-mid-push-pull proof);
    ``folded_counters``: cluster-wide counter fold (carries the agents'
    ``serf.proc.task_failures`` no-task-death evidence); ``load``: a
    ``faults.proc.ProcLoadReport`` when the plan offered load."""
    rep = InvariantReport(plane="proc", plan=plan.name)
    live_ids = set(views)

    # 1. post-heal membership convergence: every polled process sees
    # every polled process ALIVE (bounded by the runner's settle budget)
    missing = {}
    for nid, view in views.items():
        lack = live_ids - set(view.get("alive", ()))
        if lack:
            missing[nid] = sorted(lack)
    ok = not missing and settle_converged and bool(views)
    rep.add("membership-convergence", ok,
            f"views missing: {missing}" if missing
            else ("settle poll timed out" if not settle_converged
                  else f"{len(views)} live processes agree"))

    # 2. no false DEAD: a process the plan never crashed/paused stayed
    # responsive throughout — no live view may hold it FAILED now
    ever_down = {f"p{i}" for i in plan.ever_down()}
    false_dead = {}
    for nid, view in views.items():
        bad = sorted(x for x in view.get("failed", ())
                     if x in live_ids and x not in ever_down)
        if bad:
            false_dead[nid] = bad
    rep.add("no-false-dead", not false_dead,
            f"responsive processes held FAILED: {false_dead}" if false_dead
            else f"{len(ever_down)} plan-downed processes exempt")

    # 3. clock monotonicity per process per restart generation — the
    # generation stamp comes from the incarnation that answered the poll
    regressions = []
    for nid, series in samples.items():
        prev = None
        for s in series:
            if prev is not None and s.generation == prev.generation:
                if (s.clock < prev.clock or s.event < prev.event
                        or s.query < prev.query):
                    regressions.append(
                        (nid, s.generation,
                         (prev.clock, prev.event, prev.query),
                         (s.clock, s.event, s.query)))
            prev = s
    rep.add("clock-monotonicity", not regressions,
            f"regressions: {regressions[:3]}" if regressions
            else f"{sum(len(v) for v in samples.values())} samples")

    # 4. crash-restart rejoin: a re-exec'd process (same snapshot dir,
    # generation > 0) is back in everyone's view with clocks NOT
    # regressed across the restart boundary (snapshot replay seeds them)
    restarted = [i for i, g in generation.items() if g > 0]
    rejoin_ok = True
    detail = "no restarts in plan"
    if restarted:
        problems = []
        for i in restarted:
            nid = f"p{i}"
            if nid not in views or not all(
                    nid in v.get("alive", ()) for v in views.values()):
                problems.append(f"{nid} did not rejoin")
                continue
            series = samples.get(nid, [])
            for g in range(1, generation[i] + 1):
                before = [x for x in series if x.generation == g - 1]
                after = [x for x in series if x.generation == g]
                if before and after and (
                        after[0].clock < before[-1].clock
                        or after[0].event < before[-1].event):
                    problems.append(
                        f"{nid} gen{g} clock regressed across restart "
                        f"({before[-1].clock},{before[-1].event}) -> "
                        f"({after[0].clock},{after[0].event})")
        rejoin_ok = not problems
        detail = ("; ".join(problems) if problems
                  else f"{len(restarted)} restart(s) from snapshot")
    rep.add("crash-restart-rejoin", rejoin_ok, detail)

    # 5. degradation fired on survivors (crash plans only): a SIGKILL
    # mid-sync must register as probe failures / breaker activity /
    # dial retries on the peers that outlived it — graceful degradation
    # is only proven if the machinery demonstrably engaged
    if any(ph.crash for ph in plan.phases) and survivor_counters is not None:
        fired = {k: v for k, v in survivor_counters.items()
                 if (k.startswith("serf.degraded.")
                     or k == "memberlist.probe.failed") and v > 0}
        rep.add("degradation-fired", bool(fired),
                f"survivor counters: " + ", ".join(
                    f"{k}={int(v)}" for k, v in sorted(fired.items()))
                if fired else
                "no degradation counters fired on surviving processes")

    # 6. no task death: every agent's utils.tasks failure hook counted
    # zero background-task deaths across the whole run
    if folded_counters is not None:
        deaths = folded_counters.get("serf.proc.task_failures", 0.0)
        rep.add("no-task-death", deaths == 0,
                f"{int(deaths)} background task death(s) across agents"
                if deaths else "zero background-task deaths")

    # 7. shed accounting (load plans): every offered op in a delivered
    # batch is accounted admitted or shed by the engine's own admission
    # verdicts, relayed per call over the control channel
    if load is not None:
        ev_ok = (load.events_admitted + load.events_shed
                 == load.events_offered)
        q_ok = (load.queries_admitted + load.queries_shed
                == load.queries_offered)
        rep.add("shed-accounting", ev_ok and q_ok,
                f"events {load.events_admitted}+{load.events_shed}"
                f"=={load.events_offered}, queries "
                f"{load.queries_admitted}+{load.queries_shed}"
                f"=={load.queries_offered}" if ev_ok and q_ok else
                f"events {load.events_admitted}+{load.events_shed}"
                f"!={load.events_offered} or queries "
                f"{load.queries_admitted}+{load.queries_shed}"
                f"!={load.queries_offered}")

    # 8. key rotation (encrypted plans): the SAME keyring-divergence /
    # no-message-loss invariants as the host plane, judged from the
    # agents' ctl-channel key ops and digests
    if rotation is not None:
        check_rotation(rep, rotation)
    return rep


# ---------------------------------------------------------------------------
# device plane
# ---------------------------------------------------------------------------


def check_device(plan: FaultPlan, state, cfg, init_alive,
                 rounds_run: int, offered: int = 0,
                 expect_overflow: bool = False,
                 stretch_q=None) -> InvariantReport:
    """Judge the device-plane invariants on a finished chaos scan.
    ``offered`` is the executor's own injection count;
    ``expect_overflow`` asserts the run included a burst past ring
    capacity, so the overflow ledger MUST be nonzero (otherwise the
    bound check alone would be unfalsifiable).  ``stretch_q`` is the
    adaptive controller's FINAL suspicion stretch (controlled runs):
    the false-DEAD judgment honors the semantics the cluster actually
    ran, same as the telemetry row."""
    import jax
    import jax.numpy as jnp

    from serf_tpu.models.antientropy import knowledge_agreement
    from serf_tpu.models.dissemination import ltime_window_violation
    from serf_tpu.models.failure import believed_dead

    rep = InvariantReport(plane="device", plan=plan.name)
    g = state.gossip
    false_dead = believed_dead(g, cfg.gossip, cfg.failure,
                               stretch_q=stretch_q) & g.alive
    vals = jax.device_get({
        "agreement": knowledge_agreement(g, cfg.gossip),
        "false_dead": jnp.sum(false_dead),
        "ltime_violation": ltime_window_violation(g.facts),
        "round": g.round,
        "alive": jnp.sum(g.alive),
        "expected_alive": jnp.sum(init_alive),
        "overflow": g.overflow,
        "injected": g.injected,
    })

    # 1. post-heal convergence within the settle bound: every alive node
    # holds every valid fact (dissemination + anti-entropy healed)
    rep.add("membership-convergence",
            float(vals["agreement"]) >= 1.0,
            f"knowledge agreement {float(vals['agreement']):.4f}")

    # 2. no false DEAD: no alive node is believed dead (Lifeguard's
    # refutation path must win once the partition heals)
    rep.add("no-false-dead", int(vals["false_dead"]) == 0,
            f"{int(vals['false_dead'])} alive node(s) believed dead")

    # 3. Lamport window: u32 ltimes still comparable under the windowed
    # two's-complement rule (fail-loud guard for the wrap story)
    rep.add("ltime-window", not bool(vals["ltime_violation"]),
            "valid fact ltimes within the 2^31 window"
            if not bool(vals["ltime_violation"])
            else "ltime span >= 2^31: windowed comparison unsound")

    # 4. round accounting: the scan ran exactly the planned rounds and
    # every plan-restarted node is back (liveness restored)
    ok_rounds = int(vals["round"]) == rounds_run
    ok_alive = int(vals["alive"]) == int(vals["expected_alive"])
    rep.add("round-advance", ok_rounds and ok_alive,
            f"round={int(vals['round'])}/{rounds_run}, "
            f"alive={int(vals['alive'])}/{int(vals['expected_alive'])}")

    # 5. overflow accounted (ISSUE 5): the injection-overflow counter —
    # (control-stability, when the adaptive controller ran, is appended
    # by the executor via check_control_device)
    # facts clobbered while still inside their transmit window — is the
    # device plane's shed ledger.  It can never exceed the model's own
    # total-injection counter (every clobber retires a previously
    # injected fact; SWIM suspicions/declarations/refutations inject
    # too, not just the executor), and a storm past ring capacity must
    # show up in it rather than vanish silently.
    dropped = int(vals["overflow"])
    total = int(vals["injected"])
    ok = 0 <= dropped <= total and (dropped > 0 or not expect_overflow)
    rep.add("overflow-accounted", ok,
            f"{dropped} clobbered in-window of {total} injected "
            f"({offered} by the executor"
            + (", burst past capacity: nonzero required" if expect_overflow
               else "") + ")")
    return rep


# ---------------------------------------------------------------------------
# adaptive-control stability (ISSUE 11) — both planes
# ---------------------------------------------------------------------------

#: maximum direction reversals a knob trajectory may show before the
#: checker calls it a limit cycle.  Calibration: a genuine adaptation
#: episode (signal appears -> protective moves -> signal clears ->
#: relax) costs up to 2 reversals, and a chaos plan has at most ~3
#: episodes (warm-up convergence, the fault window, settle) — so a
#: healthy trajectory stays <= 6.  A hysteresis-defeating limit cycle
#: reverses every ~2*hysteresis rounds: 12+ over a typical 72-round
#: plan — cleanly separated from the bound.
CONTROL_MAX_REVERSALS = 6


def _trajectory_stability(values, steps, lo, hi, min_gap: float,
                          mult: bool = False):
    """Judge one knob's actuation trajectory ``[(t, value), ...]``:

    - **bounded step** — each move stays within its per-actuation clamp
      (additive ``steps``; or a ``steps``-ratio band when ``mult``);
    - **clamp band** — every value inside ``[lo, hi]`` (small epsilon);
    - **hysteresis** — consecutive actuations at least ``min_gap``
      ticks/rounds apart;
    - **no limit cycle** — direction reversals <= CONTROL_MAX_REVERSALS
      (monotone tails are fine: a knob still relaxing toward base when
      the run ends has settled, a knob oscillating has not).

    Returns a list of violation strings (empty = stable)."""
    out = []
    eps = 1e-9
    last_dir = 0
    reversals = 0
    last_t = None
    for (t0, v0), (t1, v1) in zip(values, values[1:]):
        d = v1 - v0
        if abs(d) <= eps:
            continue
        if mult:
            ratio = v1 / v0 if v0 else float("inf")
            if not (1.0 / steps - 1e-6 <= ratio <= steps + 1e-6):
                out.append(f"step {v0:g}->{v1:g} outside x{steps:g} band")
        elif abs(d) > steps + eps:
            out.append(f"step {v0:g}->{v1:g} exceeds +-{steps:g}")
        if not (lo - eps <= v1 <= hi + eps):
            out.append(f"value {v1:g} outside [{lo:g}, {hi:g}]")
        direction = 1 if d > 0 else -1
        if last_dir and direction != last_dir:
            reversals += 1
        last_dir = direction
        if last_t is not None and (t1 - last_t) < min_gap - eps:
            out.append(f"actuations {last_t:g} and {t1:g} closer than "
                       f"the {min_gap:g}-tick hysteresis window")
        last_t = t1
    if reversals > CONTROL_MAX_REVERSALS:
        out.append(f"{reversals} direction reversals "
                   f"(> {CONTROL_MAX_REVERSALS}): limit cycle")
    return out


def check_control_device(rep: InvariantReport, control_rows, ccfg,
                         bounds) -> None:
    """Append the ``control-stability`` invariant to a device report:
    the per-round knob trajectory (``control.device.control_row``
    stacking) must show bounded steps inside the clamp bands,
    hysteresis-spaced actuations, and no limit cycle."""
    import numpy as np

    from serf_tpu.control.device import KNOB_FIELDS

    base, lo, hi, step = bounds
    rows = np.asarray(control_rows)
    problems = []
    min_gap = float(min(ccfg.hyst_up, ccfg.hyst_down))
    for i, name in enumerate(KNOB_FIELDS):
        traj = [(0.0, float(base[i]))] + [
            (float(r + 1), float(rows[r, i])) for r in range(len(rows))]
        # collapse to actuation points (value changes) but KEEP the
        # round timestamps so the hysteresis-gap check is in rounds
        changes = [traj[0]]
        for t, v in traj[1:]:
            if v != changes[-1][1]:
                changes.append((t, v))
        for p in _trajectory_stability(changes, float(step[i]),
                                       float(lo[i]), float(hi[i]),
                                       min_gap):
            problems.append(f"{name}: {p}")
    n_act = sum(1 for r in range(1, len(rows))
                if not np.array_equal(rows[r, :len(KNOB_FIELDS)],
                                      rows[r - 1, :len(KNOB_FIELDS)]))
    rep.add("control-stability", not problems,
            "; ".join(problems[:4]) if problems else
            f"{n_act} actuation(s) over {len(rows)} rounds, "
            f"shed {int(rows[-1, len(KNOB_FIELDS)])}, knobs settled "
            "inside clamps")


def check_control_host(rep: InvariantReport, controller) -> None:
    """Append the ``control-stability`` invariant to a host report from
    a ``control.host.ControllerTick`` decision log: bounded
    (multiplicative or integer) steps inside the clamp bands,
    hysteresis-spaced ticks, no limit cycle."""
    from serf_tpu.control.host import _INT_KNOBS

    cfg = controller.cfg
    bounds = controller.bounds() if controller._base is not None else {}
    problems = []
    min_gap = float(min(cfg.hyst_up, cfg.hyst_down))
    for knob, traj in controller.trajectories().items():
        if len(traj) < 2:
            continue
        lo, hi, step = bounds[knob]
        for p in _trajectory_stability(
                traj, step, lo, hi, min_gap, mult=knob not in _INT_KNOBS):
            problems.append(f"{knob}: {p}")
    rep.add("control-stability", not problems,
            "; ".join(problems[:4]) if problems else
            f"{len(controller.decisions)} actuation(s) over "
            f"{controller.ticks} ticks, knobs settled inside clamps")
