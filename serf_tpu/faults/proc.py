"""Process-plane fault executor: FaultPlan -> real OS processes.

The THIRD executor over the same plan objects as ``faults.host`` and
``faults.device`` (ISSUE 19): :func:`run_proc_plan` spawns N serf agents
(``serf_tpu.host.agent``) as real subprocesses on ephemeral loopback
ports and lowers plan phases to REAL faults:

========================  =================================================
plan construct            process-plane lowering
========================  =================================================
``crash=(i,)``            SIGKILL of agent i's process group (no leave,
                          no flush — the snapshot's torn-tail repair and
                          the peers' failure detector carry the proof)
``pause=(i,)``            SIGSTOP (process alive, scheduler-frozen;
                          network silent); ``restart`` sends SIGCONT
``restart=(i,)``          crashed agents re-exec against the SAME
                          snapshot dir on the SAME port (generation+1),
                          then rejoin through a live seed
``partitions``/``drop``/  compiled to a :class:`ChaosRule`
``corrupt``/``edges``     (``compile_phase``) and installed over the
                          control channel onto every live agent's
                          ``attach_transport_chaos`` sender seam
``delay``/``duplicate``/  LOWERING NOTE: the real-transport sender seam
``reorder``/``jitter``    enforces drop + corruption + blocking only —
                          latency shaping is a loopback-fabric feature
                          (same note as the device plane's schedule)
``event_rate``/           batched ``load`` ops over the control channel
``query_rate``            to random live agents (offered counted by the
                          executor, admitted/shed by the engine)
``stall=(i,)``            LOWERING NOTE: agents run without subscribers;
                          consumer stalls are host-plane only
========================  =================================================

Per-process metrics/clock/membership artifacts are folded over the
control channel and judged by ``invariants.check_proc`` ACROSS process
boundaries.  Harness hygiene (ISSUE 19 satellite): every agent runs in
its own process group, teardown killpg-reaps in a ``finally`` on every
exit path (including cancellation — the reap is fully synchronous), and
ephemeral-port bind races retry bounded times inside the agent.
"""

from __future__ import annotations

import asyncio
import json
import os
import random
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from serf_tpu.faults.host import ClockSample, compile_phase, make_addr_of
from serf_tpu.faults.plan import FaultPhase, FaultPlan
from serf_tpu.host import ctl
from serf_tpu.obs import flight
from serf_tpu.utils import metrics
from serf_tpu.utils.files import atomic_write_text
from serf_tpu.utils.logging import get_logger

log = get_logger("faults.proc")

#: how long one agent may take from spawn to ready-file publish
READY_DEADLINE_S = 15.0
#: clock/stat sampling cadence over the control channel
SAMPLE_PERIOD_S = 0.25


def _fold_counters(metrics_snapshot: dict, out: Dict[str, float]) -> None:
    """Accumulate one agent's counter snapshot into ``out``, collapsing
    label sets (keys are ``name`` or ``name{k=v,...}``)."""
    for key, value in (metrics_snapshot.get("counters") or {}).items():
        name = key.split("{", 1)[0]
        out[name] = out.get(name, 0.0) + float(value)


@dataclass
class ProcAgent:
    """One agent incarnation's handle inside the harness."""

    index: int
    node_id: str
    directory: str
    proc: subprocess.Popen
    addr: str = ""                      # cluster "host:port" (from ready file)
    ctl_addr: str = ""
    client: Optional[ctl.ControlClient] = None
    generation: int = 0
    state: str = "starting"             # starting|alive|paused|crashed|done
    #: engine counters folded from every incarnation that got a final
    #: stats read (a SIGKILLed incarnation's counters die with it)
    blackbox_dir: str = ""


@dataclass
class ProcLoadReport:
    """Offered-load accounting for a proc run.  Offered counts only
    batches whose control response arrived (a batch lost to a crash has
    unknowable admission splits); admitted/shed are the ENGINE's own
    admission verdicts per call (OverloadError = shed), relayed in the
    load response — so accounting still cross-checks the engine's
    decisions, per op, across process boundaries."""

    events_offered: int = 0
    queries_offered: int = 0
    events_admitted: int = 0
    events_shed: int = 0
    queries_admitted: int = 0
    queries_shed: int = 0

    def to_dict(self) -> Dict[str, int]:
        return dict(self.__dict__)


@dataclass
class ProcChaosResult:
    plan: FaultPlan
    report: object                      # invariants.InvariantReport
    clock_samples: Dict[str, List[ClockSample]] = field(default_factory=dict)
    #: cluster-wide engine counters folded from final live-agent stats
    counters: Dict[str, float] = field(default_factory=dict)
    #: survivors-only (never crashed/paused) degradation counters — the
    #: SIGKILL-mid-push-pull proof reads breaker/backoff activity here
    survivor_counters: Dict[str, float] = field(default_factory=dict)
    #: node_id -> {"alive": [...], "failed": [...], "left": [...]} final views
    views: Dict[str, Dict[str, list]] = field(default_factory=dict)
    load: Optional[ProcLoadReport] = None
    quiet_convergence_s: float = 0.0
    settle_convergence_s: float = 0.0
    settle_converged: bool = True
    #: per-node blackbox bundle directories (dump-on-fail artifacts)
    blackbox_dirs: Dict[str, str] = field(default_factory=dict)
    #: pids of every process incarnation the harness ever spawned —
    #: the leak test asserts all of them are reaped after teardown
    all_pids: List[int] = field(default_factory=list)
    #: folded per-node lifecycle ledger snapshots (final poll)
    lifecycle: Dict[str, dict] = field(default_factory=dict)
    #: key-rotation evidence (``plan.encrypted`` runs): same shape as
    #: the host plane's ``HostChaosResult.rotation`` — ctl-driven op
    #: rows, list-query message-loss probes, the reconcile verdict,
    #: decrypt fallback/fail folds and per-agent keyring digests
    rotation: Optional[Dict] = None


class ProcCluster:
    """Spawns and drives N agent processes on ephemeral loopback ports.

    Also the bench harness's real-socket cluster: ``start()`` +
    ``clients`` + ``teardown()`` with the same leak-proof reaping the
    chaos executor uses."""

    def __init__(self, n: int, tmp_dir: str, profile: str = "proc",
                 options: Optional[dict] = None, seed: int = 0,
                 lifecycle_sample_n: Optional[int] = None,
                 initial_keyring: Optional[List[bytes]] = None):
        self.n = n
        self.tmp_dir = tmp_dir
        self.profile = profile
        self.options = options
        self.lifecycle_sample_n = lifecycle_sample_n
        #: encrypted clusters: every agent's generation-0 keyring file
        #: is seeded with these keys (first = primary) before spawn; a
        #: RESTART finds the file already there — possibly mutated and
        #: persisted by rotation ops — and resumes from it
        self.initial_keyring = initial_keyring
        self.rng = random.Random(seed ^ 0x9C0C)
        # serflint: ignore[async-shared-mut] -- phase ops run strictly
        # sequentially in the executor's single task; the sampler/load
        # tasks only READ live() snapshots between awaits
        self.agents: Dict[int, ProcAgent] = {}
        self.all_procs: List[subprocess.Popen] = []
        self.addr_of = None             # set once every agent is ready

    # -- spawning ------------------------------------------------------------

    def _spawn_proc(self, i: int, generation: int, bind: str,
                    join: Optional[List[str]] = None) -> ProcAgent:
        node_dir = os.path.join(self.tmp_dir, f"p{i}")
        os.makedirs(node_dir, exist_ok=True)
        ready_file = os.path.join(node_dir, f"ready.g{generation}.json")
        try:
            os.unlink(ready_file)
        except OSError:
            pass
        cfg = {
            "node_id": f"p{i}",
            "bind": bind,
            "ctl": "127.0.0.1:0",
            "join": join or [],
            "snapshot_path": os.path.join(node_dir, "serf.snap"),
            "ready_file": ready_file,
            "blackbox_dir": os.path.join(node_dir, "blackbox"),
            "profile": self.profile,
            "generation": generation,
            "options": self.options,
            "lifecycle_sample_n": self.lifecycle_sample_n,
        }
        if self.initial_keyring is not None:
            keyring_file = os.path.join(node_dir, "serf.keyring")
            cfg["keyring_file"] = keyring_file
            if not os.path.exists(keyring_file):
                # seed only when absent: a restart must load the ring
                # the dead incarnation last PERSISTED (possibly already
                # rotated), not the plan's day-zero keys
                from serf_tpu.host.keyring import SecretKeyring
                SecretKeyring(self.initial_keyring[0],
                              list(self.initial_keyring[1:])
                              ).save(keyring_file)
        config_path = os.path.join(node_dir, f"agent.g{generation}.json")
        # harness-written config is atomic (satellite): a harness crash
        # mid-write must never leave a torn config a respawn then trusts
        atomic_write_text(config_path, json.dumps(cfg, indent=1))
        log_path = os.path.join(node_dir, f"agent.g{generation}.log")
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        with open(log_path, "ab") as logf:
            proc = subprocess.Popen(
                [sys.executable, "-m", "serf_tpu.host.agent",
                 "--config", config_path],
                cwd=repo_root, env=env,
                stdout=logf, stderr=subprocess.STDOUT,
                # own process group: teardown killpg-reaps the agent AND
                # anything it ever forks, on every failure path
                start_new_session=True)
        self.all_procs.append(proc)
        metrics.incr("serf.proc.spawned", 1)
        flight.record("proc-agent", action="spawn", node=f"p{i}",
                      pid=proc.pid, generation=generation)
        agent = ProcAgent(index=i, node_id=f"p{i}", directory=node_dir,
                          proc=proc, generation=generation,
                          blackbox_dir=cfg["blackbox_dir"])
        agent._ready_file = ready_file
        return agent

    async def _wait_ready(self, agent: ProcAgent) -> None:
        deadline = time.monotonic() + READY_DEADLINE_S
        path = agent._ready_file
        while True:
            if agent.proc.poll() is not None:
                raise RuntimeError(
                    f"agent {agent.node_id} exited rc={agent.proc.returncode} "
                    f"before ready (see {agent.directory})")
            if os.path.exists(path):
                try:
                    with open(path) as f:
                        info = json.load(f)
                    break
                except (OSError, ValueError):
                    pass        # mid-rename race: retry
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"agent {agent.node_id} not ready after "
                    f"{READY_DEADLINE_S}s (see {agent.directory})")
            await asyncio.sleep(0.05)
        agent.addr = info["addr"]
        agent.ctl_addr = info["ctl"]
        agent.client = await ctl.ControlClient.connect(agent.ctl_addr)
        agent.state = "alive"

    async def start(self) -> None:
        """Spawn all agents concurrently on ephemeral ports, then join
        everyone through agent 0."""
        for i in range(self.n):
            self.agents[i] = self._spawn_proc(i, 0, "127.0.0.1:0")
        await asyncio.gather(*(self._wait_ready(a)
                               for a in self.agents.values()))
        self.addr_of = make_addr_of(
            self.n, {i: a.addr for i, a in self.agents.items()})
        seed_addr = self.agents[0].addr
        await asyncio.gather(*(
            self.agents[i].client.call("join", addrs=[seed_addr])
            for i in range(1, self.n)))

    # -- process-level faults ------------------------------------------------

    def kill(self, i: int) -> None:
        """crash lowering: SIGKILL the whole process group — no leave,
        no snapshot flush, sockets torn mid-flight."""
        a = self.agents[i]
        if a.state in ("crashed", "done"):
            return
        try:
            os.killpg(os.getpgid(a.proc.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        a.proc.wait()
        a.state = "crashed"
        if a.client is not None:
            a.client.close_nowait()
            a.client = None
        metrics.incr("serf.proc.crashed", 1)
        flight.record("proc-agent", action="kill", node=a.node_id,
                      pid=a.proc.pid)

    def pause(self, i: int) -> None:
        a = self.agents[i]
        if a.state != "alive":
            return
        os.killpg(os.getpgid(a.proc.pid), signal.SIGSTOP)
        a.state = "paused"
        metrics.incr("serf.proc.paused", 1)
        flight.record("proc-agent", action="pause", node=a.node_id,
                      pid=a.proc.pid)

    def resume(self, i: int) -> None:
        a = self.agents[i]
        if a.state != "paused":
            return
        os.killpg(os.getpgid(a.proc.pid), signal.SIGCONT)
        a.state = "alive"
        metrics.incr("serf.proc.resumed", 1)
        flight.record("proc-agent", action="resume", node=a.node_id,
                      pid=a.proc.pid)

    async def restart(self, i: int, seed_addr: Optional[str]) -> None:
        """restart lowering: re-exec against the SAME snapshot dir on the
        SAME port (generation+1); the agent's bounded bind-retry absorbs
        the dead incarnation's lingering socket, the snapshot replay
        seeds the clocks (no regression) and auto-rejoin + an explicit
        seed join pull it back into the fabric."""
        old = self.agents[i]
        gen = old.generation + 1
        agent = self._spawn_proc(i, gen, old.addr,
                                 join=[seed_addr] if seed_addr else [])
        await self._wait_ready(agent)
        self.agents[i] = agent
        metrics.incr("serf.proc.restarted", 1)
        flight.record("proc-agent", action="restart", node=agent.node_id,
                      pid=agent.proc.pid, generation=gen)

    def terminate(self, i: int) -> None:
        """graceful stop: SIGTERM → agent leaves (peers see Left) and
        flushes its snapshot before exiting."""
        a = self.agents[i]
        if a.state != "alive":
            return
        os.kill(a.proc.pid, signal.SIGTERM)
        flight.record("proc-agent", action="terminate", node=a.node_id,
                      pid=a.proc.pid)

    async def wait_exit(self, i: int, timeout: float = 10.0) -> int:
        """Await a terminated agent's actual exit (without blocking the
        loop) and retire it from the live set; returns the exit code."""
        a = self.agents[i]
        end = time.monotonic() + timeout
        while a.proc.poll() is None:
            if time.monotonic() > end:
                raise TimeoutError(f"{a.node_id} still running after "
                                   f"{timeout}s")
            await asyncio.sleep(0.05)
        if a.client is not None:
            a.client.close_nowait()
            a.client = None
        a.state = "done"
        return a.proc.returncode

    # -- queries over the control plane --------------------------------------

    def live(self) -> List[ProcAgent]:
        return [a for a in self.agents.values() if a.state == "alive"]

    async def wait_convergence(self, deadline_s: float,
                               poll_s: float = 0.1) -> bool:
        """Poll every live agent's member view until each sees every
        live agent ALIVE (the cross-process sibling of
        ``invariants.wait_host_convergence``)."""
        end = time.monotonic() + deadline_s
        while True:
            ok = await self._converged()
            if ok or time.monotonic() > end:
                return ok
            await asyncio.sleep(poll_s)

    async def _converged(self) -> bool:
        live = self.live()
        want = {a.node_id for a in live}
        for a in live:
            try:
                resp = await a.client.call("members", timeout=5.0)
            except (ConnectionError, TimeoutError, RuntimeError, OSError):
                return False
            alive = {m["id"] for m in resp["members"]
                     if m["status"] == "ALIVE"}
            if not want <= alive:
                return False
        return bool(live)

    async def views(self) -> Dict[str, Dict[str, list]]:
        out: Dict[str, Dict[str, list]] = {}
        for a in self.live():
            try:
                resp = await a.client.call("members", timeout=5.0)
            except (ConnectionError, TimeoutError, RuntimeError, OSError):
                continue
            view: Dict[str, list] = {"alive": [], "failed": [], "left": []}
            for m in resp["members"]:
                view.setdefault(m["status"].lower(), []).append(m["id"])
            out[a.node_id] = view
        return out

    async def push_rule(self, rule_dict: Optional[dict]) -> None:
        async def _push(a: ProcAgent) -> None:
            try:
                await a.client.call("chaos", rule=rule_dict, timeout=5.0)
            except (ConnectionError, TimeoutError, RuntimeError, OSError):
                log.warning("chaos push to %s failed", a.node_id)
        await asyncio.gather(*(_push(a) for a in self.live()))

    # -- teardown ------------------------------------------------------------

    def teardown(self) -> None:
        """Kill and reap EVERY process incarnation ever spawned —
        deliberately synchronous so it runs to completion even inside a
        cancelled task's ``finally`` (an abort mid-phase must not leak a
        single child).  killpg catches anything an agent forked; SIGKILL
        also kills SIGSTOPped processes (it cannot be blocked)."""
        for a in self.agents.values():
            if a.client is not None:
                a.client.close_nowait()
                a.client = None
        for proc in self.all_procs:
            if proc.poll() is None:
                try:
                    os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    try:
                        proc.kill()
                    except OSError:
                        pass
            try:
                proc.wait(timeout=5.0)
                metrics.incr("serf.proc.reaped", 1)
            except subprocess.TimeoutExpired:  # pragma: no cover — SIGKILL
                log.error("process %d survived SIGKILL reap window",
                          proc.pid)
        for a in self.agents.values():
            a.state = "done"

    def leaked_pids(self) -> List[int]:
        """Post-teardown audit: pids of spawned processes still alive
        (the abort-mid-phase test asserts this is empty)."""
        out = []
        for proc in self.all_procs:
            if proc.poll() is None:
                out.append(proc.pid)
        return out


async def run_proc_plan(plan: FaultPlan, tmp_dir: str,
                        profile: str = "proc",
                        options: Optional[dict] = None,
                        blackbox_on_fail: bool = False,
                        lifecycle_sample_n: Optional[int] = None
                        ) -> ProcChaosResult:
    """Run ``plan`` against a fresh N-process real-socket cluster and
    judge the invariants across process boundaries.

    ``tmp_dir`` hosts every per-process artifact: configs, snapshots,
    agent logs, blackbox bundle dirs.  ``options`` deep-overrides the
    agent profile (same schema as ``AgentConfig.options``).
    ``blackbox_on_fail`` asks every live agent for a black-box dump when
    the report comes back red (``tools/chaos.py --record-on-fail``)."""
    plan.validate()
    n = plan.n
    rot_base = rot_next = None
    rotation_ops: List[Dict] = []
    if plan.encrypted:
        from serf_tpu.faults.host import rotation_keys
        rot_base, rot_next = rotation_keys(plan.seed)
    cluster = ProcCluster(n, tmp_dir, profile=profile, options=options,
                          seed=plan.seed,
                          lifecycle_sample_n=lifecycle_sample_n,
                          initial_keyring=[rot_base] if plan.encrypted
                          else None)
    samples: Dict[str, List[ClockSample]] = {f"p{i}": [] for i in range(n)}
    generation = {i: 0 for i in range(n)}
    load = ProcLoadReport()
    with_load = plan.has_load()
    rng = random.Random(plan.seed ^ 0x9C0C)
    stop = asyncio.Event()
    current_phase: List[Optional[FaultPhase]] = [None]
    result = ProcChaosResult(plan=plan, report=None)

    async def sample_once() -> None:
        for a in cluster.live():
            try:
                s = await a.client.call("stats", timeout=5.0)
            except (ConnectionError, TimeoutError, RuntimeError, OSError):
                continue
            samples[a.node_id].append(ClockSample(
                mono=time.monotonic(), generation=a.generation,
                clock=int(s["member_time"]), event=int(s["event_time"]),
                query=int(s["query_time"])))

    async def sampler() -> None:
        while not stop.is_set():
            await asyncio.sleep(SAMPLE_PERIOD_S)
            await sample_once()

    async def load_gen() -> None:
        """Offer the current phase's event/query rates as batched load
        ops to random live agents (tick-sized batches; offered counts
        only batches whose response arrived)."""
        credit_e = credit_q = 0.0
        tick = 0.1
        seq = 0
        while not stop.is_set():
            await asyncio.sleep(tick)
            phase = current_phase[0]
            if phase is None or not phase.has_load():
                credit_e = credit_q = 0.0
                continue
            live = cluster.live()
            if not live:
                continue
            credit_e += phase.event_rate * tick
            credit_q += phase.query_rate * tick
            ev, credit_e = int(credit_e), credit_e - int(credit_e)
            qn, credit_q = int(credit_q), credit_q - int(credit_q)
            if not ev and not qn:
                continue
            seq += 1
            target = rng.choice(live)
            try:
                resp = await target.client.call(
                    "load", events=ev, queries=qn,
                    prefix=f"storm-{seq}", timeout=10.0)
            except (ConnectionError, TimeoutError, RuntimeError, OSError):
                continue    # batch lost to a crash: not counted as offered
            load.events_offered += ev
            load.queries_offered += qn
            load.events_admitted += resp["events_admitted"]
            load.events_shed += resp["events_shed"]
            load.queries_admitted += resp["queries_admitted"]
            load.queries_shed += resp["queries_shed"]

    async def issue_rotation(op: str, phase_name: str) -> None:
        """One phase-entry rotation op over the lowest live agent's ctl
        channel (install -> next key, use -> next key, remove -> base).
        Mirrors the host executor: the row is evidence either way."""
        from serf_tpu.host.keyring import key_digest
        row: Dict = {"phase": phase_name, "op": op}
        live = cluster.live()
        if not live:
            row["error"] = "no live agent to issue from"
            rotation_ops.append(row)
            return
        agent = min(live, key=lambda a: a.index)
        key = rot_base if op == "remove" else rot_next
        row["key"] = key_digest(key)
        try:
            resp = await agent.client.call("keys", action=op,
                                           key_b64=ctl.b64(key),
                                           timeout=30.0)
        except (ConnectionError, TimeoutError, RuntimeError, OSError) as e:
            row["error"] = repr(e)[:200]
        else:
            row.update(num_nodes=resp["num_nodes"],
                       num_resp=resp["num_resp"],
                       num_err=resp["num_err"],
                       attempts=resp["attempts"],
                       quorum_ok=resp["quorum_ok"])
            if resp.get("messages"):
                row["messages"] = dict(
                    list(resp["messages"].items())[:4])
        rotation_ops.append(row)

    async def rotation_finale() -> Dict:
        """Proc sibling of the host ``_rotation_finale``: (1) message-
        loss probes — every live agent issues a cluster-wide ``keys
        list`` query through the (possibly still mixed-key) encrypted
        fabric; a full response set proves round-trip delivery on every
        node; (2) bounded reconcile — use(next)/remove(base) off one
        agent until every ring reports the next key as sole primary;
        (3) per-agent local ring digests over ctl."""
        from serf_tpu.host.keyring import key_digest
        deadline = max(2.0, plan.settle_s)
        live = cluster.live()
        nlive = len(live)
        next_digest = key_digest(rot_next)
        base_digest = key_digest(rot_base)
        offered = sent = delivered = 0
        t0 = time.monotonic()
        for a in live:
            offered += 1
            try:
                resp = await a.client.call("keys", action="list",
                                           timeout=30.0)
            except (ConnectionError, TimeoutError, RuntimeError, OSError):
                continue
            sent += 1
            if resp["num_resp"] >= nlive:
                delivered += 1
        probes = {"offered": offered, "sent": sent,
                  "delivered": delivered, "nodes": nlive,
                  "probe_s": round(time.monotonic() - t0, 3)}
        driver = min(live, key=lambda a: a.index) if live else None
        t1 = time.monotonic()
        converged = False
        rounds = 0
        while driver is not None and time.monotonic() - t1 <= deadline:
            rounds += 1
            try:
                await driver.client.call("keys", action="use",
                                         key_b64=ctl.b64(rot_next),
                                         timeout=30.0)
                await driver.client.call("keys", action="remove",
                                         key_b64=ctl.b64(rot_base),
                                         timeout=30.0)
                lk = await driver.client.call("keys", action="list",
                                              timeout=30.0)
            except (ConnectionError, TimeoutError, RuntimeError, OSError):
                await asyncio.sleep(0.2)
                continue
            if (lk["num_resp"] >= nlive
                    and lk["primary_keys"].get(next_digest, 0) >= nlive
                    and base_digest not in lk["keys"]):
                converged = True
                break
            await asyncio.sleep(0.2)
        reconcile_s = round(time.monotonic() - t1, 3)
        keyrings = {}
        for a in cluster.live():
            try:
                d = await a.client.call("keys", action="digest",
                                        timeout=10.0)
            except (ConnectionError, TimeoutError, RuntimeError, OSError):
                continue
            keyrings[a.node_id] = d["digest"]
        metrics.gauge("serf.rotation.reconcile-s", reconcile_s)
        flight.record("key-rotation", op="finale", plan=plan.name,
                      plane="proc", converged=converged,
                      reconcile_s=reconcile_s,
                      probes_delivered=delivered, probes_offered=offered)
        return {
            "ops": rotation_ops,
            "probes": probes,
            "converged": converged,
            "reconcile_s": reconcile_s,
            "reconcile_rounds": rounds,
            "latency_s": reconcile_s,
            "expected_primary": next_digest,
            "keyrings": keyrings,
        }

    from serf_tpu.utils.tasks import spawn_logged
    sample_task = spawn_logged(sampler(), "proc-chaos-sampler")
    load_task = (spawn_logged(load_gen(), "proc-chaos-load")
                 if with_load else None)
    try:
        t0 = time.monotonic()
        await cluster.start()
        converged = await cluster.wait_convergence(plan.settle_s)
        result.quiet_convergence_s = time.monotonic() - t0
        if not converged:
            log.warning("quiet convergence not reached in %.1fs",
                        plan.settle_s)

        down: set = set()
        for pi, phase in enumerate(plan.phases):
            metrics.gauge("serf.faults.phase", pi)
            flight.record("fault-phase", plan=plan.name, phase=pi,
                          name=phase.name, plane="proc")
            # crash/pause BEFORE the rule install, mirroring the host
            # executor: the rule never references a half-dead node
            for i in phase.crash:
                cluster.kill(i)
                down.add(i)
            for i in phase.pause:
                cluster.pause(i)
                down.add(i)
            rule = compile_phase(phase, cluster.addr_of)
            rule_dict = (ctl.chaos_rule_to_dict(rule)
                         if _phase_has_net_faults(phase) else None)
            await cluster.push_rule(rule_dict)
            for i in phase.restart:
                agent = cluster.agents[i]
                if agent.state == "paused":
                    cluster.resume(i)
                elif agent.state == "crashed":
                    seeds = [a for a in cluster.live()]
                    seed_addr = (rng.choice(seeds).addr if seeds else None)
                    await cluster.restart(i, seed_addr)
                    generation[i] = cluster.agents[i].generation
                down.discard(i)
                # late joiners missed the phase-entry rule push
                back = cluster.agents[i]
                if rule_dict is not None and back.client is not None:
                    try:
                        await back.client.call("chaos", rule=rule_dict,
                                               timeout=5.0)
                    except (ConnectionError, TimeoutError, RuntimeError,
                            OSError):
                        pass
            # rotation ops at phase ENTRY, after crash/restart and under
            # the phase's installed faults (mirrors the host executor)
            for op in phase.rotate:
                await issue_rotation(op, phase.name)
            if phase.stall:
                log.info("phase %r: stall lowering note — agents run "
                         "without subscribers on the proc plane",
                         phase.name)
            current_phase[0] = phase
            await asyncio.sleep(phase.duration_s)
            current_phase[0] = None

        # heal: clear every rule, wait the settle budget, judge
        metrics.gauge("serf.faults.phase", -1)
        flight.record("fault-phase", plan=plan.name, phase=-1,
                      name="healed", plane="proc")
        await cluster.push_rule(None)
        t1 = time.monotonic()
        result.settle_converged = await cluster.wait_convergence(
            plan.settle_s)
        result.settle_convergence_s = time.monotonic() - t1
        await sample_once()

        # quiesce load BEFORE the final artifact fold so no batch is in
        # flight between the offered tally and the engine's verdicts
        stop.set()
        for t in (sample_task, load_task):
            if t is not None:
                t.cancel()
                try:
                    await t
                except (asyncio.CancelledError, Exception):  # noqa: BLE001
                    pass

        result.views = await cluster.views()
        # every incarnation ever spawned, restarts included — the leak
        # test asserts each of these is reaped after teardown
        result.all_pids = [p.pid for p in cluster.all_procs]
        # rotation finale BEFORE the stats fold, so the fold's decrypt
        # counters include the probe/reconcile traffic.  Encrypted
        # plans without rotate ops skip it (rings never leave the base
        # key — "converge to K2" would wait out the deadline and judge
        # red), matching the host executor
        if plan.encrypted and plan.has_rotation():
            result.rotation = await rotation_finale()
        crashed_or_paused = {f"p{i}" for i in plan.ever_down()}
        for a in cluster.live():
            try:
                s = await a.client.call("stats", timeout=5.0)
                lc = await a.client.call("lifecycle", timeout=5.0)
            except (ConnectionError, TimeoutError, RuntimeError, OSError):
                continue
            _fold_counters(s["metrics"], result.counters)
            if a.node_id not in crashed_or_paused:
                _fold_counters(s["metrics"], result.survivor_counters)
            result.lifecycle[a.node_id] = lc["lifecycle"]
            result.blackbox_dirs[a.node_id] = a.blackbox_dir

        from serf_tpu.faults import invariants as inv
        result.load = load if with_load else None
        if result.rotation is not None:
            # decrypt fallback/fail evidence: folded engine counters
            # from every live agent's final stats (fresh processes —
            # no baseline subtraction needed)
            result.rotation["decrypt_fallback"] = int(
                result.counters.get("serf.keyring.decrypt_fallback", 0))
            result.rotation["decrypt_fail"] = int(
                result.counters.get("serf.keyring.decrypt_fail", 0))
        result.report = inv.check_proc(
            plan, result.views, samples, generation,
            survivor_counters=result.survivor_counters,
            folded_counters=result.counters,
            load=result.load,
            settle_converged=result.settle_converged,
            rotation=result.rotation)
        result.clock_samples = samples

        if blackbox_on_fail and not result.report.ok:
            for a in cluster.live():
                try:
                    await a.client.call("blackbox", reason="invariant-red",
                                        detail=plan.name, timeout=10.0)
                except (ConnectionError, TimeoutError, RuntimeError,
                        OSError):
                    pass
        return result
    finally:
        stop.set()
        for t in (sample_task, load_task):
            if t is not None:
                t.cancel()
        # synchronous killpg-reap on EVERY path — including cancellation,
        # where further awaits in this finally could be re-cancelled
        cluster.teardown()
        leaked = cluster.leaked_pids()
        if leaked:  # pragma: no cover — SIGKILL reap failure
            log.error("leaked processes after teardown: %s", leaked)


def _phase_has_net_faults(phase: FaultPhase) -> bool:
    return bool(phase.partitions or phase.edges or phase.drop
                or phase.corrupt or phase.duplicate or phase.reorder
                or phase.delay or phase.jitter)
