"""FaultPlan: one seeded, declarative chaos schedule for BOTH planes.

A plan is a timeline of :class:`FaultPhase` entries.  Each phase can
partition the cluster into groups, impose per-edge or global
drop/delay/duplicate/reorder rates, corrupt payloads (bit flips), and
crash/pause/restart nodes.  The SAME plan object drives:

- the host plane (``faults.host``): phases run for ``duration_s`` wall
  seconds against a ``LoopbackNetwork`` cluster (or wrapped real
  transports), compiled to :class:`serf_tpu.host.transport.ChaosRule`;
- the device plane (``faults.device``): phases run for ``rounds``
  protocol rounds, lowered to per-round group/drop/liveness masks
  consumed by ``models/swim.cluster_round`` inside the scan.

Node references are integer indices ``0..n-1`` on both planes; the host
runner maps index ``i`` to cluster node ``n{i}``.  Everything is seeded
(``FaultPlan.seed``) so a chaos run is reproducible end to end —
Jepsen-style schedules, not dice rolls (PAPERS.md: Lifeguard;
SNIPPETS/Jepsen discipline).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Sequence, Tuple


@dataclass(frozen=True)
class EdgeFault:
    """Fault rates on the directed edge ``src -> dst`` (indices).
    ``bidirectional=True`` mirrors the rates onto ``dst -> src``."""

    src: int
    dst: int
    drop: float = 0.0        # 1.0 = blackhole (also refuses stream dials)
    delay: float = 0.0       # seconds, host plane only
    duplicate: float = 0.0
    reorder: float = 0.0
    corrupt: float = 0.0
    bidirectional: bool = False


@dataclass(frozen=True)
class FaultPhase:
    """One segment of the chaos timeline.

    ``partitions``: groups of node indices; nodes in different groups
    cannot communicate.  Nodes not listed in any group form one implicit
    extra group together (consistent across planes).  Empty = no
    partition.  ``crash``/``pause`` take nodes down at phase entry
    (crash = process death: the host runner shuts the Serf down; pause =
    network silence, process alive); ``restart`` brings previously
    crashed/paused nodes back.  Down-ness persists across phases until
    restarted.

    LOAD phases (ISSUE 5 — overload scenarios through the same plan):
    ``event_rate``/``query_rate`` are OFFERED user-plane load in ops/sec
    (aggregate across the cluster).  The host executor fires real
    ``user_event``/``query`` calls at that rate from random live nodes,
    counting offered/admitted/shed so the accounting invariant
    (admitted + shed == offered) can be judged.  The device executor
    lowers ``ceil((event_rate + query_rate) * duration_s)`` extra fact
    injections into the phase (query fan-out rides the same
    dissemination plane on device — an explicit lowering, noted on the
    schedule).  ``stall`` names nodes whose event CONSUMER stops reading
    for the phase (slow-subscriber overload; host-plane only — the
    device model has no subscriber seam, noted on the schedule).
    """

    name: str = ""
    duration_s: float = 0.5          # host-plane phase length
    rounds: int = 8                  # device-plane phase length
    partitions: Tuple[Sequence[int], ...] = ()
    drop: float = 0.0                # global per-packet loss
    delay: float = 0.0               # host: fixed extra latency
    jitter: float = 0.0              # host: uniform extra latency
    duplicate: float = 0.0
    reorder: float = 0.0
    corrupt: float = 0.0             # per-packet bit-flip probability
    edges: Tuple[EdgeFault, ...] = ()
    crash: Tuple[int, ...] = ()
    pause: Tuple[int, ...] = ()
    restart: Tuple[int, ...] = ()
    event_rate: float = 0.0          # offered user events/sec (cluster)
    query_rate: float = 0.0          # offered queries/sec (cluster)
    stall: Tuple[int, ...] = ()      # event consumers stalled this phase
    #: key-rotation ops issued at phase ENTRY (ISSUE 20), in order, by
    #: the lowest-index live node: "install" (new key everywhere),
    #: "use" (new key becomes primary), "remove" (old key retired).
    #: Requires FaultPlan.encrypted.  The device executor ignores these
    #: (no crypto plane in the simulation — a lowering note records it).
    rotate: Tuple[str, ...] = ()

    def has_load(self) -> bool:
        return (self.event_rate > 0 or self.query_rate > 0
                or bool(self.stall))

    def validate(self, n: int) -> None:
        if self.duration_s < 0 or self.rounds < 0:
            raise ValueError(f"phase {self.name!r}: negative length")
        if self.event_rate < 0 or self.query_rate < 0:
            raise ValueError(f"phase {self.name!r}: negative load rate")
        for rate in (self.drop, self.duplicate, self.reorder, self.corrupt):
            if not 0.0 <= rate <= 1.0:
                raise ValueError(
                    f"phase {self.name!r}: rate {rate} outside [0, 1]")
        seen: set = set()
        for g in self.partitions:
            for node in g:
                if not 0 <= node < n:
                    raise ValueError(
                        f"phase {self.name!r}: node {node} outside 0..{n - 1}")
                if node in seen:
                    raise ValueError(
                        f"phase {self.name!r}: node {node} in two groups")
                seen.add(node)
        for nodes in (self.crash, self.pause, self.restart, self.stall):
            for node in nodes:
                if not 0 <= node < n:
                    raise ValueError(
                        f"phase {self.name!r}: node {node} outside 0..{n - 1}")
        for e in self.edges:
            if not (0 <= e.src < n and 0 <= e.dst < n):
                raise ValueError(
                    f"phase {self.name!r}: edge ({e.src},{e.dst}) "
                    f"outside 0..{n - 1}")
        for op in self.rotate:
            if op not in ("install", "use", "remove"):
                raise ValueError(
                    f"phase {self.name!r}: unknown rotation op {op!r} "
                    "(install/use/remove)")


@dataclass(frozen=True)
class FaultPlan:
    """A named, seeded chaos schedule over ``n`` nodes."""

    name: str
    n: int
    phases: Tuple[FaultPhase, ...]
    seed: int = 0
    #: settle budget after the last phase: host seconds / device rounds
    #: the cluster gets to re-converge before invariants are judged
    settle_s: float = 8.0
    settle_rounds: int = 40
    #: encrypted transport (ISSUE 20): the host/proc executors stand the
    #: cluster up with a shared keyring (keys derived from ``seed``) and
    #: judge the keyring-divergence / no-message-loss-mid-rotation
    #: invariants + the rotation-latency SLO after the run
    encrypted: bool = False

    def validate(self) -> None:
        if self.n < 2:
            raise ValueError("a chaos plan needs at least 2 nodes")
        if not self.phases:
            raise ValueError("a chaos plan needs at least one phase")
        for ph in self.phases:
            ph.validate(self.n)
        if self.has_rotation() and not self.encrypted:
            raise ValueError(
                f"plan {self.name!r} rotates keys but is not encrypted "
                "(set encrypted=True)")
        down: set = set()
        for ph in self.phases:
            down |= set(ph.crash) | set(ph.pause)
            down -= set(ph.restart)
        if down:
            # invariants judge post-heal convergence of RESPONSIVE nodes;
            # a plan that ends with nodes still down is judging a cluster
            # that is legitimately still degraded
            raise ValueError(
                f"plan {self.name!r} ends with nodes still down: "
                f"{sorted(down)} (add them to a later phase's restart)")

    def total_rounds(self) -> int:
        return sum(ph.rounds for ph in self.phases)

    def has_load(self) -> bool:
        """Any phase offers user-plane load (the executors then track
        overload accounting and the checker judges the overload
        invariants)."""
        return any(ph.has_load() for ph in self.phases)

    def offered_rate(self) -> float:
        """Peak offered ops/sec across phases (admission sizing aid)."""
        return max((ph.event_rate + ph.query_rate for ph in self.phases),
                   default=0.0)

    def has_rotation(self) -> bool:
        """Any phase issues key-rotation ops (the executors then drive
        the rotation protocol and collect the rotation evidence)."""
        return any(ph.rotate for ph in self.phases)

    def ever_down(self) -> frozenset:
        """Nodes the plan crashes or pauses at any point — exempt from
        the no-false-DEAD invariant while they were genuinely down."""
        out: set = set()
        for ph in self.phases:
            out |= set(ph.crash) | set(ph.pause)
        return frozenset(out)


# ---------------------------------------------------------------------------
# named plans (tools/chaos.py and the tier-1 acceptance tests run these)
# ---------------------------------------------------------------------------


def _partition_heal_loss(n: int = 6) -> FaultPlan:
    """THE acceptance scenario (ISSUE 4): bisect the cluster, keep 5%
    loss on every edge, heal, and require full re-convergence with zero
    false deaths among responsive nodes."""
    half = n // 2
    # phases share one round count (and settle is a multiple of it) so
    # the device executor's phase scan compiles exactly ONCE per run
    return FaultPlan(
        name="partition-heal-loss",
        n=n,
        seed=7,
        phases=(
            FaultPhase(name="warm", duration_s=0.6, rounds=12),
            FaultPhase(name="bisect+loss", duration_s=1.0, rounds=12,
                       partitions=(tuple(range(half)),
                                   tuple(range(half, n))),
                       drop=0.05),
            FaultPhase(name="heal+loss", duration_s=0.8, rounds=12,
                       drop=0.05),
        ),
        settle_s=10.0,
        settle_rounds=48,
    )


def _crash_restart(n: int = 5) -> FaultPlan:
    """Kill one node mid-run (no leave), then restart it: exercises
    snapshot crash-restart rejoin + refutation of its death story."""
    return FaultPlan(
        name="crash-restart",
        n=n,
        seed=11,
        phases=(
            FaultPhase(name="warm", duration_s=0.6, rounds=12),
            FaultPhase(name="crash", duration_s=1.0, rounds=12,
                       crash=(n - 1,)),
            FaultPhase(name="restart", duration_s=0.8, rounds=12,
                       restart=(n - 1,)),
        ),
        settle_s=10.0,
        settle_rounds=48,
    )


def _flaky_edges(n: int = 5) -> FaultPlan:
    """Asymmetric edge faults + duplication/reorder/corruption: the
    graceful-degradation gauntlet (every packet effect at once)."""
    return FaultPlan(
        name="flaky-edges",
        n=n,
        seed=13,
        phases=(
            FaultPhase(name="warm", duration_s=0.5, rounds=12),
            FaultPhase(name="flaky", duration_s=1.2, rounds=12,
                       drop=0.05, duplicate=0.05, reorder=0.10,
                       corrupt=0.02, jitter=0.002,
                       edges=(EdgeFault(src=0, dst=1, drop=0.5),
                              EdgeFault(src=2, dst=3, drop=1.0,
                                        bidirectional=True))),
        ),
        settle_s=8.0,
        settle_rounds=48,
    )


def _query_storm(n: int = 5) -> FaultPlan:
    """THE overload acceptance scenario (ISSUE 5): a 10x event + query
    stampede against admission-controlled nodes.  The storm phase offers
    far more user-plane load than the admission buckets allow, so shed
    counters MUST be nonzero and must fully account for the offered load
    (ingress admitted + shed == offered); every buffer stays under its
    byte/depth bound for the whole run, and post-storm membership
    convergence stays within 2x of the quiet baseline."""
    return FaultPlan(
        name="query-storm",
        n=n,
        seed=17,
        phases=(
            FaultPhase(name="warm", duration_s=0.6, rounds=12),
            FaultPhase(name="storm", duration_s=1.2, rounds=12,
                       event_rate=500.0, query_rate=300.0),
            FaultPhase(name="recover", duration_s=0.6, rounds=12),
        ),
        settle_s=10.0,
        settle_rounds=48,
    )


def _slow_consumer(n: int = 4) -> FaultPlan:
    """Slow-subscriber overload: one node's event consumer stalls while
    events keep flowing — memory must stay bounded (tee backpressure +
    inbox shedding) and the stalled node must catch up after the phase."""
    return FaultPlan(
        name="slow-consumer",
        n=n,
        seed=19,
        phases=(
            FaultPhase(name="warm", duration_s=0.5, rounds=12),
            FaultPhase(name="stall", duration_s=1.0, rounds=12,
                       event_rate=200.0, stall=(1,)),
            FaultPhase(name="drain", duration_s=0.6, rounds=12),
        ),
        settle_s=8.0,
        settle_rounds=48,
    )


def _control_loss_converge(n: int = 8) -> FaultPlan:
    """Adaptive-control acceptance #1 (ISSUE 11): a long heavy-loss
    window strands facts past their transmit window at a conservative
    static fan-out — with anti-entropy off, their coverage freezes below
    1.0 and the convergence-settle SLO breaches no matter how long the
    (fault-free) settle runs.  The controller's agreement law widens
    fan-out IN-FLIGHT (convergence-settle burning → widen fanout, the
    Lifeguard philosophy cluster-wide), facts disseminate inside their
    window, and the same plan re-converges to all-green.  A/B via
    ``tools/chaos.py --plan control-loss-converge --controller ab``
    (config profiles: serf_tpu/control/profiles.py)."""
    return FaultPlan(
        name="control-loss-converge",
        n=n,
        seed=23,
        phases=(
            FaultPhase(name="warm", duration_s=0.5, rounds=12),
            FaultPhase(name="loss1", duration_s=0.8, rounds=12, drop=0.55),
            FaultPhase(name="loss2", duration_s=0.8, rounds=12, drop=0.55),
            FaultPhase(name="loss3", duration_s=0.8, rounds=12, drop=0.55),
        ),
        settle_s=8.0,
        settle_rounds=24,
    )


def _control_overload_shed(n: int = 6) -> FaultPlan:
    """Adaptive-control acceptance #2 (ISSUE 11): repeated injection
    storms far past ring capacity.  Static configs accept everything and
    clobber nearly all of it mid-flight (device shed-ratio breaches; on
    the host plane the static admission buckets shed >95% of offered
    load — breach).  The controller's overflow law tightens the device
    injection budget (admit what can finish disseminating, shed the
    rest up front) and the host controller widens the admission buckets
    while node health holds — both planes re-converge to all-green."""
    return FaultPlan(
        name="control-overload-shed",
        n=n,
        seed=29,
        phases=(
            FaultPhase(name="warm", duration_s=0.5, rounds=12),
            FaultPhase(name="burst1", duration_s=1.0, rounds=12,
                       event_rate=900.0),
            FaultPhase(name="burst2", duration_s=1.0, rounds=12,
                       event_rate=900.0),
            FaultPhase(name="burst3", duration_s=1.0, rounds=12,
                       event_rate=900.0),
        ),
        settle_s=8.0,
        settle_rounds=24,
    )


def _rotate_under_churn(n: int = 5) -> FaultPlan:
    """Key-rotation acceptance #1 (ISSUE 20): install→use→remove while
    nodes crash and restart under live event load.  Every restarted node
    reloads its snapshotted keyring, and each restart phase re-issues
    "use" so a node that missed the switch catches up BEFORE the old key
    is removed — the plan must never retire a key some live node still
    encrypts with (that would be a standing crypto split, not chaos)."""
    return FaultPlan(
        name="rotate-under-churn",
        n=n,
        seed=31,
        encrypted=True,
        phases=(
            FaultPhase(name="warm+install", duration_s=0.6, rounds=12,
                       rotate=("install",)),
            FaultPhase(name="use+crash", duration_s=1.0, rounds=12,
                       crash=(n - 1,), rotate=("use",), event_rate=80.0),
            FaultPhase(name="churn", duration_s=1.0, rounds=12,
                       restart=(n - 1,), crash=(n - 2,), rotate=("use",),
                       event_rate=80.0),
            FaultPhase(name="recover", duration_s=0.8, rounds=12,
                       restart=(n - 2,), rotate=("use",)),
            FaultPhase(name="retire-old", duration_s=0.6, rounds=12,
                       rotate=("remove",)),
        ),
        settle_s=10.0,
        settle_rounds=48,
    )


def _rotate_under_partition(n: int = 6) -> FaultPlan:
    """Key-rotation acceptance #2 (THE ISSUE-20 acceptance plan):
    "use" fires while the cluster is bisected, so one side switches
    primaries and the other keeps encrypting with the old key.  The heal
    phase deliberately issues NO catch-up op — the mixed-primary window
    is genuine, and cross-group delivery must ride the decrypt fallback
    (counted, transient).  The post-heal reconcile (executor finale)
    converges everyone to the new primary and retires the old key; the
    keyring-divergence invariant and the rotation-latency SLO judge it."""
    half = n // 2
    return FaultPlan(
        name="rotate-under-partition",
        n=n,
        seed=37,
        encrypted=True,
        phases=(
            FaultPhase(name="warm+install", duration_s=0.6, rounds=12,
                       rotate=("install",)),
            FaultPhase(name="bisect+use", duration_s=1.0, rounds=12,
                       partitions=(tuple(range(half)),
                                   tuple(range(half, n))),
                       rotate=("use",), event_rate=60.0),
            FaultPhase(name="mixed-heal", duration_s=0.8, rounds=12,
                       event_rate=60.0),
        ),
        settle_s=10.0,
        settle_rounds=48,
    )


def _rotate_crash_restart(n: int = 5) -> FaultPlan:
    """Key-rotation acceptance #3 (ISSUE 20): a node dies AT the "use"
    switch (proc plane: real SIGKILL mid-rotation), restarts from its
    snapshotted keyring — which may predate the switch — and must catch
    up via the re-issued "use" before the old key is retired."""
    return FaultPlan(
        name="rotate-crash-restart",
        n=n,
        seed=41,
        encrypted=True,
        phases=(
            FaultPhase(name="warm+install", duration_s=0.6, rounds=12,
                       rotate=("install",)),
            FaultPhase(name="kill-mid-rotation", duration_s=1.0, rounds=12,
                       crash=(n - 1,), rotate=("use",), event_rate=60.0),
            FaultPhase(name="restart-from-keyring", duration_s=0.8,
                       rounds=12, restart=(n - 1,), rotate=("use",)),
            FaultPhase(name="retire-old", duration_s=0.6, rounds=12,
                       rotate=("remove",)),
        ),
        settle_s=10.0,
        settle_rounds=48,
    )


def _self_check(n: int = 4) -> FaultPlan:
    """Tiny fast plan for ``tools/chaos.py --self-check`` (tier-1)."""
    return FaultPlan(
        name="self-check",
        n=n,
        seed=3,
        phases=(
            FaultPhase(name="warm", duration_s=0.4, rounds=10),
            FaultPhase(name="split", duration_s=0.6, rounds=10,
                       partitions=((0, 1), (2, 3)), drop=0.05),
        ),
        settle_s=8.0,
        settle_rounds=40,
    )


_PLANS: Dict[str, object] = {
    "partition-heal-loss": _partition_heal_loss,
    "crash-restart": _crash_restart,
    "flaky-edges": _flaky_edges,
    "query-storm": _query_storm,
    "slow-consumer": _slow_consumer,
    "self-check": _self_check,
    "control-loss-converge": _control_loss_converge,
    "control-overload-shed": _control_overload_shed,
    "rotate-under-churn": _rotate_under_churn,
    "rotate-under-partition": _rotate_under_partition,
    "rotate-crash-restart": _rotate_crash_restart,
}


def named_plan(name: str, n: int = 0) -> FaultPlan:
    """Look up a built-in plan by name (optionally resized to ``n``)."""
    try:
        factory = _PLANS[name]
    except KeyError:
        raise KeyError(
            f"unknown plan {name!r}; have {sorted(_PLANS)}") from None
    plan = factory(n) if n else factory()
    plan.validate()
    return plan


def plan_names() -> Tuple[str, ...]:
    return tuple(sorted(_PLANS))
