"""Operator views of the device-plane cluster: the Stats analog and the
host-tags → device-tag-plane bridge.

- ``cluster_stats`` mirrors the reference's ``Serf::stats()`` snapshot
  (serf-core/src/serf/api.rs:586-602) as one jit-able device reduction:
  member counts by believed status, queue depth (facts with live transmit
  budget), and the protocol clock maxima.
- ``TagInterner`` turns host-plane string tags (``types/tags.py``) into the
  i32 tag plane the device query engine filters on (``models/query.py``
  ``tag_filter_mask``): regex/equality filters over interned values.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional, Sequence

import jax.numpy as jnp

from serf_tpu.models.dissemination import (
    GossipConfig,
    GossipState,
    K_DEAD,
    K_JOIN,
    K_LEAVE,
    K_QUERY,
    K_SUSPECT,
    budgets_of,
    K_USER_EVENT,
)


class ClusterStats(NamedTuple):
    """Device-side operator snapshot; every field is a 0-d device scalar
    (one ``jax.device_get(stats)`` ships the whole thing)."""

    members: jnp.ndarray          # i32 alive nodes (ground truth)
    failed: jnp.ndarray           # i32 dead nodes
    suspected: jnp.ndarray        # i32 subjects with a live suspicion fact
    declared_dead: jnp.ndarray    # i32 subjects with a live dead fact OR a
                                  # durable tombstone record (the member
                                  # table's FAILED entries persist in the
                                  # reference Stats after the broadcast
                                  # queue drains)
    leaving: jnp.ndarray          # i32 subjects with a live leave intent
    queue_depth: jnp.ndarray      # i32 facts still holding transmit budget
    intent_facts: jnp.ndarray     # i32 live join/leave intent facts
    event_facts: jnp.ndarray      # i32 live user-event facts
    query_facts: jnp.ndarray      # i32 live query facts
    max_ltime: jnp.ndarray        # u32 highest fact lamport time
    round: jnp.ndarray            # i32 protocol round (the Epoch)


def _count_kind(state: GossipState, kind: int) -> jnp.ndarray:
    return jnp.sum((state.facts.kind == kind)
                   & state.facts.valid).astype(jnp.int32)


def _subjects_with_kind(state: GossipState, n: int, kind: int,
                        also=None) -> jnp.ndarray:
    """``also``: optional bool[N] of subjects that count regardless of
    live ring facts (the tombstone plane for K_DEAD)."""
    mask = (state.facts.kind == kind) & state.facts.valid
    subj = jnp.clip(state.facts.subject, 0)
    hit = jnp.zeros((n,), bool).at[subj].max(mask)
    if also is not None:
        hit = hit | also
    return jnp.sum(hit).astype(jnp.int32)


def cluster_stats(state: GossipState, cfg: GossipConfig) -> ClusterStats:
    """One reduction pass; call under jit and ``device_get`` the result."""
    n = cfg.n
    return ClusterStats(
        members=jnp.sum(state.alive).astype(jnp.int32),
        failed=jnp.sum(~state.alive).astype(jnp.int32),
        suspected=_subjects_with_kind(state, n, K_SUSPECT),
        declared_dead=_subjects_with_kind(state, n, K_DEAD,
                                          also=state.tombstone),
        leaving=_subjects_with_kind(state, n, K_LEAVE),
        queue_depth=jnp.sum(
            jnp.any(budgets_of(state, cfg) > 0, axis=0)
            & state.facts.valid).astype(jnp.int32),
        intent_facts=_count_kind(state, K_JOIN) + _count_kind(state, K_LEAVE),
        event_facts=_count_kind(state, K_USER_EVENT),
        query_facts=_count_kind(state, K_QUERY),
        max_ltime=jnp.max(jnp.where(state.facts.valid, state.facts.ltime,
                                    jnp.uint32(0))),
        round=state.round,
    )


class TagInterner:
    """Host-side bridge from string tags to the device tag plane.

    The reference filters responders with ``Filter::Tag(tag, regex)``
    (serf-core/src/types/filter.rs); the device plane filters with integer
    equality masks over an i32[N, T] plane (``tag_filter_mask``).  The
    interner fixes the tag-key columns and interns values; a regex filter
    compiles to the set of interned values it matches — an OR of equality
    masks.

    0 is reserved for "tag absent".
    """

    ABSENT = 0

    def __init__(self, keys: Sequence[str]):
        self.keys: List[str] = list(keys)
        self._key_idx: Dict[str, int] = {k: i for i, k in enumerate(self.keys)}
        self._values: Dict[str, int] = {}

    @property
    def num_keys(self) -> int:
        return len(self.keys)

    def intern(self, value: str) -> int:
        vid = self._values.get(value)
        if vid is None:
            vid = len(self._values) + 1   # 0 = absent
            self._values[value] = vid
        return vid

    def plane(self, node_tags: Sequence[Optional[Dict[str, str]]]) -> jnp.ndarray:
        """i32[N, T] tag plane from per-node tag mappings (None = no tags)."""
        import numpy as np

        n = len(node_tags)
        out = np.zeros((n, self.num_keys), np.int32)
        for i, tags in enumerate(node_tags):
            if not tags:
                continue
            for k, v in tags.items():
                col = self._key_idx.get(k)
                if col is not None:
                    out[i, col] = self.intern(v)
        return jnp.asarray(out)

    def filter_values(self, key: str, pattern: str) -> List[int]:
        """Interned values matching a reference-style tag regex — the set a
        ``TagFilter(key, pattern)`` would accept (regex alternation becomes
        an OR of equality masks on device)."""
        import re

        rx = re.compile(pattern)
        return [vid for v, vid in self._values.items() if rx.search(v)]

    def filter_mask(self, tag_plane: jnp.ndarray, key: str,
                    pattern: str) -> jnp.ndarray:
        """bool[N] eligibility mask for a (key, regex) tag filter: one
        membership test over the matched value set."""
        col = self._key_idx.get(key)
        if col is None:
            return jnp.zeros((tag_plane.shape[0],), bool)
        vals = self.filter_values(key, pattern)
        if not vals:
            return jnp.zeros((tag_plane.shape[0],), bool)
        return jnp.isin(tag_plane[:, col], jnp.asarray(vals, jnp.int32))
