"""Device plane: the cluster simulation as HBM-resident arrays.

- ``dissemination`` — fact-ring gossip (pull kernel + exact push/MXU mode)
- ``failure`` — probe/suspect/refute/declare failure detection
- ``antientropy`` — push/pull full sync, partition/heal
- ``vivaldi`` — vectorized network coordinates
- ``membership`` — serf intent views (Lamport merge semilattice)
- ``swim`` — the composed flagship cluster round
- ``events`` — device→host event-delta streaming
- ``checkpoint`` — bit-exact state save/restore
- ``query`` — scatter/filter/gather query engine + conflict majority vote
- ``churn`` — Poisson leave/fail/rejoin processes with ground-truth traces
- ``views`` — operator stats snapshot + string-tags→tag-plane bridge
- ``accounting`` — HBM/ICI bytes-per-round models (the tracked perf budget)
"""

from serf_tpu.models.swim import (
    ClusterConfig,
    ClusterState,
    cluster_round,
    flagship_config,
    make_cluster,
    run_cluster,
    run_cluster_sustained,
)
from serf_tpu.models.dissemination import (
    GossipConfig,
    GossipState,
    inject_fact,
    make_state,
    round_step,
    run_rounds,
)
from serf_tpu.models.failure import FailureConfig, run_swim, swim_round
from serf_tpu.models.churn import ChurnConfig, churn_round, run_cluster_churn
from serf_tpu.models.query import (
    QueryConfig,
    QueryState,
    launch_query,
    make_queries,
    majority_vote,
    query_round,
)
from serf_tpu.models.views import ClusterStats, TagInterner, cluster_stats

__all__ = [
    "ClusterConfig", "ClusterState", "cluster_round", "make_cluster",
    "run_cluster", "GossipConfig", "GossipState", "inject_fact",
    "make_state", "round_step", "run_rounds", "FailureConfig",
    "run_swim", "swim_round", "QueryConfig", "QueryState", "launch_query",
    "make_queries", "majority_vote", "query_round", "ChurnConfig",
    "churn_round", "run_cluster_churn", "ClusterStats", "TagInterner",
    "cluster_stats",
]
