"""The flagship device-plane model: a full SWIM/serf cluster simulation.

Composes the four device-plane subsystems into one jit-able round function —
the "one model running end-to-end" of SURVEY.md §7:

- dissemination (fact gossip with transmit-limited budgets)
- failure detection (probe/suspect/refute/declare)
- anti-entropy push/pull (periodic full sync; partition/heal aware)
- Vivaldi coordinates (co-trained on the same peer samples)

The whole cluster state is one pytree of HBM-resident arrays; a simulation
is ``lax.scan`` over ``cluster_round``; multi-chip runs shard every N-major
array over the device mesh (see ``serf_tpu.parallel``).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from serf_tpu.models.antientropy import push_pull_round
from serf_tpu.models.dissemination import (
    GossipConfig,
    GossipState,
    K_USER_EVENT,
    inject_facts_batch,
    ltime_window_violation,
    make_state,
    rolled_rows,
    round_step,
    sample_offsets,
    unpack_bits,
)
from serf_tpu.models.failure import (
    FailureConfig,
    K_DEAD,
    K_SUSPECT,
    _facts_about,
    believed_subjects,
    believer_counts,
    declare_round,
    live_suspicions,
    probe_round,
    refute_round,
    subject_incarnations,
)
from serf_tpu.models.vivaldi import (
    VivaldiConfig,
    VivaldiState,
    ground_truth_rtt,
    ground_truth_rtt_rolled,
    make_vivaldi,
    vivaldi_update,
)
from serf_tpu.control.device import (
    KNOB_FANOUT,
    KNOB_PROBE_MULT,
    KNOB_STAMP_UNIT,
    KNOB_STRETCH_Q,
    ControlConfig,
    ControlSignals,
    ControlState,
    control_step,
    gate_injections,
    make_control,
)


@dataclasses.dataclass(frozen=True)
class ClusterConfig:
    gossip: GossipConfig
    failure: FailureConfig = FailureConfig()
    vivaldi: VivaldiConfig = VivaldiConfig()
    #: the adaptive control plane (serf_tpu.control.device): with
    #: ``control.enabled`` the controller-writable knob subset —
    #: effective fanout, probe-cadence multiplier, suspicion stretch,
    #: injection admission budget — lives as traced ControlState leaves
    #: updated inside the scan from the per-round telemetry row.
    #: Disabled (default): the control leaves ride the pytree untouched
    #: and every round is bit-exact with the static path.
    control: ControlConfig = ControlConfig()
    push_pull_every: int = 0       # rounds between anti-entropy syncs; 0=off
    #: gossip rounds per probe (and per Vivaldi update, which rides probe
    #: acks in the reference).  1 = probe every round (the conservative
    #: default every detection test assumes).  The reference LAN profile
    #: is gossip_interval=200ms / probe_interval=1s — i.e. probe_every=5
    #: is the FAITHFUL cadence mapping; suspicion windows stay measured
    #: in gossip rounds either way.  refute stays every round (driven by
    #: gossiped facts, and its could-still-act gate makes it free when
    #: idle); declare rides the probe cadence — its expiry scan re-reads
    #: the whole stamp plane (the detection regime's biggest read,
    #: accounting.py), and the reference's suspicion timers are likewise
    #: checked on the probe/reap cadence, not per gossip tick.  A
    #: declaration can land up to probe_every-1 rounds late; the
    #: suspicion window itself is unchanged.
    probe_every: int = 1
    with_failure: bool = True
    with_vivaldi: bool = True
    #: ICI schedule of the sharded exchange leg when ``cluster_round``
    #: runs with a mesh ("ring" | "allgather"; ignored unsharded).
    #: Default ring: at flagship scale the block is far past the
    #: dispatch-latency crossover, so the all-gather's full-plane
    #: materialization (extra HBM round-trip + D× peak memory) costs
    #: more than the ring's D-1 overlapped neighbor hops — the decision
    #: rule and both schedules' per-chip bytes live in
    #: ``accounting.ici_round_traffic``.
    exchange_schedule: str = "ring"

    def __post_init__(self):
        if self.probe_every < 1:
            # no "0 = off" convention here (unlike push_pull_every):
            # disabling probing entirely is with_failure=False
            raise ValueError(
                f"probe_every must be >= 1, got {self.probe_every} "
                f"(use with_failure=False to disable probing)")
        from serf_tpu.parallel.ring import EXCHANGE_SCHEDULES
        if self.exchange_schedule not in EXCHANGE_SCHEDULES:
            raise ValueError(
                f"unknown exchange_schedule {self.exchange_schedule!r} "
                f"(one of {EXCHANGE_SCHEDULES})")

    @property
    def n(self) -> int:
        return self.gossip.n


class ClusterState(NamedTuple):
    gossip: GossipState
    vivaldi: VivaldiState
    positions: jnp.ndarray   # f32[N, P] hidden latency-space ground truth
    group: jnp.ndarray       # i32[N] partition group (all zeros = healed)
    control: ControlState = None  # type: ignore[assignment]
                             # adaptive-control knobs/streaks/ledgers
                             # (serf_tpu.control.device) — ALWAYS a real
                             # ControlState after make_cluster; read only
                             # when cfg.control.enabled (inert leaves
                             # otherwise — pinned bit-exact by
                             # tests/test_control.py)


def flagship_config(n: int, k_facts: int = 64) -> ClusterConfig:
    """The flagship configuration — the ONE definition of the workload
    bench.py measures, the accounting model budgets, and the tests pin.
    rotation sampling + round-robin probes (no 1M-row random gathers),
    probe_every=5 = the reference LAN profile's gossip:probe cadence
    (200 ms : 1 s), push/pull anti-entropy every 16 rounds."""
    return ClusterConfig(
        gossip=GossipConfig(n=n, k_facts=k_facts,
                            peer_sampling="rotation"),
        failure=FailureConfig(suspicion_rounds=12, max_new_facts=8,
                              probe_schedule="round_robin"),
        push_pull_every=16, probe_every=5,
        with_failure=True, with_vivaldi=True)


def make_cluster(cfg: ClusterConfig, key: jax.Array) -> ClusterState:
    n = cfg.n
    positions = jax.random.uniform(key, (n, 3), jnp.float32) * 0.05
    return ClusterState(
        gossip=make_state(cfg.gossip),
        vivaldi=make_vivaldi(n, cfg.vivaldi),
        positions=positions,
        group=jnp.zeros((n,), jnp.int32),
        control=make_control(cfg.control, cfg.gossip, cfg.failure),
    )


def cluster_round(state: ClusterState, cfg: ClusterConfig,
                  key: jax.Array, drop_rate=None, mesh=None,
                  collect_propagation: bool = False):
    """One full protocol round for every simulated node.

    ``drop_rate`` (optional f32 scalar, may be traced) is the chaos
    plane's per-round loss input (serf_tpu.faults.device): it masks the
    gossip exchange AND overrides the probe-path drop rate, so the same
    FaultPlan loss phase degrades dissemination and pressures the
    failure detector exactly like host-plane UDP loss.  ``state.group``
    is the per-round partition/adjacency mask throughout (gossip,
    probes, push/pull, Vivaldi).

    ``mesh`` (optional ``jax.sharding.Mesh``, node axis) makes this the
    SHARDED flagship round: the gossip exchange runs as an explicit
    shard_map leg (``parallel.ring.exchange_sharded``, ICI schedule per
    ``cfg.exchange_schedule``) so each chip streams only its N/P slice
    and only packet words ride the interconnect; every other phase is
    elementwise or rolled, which GSPMD keeps chip-local over
    node-sharded state (``parallel.mesh.shard_state``).  Bit-exact with
    the unsharded round for the same keys — the exchange hook swaps the
    collective schedule, never the arithmetic.

    ``collect_propagation`` (static, default off) threads the redundancy
    ledger flag into the gossip leg and returns ``(state, (slots_sent,
    slots_learned))`` — see :func:`round_step`; the ledger scopes the
    gossip exchange leg only (probe/refute/push-pull traffic is priced
    by ``models.accounting``, not traced here).  Off, this function is
    byte-identical Python to the untraced round."""
    k_gossip, k_probe, k_refute, k_declare, k_pp, k_viv, k_peer = \
        jax.random.split(key, 7)
    g = state.gossip
    # adaptive knobs (serf_tpu.control.device): trace-time gated — the
    # disabled default never reads the control leaves, so the static
    # path's jaxpr is exactly the pre-control one
    ctrl = state.control if cfg.control.enabled else None
    eff_fanout = None
    stretch_q = None
    stamp_unit = None
    if ctrl is not None:
        eff_fanout = ctrl.knobs[KNOB_FANOUT]
        stretch_q = ctrl.knobs[KNOB_STRETCH_Q]
        if cfg.gossip.stamp_deferred:
            # live flush cadence: knob stores log2(unit) (control.device)
            # — only consulted on deferred configs so the per-round
            # path's jaxpr never reads it
            stamp_unit = jnp.int32(1) << ctrl.knobs[KNOB_STAMP_UNIT]
        # probe-cadence multiplier: probes (declare + Vivaldi ride the
        # same tick) run every probe_every * probe_mult rounds — always
        # the traced-cond path under control
        probe_tick = (g.round
                      % (cfg.probe_every * ctrl.knobs[KNOB_PROBE_MULT])
                      ) == 0
    else:
        probe_tick = (g.round % cfg.probe_every == 0) \
            if cfg.probe_every > 1 else None
    chaos_group = state.group if drop_rate is not None else None
    prop = None
    if mesh is not None:
        # THE one sharded round in the tree (parallel.ring): round_step
        # with only the exchange leg swapped for the explicit shard_map
        # schedule (and the single-device pallas kernels trace-time
        # disabled, loudly)
        from serf_tpu.parallel.ring import sharded_round_step
        g = sharded_round_step(g, cfg.gossip, k_gossip, mesh,
                               schedule=cfg.exchange_schedule,
                               group=state.group, drop_rate=drop_rate,
                               eff_fanout=eff_fanout,
                               stamp_unit=stamp_unit,
                               collect_propagation=collect_propagation)
    else:
        g = round_step(g, cfg.gossip, k_gossip, group=state.group,
                       drop_rate=drop_rate, eff_fanout=eff_fanout,
                       stamp_unit=stamp_unit,
                       collect_propagation=collect_propagation)
    if collect_propagation:
        g, prop = g
    if cfg.with_failure:
        if probe_tick is None:
            g = probe_round(g, cfg.gossip, cfg.failure, k_probe,
                            group=chaos_group, drop_override=drop_rate)
            g = refute_round(g, cfg.gossip, cfg.failure, k_refute)
            g = declare_round(g, cfg.gossip, cfg.failure, k_declare)
        else:
            g = jax.lax.cond(
                probe_tick,
                lambda s: probe_round(s, cfg.gossip, cfg.failure, k_probe,
                                      group=chaos_group,
                                      drop_override=drop_rate),
                lambda s: s, g)
            g = refute_round(g, cfg.gossip, cfg.failure, k_refute)
            # declare rides the probe cadence: its expiry scan re-reads
            # the stamp plane (see ClusterConfig.probe_every)
            g = jax.lax.cond(
                probe_tick,
                lambda s: declare_round(s, cfg.gossip, cfg.failure,
                                        k_declare, stretch_q=stretch_q),
                lambda s: s, g)
    if cfg.push_pull_every > 0:
        g = jax.lax.cond(
            g.round % cfg.push_pull_every == 0,
            lambda s: push_pull_round(s, cfg.gossip, k_pp, group=state.group),
            lambda s: s,
            g)
    viv = state.vivaldi
    if cfg.with_vivaldi:
        def viv_step(viv):
            return vivaldi_phase(state._replace(gossip=g, vivaldi=viv),
                                 cfg, k_peer, k_viv)

        if probe_tick is None:
            viv = viv_step(viv)
        else:
            # coordinate samples ride probe acks (reference delegate
            # ping payloads), so they follow the probe cadence
            viv = jax.lax.cond(probe_tick, viv_step, lambda v: v, viv)
    nxt = state._replace(gossip=g, vivaldi=viv)
    if collect_propagation:
        return nxt, prop
    return nxt


def vivaldi_phase(state: ClusterState, cfg: ClusterConfig, k_peer,
                  k_viv) -> VivaldiState:
    """One Vivaldi co-training step on the current liveness/partition
    state — the coordinate phase of :func:`cluster_round`, module-level
    so the per-phase profiler (serf_tpu/obs/profile.py) jits exactly the
    production code path in isolation."""
    n = cfg.n
    g = state.gossip
    viv = state.vivaldi
    if cfg.gossip.peer_sampling == "rotation":
        # one rotation pairs every node with a pseudo-random RTT
        # partner; every peer read (liveness, group, hidden position,
        # coordinate state) is a contiguous roll, no 1M-row gather
        voff = sample_offsets(k_peer, 1, n)[0]
        same_group = state.group == rolled_rows(state.group, voff)
        reachable = g.alive & rolled_rows(g.alive, voff) & same_group
        rtt = ground_truth_rtt_rolled(state.positions, voff)
        return vivaldi_update(viv, cfg.vivaldi, None, rtt, k_viv,
                              active=reachable, peer_roll=voff)
    peers = jax.random.randint(k_peer, (n,), 0, n)
    same_group = state.group == state.group[peers]
    reachable = g.alive & g.alive[peers] & same_group \
        & (peers != jnp.arange(n))
    rtt = ground_truth_rtt(state.positions, jnp.arange(n), peers)
    return vivaldi_update(viv, cfg.vivaldi, peers, rtt, k_viv,
                          active=reachable)


def control_tick(state: ClusterState, cfg: ClusterConfig, row=None,
                 mesh=None):
    """Apply the device control law after a round: extract the law
    signals from the (post-round) telemetry ``row`` and advance
    ``state.control`` — the decision feeds forward as round R+1's
    dynamic config.  Returns ``(state, row)``; ``row`` is computed here
    when the caller did not already collect telemetry, so the two
    consumers share ONE N×K unpack per round.  ``mesh`` routes that
    computation through the in-collective sharded leg (the sharded
    flagship's controller reads the SAME bit-identical row).  A no-op
    pass-through when the controller is disabled."""
    if not cfg.control.enabled:
        return state, row
    if row is None:
        row = round_telemetry(state, cfg, mesh=mesh)
    sig = ControlSignals(
        agreement=row[TELEMETRY_FIELDS.index("agreement")],
        false_dead=row[TELEMETRY_FIELDS.index("false_dead")],
        overflow=row[TELEMETRY_FIELDS.index("overflow")],
    )
    ctrl = control_step(state.control, sig, cfg.control, cfg.gossip,
                        cfg.failure)
    return state._replace(control=ctrl), row


def run_cluster(state: ClusterState, cfg: ClusterConfig, key: jax.Array,
                num_rounds: int, mesh=None) -> ClusterState:
    def body(carry, subkey):
        nxt = cluster_round(carry, cfg, subkey, mesh=mesh)
        nxt, _ = control_tick(nxt, cfg, mesh=mesh)
        return nxt, ()

    keys = jax.random.split(key, num_rounds)
    final, _ = jax.lax.scan(body, state, keys)
    return final


def sustained_round(state: ClusterState, cfg: ClusterConfig, key: jax.Array,
                    events_per_round: int, mesh=None,
                    collect_propagation: bool = False):
    """``cluster_round`` under continuous dissemination load: inject
    ``events_per_round`` fresh user events at uniform random origins, then
    run the round.

    This is the device analog of the reference's steady broadcast workload
    (``Serf::user_event`` arriving every gossip tick, SURVEY.md §3.3 /
    BASELINE.json config #2): the fact ring keeps cycling, the
    ``last_learn`` quiescent gate never closes, and every round pays the
    full select/exchange/merge cost — so a throughput number measured here
    rewards doing the work faster, not gating it off.  Each fact lives
    ``k_facts / events_per_round`` rounds before its ring slot recycles;
    keep that above ``transmit_limit`` (e.g. 2/round at K=64, n=1M) so
    facts can fully disseminate before retirement, matching the
    reference's event-buffer headroom sizing (event_buffer_size=512).

    Origins are sampled over ALL nodes: a fact injected at a dead origin
    never spreads (exactly the reference — an event originating at a node
    that dies with the queue undrained is lost); with realistic churn
    fractions this is noise.
    """
    m = events_per_round
    # fact-lifetime headroom (ADVICE r5): each fact lives
    # k_facts/events_per_round rounds before its ring slot recycles; at
    # or below transmit_limit the ring cycles faster than facts can
    # disseminate, silently churning suspect/declare forever.  Static
    # shapes make this a trace-time check, so it costs nothing per round.
    window = cfg.gossip.transmit_window_rounds
    if m and cfg.gossip.k_facts / m <= window:
        raise ValueError(
            f"sustained_round ring churn: k_facts/events_per_round = "
            f"{cfg.gossip.k_facts}/{m} = {cfg.gossip.k_facts / m:.0f} "
            f"rounds per fact <= the {window}-round transmit window — "
            f"facts retire before they can disseminate (raise k_facts "
            f"or lower events_per_round)")
    k_org, k_rnd = jax.random.split(key)
    g = state.gossip
    # unique, monotonically increasing event ids double as ltimes
    eids = g.round * m + jnp.arange(m, dtype=jnp.int32) + 1
    origins = jax.random.randint(k_org, (m,), 0, cfg.n, dtype=jnp.int32)
    active = jnp.ones((m,), bool)
    if cfg.control.enabled:
        # device-plane admission (control.gate_injections): the
        # controller's per-round token budget sheds offered load the
        # ring would only clobber mid-flight anyway
        active, ctrl = gate_injections(state.control, active)
        state = state._replace(control=ctrl)
    g = inject_facts_batch(
        g, cfg.gossip, eids, K_USER_EVENT,
        incarnations=jnp.zeros((m,), jnp.uint32),
        ltimes=eids.astype(jnp.uint32),
        origins=origins, active=active)
    return cluster_round(state._replace(gossip=g), cfg, k_rnd, mesh=mesh,
                         collect_propagation=collect_propagation)


def run_cluster_sustained(state: ClusterState, cfg: ClusterConfig,
                          key: jax.Array, num_rounds: int,
                          events_per_round: int = 2,
                          mesh=None, collect_telemetry: bool = False,
                          collect_propagation: bool = False,
                          collect_invariants: bool = False,
                          inv_cov0=None):
    """``collect_telemetry`` (static) additionally stacks one
    :func:`round_telemetry` row per round as a scan output and returns
    ``(final_state, rows f32[R, F])`` — the continuous-telemetry plane's
    device feed.  The rows stay on device until the CALLER's single
    ``device_get``: one transfer per run, never per round (the PR-9
    digest-plane pattern).

    ``collect_propagation`` (static) additionally traces dissemination
    itself (the PR-16 propagation observatory): the first injected batch
    becomes the M sentinel facts (their event ids are derived from the
    entry round, so the contract survives resumed runs), and every round
    stacks one :func:`propagation_row` — the redundancy-ledger pair from
    the gossip exchange plus sentinel coverage folded from the SAME
    ``colcnt`` partials the telemetry row already reduces (one
    known-plane unpack serves both rows; ``with_cols`` below).  Appends
    ``(prop_rows f32[R, P], sentinel_cov f32[R, M])`` to the return
    tuple, after the telemetry rows when both are on; same
    one-device_get discipline.

    ``collect_invariants`` (static) additionally judges the watchdog's
    invariant predicates every round (the ISSUE-17 always-on watchdog):
    one :func:`invariant_row` per round, folded from the SAME
    already-reduced operands the telemetry row produced — appends
    ``irows f32[R, F]`` LAST to the return tuple.  When the propagation
    tracer rides too, the coverage-monotonicity predicate threads the
    per-sentinel running coverage maximum through the scan carry;
    ``inv_cov0`` (``f32[M]``, default zeros) seeds it, so a chunked
    caller (``faults/device.run_device_plan``) can pass the previous
    chunk's final maximum and keep the predicate exact across chunk
    boundaries — the final maximum is returned as the LAST element of
    the invariant entry, i.e. the entry becomes ``(irows, cov_fin)``."""
    if collect_propagation and events_per_round <= 0:
        raise ValueError(
            "collect_propagation traces the first injected batch as "
            "sentinel facts — it needs events_per_round >= 1")
    if collect_propagation:
        m = events_per_round
        # scan-invariant sentinel ids: exactly the eids sustained_round
        # assigns to the FIRST round's batch (round r0: r0*m + 1..m)
        sentinels = (state.gossip.round * m
                     + jnp.arange(m, dtype=jnp.int32) + 1)

    # the coverage-monotonicity carry exists only when BOTH the
    # invariant row and the propagation tracer ride (static flags: the
    # off-path scan carry — and jaxpr — is untouched)
    track_cov = collect_invariants and collect_propagation

    def body(carry, subkey):
        if track_cov:
            carry, prev_cov = carry
        if collect_propagation:
            nxt, pair = sustained_round(carry, cfg, subkey,
                                        events_per_round, mesh=mesh,
                                        collect_propagation=True)
            row, colcnt, alive_cnt = round_telemetry(nxt, cfg, mesh=mesh,
                                                     with_cols=True)
        else:
            nxt = sustained_round(carry, cfg, subkey, events_per_round,
                                  mesh=mesh)
            row = round_telemetry(nxt, cfg, mesh=mesh) \
                if (collect_telemetry or collect_invariants
                    or cfg.control.enabled) else None
        nxt, row = control_tick(nxt, cfg, row, mesh=mesh)
        out = ()
        if collect_telemetry:
            out = out + (row,)
        if collect_propagation:
            prop_out = propagation_row(nxt.gossip, pair, colcnt,
                                       alive_cnt, sentinels)
            out = out + (prop_out,)
        if collect_invariants:
            irow, new_prev_cov = invariant_row(
                nxt.gossip, row,
                sentinels if track_cov else None,
                colcnt if track_cov else None,
                prev_cov if track_cov else None,
                deferred=cfg.gossip.stamp_deferred)
            out = out + (irow,)
            if track_cov:
                return (nxt, new_prev_cov), out
        return nxt, out

    keys = jax.random.split(key, num_rounds)
    carry0 = state
    if track_cov:
        if inv_cov0 is None:
            inv_cov0 = (jnp.zeros((events_per_round,), jnp.float32),
                        jnp.float32(-1.0))
        carry0 = (state, inv_cov0)
    final, out = jax.lax.scan(body, carry0, keys)
    if track_cov:
        final, cov_fin = final
        out = tuple(out)
        out = out[:-1] + ((out[-1], cov_fin),)
    return (final,) + tuple(out) if out else final


#: field order of the per-round device telemetry row (``f32[F]``) —
#: :mod:`serf_tpu.obs.timeseries.TELEMETRY_SERIES` maps each field to
#: its declared metric name.  Values are exact in f32 up to 2^24
#: (counts at the 1M flagship scale fit; only a pathological
#: multi-billion-injection ledger would round).
TELEMETRY_FIELDS = ("alive", "facts_valid", "agreement", "coverage",
                    "overflow", "injected", "suspicions", "false_dead")

#: THE in-collective merge contract (ISSUE 15): how each telemetry
#: field's per-chip partial combines across the node shards when the row
#: is computed INSIDE the sharded exchange collective
#: (``parallel.ring.round_telemetry_sharded``):
#:
#: - ``"sum"``  — the field is assembled from integer partial sums that
#:   ride a fused ``lax.psum`` leg (ratios like agreement/coverage psum
#:   their numerator/denominator counts and divide AFTER the reduce, so
#:   the float math runs once on globally-identical integers);
#: - ``"max"`` / ``"min"`` — the partial rides a ``lax.pmax`` /
#:   ``lax.pmin`` leg (the subject-incarnation staleness gate uses the
#:   pmax shape internally; row fields may too);
#: - ``"replicated"`` — computed identically on every chip from
#:   replicated inputs only (fact-table K-planes, scalar ledgers): no
#:   collective at all.
#:
#: A NEW FIELD MUST BE ASSOCIATIVE (and commutative) under its declared
#: op — partials from disjoint node shards must combine to exactly the
#: global value in any order — or it cannot ride the collective and has
#: no place in this row.  serflint's ``telemetry-field-drift`` rule
#: holds this table, TELEMETRY_FIELDS, and the README telemetry table
#: to each other, both ways.
TELEMETRY_MERGE = {
    "alive": "sum",
    "facts_valid": "replicated",
    "agreement": "sum",
    "coverage": "sum",
    "overflow": "replicated",
    "injected": "replicated",
    "suspicions": "replicated",
    "false_dead": "sum",
}


def telemetry_counts(g: GossipState, cfg: ClusterConfig, stretch_q=None,
                     subj_inc=None):
    """Stage-1 of the telemetry row: the integer partials over (this
    shard of) the cluster — ``(alive_cnt, colcnt i32[K],
    believers i32[K])``, every one a plain integer sum over the node
    axis, so partials over disjoint shards psum to exactly the global
    counts (the TELEMETRY_MERGE "sum" contract).  ``subj_inc`` forwards
    the pmax-assembled subject incarnations on the sharded path.

    Cost discipline (the ``obs_overhead`` bench band): the row rides
    EVERY round, so its heavy stage — the believed-dead evidence pass
    ([N, K] staleness/age planes + the knower-refutation product) — is
    skip-gated exactly like the round's own detection phases: with no
    current-incarnation dead/suspect fact in the ring (the sustained
    steady state once detection completes and the ring recycles), the
    evidence plane is identically zero, so the gated branch returns the
    zero vector the full computation would — bit-exact, paying one
    K-plane predicate instead of the [N, K] passes.  ``agreement``'s
    numerator/denominator need no planes of their own: they are exact
    K-sized integer folds of ``colcnt``/``alive_cnt`` (see
    :func:`telemetry_finish`)."""
    known = unpack_bits(g.known, cfg.gossip.k_facts)     # bool[N(l), K]
    alive_col = g.alive[:, None]
    alive_cnt = jnp.sum(g.alive)
    colcnt = jnp.sum(known & alive_col, axis=0)          # i32[K]
    if subj_inc is None:
        subj_inc = subject_incarnations(g)
    dead_fact = _facts_about(g, (K_DEAD,), inc_current=True,
                             subj_inc=subj_inc)
    aged_suspect = _facts_about(g, (K_SUSPECT,), inc_current=True,
                                subj_inc=subj_inc)
    k = cfg.gossip.k_facts
    believers = jax.lax.cond(
        jnp.any(dead_fact | aged_suspect),
        lambda: believer_counts(
            g, cfg.gossip, cfg.failure, stretch_q=stretch_q,
            subj_inc=subj_inc, known=known,
            evidence_facts=(dead_fact, aged_suspect)).astype(colcnt.dtype),
        lambda: jnp.zeros((k,), colcnt.dtype))
    return alive_cnt, colcnt, believers


def telemetry_finish(g: GossipState, cfg: ClusterConfig, alive_cnt,
                     colcnt, false_dead, subj_inc=None) -> jnp.ndarray:
    """Stage-2 of the telemetry row: assemble ``f32[F]`` from globally
    reduced integer counts plus the replicated fields.  The float math
    (agreement/coverage ratios) runs here, AFTER the reduce, on
    integers every chip agrees on — which is what makes the sharded row
    bit-identical to the gathered one.  ``agreement``'s counts are
    exact integer folds of the reduced operands: ``hit = Σ_k valid[k] ·
    colcnt[k]`` re-associates the same bool sum per-fact-column first
    (integer addition — exact in any order) and ``cells = alive · valid``
    is the same product the masked [N, K] sum computes."""
    valid = g.facts.valid
    n_valid_i = jnp.sum(valid)
    cells = alive_cnt * n_valid_i
    hit = jnp.sum(jnp.where(valid, colcnt, 0))
    n_alive = jnp.maximum(alive_cnt, 1).astype(jnp.float32)
    agreement = jnp.where(cells > 0,
                          hit.astype(jnp.float32)
                          / jnp.maximum(cells, 1).astype(jnp.float32),
                          1.0)
    n_valid = jnp.maximum(n_valid_i, 1).astype(jnp.float32)
    cov = colcnt.astype(jnp.float32) / n_alive
    mean_cov = jnp.sum(jnp.where(valid, cov, 0.0)) / n_valid
    return jnp.stack([
        alive_cnt.astype(jnp.float32),
        n_valid_i.astype(jnp.float32),
        agreement.astype(jnp.float32),
        mean_cov.astype(jnp.float32),
        g.overflow.astype(jnp.float32),
        g.injected.astype(jnp.float32),
        jnp.sum(live_suspicions(g, subj_inc=subj_inc))
           .astype(jnp.float32),
        false_dead.astype(jnp.float32),
    ])


def telemetry_stretch(state: ClusterState, cfg: ClusterConfig):
    """The live suspicion-stretch knob the believed-dead judgment must
    honor (None when the controller is disabled): under adaptive
    control the signal the controller reads is the semantics it
    changed."""
    return state.control.knobs[KNOB_STRETCH_Q] \
        if cfg.control.enabled else None


def round_telemetry(state: ClusterState, cfg: ClusterConfig,
                    mesh=None, with_cols: bool = False):
    """One compact counters row (``f32[len(TELEMETRY_FIELDS)]``) off the
    current cluster state, cheap enough to ride EVERY round as a scan
    output: alive count, valid facts, knowledge agreement + mean
    coverage (one shared ``known``-plane unpack), the overflow/injection
    ledger, live suspicions, and false-DEAD count (alive nodes the
    cluster believes dead — the probe/refute outcome the SLO plane
    judges).  Pure function of the state — safe inside jit/scan, and the
    quantities agree with ``emit_*_metrics`` by construction.

    ``mesh`` (the sharded flagship round's mesh) computes the SAME row
    in-collective (``parallel.ring.round_telemetry_sharded``): each chip
    reduces its own node shard and O(fields)-sized psum/pmax legs
    assemble the cluster row — no N-plane gather, bit-identical by the
    stage-1/stage-2 split above (integer partials reduce exactly; the
    float math runs after the reduce on identical operands).

    ``with_cols`` (static) additionally returns the globally-reduced
    stage-1 operands the row was folded from — ``(row, colcnt i32[K],
    alive_cnt i32)`` — so a rider (the propagation observatory's
    sentinel-coverage fold) shares the one known-plane unpack instead of
    paying its own; on the sharded path the extras are the post-psum
    replicated partials, already exactly global."""
    if mesh is not None:
        from serf_tpu.parallel.ring import round_telemetry_sharded
        return round_telemetry_sharded(state, cfg, mesh,
                                       with_cols=with_cols)
    g = state.gossip
    stretch = telemetry_stretch(state, cfg)
    subj_inc = subject_incarnations(g)
    alive_cnt, colcnt, believers = telemetry_counts(
        g, cfg, stretch_q=stretch, subj_inc=subj_inc)
    believed = believed_subjects(g, cfg.n, believers, alive_cnt) \
        | g.tombstone
    false_dead = jnp.sum(believed & g.alive)
    row = telemetry_finish(g, cfg, alive_cnt, colcnt, false_dead,
                           subj_inc=subj_inc)
    if with_cols:
        return row, colcnt, alive_cnt
    return row


def propagation_row(g: GossipState, pair, colcnt, alive_cnt,
                    sentinels: jnp.ndarray):
    """Stage-2 of the propagation observatory's per-round row
    (``serf_tpu.obs.propagation.PROPAGATION_FIELDS`` order — hardcoded
    stack below, exactly the :func:`telemetry_finish` convention):
    the redundancy-ledger pair from the round's gossip exchange plus
    sentinel coverage folded from the telemetry row's OWN globally
    reduced ``colcnt`` partials (``round_telemetry(..., with_cols=True)``
    — no second known-plane unpack, no collective of its own).

    Sentinel coverage is a fact-identity match: ``cov_i = Σ_k
    [subject_k == sentinel_i ∧ valid_k] · colcnt[k]`` — an [M, K]
    compare against replicated fact-table planes, so the fold is
    bit-identical sharded vs. not.  A sentinel whose ring slot has
    recycled matches nothing and reads 0 — callers monotonize the
    coverage curve host-side (cummax over rounds) before reading
    time-to-X% off it.  Returns ``(row f32[P], cov f32[M])`` with
    coverage as a fraction of the current alive count, clamped to 1.0
    (``colcnt`` counts every holder's known bit, so when holders die
    after learning the raw ratio exceeds one)."""
    sent, learned = pair
    match = (g.facts.subject[None, :] == sentinels[:, None]) \
        & g.facts.valid[None, :]
    cov_cnt = jnp.sum(jnp.where(match, colcnt[None, :], 0), axis=1)
    n_alive = jnp.maximum(alive_cnt, 1).astype(jnp.float32)
    cov = jnp.minimum(cov_cnt.astype(jnp.float32) / n_alive, 1.0)
    sentf = sent.astype(jnp.float32)
    learnedf = learned.astype(jnp.float32)
    redundant = sentf - learnedf
    row = jnp.stack([
        sentf,
        learnedf,
        redundant,
        redundant / jnp.maximum(sentf, 1.0),
        alive_cnt.astype(jnp.float32),
        jnp.min(cov),
        jnp.mean(cov),
        jnp.max(cov),
    ])
    return row, cov


def invariant_row(g: GossipState, row: jnp.ndarray, sentinels=None,
                  colcnt=None, prev=None, deferred: bool = False):
    """Stage-2 of the watchdog's per-round invariant row
    (``serf_tpu.obs.watchdog.INVARIANT_FIELDS`` order — hardcoded stack
    below, exactly the :func:`propagation_row` convention): the
    predicates the post-hoc checker (``faults/invariants.check_device``)
    judges once per RUN become one boolean row per ROUND, computed
    inside the jitted scan from operands the telemetry row already
    reduced — the row itself, the replicated overflow/injection
    ledgers, the replicated fact-table K-planes, and (when the
    propagation tracer rides) the same globally-reduced ``colcnt``
    partials.  Every field folds from already-global values, identical
    on every chip: no collective of its own, no second known-plane
    unpack (the INVARIANT_MERGE all-"replicated" contract).

    ``sentinels``/``colcnt``/``prev`` (present only when the
    propagation tracer is on) drive the coverage-monotonicity
    predicate.  Gossip learning is monotone — a resident fact's knower
    set only grows — so per-sentinel alive-knower coverage must never
    regress while the population holds still.  The fold here is
    KIND-filtered (user-event facts only: sentinel event ids share the
    i32 subject namespace with SWIM's node ids, and a suspicion fact
    about node 1 must not count as coverage of sentinel event 1 — the
    raw :func:`propagation_row` curve tolerates that collision because
    its callers cummax host-side; a per-round predicate cannot).  Two
    legitimate regressions are exempt: a recycled ring slot reads 0,
    and a round where the alive count moved (deaths remove knowers,
    restarts add non-knowers) resets the baseline instead of judging.
    ``prev`` is the carried ``(running-max coverage f32[M], previous
    alive count f32)``; returns ``(irow f32[F], new_prev)`` —
    ``new_prev`` is ``None`` untraced, where the field is fixed 1.0."""
    overflow_ok = (g.overflow >= 0) & (g.overflow <= g.injected)
    ltime_ok = ~ltime_window_violation(g.facts)
    no_false_dead = row[TELEMETRY_FIELDS.index("false_dead")] <= 0.0
    if sentinels is not None:
        prev_cov, prev_alive = prev
        match = (g.facts.subject[None, :] == sentinels[:, None]) \
            & g.facts.valid[None, :] \
            & (g.facts.kind[None, :] == K_USER_EVENT)
        cov_cnt = jnp.sum(jnp.where(match, colcnt[None, :], 0), axis=1)
        alive_f = row[TELEMETRY_FIELDS.index("alive")]
        cov = jnp.minimum(
            cov_cnt.astype(jnp.float32) / jnp.maximum(alive_f, 1.0), 1.0)
        alive_moved = alive_f != prev_alive
        regress = (cov < prev_cov - 1e-6) & (cov > 0.0) & ~alive_moved
        coverage_monotone = ~jnp.any(regress)
        new_prev = (jnp.where(alive_moved, cov,
                              jnp.maximum(prev_cov, cov)), alive_f)
    else:
        coverage_monotone = jnp.asarray(True)
        new_prev = None
    if deferred:
        # deferred stamp flushes (PR-18): pending overlay learns must be
        # no older than the current stamp quarter — the cohort flush is
        # due within stamp_flush_unit <= STAMP_UNIT rounds of any learn,
        # so a pending learn that predates the quarter floor means a
        # flush was missed and the overlay's age-0 read-through is lying
        # about a fact that should have aged.  pending compares the
        # learn/flush watermarks (push_pull backdates last_flush below a
        # same-round flush, hence >=, never >, on the floor compare).
        pending = g.last_learn > g.last_flush
        stamp_staleness_ok = ~pending | (
            g.last_learn >= ((g.round >> 2) << 2))
    else:
        # per-round configs flush every round by definition
        stamp_staleness_ok = jnp.asarray(True)
    flags = jnp.stack([overflow_ok, ltime_ok, no_false_dead,
                       coverage_monotone, stamp_staleness_ok])
    bits = jnp.asarray([1, 2, 4, 8, 16], jnp.int32)
    viol_mask = jnp.sum(jnp.where(flags, 0, bits))
    irow = jnp.concatenate([flags.astype(jnp.float32),
                            viol_mask.astype(jnp.float32)[None]])
    return irow, new_prev


def emit_cluster_metrics(state: ClusterState, cfg: ClusterConfig,
                         labels=None) -> dict:
    """One call emits every device-plane gauge for the flagship cluster:
    dissemination + SWIM outcomes + (when enabled) Vivaldi.  Pull-based —
    the model runs under jit where counters cannot fire, so benchmarks
    and tests call this between scans; one device->host sync.  Returns
    the merged ``{name: value}`` dict (bench.py embeds it in
    BENCH_DETAIL.json).
    """
    from serf_tpu.models.dissemination import emit_gossip_metrics
    from serf_tpu.models.failure import emit_swim_metrics
    from serf_tpu.models.vivaldi import emit_vivaldi_metrics

    out = {}
    out.update(emit_gossip_metrics(state.gossip, cfg.gossip, labels))
    out.update(emit_swim_metrics(state.gossip, cfg.gossip, cfg.failure,
                                 labels))
    if cfg.with_vivaldi:
        out.update(emit_vivaldi_metrics(state.vivaldi, labels))
    return out
