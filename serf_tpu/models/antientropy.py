"""Device-plane push/pull anti-entropy and partition/heal.

Maps the reference's periodic full-state push/pull sync
(SURVEY.md §2.9, delegate.rs:386-554) onto the array representation: each
node picks one random partner and merges the partner's *entire* knowledge
bitset (not just budgeted packets) — a masked elementwise OR, which is how
"pairwise state-sync as a batched merge of status_ltimes maps" (SURVEY.md §7
stage 6) lands on the device plane.

Partition = an i32 group id per node; edges across groups carry nothing.
Heal = drop the mask.  Two-cluster merge parity is the baseline config #4
scenario (BASELINE.json).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from serf_tpu.models.dissemination import (
    GossipConfig,
    GossipState,
    bump_last_learn,
    clamp_learn_bytes,
    clamp_nibbles,
    rolled_rows,
    round_q,
    sample_offsets,
    unpack_bits,
)


def push_pull_round(state: GossipState, cfg: GossipConfig, key: jax.Array,
                    group=None) -> GossipState:
    """Each alive node full-syncs with one random partner.

    Newly learned facts get fresh transmit budgets, so anti-entropy
    re-energizes dissemination of still-relevant facts after a partition
    heals — the same effect as the reference replaying intents out of the
    push/pull status_ltimes map.
    """
    n, k = cfg.n, cfg.k_facts
    if cfg.peer_sampling == "rotation":
        # one random rotation pairs everyone: partner reads are contiguous
        # rolls, no 1M-row gather (see GossipConfig.peer_sampling)
        off = sample_offsets(key, 1, n)[0]
        partner_known = rolled_rows(state.known, off)         # u32[N, W]
        ok = state.alive & rolled_rows(state.alive, off)
        if group is not None:
            ok = ok & (group == rolled_rows(group, off))
    else:
        partners = jax.random.randint(key, (n,), 0, n)
        partner_known = state.known[partners]                 # u32[N, W]
        ok = state.alive & state.alive[partners]
        if group is not None:
            ok = ok & (group == group[partners])
    incoming = jnp.where(ok[:, None], partner_known, jnp.uint32(0))
    new_words = incoming & ~state.known
    known = state.known | new_words
    learned_any = jnp.any(new_words != 0)

    if cfg.stamp_deferred:
        # deferred flavor: the sync's learns ride the overlay (q-age 0
        # through every mod_age reader) and the next cohort flush
        # retires them — no stamp pass here at all, and no last_clamp
        # bump (the flush owns the clamp).  The flush writes them with
        # the quarter of flush-1, which IS this round's quarter: the
        # first cohort boundary after ``round`` is < the first quarter
        # boundary after it (units divide STAMP_UNIT).  One intra-round
        # ordering wrinkle: a flush may have already run THIS round
        # (round_step's merge), leaving ``last_flush == round`` — these
        # learns are newer than that flush, so re-arm the pending
        # predicate by backdating last_flush below last_learn
        # (last_flush is only ever compared, never used as a stamp
        # operand).
        last_learn = bump_last_learn(learned_any, state.round,
                                     state.last_learn)
        last_flush = jnp.where(
            learned_any,
            jnp.minimum(state.last_flush,
                        jnp.asarray(state.round - 1, jnp.int32)),
            state.last_flush)
        if cfg.use_sendable_cache:
            sendable = state.sendable | new_words
            sendable_round = state.sendable_round
        else:
            sendable = state.sendable
            sendable_round = jnp.where(learned_any, jnp.int32(-1),
                                       state.sendable_round)
        return state._replace(known=known,
                              overlay=state.overlay | new_words,
                              sendable=sendable,
                              sendable_round=sendable_round,
                              last_learn=last_learn,
                              last_flush=last_flush)

    # a fresh stamp = q-age 0 = fresh transmit budget for newly synced
    # facts.  Gated on learned_any: a fully in-sync pair exchange learns
    # nothing and the stamp where-pass (R+W the whole stamp plane) is a
    # bit-exact identity — skipping it makes the periodic sync of a
    # converged cluster cost only the known-word merge (accounting.py
    # quantifies).  When the pass DOES run it streams the plane, so the
    # wrap clamp rides it for free (last_clamp bumped below).
    def stamp_learns(s):
        if cfg.pack_stamp:
            # the shared clamp+learn byte pass (dissemination.
            # clamp_learn_bytes — one copy of the nibble arithmetic);
            # push_pull keeps its own OR-based cache handling outside
            return clamp_learn_bytes(s, new_words, state.round, k)[0]
        nib = clamp_nibbles(s, state.round)
        new_mask = unpack_bits(new_words, k)
        return jnp.where(new_mask, round_q(state.round), nib)

    stamp = jax.lax.cond(learned_any, stamp_learns, lambda s: s, state.stamp)
    last_clamp = jnp.where(learned_any,
                           jnp.asarray(state.round, jnp.int32),
                           state.last_clamp)
    # sendable cache (flag-gated at trace time): the newly synced facts
    # are age-0 sendable — OR-ing their packed bits preserves the cache
    # invariant for the round the plane is valid for (round_step's merge
    # set it for the CURRENT round; on a stale plane the OR is harmless,
    # it is never read)
    if cfg.use_sendable_cache:
        sendable = state.sendable | new_words
        sendable_round = state.sendable_round
    else:
        sendable = state.sendable
        # learned without mirroring: mixed-flag hygiene (see inject_fact)
        sendable_round = jnp.where(learned_any, jnp.int32(-1),
                                   state.sendable_round)
    last_learn = bump_last_learn(learned_any, state.round, state.last_learn)
    return state._replace(known=known, stamp=stamp, sendable=sendable,
                          sendable_round=sendable_round,
                          last_learn=last_learn, last_clamp=last_clamp)


def make_partition(n: int, split: float = 0.5) -> jnp.ndarray:
    """Two-group partition vector: first ``split`` fraction is group 0."""
    cut = int(n * split)
    return jnp.where(jnp.arange(n) < cut, 0, 1).astype(jnp.int32)


def knowledge_agreement(state: GossipState, cfg: GossipConfig) -> jnp.ndarray:
    """Scalar in [0,1]: mean pairwise-agreement proxy — fraction of
    (alive node, valid fact) cells known.  1.0 = fully merged."""
    known = unpack_bits(state.known, cfg.k_facts)
    valid = state.facts.valid[None, :]
    alive = state.alive[:, None]
    cells = jnp.sum(valid & alive)
    hit = jnp.sum(known & valid & alive)
    return jnp.where(cells > 0, hit / jnp.maximum(cells, 1), 1.0)
