"""Device-plane failure detection: probe / suspect / refute / declare-dead.

Vectorizes the SWIM failure-detector semantics the reference gets from
memberlist (SURVEY.md §2.9, §3.5): every round each alive node probes one
random peer; a missed ack yields a *suspicion fact* injected into the shared
fact ring (bounded per round, like the reference's broadcast queue); nodes
that learn they are suspected refute by bumping their incarnation and
emitting an alive fact; suspicions that age past the suspicion window
without refutation are promoted to dead declarations.

The per-edge drop mask is a first-class input (the device analog of the
reference's test-only ``MessageDropper``, SURVEY.md §4): fault injection is
an input tensor, not a code path.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from serf_tpu.models.dissemination import (
    AGE_PIN_Q,
    STAMP_UNIT,
    GossipConfig,
    GossipState,
    K_ALIVE,
    K_DEAD,
    K_SUSPECT,
    inject_facts_batch,
    mod_age,
    nibble_age_pred_words,
    pack_bits,
    pick_bounded,
    rolled_rows,
    round_step,
    sample_offsets,
    unpack_bits,
)


@dataclasses.dataclass(frozen=True)
class FailureConfig:
    suspicion_rounds: int = 12     # suspicion timeout in gossip rounds
    max_new_facts: int = 8         # injection bound per category per round
    probe_drop_rate: float = 0.0   # chance any one probe path is lost
    indirect_probes: int = 3       # SWIM indirect-probe helpers (k)
    #: "random": every node samples a uniform target each round (coverage in
    #: expectation).  "round_robin": the vectorized analog of memberlist's
    #: shuffled probe list — each round all nodes probe at one pseudo-random
    #: rotation offset, so every node is probed by EXACTLY one prober per
    #: round (deterministic coverage, no N×N schedule state).
    probe_schedule: str = "random"

    def __post_init__(self):
        if self.probe_schedule not in ("random", "round_robin"):
            raise ValueError(
                f"unknown probe_schedule {self.probe_schedule!r}")
        # knowledge ages derive from 4-bit quarter-round stamps pinned at
        # AGE_PIN_Q q-ticks, so windows beyond the pin are unrepresentable
        if not (0 < self.suspicion_rounds <= AGE_PIN_Q * STAMP_UNIT):
            raise ValueError(
                f"suspicion_rounds must be in [1, "
                f"{AGE_PIN_Q * STAMP_UNIT}] (stamp age pin), got "
                f"{self.suspicion_rounds}")

    @property
    def suspicion_q(self) -> int:
        """The suspicion window in quarter-round stamp ticks — the unit
        the expiry scan compares in.  Windows quantize to STAMP_UNIT
        rounds: a suspicion learned mid-quarter expires up to
        STAMP_UNIT-1 rounds early (the reference's suspicion timeout is
        wall-clock-approximate anyway)."""
        return -(-self.suspicion_rounds // STAMP_UNIT)


def rotation_offset(round_, n: int) -> jnp.ndarray:
    """Round-robin probe rotation: a pseudo-random offset in [1, n-1]
    (uint32 arithmetic; Knuth multiplicative constant is odd, so offsets
    sweep the distance space as rounds advance)."""
    return jnp.uint32(1) + (jnp.asarray(round_, jnp.uint32)
                            * jnp.uint32(2654435761)) % jnp.uint32(max(1, n - 1))


def subject_incarnations(state: GossipState) -> jnp.ndarray:
    """u32[K]: each fact subject's CURRENT ground-truth incarnation —
    the staleness-gate operand of ``_facts_about(inc_current=True)``.

    Factored out for the in-collective telemetry leg (parallel.ring):
    with the incarnation plane node-sharded, each chip contributes the
    incarnations of the subjects living in its shard and a K-sized
    ``pmax`` assembles the same vector this global gather produces —
    O(K) on the wire instead of gathering an N-plane."""
    subj = jnp.clip(state.facts.subject, 0)
    return state.incarnation[subj]


def _facts_about(state: GossipState, kinds, inc_current: bool = False,
                 subj_inc=None):
    """bool[K]: table slots that are valid facts of one of ``kinds``.

    ``inc_current=True`` additionally requires the fact's incarnation to
    be >= its subject's current ground-truth incarnation — THE
    staleness gate (single definition): a fact whose subject has since
    bumped past it (a refutation happened, even if the K_ALIVE fact was
    recycled out of the ring) is no longer current evidence.
    ``subj_inc`` (u32[K]) overrides the subject-incarnation lookup with
    a precomputed vector (the sharded telemetry leg's pmax-assembled
    one); None keeps the direct ``incarnation[subject]`` gather."""
    m = jnp.zeros_like(state.facts.valid)
    for k in kinds:
        m = m | (state.facts.kind == k)
    m = m & state.facts.valid
    if inc_current:
        if subj_inc is None:
            subj_inc = subject_incarnations(state)
        m = m & (state.facts.incarnation >= subj_inc)
    return m


def _subject_covered(state: GossipState, cfg: GossipConfig,
                     kinds) -> jnp.ndarray:
    """bool[N]: subject already has a valid fact of ``kinds`` with
    incarnation >= the subject's current ground-truth incarnation."""
    active = _facts_about(state, kinds, inc_current=True)
    subj = jnp.clip(state.facts.subject, 0)
    covered = jnp.zeros((cfg.n,), bool)
    covered = covered.at[subj].max(active)
    return covered


def accusations_pending(state: GossipState) -> jnp.ndarray:
    """bool[K]: accusation facts (suspect/dead) that could still trigger a
    refutation — incarnation beats the subject's AND the subject is
    alive.  The refute_round skip-gate: all-False means the phase is a
    bit-exact identity (retired-but-valid ring facts fail this, so the
    gate switches OFF again in the post-detection steady state)."""
    subj = jnp.clip(state.facts.subject, 0)
    return (_facts_about(state, (K_SUSPECT, K_DEAD), inc_current=True)
            & state.alive[subj])


def _refutation_matrix(state: GossipState) -> jnp.ndarray:
    """bool[K, K]: slot j refutes slot i — an alive fact about the same
    subject with STRICTLY higher incarnation.  The single source of the
    refutation semantics; the declare gate, declare body, and
    believed_dead all derive from it (a semantic change here must not be
    able to diverge between the gate and the body it keys)."""
    alive_facts = _facts_about(state, (K_ALIVE,))
    same_subject = (state.facts.subject[:, None]
                    == state.facts.subject[None, :])
    higher_inc = (state.facts.incarnation[None, :]
                  > state.facts.incarnation[:, None])
    return same_subject & alive_facts[None, :] & higher_inc


def live_suspicions(state: GossipState, subj_inc=None) -> jnp.ndarray:
    """bool[K]: suspicion facts that could still produce a declaration —
    neither refuted (alive fact, same subject, higher incarnation) nor
    already covered by a dead declaration.  The declare_round skip-gate;
    all-False makes the phase a bit-exact identity.  ``subj_inc``
    forwards to the staleness gate (see :func:`subject_incarnations`) —
    only the sharded telemetry leg passes it."""
    suspect = _facts_about(state, (K_SUSPECT,))
    refuted = jnp.any(_refutation_matrix(state), axis=1)
    same_subject = (state.facts.subject[:, None]
                    == state.facts.subject[None, :])
    dead_slot = _facts_about(state, (K_DEAD,), inc_current=True,
                             subj_inc=subj_inc)
    dead_covered = jnp.any(same_subject & dead_slot[None, :], axis=1)
    return suspect & ~refuted & ~dead_covered


def _bounded_inject(state: GossipState, cfg: GossipConfig, candidates,
                    kind: int, incarnations, origins, max_new: int,
                    key: jax.Array) -> GossipState:
    """Inject up to ``max_new`` facts for candidate subjects (bool[N]).

    Random tie-break keeps the choice unbiased; static-shape top_k keeps it
    jit-compatible.  Real candidates come out of top_k as a contiguous
    prefix (their scores are > 0, non-candidates score 0), so the whole
    batch lands in one masked multi-slot scatter — no per-candidate copy of
    the cluster state.

    Skip-gated: with zero candidates the pick + scatters + the N×W known
    pass are bit-exact identities, so the whole body runs under
    ``lax.cond`` on ``any(candidates)`` — on quiescent rounds (no new
    suspicions/refutations/deaths, the steady state of a healthy
    cluster) the phase costs one N-reduce instead of a top_k plus a full
    known-plane rewrite.
    """
    def do(st):
        _, subjects, active = pick_bounded(candidates, max_new, key)
        return inject_facts_batch(
            st, cfg,
            subjects=subjects,
            kind=kind,
            incarnations=incarnations[subjects],
            ltimes=jnp.full((max_new,), st.round.astype(jnp.uint32)),
            origins=origins[subjects],
            active=active,
        )

    return jax.lax.cond(jnp.any(candidates), do, lambda st: st, state)


def probe_round(state: GossipState, cfg: GossipConfig, fcfg: FailureConfig,
                key: jax.Array, group=None,
                drop_override=None) -> GossipState:
    """Probe + indirect probes + suspicion injection.

    SWIM semantics: a missed direct ack falls back to ``indirect_probes``
    helper paths (reference memberlist probe loop, SURVEY.md §2.9); only a
    target unreachable on EVERY path is suspected.  That makes the false-
    suspicion probability ~drop^(1+k) per probe — without it, realistic
    packet loss at 100k nodes floods the fact ring with false suspicions
    every round and starves real death declarations of ring residency.

    Chaos-plane inputs (serf_tpu.faults.device): ``group`` (i32[N])
    makes cross-partition targets unreachable — an unreachable-but-alive
    node IS suspected, exactly as SWIM would (the post-heal refutation
    path then clears it); ``drop_override`` (f32 scalar, may be traced)
    replaces ``fcfg.probe_drop_rate`` for this round.
    """
    n = cfg.n
    k_target, k_drop, k_help, k_hdrop, k_pick = jax.random.split(key, 5)
    p_drop = (drop_override if drop_override is not None
              else fcfg.probe_drop_rate)
    dropped = jax.random.bernoulli(k_drop, p_drop, (n,))
    prober_ok = state.alive
    if fcfg.probe_schedule == "round_robin":
        # one pseudo-random nonzero rotation per round: node i probes
        # (i + offset) % n, so every node is probed exactly once — AND the
        # rotation is invertible, so target liveness is a contiguous roll
        # and "who probed me" is analytic: no 1M-row gather or scatter
        # (each of those lowers to a serial loop on TPU, ~10 ms apiece).
        # alive is rolled at 1 + indirect_probes shifts — hoist its
        # doubled copy once (see rolled_rows)
        offset = rotation_offset(state.round, n).astype(jnp.int32)
        dalive = jnp.concatenate([state.alive, state.alive], axis=0)
        target_up = rolled_rows(state.alive, offset, doubled=dalive)
        if group is not None:
            dgroup = jnp.concatenate([group, group], axis=0)
            target_up = target_up & (
                rolled_rows(group, offset, doubled=dgroup) == group)
        else:
            dgroup = None
        ack = target_up & ~dropped
        if fcfg.indirect_probes > 0:
            # helpers are per-round random rotations too (the reference
            # samples k random helpers; a fresh random cyclic matching per
            # path keeps the drop paths independent where it matters)
            h_offs = sample_offsets(k_help, fcfg.indirect_probes, n)
            h_drop = jax.random.bernoulli(
                k_hdrop, p_drop, (n, fcfg.indirect_probes))
            for h in range(fcfg.indirect_probes):
                helper_ok = rolled_rows(state.alive, h_offs[h],
                                        doubled=dalive)
                if group is not None:
                    # groups are equivalence classes: helper reachable
                    # from the prober implies helper↔target reachability
                    # whenever the target is in the prober's group
                    helper_ok = helper_ok & (
                        rolled_rows(group, h_offs[h],
                                    doubled=dgroup) == group)
                ack = ack | (target_up & helper_ok & ~h_drop[:, h])
        # offset ∈ [1, n-1] means never self-probe — except n == 1, where
        # every rotation is the identity and the lone node must not be
        # able to suspect itself
        detected = prober_ok & ~ack & (n > 1)
        # invert the rotation: subject j's prober is (j - offset) % n
        subject_detected = rolled_rows(detected, n - offset)
        detector_of = (jnp.arange(n, dtype=jnp.int32) + (n - offset)) % n
    else:
        targets = jax.random.randint(k_target, (n,), 0, n)
        target_up = state.alive[targets]
        if group is not None:
            target_up = target_up & (group[targets] == group)
        ack = target_up & ~dropped
        if fcfg.indirect_probes > 0:
            ki = fcfg.indirect_probes
            helpers = jax.random.randint(k_help, (n, ki), 0, n)
            helper_ok = state.alive[helpers]                   # bool[N, ki]
            if group is not None:
                helper_ok = helper_ok & (group[helpers] == group[:, None])
            h_drop = jax.random.bernoulli(
                k_hdrop, p_drop, (n, ki))
            ack_indirect = target_up[:, None] & helper_ok & ~h_drop
            ack = ack | jnp.any(ack_indirect, axis=1)
        detected = prober_ok & ~ack & (targets != jnp.arange(n))

        # which subjects were detected, and by whom.  The scatter must be
        # masked: writing a default for non-detecting probers would hand
        # subject 0 a bogus (possibly dead) detector whose packets never
        # flow.  scatter-max of detector+1 (0 = none) composes correctly
        # under duplicate targets.
        subject_detected = jnp.zeros((n,), bool).at[targets].max(detected)
        det_writes = jnp.where(detected,
                               jnp.arange(n, dtype=jnp.int32) + 1, 0)
        detector_plus1 = jnp.zeros((n,), jnp.int32).at[targets].max(
            det_writes)
        detector_of = jnp.maximum(detector_plus1 - 1, 0)

    # tombstoned subjects are durably recorded dead — re-suspecting them
    # every ring cycle would churn injections forever under sustained
    # load (the reference never re-suspects a FAILED member either)
    already = (_subject_covered(state, cfg, (K_SUSPECT, K_DEAD))
               | state.tombstone)
    candidates = subject_detected & ~already
    return _bounded_inject(state, cfg, candidates, K_SUSPECT,
                           state.incarnation, detector_of,
                           fcfg.max_new_facts, k_pick)


def refute_round(state: GossipState, cfg: GossipConfig, fcfg: FailureConfig,
                 key: jax.Array) -> GossipState:
    """Alive nodes that know they are suspected/declared-dead bump their
    incarnation and emit an alive fact (reference _refute semantics).

    Skip-gated on a K-sized predicate: an accusation fact can only
    trigger a refutation while its incarnation still beats the subject's
    AND the subject is alive.  Retired-but-valid ring facts (a declared
    death, a refuted suspicion) fail the predicate, so the gate switches
    the phase OFF again in the post-detection steady state — with it the
    N×K accusation scan and the inject are bit-exact identities.

    A TOMBSTONED subject that is actually alive also refutes: its death
    declaration fully disseminated and retired into the durable record
    while it was down (crash → restart), so no ring fact remains to
    accuse it and nothing else would ever clear the tombstone.  This is
    the device analog of the reference's gossip-to-dead refutation
    window (a restarted node learns it is believed dead through any
    interaction and re-broadcasts alive); the K_ALIVE injection clears
    the tombstone (inject_facts_batch).  ``tombstone & alive`` is empty
    for every genuinely dead subject, so the steady-state gate stays
    closed and the phase stays free."""
    n, k = cfg.n, cfg.k_facts
    could_accuse = accusations_pending(state)
    tomb_alive = state.tombstone & state.alive

    def do(state):
        # single-source with the gate: per-fact pending already encodes
        # "accusation kind & incarnation beats the subject's & subject
        # alive" for exactly the about_me row, so the body can never
        # diverge from the gate it runs under
        known = unpack_bits(state.known, k)                  # bool[N, K]
        about_me = state.facts.subject[None, :] == jnp.arange(n)[:, None]
        accused = jnp.any(known & could_accuse[None, :] & about_me,
                          axis=1) | tomb_alive
        new_inc = jnp.where(accused, state.incarnation + 1,
                            state.incarnation)
        state = state._replace(incarnation=new_inc)
        return _bounded_inject(state, cfg, accused, K_ALIVE, new_inc,
                               jnp.arange(n, dtype=jnp.int32),
                               fcfg.max_new_facts, key)

    return jax.lax.cond(jnp.any(could_accuse) | jnp.any(tomb_alive),
                        do, lambda st: st, state)


def suspicion_q_of(fcfg: FailureConfig, stretch_q=None) -> jnp.ndarray:
    """The live suspicion window in q-ticks: the static config value
    plus the adaptive control plane's stretch (serf_tpu.control.device
    ``stretch_q`` knob — Lifeguard's timeout stretch, cluster-wide),
    clamped to the AGE_PIN_Q stamp-representability bound.  THE one
    definition both the declare expiry scan and the ``believed_dead``
    judgment use, so stretching the declaration timer and judging
    false-DEADs can never diverge."""
    if stretch_q is None:
        return jnp.uint8(fcfg.suspicion_q)
    return jnp.clip(jnp.asarray(fcfg.suspicion_q, jnp.int32)
                    + jnp.asarray(stretch_q, jnp.int32),
                    1, AGE_PIN_Q).astype(jnp.uint8)


def declare_round(state: GossipState, cfg: GossipConfig, fcfg: FailureConfig,
                  key: jax.Array, stretch_q=None) -> GossipState:
    """Suspicions that aged out without refutation become dead declarations.

    Skip-gated on a K-sized predicate: a suspicion can only produce a
    declaration while it is neither refuted (an alive fact about the
    same subject with higher incarnation) nor already covered by a dead
    declaration.  Retired-but-valid ring facts fail it, so the gate
    switches the phase OFF again in the post-detection steady state —
    with it every mask in the body is all-False and the round is a
    bit-exact identity skipping the N×K scans.

    ``stretch_q`` (optional i32 scalar, may be traced) widens the
    suspicion window by that many quarter-round ticks — the adaptive
    control plane's Lifeguard stretch (:func:`suspicion_q_of`)."""
    suspect = _facts_about(state, (K_SUSPECT,))
    return jax.lax.cond(
        jnp.any(live_suspicions(state)),
        lambda st: _declare_round_body(st, cfg, fcfg, suspect, key,
                                       stretch_q=stretch_q),
        lambda st: st,
        state)


def _declare_round_body(state: GossipState, cfg: GossipConfig,
                        fcfg: FailureConfig, suspect: jnp.ndarray,
                        key: jax.Array, stretch_q=None) -> GossipState:
    n, k = cfg.n, cfg.k_facts
    refuted = jnp.any(_refutation_matrix(state), axis=1)
    # K-sized fact filter, packed once (suspicions that could declare)
    fact_words = pack_bits(suspect & ~refuted)                # u32[W]
    # the expiry scan is the detection regime's biggest plane read —
    # evaluate the q-age predicate in BYTE space on the packed flavor
    # (per-nibble compares woven straight into fact words, no K-order
    # interleave; see dissemination.pack_pred_words) and gate with the
    # packed known/alive planes before ONE contiguous unpack.  mod_age
    # is garbage where the known bit is clear; the known AND gates it.
    sq = suspicion_q_of(fcfg, stretch_q)
    if cfg.pack_stamp:
        b = state.stamp
        aged_words = nibble_age_pred_words(b & jnp.uint8(0xF), b >> 4,
                                           state.round, sq, ge=True)
        if cfg.stamp_deferred:
            # deferred flavor: a learned-since-flush cell's q-age is 0
            # (< any window) regardless of its stale nibble — the packed
            # read-through twin of mod_age's overlay amendment, which the
            # unpacked branch below gets centrally
            aged_words = aged_words & ~state.overlay
    else:
        aged_words = pack_bits(mod_age(state, cfg) >= sq)
    alive_words = jnp.where(state.alive[:, None],
                            jnp.uint32(0xFFFFFFFF), jnp.uint32(0))
    expired = unpack_bits(state.known & aged_words & fact_words[None, :]
                          & alive_words, k)                   # bool[N, K]
    # subjects with at least one expired suspicion at some knower
    subj = jnp.clip(state.facts.subject, 0)
    subject_expired = jnp.zeros((n,), bool).at[subj].max(jnp.any(expired, axis=0))
    already_dead = _subject_covered(state, cfg, (K_DEAD,)) | state.tombstone
    candidates = subject_expired & ~already_dead
    # declarer PER SUBJECT: the lowest-id knower whose suspicion of that
    # subject expired (argmax of bool = first True).  A single global
    # declarer would skew per-node fairness accounting.
    fact_has_expired = jnp.any(expired, axis=0)              # bool[K]
    declarer_of_fact = jnp.argmax(expired, axis=0).astype(jnp.int32)  # [K]
    declarers_p1 = jnp.zeros((n,), jnp.int32).at[subj].max(
        jnp.where(fact_has_expired, declarer_of_fact + 1, 0))
    declarers = jnp.maximum(declarers_p1 - 1, 0)
    return _bounded_inject(state, cfg, candidates, K_DEAD,
                           state.incarnation, declarers,
                           fcfg.max_new_facts, key)


def swim_round(state: GossipState, cfg: GossipConfig, fcfg: FailureConfig,
               key: jax.Array) -> GossipState:
    """One full protocol round: gossip exchange + probe + refute + declare."""
    k1, k2, k3, k4 = jax.random.split(key, 4)
    state = round_step(state, cfg, k1)
    state = probe_round(state, cfg, fcfg, k2)
    state = refute_round(state, cfg, fcfg, k3)
    state = declare_round(state, cfg, fcfg, k4)
    return state


def run_swim(state: GossipState, cfg: GossipConfig, fcfg: FailureConfig,
             key: jax.Array, num_rounds: int) -> GossipState:
    def body(carry, subkey):
        return swim_round(carry, cfg, fcfg, subkey), ()

    keys = jax.random.split(key, num_rounds)
    final, _ = jax.lax.scan(body, state, keys)
    return final


# -- views / metrics ---------------------------------------------------------

def believer_counts(state: GossipState, cfg: GossipConfig,
                    fcfg: FailureConfig, stretch_q=None,
                    subj_inc=None, known=None,
                    evidence_facts=None) -> jnp.ndarray:
    """i32[K]: per-fact count of ALIVE believers among (this shard of)
    the cluster — the stage-1 partial of the believed-dead judgment.

    Associative under elementwise ``+``: the knower axis reductions are
    plain integer sums, so partials computed over disjoint node shards
    psum to exactly the global count (the in-collective telemetry leg,
    ``parallel.ring.round_telemetry_sharded``, relies on this).
    ``subj_inc``/``known``/``evidence_facts`` let the caller supply the
    pmax-assembled subject incarnations / an already-unpacked known
    plane / already-computed ``(dead_fact, aged_suspect)`` masks (the
    telemetry path's skip-gate computed them for its predicate).
    """
    k = cfg.k_facts
    if known is None:
        known = unpack_bits(state.known, k)
    # an accusation stale w.r.t. the subject's CURRENT incarnation is no
    # evidence: the incarnation plane is the durable record of a
    # refutation (the K_ALIVE fact itself may have been recycled out of
    # the ring — the dual of the tombstone plane for deaths; reference
    # member tables ignore stale-incarnation dead messages forever)
    if evidence_facts is not None:
        dead_fact, aged_suspect = evidence_facts
    else:
        dead_fact = _facts_about(state, (K_DEAD,), inc_current=True,
                                 subj_inc=subj_inc)
        aged_suspect = _facts_about(state, (K_SUSPECT,),
                                    inc_current=True, subj_inc=subj_inc)
    aged = mod_age(state, cfg) >= suspicion_q_of(fcfg, stretch_q)
    # (gated by `known` below)
    evidence = known & (dead_fact[None, :] | (aged_suspect[None, :] & aged))
    # refutation: knower also knows an alive fact about the same subject
    # with strictly higher incarnation.  knower_refutes[n, j] =
    # any_k(known[n, k] & refutes[j, k]) — computed as bit overlap
    # against the ALREADY-PACKED known words instead of the former
    # [N,K]·[K,K] float einsum: K/32 u32 AND-passes replace N·K·K MACs
    # (identical booleans: a 0/1 dot product is > 0 iff some bit is
    # shared), which keeps the telemetry row's gate-open cost a
    # fraction of a round instead of a multiple of one
    refutes = _refutation_matrix(state)                      # [K, K]
    words = k // 32
    r3 = refutes.reshape(k, words, 32).astype(jnp.uint32)
    packed = jnp.sum(r3 << jnp.arange(32, dtype=jnp.uint32),
                     axis=-1)                                # u32[K, W]
    knower_refutes = jnp.zeros(known.shape, bool)
    for w in range(words):
        knower_refutes = knower_refutes | (
            (state.known[:, w][:, None] & packed[None, :, w]) != 0)
    active = evidence & ~knower_refutes                  # bool[N(l), K]
    return jnp.sum(active & state.alive[:, None], axis=0)


def believed_subjects(state: GossipState, n: int, believer_cnt,
                      alive_cnt) -> jnp.ndarray:
    """bool[N]: stage-2 of the believed-dead judgment from GLOBALLY
    reduced counts — 'every alive node believes subject dead' scattered
    onto the subject axis.  A pure function of the (replicated) fact
    table and two reduced count operands, so every shard of a sharded
    cluster computes it identically; the tombstone OR stays with the
    caller (the tombstone plane is node-sharded)."""
    all_believe = believer_cnt >= jnp.maximum(alive_cnt, 1)
    subj = jnp.clip(state.facts.subject, 0)
    return jnp.zeros((n,), bool).at[subj].max(
        all_believe & state.facts.valid)


def believed_dead(state: GossipState, cfg: GossipConfig,
                  fcfg: FailureConfig, stretch_q=None) -> jnp.ndarray:
    """bool[N, N']→ compressed: for each node i (knower) and table slot j,
    whether i currently believes the fact's subject is dead; reduced to
    bool[N_subjects] 'every alive node believes subject dead'.

    ``stretch_q`` widens the aged-suspicion evidence window exactly like
    the declare scan (:func:`suspicion_q_of`): a controlled cluster that
    stretched its suspicion timers is judged by the semantics it runs.

    Staged through :func:`believer_counts` / :func:`believed_subjects`
    so the sharded telemetry leg can psum the stage-1 partials instead
    of gathering the knower planes — this unsharded composition is the
    bit-identical reference the sharded row is pinned against."""
    per_fact_believers = believer_counts(state, cfg, fcfg, stretch_q)
    believed = believed_subjects(state, cfg.n, per_fact_believers,
                                 jnp.sum(state.alive))
    # durable record: a fully-disseminated death whose ring slot has
    # recycled lives on in the tombstone plane (GossipState.tombstone)
    return believed | state.tombstone


def detection_complete(state: GossipState, cfg: GossipConfig,
                       fcfg: FailureConfig) -> jnp.ndarray:
    """Scalar bool: every dead node is believed dead by every alive node."""
    believed = believed_dead(state, cfg, fcfg)
    return jnp.all(jnp.where(~state.alive, believed, True))


def emit_swim_metrics(state: GossipState, cfg: GossipConfig,
                      fcfg: FailureConfig = FailureConfig(),
                      labels=None) -> dict:
    """Emit device-plane SWIM round-outcome gauges onto the process sink.

    The host-side companion of :func:`serf_tpu.models.dissemination.
    emit_gossip_metrics` (same pull-based contract: one device->host
    sync, call between scans, never inside jit): how many suspicions are
    live (could still declare), how many accusations could still be
    refuted, and how many death declarations occupy ring slots — the
    numbers that say which phase gates are open and why.
    """
    from serf_tpu.utils import metrics

    # one device_get for the whole dict (see emit_gossip_metrics)
    vals = jax.device_get({
        "serf.model.swim.live-suspicions":
            jnp.sum(live_suspicions(state)),
        "serf.model.swim.accusations-pending":
            jnp.sum(accusations_pending(state)),
        "serf.model.swim.dead-facts":
            jnp.sum(_facts_about(state, (K_DEAD,))),
        "serf.model.swim.undetected-deaths":
            jnp.sum(~state.alive
                    & ~believed_dead(state, cfg, fcfg)),
        # false-DEAD: responsive (alive) nodes the cluster believes dead
        # — Lifeguard's refutation path must drive this back to zero
        # after heal; the SLO plane's false-dead objective watches it
        "serf.model.swim.false-dead":
            jnp.sum(believed_dead(state, cfg, fcfg) & state.alive),
    })
    vals = {name: float(v) for name, v in vals.items()}
    for name, v in vals.items():
        metrics.gauge(name, v, labels)
    return vals
